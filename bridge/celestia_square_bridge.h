/* C ABI for the TPU square pipeline — the host-language integration seam.
 *
 * This is the native bridge SURVEY §2.3 calls for: a consensus node written
 * in another language (the reference is Go) loads this library and routes
 * rsmt2d.Codec / wrapper.Constructor calls through it instead of its CPU
 * codec, keeping PrepareProposal/ProcessProposal byte-identical while the
 * RS extension + NMT forest + DAH run on the accelerator.
 *
 * The library owns a persistent worker process hosting the XLA runtime
 * (celestia_app_tpu.bridge.worker) and speaks a length-prefixed binary
 * protocol over its stdio; kernels are compiled once at init (AOT warmup)
 * so no compilation ever sits on the block-production critical path.
 */

#ifndef CELESTIA_SQUARE_BRIDGE_H
#define CELESTIA_SQUARE_BRIDGE_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct cstpu_client cstpu_client;

/* Spawn the persistent runtime worker.  `worker_argv` is a NULL-terminated
 * argv (e.g. {"python3", "-m", "celestia_app_tpu.bridge.worker", NULL}).
 * `warmup_ks` lists square sizes to AOT-compile (may be NULL / n = 0).
 * Returns NULL on failure. */
cstpu_client *cstpu_init(const char *const *worker_argv,
                         const uint32_t *warmup_ks, size_t n_warmup);

/* Liveness probe (watchdog hook).  Returns 0 when healthy. */
int cstpu_ping(cstpu_client *c);

/* Extend a k x k ODS and compute all commitments in one device program.
 *   ods:        k*k*512 bytes, row-major
 *   eds_out:    2k*2k*512 bytes (may be NULL if only roots are needed)
 *   row_roots:  2k*90 bytes    col_roots: 2k*90 bytes
 *   data_root:  32 bytes
 * Returns 0 on success; any nonzero status means the caller must fall back
 * to its CPU path (the fallback contract of SURVEY §7 phase 6). */
int cstpu_extend_and_dah(cstpu_client *c, const uint8_t *ods, uint32_t k,
                         uint8_t *eds_out, uint8_t *row_roots,
                         uint8_t *col_roots, uint8_t *data_root);

/* Terminate the worker and free the client. */
void cstpu_shutdown(cstpu_client *c);

#ifdef __cplusplus
}
#endif

#endif /* CELESTIA_SQUARE_BRIDGE_H */

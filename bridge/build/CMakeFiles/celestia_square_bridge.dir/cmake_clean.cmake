file(REMOVE_RECURSE
  "CMakeFiles/celestia_square_bridge.dir/celestia_square_bridge.cpp.o"
  "CMakeFiles/celestia_square_bridge.dir/celestia_square_bridge.cpp.o.d"
  "libcelestia_square_bridge.pdb"
  "libcelestia_square_bridge.so"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/celestia_square_bridge.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()

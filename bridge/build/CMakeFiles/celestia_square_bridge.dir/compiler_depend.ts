# CMAKE generated file: DO NOT EDIT!
# Timestamp file for compiler generated dependencies management for celestia_square_bridge.

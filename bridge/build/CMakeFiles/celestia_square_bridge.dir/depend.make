# Empty dependencies file for celestia_square_bridge.
# This may be replaced when dependencies are built.

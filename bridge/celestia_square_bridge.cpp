/* Persistent-worker bridge implementation.
 *
 * Design (SURVEY §2.3 item 3, §7 phase 6): the consensus daemon must never
 * block on interpreter startup or kernel compilation, so the library forks
 * ONE long-lived worker hosting the XLA runtime and multiplexes requests
 * over its stdio with a length-prefixed binary protocol.  All calls are
 * serialized by a mutex (the square pipeline is one-block-at-a-time on the
 * consensus path anyway); any protocol/worker failure poisons the client
 * and surfaces as a nonzero status so the caller falls back to its CPU
 * codec.
 *
 * Protocol (little-endian):
 *   request:  magic "CSQ1" | op u32 | k u32 | payload_len u64 | payload
 *   response: magic "CSQR" | status u32 | payload_len u64 | payload
 *   ops: 1 = extend_and_dah (payload = ODS bytes; response payload =
 *        EDS || row_roots || col_roots || data_root), 2 = ping,
 *        3 = warmup (payload = none; k = square size), 4 = shutdown.
 */

#include "celestia_square_bridge.h"

#include <errno.h>
#include <mutex>
#include <signal.h>
#include <stdlib.h>
#include <string.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

namespace {

constexpr uint32_t kReqMagic = 0x31515343;   // "CSQ1"
constexpr uint32_t kRespMagic = 0x52515343;  // "CSQR"
constexpr uint32_t kOpExtend = 1;
constexpr uint32_t kOpPing = 2;
constexpr uint32_t kOpWarmup = 3;
constexpr uint32_t kOpShutdown = 4;
constexpr size_t kShareSize = 512;
constexpr size_t kNmtRootSize = 90;

bool write_all(int fd, const void *buf, size_t n) {
  const char *p = static_cast<const char *>(buf);
  while (n > 0) {
    ssize_t w = write(fd, p, n);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool read_all(int fd, void *buf, size_t n) {
  char *p = static_cast<char *>(buf);
  while (n > 0) {
    ssize_t r = read(fd, p, n);
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    if (r == 0) return false;  // worker died
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

}  // namespace

struct cstpu_client {
  pid_t worker_pid = -1;
  int to_worker = -1;    // write end
  int from_worker = -1;  // read end
  bool poisoned = false;
  std::mutex mu;

  ~cstpu_client() {
    if (to_worker >= 0) close(to_worker);
    if (from_worker >= 0) close(from_worker);
    if (worker_pid > 0) {
      kill(worker_pid, SIGTERM);
      waitpid(worker_pid, nullptr, 0);
    }
  }

  bool request(uint32_t op, uint32_t k, const uint8_t *payload,
               uint64_t payload_len, uint8_t *resp, uint64_t resp_cap,
               uint64_t *resp_len) {
    if (poisoned) return false;
    uint8_t header[20];
    memcpy(header, &kReqMagic, 4);
    memcpy(header + 4, &op, 4);
    memcpy(header + 8, &k, 4);
    memcpy(header + 12, &payload_len, 8);
    if (!write_all(to_worker, header, sizeof(header)) ||
        (payload_len && !write_all(to_worker, payload, payload_len))) {
      poisoned = true;
      return false;
    }
    uint8_t rhead[16];
    if (!read_all(from_worker, rhead, sizeof(rhead))) {
      poisoned = true;
      return false;
    }
    uint32_t magic, status;
    uint64_t rlen;
    memcpy(&magic, rhead, 4);
    memcpy(&status, rhead + 4, 4);
    memcpy(&rlen, rhead + 8, 8);
    if (magic != kRespMagic || rlen > resp_cap) {
      poisoned = true;
      return false;
    }
    if (rlen && !read_all(from_worker, resp, rlen)) {
      poisoned = true;
      return false;
    }
    if (resp_len) *resp_len = rlen;
    return status == 0;
  }
};

extern "C" {

cstpu_client *cstpu_init(const char *const *worker_argv,
                         const uint32_t *warmup_ks, size_t n_warmup) {
  if (!worker_argv || !worker_argv[0]) return nullptr;
  int in_pipe[2];   // parent -> child
  int out_pipe[2];  // child -> parent
  if (pipe(in_pipe) != 0) return nullptr;
  if (pipe(out_pipe) != 0) {
    close(in_pipe[0]);
    close(in_pipe[1]);
    return nullptr;
  }
  pid_t pid = fork();
  if (pid < 0) {
    close(in_pipe[0]); close(in_pipe[1]);
    close(out_pipe[0]); close(out_pipe[1]);
    return nullptr;
  }
  if (pid == 0) {
    dup2(in_pipe[0], STDIN_FILENO);
    dup2(out_pipe[1], STDOUT_FILENO);
    close(in_pipe[0]); close(in_pipe[1]);
    close(out_pipe[0]); close(out_pipe[1]);
    execvp(worker_argv[0], const_cast<char *const *>(worker_argv));
    _exit(127);
  }
  close(in_pipe[0]);
  close(out_pipe[1]);

  cstpu_client *c = new cstpu_client();
  c->worker_pid = pid;
  c->to_worker = in_pipe[1];
  c->from_worker = out_pipe[0];

  if (cstpu_ping(c) != 0) {
    delete c;
    return nullptr;
  }
  for (size_t i = 0; i < n_warmup; i++) {
    std::lock_guard<std::mutex> lock(c->mu);
    if (!c->request(kOpWarmup, warmup_ks[i], nullptr, 0, nullptr, 0, nullptr)) {
      delete c;
      return nullptr;
    }
  }
  return c;
}

int cstpu_ping(cstpu_client *c) {
  if (!c) return -1;
  std::lock_guard<std::mutex> lock(c->mu);
  return c->request(kOpPing, 0, nullptr, 0, nullptr, 0, nullptr) ? 0 : -1;
}

int cstpu_extend_and_dah(cstpu_client *c, const uint8_t *ods, uint32_t k,
                         uint8_t *eds_out, uint8_t *row_roots,
                         uint8_t *col_roots, uint8_t *data_root) {
  if (!c || !ods || !k || !row_roots || !col_roots || !data_root) return -1;
  const uint64_t ods_len = static_cast<uint64_t>(k) * k * kShareSize;
  const uint64_t eds_len = 4 * ods_len;
  const uint64_t roots_len = static_cast<uint64_t>(2) * k * kNmtRootSize;
  const uint64_t resp_len_expect = eds_len + 2 * roots_len + 32;

  uint8_t *resp = static_cast<uint8_t *>(malloc(resp_len_expect));
  if (!resp) return -1;
  uint64_t resp_len = 0;
  bool ok;
  {
    std::lock_guard<std::mutex> lock(c->mu);
    ok = c->request(kOpExtend, k, ods, ods_len, resp, resp_len_expect, &resp_len);
  }
  if (!ok || resp_len != resp_len_expect) {
    free(resp);
    return -1;
  }
  if (eds_out) memcpy(eds_out, resp, eds_len);
  memcpy(row_roots, resp + eds_len, roots_len);
  memcpy(col_roots, resp + eds_len + roots_len, roots_len);
  memcpy(data_root, resp + eds_len + 2 * roots_len, 32);
  free(resp);
  return 0;
}

void cstpu_shutdown(cstpu_client *c) {
  if (!c) return;
  {
    std::lock_guard<std::mutex> lock(c->mu);
    c->request(kOpShutdown, 0, nullptr, 0, nullptr, 0, nullptr);
  }
  delete c;
}

}  // extern "C"

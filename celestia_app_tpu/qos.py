"""$CELESTIA_QOS: per-tenant admission control — the observe -> enforce
layer of the multi-tenant data plane.

PR 4 made every namespace's blobs/shares/bytes visible, PR 10 labeled the
read path, PR 7 gave the telemetry plane burn-rate judgment — and nothing
ACTED on any of it: a whale tenant could flood BroadcastTx and crowd the
square, a proof spammer could saturate the serve plane, and the only
recourse was an operator eyeballing /metrics.  This module closes the
loop the way serve/heal.py closed detect -> act on the read path:
declarative per-tenant limits, enforced at the two admission seams the
repo already has (mempool insert on the write path, proof assembly on the
read path), with ONE canonical throttle payload every plane renders.

Spec grammar — comma-separated `key=value` pairs (the $CELESTIA_CHAOS
shape; unknown keys raise, a typo'd limit silently enforcing nothing is
worse than no limit at all):

    CELESTIA_QOS="tx_rate=50,tx_burst=100,pool_bytes=1048576,\
deadbeef.tx_rate=5,deadbeef.slo_p99_ms=500"

    tx_rate=<r>        default per-tenant tx admissions/sec (token bucket)
    tx_burst=<n>       default bucket depth (default: max(2*rate, 1))
    bytes_rate=<r>     default per-tenant admitted bytes/sec
    bytes_burst=<n>    default byte-bucket depth (default: 2*rate)
    pool_bytes=<n>     default per-tenant RESIDENT byte quota in the
                       mempool (admission refuses while the tenant's
                       resident bytes would exceed it)
    proof_rate=<r>     default per-tenant served DAS proofs/sec (read
                       path; parity/`other` reads are protocol traffic
                       and are never tenant-throttled)
    proof_burst=<n>    default proof-bucket depth
    slo_p99_ms=<ms>    register a per-tenant e2e p99 SLOSpec on the PR 7
                       burn-rate engine (celestia_e2e_seconds
                       {phase=total, namespace=<tenant>})
    <tenant>.<key>=<v> per-tenant override of any key above, where
                       <tenant> is the namespace label (hex, the PR 4
                       label space) or the reserved `tx` bucket

Absent keys mean UNLIMITED (the default node enforces nothing and pays
one cached env read per admission); an explicit 0 means fully blocked.
Token buckets refill continuously (monotonic clock, injectable for
tests) and are keyed by the CAPPED namespace label, so the enforcement
state is bounded by the PR 4 top-N cardinality cap by construction.

Every throttle raises `QosThrottled`, whose payload is rendered by ONE
canonical encoder (`throttle_body`, sorted-keys compact JSON — the
serve/api.render discipline), so the HTTP 429 bodies on the JSON-RPC and
REST planes and the gRPC RESOURCE_EXHAUSTED detail string are
byte-identical; throttles tick `celestia_qos_throttled_total
{namespace,kind}` and the per-tenant remaining tokens land on
`celestia_qos_tokens{namespace,bucket}`.  /healthz gains a `qos` block
and GET /namespaces an enforcement section (limits, tokens remaining,
throttle counts) — see trace/exposition.py and trace/square_journal.py.
"""

from __future__ import annotations

import json
import os
import threading
import time

#: Spec keys a tenant limit can be set for (bare = the default tier).
_LIMIT_KEYS = (
    "tx_rate", "tx_burst", "bytes_rate", "bytes_burst",
    "pool_bytes", "proof_rate", "proof_burst", "slo_p99_ms",
)


class QosThrottled(Exception):
    """A per-tenant limit refused this request.

    `kind` names the exhausted resource (tx_rate | bytes_rate |
    pool_bytes | proof_rate); the payload/`detail` rendering is the ONE
    byte sequence all three planes carry (429 bodies on the HTTP planes,
    the RESOURCE_EXHAUSTED detail string on gRPC)."""

    def __init__(self, namespace: str, kind: str, limit: float,
                 retry_after_s: float = 1.0):
        self.namespace = namespace
        self.kind = kind
        self.limit = limit
        self.retry_after_s = max(round(float(retry_after_s), 3), 0.001)
        super().__init__(
            f"namespace {namespace!r} over {kind} limit ({limit:g})"
        )

    def payload(self) -> dict:
        return {
            "code": "RESOURCE_EXHAUSTED",
            "error": str(self),
            "namespace": self.namespace,
            "kind": self.kind,
            "limit": self.limit,
            "retry_after_s": self.retry_after_s,
        }


def throttle_body(e: QosThrottled) -> bytes:
    """THE canonical throttle bytes (sorted keys, compact separators —
    serve/api.render's discipline): what makes cross-plane byte-identity
    structural rather than a test invariant."""
    return json.dumps(
        e.payload(), sort_keys=True, separators=(",", ":")
    ).encode()


def retry_after_header(e: QosThrottled) -> str:
    """The Retry-After header value every HTTP plane sends for a
    throttle: the bucket's refill estimate, ceiled, floored at 1 s —
    one definition so the planes cannot round apart."""
    return str(max(1, int(-(-e.retry_after_s // 1))))


def parse_spec(raw: str) -> dict:
    """`"k=v,tenant.k=v"` -> {(tenant|None, key): float}.  Unknown keys
    and malformed pairs raise ValueError (the chaos/spec.py contract)."""
    out: dict = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        key, eq, val = part.partition("=")
        key = key.strip()
        tenant = None
        if "." in key:
            tenant, _, key = key.rpartition(".")
            tenant = tenant.strip()
            if not tenant:
                raise ValueError(f"qos spec: empty tenant in {part!r}")
        if not eq or key not in _LIMIT_KEYS:
            raise ValueError(
                f"qos spec: unknown entry {part!r} "
                f"(known keys: {sorted(_LIMIT_KEYS)!r})"
            )
        try:
            out[(tenant, key)] = float(val.strip())
        except ValueError:
            raise ValueError(f"qos spec: bad value in {part!r}") from None
    return out


class _TokenBucket:
    """Continuous-refill token bucket (classic leaky-bucket dual): up to
    `burst` tokens, refilled at `rate`/sec.  NOT self-locking — the
    enforcer serializes access per tenant."""

    __slots__ = ("rate", "burst", "tokens", "t_last")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = max(float(burst), 0.0)
        self.tokens = self.burst
        self.t_last = now

    def _refill(self, now: float) -> None:
        if now > self.t_last:
            self.tokens = min(
                self.burst, self.tokens + (now - self.t_last) * self.rate
            )
            self.t_last = now

    def take(self, n: float, now: float) -> bool:
        self._refill(now)
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def retry_after(self, n: float) -> float:
        """Seconds until `n` tokens will exist (1s floor when blocked)."""
        if self.rate <= 0:
            return 1.0
        return max((n - self.tokens) / self.rate, 0.001)


class QosEnforcer:
    """The live enforcement state for one parsed spec.

    Buckets are keyed by CAPPED namespace label (the PR 4 cardinality
    cap bounds the state), created lazily from the tenant's explicit
    limits or the default tier.  Thread-safe behind one lock — the
    guarded work is a couple of float ops, never I/O, so contention is
    noise next to the admission paths it protects (and orders of
    magnitude below the sharded mempool locks it rides behind)."""

    def __init__(self, params: dict, raw: str = "", clock=time.monotonic):
        self.params = dict(params)
        self.raw = raw
        self._clock = clock
        self._lock = threading.Lock()
        # (tenant, bucket-kind) -> _TokenBucket, built on first touch.
        self._buckets: dict[tuple[str, str], _TokenBucket] = {}
        # tenant -> {kind: throttle count} (the /namespaces + /healthz
        # enforcement story; bounded like the buckets).
        self._throttled: dict[str, dict[str, int]] = {}

    # --- limit resolution ---------------------------------------------------
    def _limit(self, tenant: str, key: str) -> float | None:
        """Tenant override first, then the default tier; None = unlimited."""
        v = self.params.get((tenant, key))
        if v is None:
            v = self.params.get((None, key))
        return v

    def tenants_with_limits(self) -> list[str]:
        """Every tenant the spec names explicitly (plus nothing else —
        default-tier limits apply lazily to whoever shows up)."""
        return sorted({t for (t, _k) in self.params if t is not None})

    def slo_specs(self):
        """Per-tenant SLOSpecs for the PR 7 burn-rate engine: one e2e
        p99 objective per `<tenant>.slo_p99_ms` (or every explicitly
        named tenant under a default `slo_p99_ms`)."""
        from celestia_app_tpu.trace.slo import SLOSpec

        out = []
        for tenant in self.tenants_with_limits():
            ms = self._limit(tenant, "slo_p99_ms")
            if ms is None or tenant == "tx":
                continue
            out.append(SLOSpec(
                name=f"qos_{tenant}_e2e_p99",
                metric="celestia_e2e_seconds",
                labels=(("phase", "total"), ("namespace", tenant)),
                quantile=0.99,
                threshold=ms / 1e3,
            ))
        return tuple(out)

    # --- enforcement --------------------------------------------------------
    def _bucket(self, tenant: str, kind: str, rate: float,
                now: float) -> _TokenBucket:
        b = self._buckets.get((tenant, kind))
        if b is None or b.rate != rate:
            burst = self._limit(tenant, f"{kind.split('_')[0]}_burst")
            if burst is None:
                # rate 0 means BLOCKED (no free burst token); a positive
                # rate defaults to a 2x-rate bucket depth, 1 minimum.
                burst = max(2.0 * rate, 1.0) if rate > 0 else 0.0
            b = _TokenBucket(rate, burst, now)
            self._buckets[(tenant, kind)] = b
        return b

    def _throttle(self, tenant: str, kind: str, limit: float,
                  retry_after_s: float):
        from celestia_app_tpu.trace.metrics import registry
        from celestia_app_tpu.trace.square_journal import (
            capped_namespace_label,
        )

        per = self._throttled.setdefault(tenant, {})
        per[kind] = per.get(kind, 0) + 1
        registry().counter(
            "celestia_qos_throttled_total",
            "per-tenant QoS refusals by exhausted resource "
            "(429 / RESOURCE_EXHAUSTED on every plane)",
        ).inc(namespace=capped_namespace_label(tenant), kind=kind)
        raise QosThrottled(tenant, kind, limit, retry_after_s)

    def admit_tx(self, tenant: str, nbytes: int,
                 resident_bytes: int = 0) -> None:
        """The write-path gate (one call per mempool admission): resident
        byte quota, then the tx-rate bucket, then the bytes-rate bucket.
        Raises QosThrottled; charges nothing on a refusal (a throttled
        spammer must not drain its own future budget)."""
        quota = self._limit(tenant, "pool_bytes")
        now = self._clock()
        with self._lock:
            if quota is not None and resident_bytes + nbytes > quota:
                self._throttle(tenant, "pool_bytes", quota, 1.0)
            tx_rate = self._limit(tenant, "tx_rate")
            if tx_rate is not None:
                b = self._bucket(tenant, "tx_rate", tx_rate, now)
                if not b.take(1.0, now):
                    self._throttle(tenant, "tx_rate", tx_rate,
                                   b.retry_after(1.0))
            bytes_rate = self._limit(tenant, "bytes_rate")
            if bytes_rate is not None:
                b = self._bucket(tenant, "bytes_rate", bytes_rate, now)
                if not b.take(float(nbytes), now):
                    # Un-charge the tx-rate token the refused admission
                    # took above: one refusal must cost zero budget.
                    if tx_rate is not None:
                        tb = self._buckets[(tenant, "tx_rate")]
                        tb.tokens = min(tb.burst, tb.tokens + 1.0)
                    self._throttle(tenant, "bytes_rate", bytes_rate,
                                   b.retry_after(float(nbytes)))
            self._refresh_token_gauges(tenant)

    def admit_proof(self, tenant: str) -> None:
        """The read-path gate (one call per served proof, labeled by the
        PR 10 capped namespace): parity/`other`/`tx` reads are protocol
        traffic, never tenant-throttled."""
        from celestia_app_tpu.trace.square_journal import OTHER_LABEL, TX_LABEL

        if tenant in (OTHER_LABEL, TX_LABEL):
            return
        rate = self._limit(tenant, "proof_rate")
        if rate is None:
            return
        now = self._clock()
        with self._lock:
            b = self._bucket(tenant, "proof_rate", rate, now)
            if not b.take(1.0, now):
                self._throttle(tenant, "proof_rate", rate,
                               b.retry_after(1.0))
            self._refresh_token_gauges(tenant)

    # --- read side ----------------------------------------------------------
    def _refresh_token_gauges(self, tenant: str) -> None:
        from celestia_app_tpu.trace.metrics import registry
        from celestia_app_tpu.trace.square_journal import (
            capped_namespace_label,
        )

        gauge = registry().gauge(
            "celestia_qos_tokens",
            "remaining per-tenant QoS tokens by bucket",
        )
        for (t, kind), b in self._buckets.items():
            if t == tenant:
                gauge.set(round(b.tokens, 3),
                          namespace=capped_namespace_label(t), bucket=kind)

    def tenant_block(self, tenant: str) -> dict:
        """One tenant's enforcement view (limits / tokens / throttles) —
        the /namespaces + /healthz row."""
        limits = {
            key: self._limit(tenant, key)
            for key in _LIMIT_KEYS
            if self._limit(tenant, key) is not None
        }
        with self._lock:
            tokens = {
                kind: round(b.tokens, 3)
                for (t, kind), b in sorted(self._buckets.items())
                if t == tenant
            }
            throttled = dict(self._throttled.get(tenant, {}))
        return {"limits": limits, "tokens": tokens, "throttled": throttled}

    def health_block(self) -> dict:
        """The /healthz `qos` face: the configured default tier, every
        tenant with explicit limits or live state, total throttles."""
        with self._lock:
            seen = sorted(
                {t for (t, _k) in self._buckets} | set(self._throttled)
            )
            total = sum(
                n for per in self._throttled.values() for n in per.values()
            )
        tenants = sorted(set(self.tenants_with_limits()) | set(seen))
        return {
            "spec": self.raw,
            "defaults": {
                key: self.params[(None, key)]
                for key in _LIMIT_KEYS if (None, key) in self.params
            },
            "tenants": {t: self.tenant_block(t) for t in tenants},
            "throttled_total": total,
        }


# --- process-level activation (the chaos/__init__ pattern) -------------------

_INSTALLED: QosEnforcer | None = None
_ENV_CACHE: tuple[str, QosEnforcer | None] = ("", None)
_LOCK = threading.Lock()


def _wire_slos(enf: QosEnforcer | None) -> None:
    """Per-tenant SLOSpecs ride the PR 7 burn-rate engine: swap the
    engine's tenant tier whenever the enforcer changes."""
    from celestia_app_tpu.trace import slo

    slo.set_tenant_specs(enf.slo_specs() if enf is not None else ())


def install(spec: str | dict) -> QosEnforcer:
    """Install a QoS spec for this process (overrides $CELESTIA_QOS)."""
    global _INSTALLED
    params = parse_spec(spec) if isinstance(spec, str) else dict(spec)
    with _LOCK:
        _INSTALLED = QosEnforcer(
            params, raw=spec if isinstance(spec, str) else ""
        )
    _wire_slos(_INSTALLED)
    return _INSTALLED


def uninstall() -> None:
    global _INSTALLED
    with _LOCK:
        _INSTALLED = None
    _wire_slos(None)


def enforcer() -> QosEnforcer | None:
    """The active enforcer, or None when no QoS is configured (the
    default node: one cached env-string compare per admission)."""
    global _ENV_CACHE
    if _INSTALLED is not None:
        return _INSTALLED
    raw = os.environ.get("CELESTIA_QOS", "")
    cached_raw, cached = _ENV_CACHE
    if raw == cached_raw:
        return cached
    enf = QosEnforcer(parse_spec(raw), raw=raw) if raw.strip() else None
    with _LOCK:
        _ENV_CACHE = (raw, enf)
    _wire_slos(enf)
    return enf


def health_block() -> dict | None:
    """The /healthz `qos` block, or None when enforcement is off (the
    block is absent, like the heal block — presence means policy)."""
    enf = enforcer()
    return enf.health_block() if enf is not None else None

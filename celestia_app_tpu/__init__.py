"""celestia_app_tpu — a TPU-native data-availability framework.

A brand-new framework with the capabilities of celestia-app (the Celestia DA
chain's state machine): block-square construction, 2D Reed-Solomon erasure
extension, Namespaced-Merkle-Tree commitments, DataAvailabilityHeader
generation, blob share commitments, inclusion proofs, and the surrounding
state machine (PayForBlobs, mint, signal, minfee...) — redesigned TPU-first
on JAX/XLA/Pallas.

Layer map (mirrors SURVEY.md §1, re-architected):

    ops/        GF(2^8)/GF(2^16) arithmetic, bitsliced RS-as-matmul, batched
                SHA-256, NMT forest kernels, RFC6962 merkle  (JAX + numpy golden)
    shares/     share format: namespaces, info byte, compact/sparse splitting
    square/     deterministic square layout builder (Build/Construct)
    da/         ExtendedDataSquare + DataAvailabilityHeader (+ repair)
    inclusion/  blob share commitments (subtree-root merkle mountain range)
    proof/      NMT range proofs, share/row inclusion proofs
    models/     the flagship jitted "square engine" pipelines (per square size)
    parallel/   shard_map multi-chip sharding of the square pipeline
    state/      state-machine modules (blob, mint, signal, minfee, bank, auth)
    app/        ABCI-style application: PrepareProposal / ProcessProposal / CheckTx
    client/     tx client + txsim-style load generator
"""

__version__ = "0.1.0"

from celestia_app_tpu.inclusion.commitment import (
    commitment_from_row_trees,
    create_commitment,
    create_commitments,
    merkle_mountain_range_sizes,
    subtree_root_coordinates,
)

__all__ = [
    "commitment_from_row_trees",
    "create_commitment",
    "create_commitments",
    "merkle_mountain_range_sizes",
    "subtree_root_coordinates",
]

"""Blob share commitments: the ShareCommitment in MsgPayForBlobs.

Behavioral parity with the reference commitment scheme
(x/blob/types/payforblob.go:48-77 -> go-square inclusion.CreateCommitment;
spec data_square_layout.md "Blob Share Commitment Rules"):

  1. split the blob into shares;
  2. chop the share run into a Merkle-mountain-range of power-of-two chunks,
     the largest being the blob's SubtreeWidth;
  3. each chunk's root is an NMT over ns-prefixed shares — identical, by the
     alignment rules, to an inner node of the row NMTs of any square the
     blob lands in;
  4. the commitment is the binary merkle root over the chunk roots.

Because of (3) the commitment is independent of the square size, and can be
re-derived from a committed square by indexing the row trees' levels — the
TPU-native replacement for the reference's RWMutex-guarded subtree-root
cache (pkg/inclusion/nmt_caching.go:80-124, SURVEY §2.4 P7).
"""

from __future__ import annotations

from celestia_app_tpu.constants import SUBTREE_ROOT_THRESHOLD
from celestia_app_tpu.merkle import hash_from_byte_slices
from celestia_app_tpu.nmt.tree import NamespacedMerkleTree
from celestia_app_tpu.shares.sparse import Blob, split_blob
from celestia_app_tpu.square.layout import round_down_power_of_two, subtree_width


def merkle_mountain_range_sizes(total_size: int, max_tree_size: int) -> list[int]:
    """Chunk sizes: max_tree_size repeated, then descending powers of two."""
    sizes: list[int] = []
    while total_size:
        if total_size >= max_tree_size:
            sizes.append(max_tree_size)
            total_size -= max_tree_size
        else:
            s = round_down_power_of_two(total_size)
            sizes.append(s)
            total_size -= s
    return sizes


def create_commitment(
    blob: Blob, subtree_root_threshold: int = SUBTREE_ROOT_THRESHOLD
) -> bytes:
    """The 32-byte share commitment for one blob."""
    shares = split_blob(blob)
    width = subtree_width(len(shares), subtree_root_threshold)
    sizes = merkle_mountain_range_sizes(len(shares), width)
    ns = blob.namespace.to_bytes()
    roots: list[bytes] = []
    cursor = 0
    for size in sizes:
        tree = NamespacedMerkleTree()
        for s in shares[cursor : cursor + size]:
            tree.push(ns + s.raw)
        roots.append(tree.root())
        cursor += size
    return hash_from_byte_slices(roots)


def create_commitments(
    blobs: list[Blob], subtree_root_threshold: int = SUBTREE_ROOT_THRESHOLD
) -> list[bytes]:
    return [create_commitment(b, subtree_root_threshold) for b in blobs]


def subtree_root_coordinates(
    start: int, share_count: int, square_size: int, subtree_root_threshold: int
) -> list[tuple[int, int, int]]:
    """(row, height, index-in-level) of each commitment chunk root.

    `start` is the blob's first share index (row-major ODS coordinates).
    Mirrors pkg/inclusion/paths.go:16-47 calculateCommitmentPaths, but as
    array coordinates into retained tree levels instead of tree-walk paths.
    The layout rules guarantee each chunk lies within one row.
    """
    width = subtree_width(share_count, subtree_root_threshold)
    sizes = merkle_mountain_range_sizes(share_count, width)
    coords: list[tuple[int, int, int]] = []
    cursor = start
    for size in sizes:
        row, col = divmod(cursor, square_size)
        if col % size or col + size > square_size:
            raise ValueError(
                f"misaligned chunk: start {cursor} size {size} in square {square_size}"
            )
        coords.append((row, size.bit_length() - 1, col // size))
        cursor += size
    return coords


def commitment_from_row_trees(
    row_trees: dict[int, NamespacedMerkleTree],
    start: int,
    share_count: int,
    square_size: int,
    subtree_root_threshold: int = SUBTREE_ROOT_THRESHOLD,
) -> bytes:
    """Re-derive a blob's commitment from a square's row trees.

    `row_trees` maps ODS row index -> that row's NMT (over the full 2k
    extended row).  Parity with pkg/inclusion/get_commit.go:12-30
    GetCommitment, with the cached-node walk replaced by level indexing.
    """
    roots: list[bytes] = []
    for row, height, idx in subtree_root_coordinates(
        start, share_count, square_size, subtree_root_threshold
    ):
        size = 1 << height
        roots.append(row_trees[row].subtree_root(idx * size, (idx + 1) * size))
    return hash_from_byte_slices(roots)

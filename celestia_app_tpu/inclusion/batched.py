"""Device-batched share commitments.

ProcessProposal's hot loop (3) (SURVEY §3.3: inclusion.CreateCommitment per
blob inside ValidateBlobTx, x/blob/types/blob_tx.go:98) recomputes every
blob's commitment every block on every validator.  Host hashing is
per-blob sequential; here ALL blobs' MMR chunks are hashed together: chunks
are grouped by size, each group is ONE batched NMT-forest call on the
device (kernels/nmt.tree_roots), and only the tiny merkle-over-peaks step
stays on host.  Chunk counts are padded to powers of two so the jit cache
stays bounded at (log sizes x log counts) entries.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from celestia_app_tpu.constants import NMT_NODE_SIZE, SHARE_SIZE, SUBTREE_ROOT_THRESHOLD
from celestia_app_tpu.inclusion.commitment import merkle_mountain_range_sizes
from celestia_app_tpu.merkle import hash_from_byte_slices
from celestia_app_tpu.shares.sparse import Blob, split_blob
from celestia_app_tpu.square.layout import round_up_power_of_two, subtree_width


@lru_cache(maxsize=None)
def _jit_tree_roots(n: int, leaves: int):
    from celestia_app_tpu.kernels.nmt import tree_roots

    return jax.jit(tree_roots)


# Commitment memo: a commitment is a pure function of (namespace, data,
# share_version, threshold), and the SAME blob is validated up to three
# times per inclusion (CheckTx admission, PrepareProposal filter,
# ProcessProposal validation — x/blob/types/blob_tx.go:98 runs each time).
# Keying on the blob's content hash collapses those to one device pass.
# Bounded FIFO so a flood of distinct blobs cannot grow it unboundedly.
_COMMIT_MEMO: dict[tuple, bytes] = {}
_COMMIT_MEMO_MAX = 2048
# The memo is shared across every node in the process (in-process
# clusters validate concurrently from relay/loader threads): all reads
# and evictions happen under this lock. Device hashing for misses runs
# OUTSIDE it — holding a lock across a jit dispatch would serialize the
# very work the batching exists to parallelize.
import threading as _threading

_COMMIT_MEMO_LOCK = _threading.Lock()


def _memo_key(blob: Blob, threshold: int) -> tuple:
    import hashlib

    return (
        blob.namespace.to_bytes(),
        hashlib.sha256(blob.data).digest(),
        blob.share_version,
        threshold,
    )


def create_commitments_batched(
    blobs: list[Blob], subtree_root_threshold: int = SUBTREE_ROOT_THRESHOLD
) -> list[bytes]:
    """Commitments for many blobs with all hashing batched on device.

    Bit-identical to inclusion.create_commitment per blob (tested), just
    scheduled as one device call per distinct chunk size. Results are
    memoized by blob content, so revalidation of an already-seen blob
    (Prepare/Process after CheckTx) costs one sha256 of its data.
    """
    if not blobs:
        return []

    keys = [_memo_key(b, subtree_root_threshold) for b in blobs]
    with _COMMIT_MEMO_LOCK:
        have = {k: _COMMIT_MEMO[k] for k in keys if k in _COMMIT_MEMO}
    missing = [i for i, k in enumerate(keys) if k not in have]
    if not missing:
        return [have[k] for k in keys]
    fresh = _create_commitments_uncached(
        [blobs[i] for i in missing], subtree_root_threshold
    )
    with _COMMIT_MEMO_LOCK:
        for i, c in zip(missing, fresh):
            have[keys[i]] = c
            # FIFO-evict one per insert, so the memo can NEVER exceed its
            # bound: the old bulk pre-eviction emptied the whole dict when
            # len(missing) > _COMMIT_MEMO_MAX and then inserted past the
            # cap anyway (a single oversized batch left the memo holding
            # the entire flood).
            if keys[i] in _COMMIT_MEMO:
                continue
            while len(_COMMIT_MEMO) >= _COMMIT_MEMO_MAX:
                _COMMIT_MEMO.pop(next(iter(_COMMIT_MEMO)))
            _COMMIT_MEMO[keys[i]] = c
    return [have[k] for k in keys]


def _create_commitments_uncached(
    blobs: list[Blob], subtree_root_threshold: int = SUBTREE_ROOT_THRESHOLD
) -> list[bytes]:
    # Chunk every blob: (blob_idx, chunk_order, size, share_range).
    blob_shares: list[np.ndarray] = []
    blob_ns: list[bytes] = []
    chunks_by_size: dict[int, list[tuple[int, int, int]]] = {}
    chunk_counts: list[int] = []
    for bi, blob in enumerate(blobs):
        shares = split_blob(blob)
        arr = np.frombuffer(b"".join(s.raw for s in shares), dtype=np.uint8)
        blob_shares.append(arr.reshape(len(shares), SHARE_SIZE))
        blob_ns.append(blob.namespace.to_bytes())
        width = subtree_width(len(shares), subtree_root_threshold)
        sizes = merkle_mountain_range_sizes(len(shares), width)
        chunk_counts.append(len(sizes))
        cursor = 0
        for ci, size in enumerate(sizes):
            chunks_by_size.setdefault(size, []).append((bi, ci, cursor))
            cursor += size

    # One batched NMT-forest call per distinct chunk size.
    roots: dict[tuple[int, int], bytes] = {}
    for size, items in chunks_by_size.items():
        n = len(items)
        n_pad = round_up_power_of_two(n)
        data = np.zeros((n_pad, size, SHARE_SIZE), dtype=np.uint8)
        ns = np.zeros((n_pad, size, 29), dtype=np.uint8)
        for slot, (bi, _ci, start) in enumerate(items):
            data[slot] = blob_shares[bi][start : start + size]
            ns[slot] = np.frombuffer(blob_ns[bi], dtype=np.uint8)
        out = np.asarray(
            _jit_tree_roots(n_pad, size)(jnp.asarray(ns), jnp.asarray(data))
        )  # (n_pad, 90)
        for slot, (bi, ci, _start) in enumerate(items):
            roots[(bi, ci)] = out[slot].tobytes()
            assert len(roots[(bi, ci)]) == NMT_NODE_SIZE

    # Merkle over each blob's peaks (host; a handful of 90-byte leaves).
    return [
        hash_from_byte_slices([roots[(bi, ci)] for ci in range(chunk_counts[bi])])
        for bi in range(len(blobs))
    ]

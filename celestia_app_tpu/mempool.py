"""Prioritized mempool (celestia-core mempool v1 semantics).

Parity with the reference node defaults (app/default_overrides.go:258-284):
version "v1" prioritized mempool, TTL of 5 blocks, MaxTxBytes cap sized to
the biggest square (128^2 x 478).  Admission runs CheckTx first (the app
sets the priority = gas price x 1e6, app/ante/fee_checker.go:17); reaping
returns txs in priority order under a byte budget, the order PrepareProposal
receives them.

Observability: every entry stores the submitting request's TraceContext
(trace/context.py), so the insert span, the reap row, and the block built
from the reap all share the submission's trace_id.  Pool health lives on
three Prometheus families — `celestia_mempool_txs` /
`celestia_mempool_size_bytes` gauges refreshed on every mutation, and
`celestia_mempool_evictions_total{reason=priority|ttl|recheck}` counting
every non-commit removal — and the lifecycle histogram gets the
`mempool_wait` (insert -> reap) and `total` (submit -> commit) phases.
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field

DEFAULT_TTL_NUM_BLOCKS = 5
DEFAULT_MAX_TX_BYTES = 128 * 128 * 478  # ~7.8 MB
DEFAULT_MAX_POOL_BYTES = 4 * DEFAULT_MAX_TX_BYTES


@dataclass
class _Entry:
    tx: bytes
    priority: int
    height: int  # admission height (for TTL)
    seq: int  # FIFO tiebreak
    ctx: object | None = None  # submitting request's TraceContext
    t_ins: float = field(default=0.0)  # perf_counter at admission
    reaped: bool = False  # mempool_wait observed (first reap only)


class PriorityMempool:
    def __init__(
        self,
        ttl_num_blocks: int = DEFAULT_TTL_NUM_BLOCKS,
        max_tx_bytes: int = DEFAULT_MAX_TX_BYTES,
        max_pool_bytes: int = DEFAULT_MAX_POOL_BYTES,
    ):
        self.ttl = ttl_num_blocks
        self.max_tx_bytes = max_tx_bytes
        self.max_pool_bytes = max_pool_bytes
        self._entries: dict[bytes, _Entry] = {}
        self._seq = 0
        self._bytes = 0

    def __len__(self) -> int:
        return len(self._entries)

    def size_bytes(self) -> int:
        return self._bytes

    @staticmethod
    def tx_key(tx: bytes) -> bytes:
        return hashlib.sha256(tx).digest()

    def has_tx(self, tx: bytes) -> bool:
        """Is this exact tx resident? (gossip relay dedup)."""
        return self.tx_key(tx) in self._entries

    def ctx_for(self, tx: bytes):
        """The TraceContext a resident tx was submitted under, if any —
        how a block adopts the trace of the request that fed it."""
        e = self._entries.get(self.tx_key(tx))
        return e.ctx if e is not None else None

    # --- metrics plumbing ---------------------------------------------------
    def _refresh_gauges(self) -> None:
        from celestia_app_tpu.trace.metrics import registry

        reg = registry()
        reg.gauge("celestia_mempool_txs", "resident mempool txs").set(
            len(self._entries)
        )
        reg.gauge(
            "celestia_mempool_size_bytes", "resident mempool bytes"
        ).set(self._bytes)

    @staticmethod
    def _tick_eviction(reason: str, n: int = 1) -> None:
        from celestia_app_tpu.trace.metrics import registry

        registry().counter(
            "celestia_mempool_evictions_total",
            "mempool removals that were not block inclusion",
        ).inc(n, reason=reason)

    # --- mutation -----------------------------------------------------------
    def insert(self, tx: bytes, priority: int, height: int, ctx=None) -> bool:
        """Admit a checked tx; False if duplicate, oversized, or the pool is
        full of higher-priority txs.  `ctx` is the submitting request's
        TraceContext (defaults to the thread's current one)."""
        from celestia_app_tpu.trace.context import current_context, trace_span

        if ctx is None:
            ctx = current_context()
        with trace_span(
            "mempool_insert", ctx=ctx, layer="mempool",
            tx_bytes=len(tx), height=height,
        ) as sp:
            ok = self._insert(tx, priority, height, ctx)
            sp["result"] = "inserted" if ok else "rejected"
        self._refresh_gauges()
        return ok

    def _insert(self, tx: bytes, priority: int, height: int, ctx) -> bool:
        if len(tx) > self.max_tx_bytes:
            return False
        key = self.tx_key(tx)
        if key in self._entries:
            return False
        # Evict lowest-priority entries to make room (prioritized admission).
        while self._bytes + len(tx) > self.max_pool_bytes and self._entries:
            victim_key, victim = min(
                self._entries.items(), key=lambda kv: (kv[1].priority, -kv[1].seq)
            )
            if victim.priority >= priority:
                return False  # everything resident outranks the newcomer
            self._remove(victim_key)
            self._tick_eviction("priority")
        self._entries[key] = _Entry(
            tx, priority, height, self._seq, ctx, time.perf_counter()
        )
        self._seq += 1
        self._bytes += len(tx)
        return True

    def _remove(self, key: bytes) -> None:
        e = self._entries.pop(key, None)
        if e is not None:
            self._bytes -= len(e.tx)

    def reap(self, max_bytes: int | None = None) -> list[bytes]:
        """Txs by (priority desc, FIFO) under a byte budget.

        Journaled: one `mempool_reap` span per call (count/bytes/skips,
        joined to the first reaped tx's trace), plus one `mempool_wait`
        e2e observation per reaped tx (insert -> reap residency).
        """
        from celestia_app_tpu.trace.context import export_span, new_context
        from celestia_app_tpu.trace.spans import observe_e2e
        from celestia_app_tpu.trace.tracer import trace_enabled

        start_unix_ns = time.time_ns()
        t0 = time.perf_counter_ns()
        ordered = sorted(
            self._entries.values(), key=lambda e: (-e.priority, e.seq)
        )
        out: list[bytes] = []
        reaped_entries: list[_Entry] = []
        total = skipped = 0
        for e in ordered:
            if max_bytes is not None and total + len(e.tx) > max_bytes:
                skipped += 1
                continue
            out.append(e.tx)
            reaped_entries.append(e)
            total += len(e.tx)
        elapsed_ns = time.perf_counter_ns() - t0
        if trace_enabled():
            # The span joins the trace of the first REAPED tx — the same
            # trace the block built from this reap adopts
            # (_block_trace_context), so the reap leg is never orphaned
            # onto a budget-skipped tx's trace.
            first_ctx = next(
                (e.ctx for e in reaped_entries if e.ctx is not None), None
            )
            ctx = first_ctx.child() if first_ctx is not None else new_context()
            export_span(
                "mempool_reap", ctx, start_unix_ns, elapsed_ns,
                {"layer": "mempool", "n_txs": len(out), "reap_bytes": total,
                 "skipped": skipped, "resident": len(ordered)},
                e2e="reap",
            )
        now = time.perf_counter()
        for e in reaped_entries:
            # First reap only: a tx the proposer reaps but drops (filter
            # rejection, square overflow) is reaped again every block
            # until TTL, and re-observing its growing residency would let
            # duplicates dominate the histogram's tail.
            if e.t_ins and not e.reaped:
                observe_e2e("mempool_wait", now - e.t_ins)
            e.reaped = True
        return out

    def update(self, height: int, committed_txs: list[bytes]) -> None:
        """Post-commit maintenance: drop included txs, expire TTLs.

        Journaled (`mempool_update` row): committed drops and TTL expiries
        were previously silent.  Each committed tx with a known submission
        context closes its lifecycle on the e2e `total` phase
        (submit wall-clock -> this commit)."""
        from celestia_app_tpu.trace.spans import observe_e2e
        from celestia_app_tpu.trace.tracer import traced

        now_ns = time.time_ns()
        committed = 0
        for tx in committed_txs:
            key = self.tx_key(tx)
            e = self._entries.get(key)
            if e is None:
                continue
            committed += 1
            if e.ctx is not None and getattr(e.ctx, "start_unix_ns", 0):
                observe_e2e("total", (now_ns - e.ctx.start_unix_ns) / 1e9)
            self._remove(key)
        expired = [
            k for k, e in self._entries.items() if height - e.height >= self.ttl
        ]
        for k in expired:
            self._remove(k)
        if expired:
            self._tick_eviction("ttl", len(expired))
        traced().write(
            "mempool_update", height=height, committed=committed,
            expired=len(expired), resident=len(self._entries),
        )
        self._refresh_gauges()

    def resident_txs(self) -> list[bytes]:
        """All resident txs in (priority desc, FIFO) order — the order a
        proposer would take them (recheck runs in this order)."""
        return [
            e.tx for e in sorted(
                self._entries.values(), key=lambda e: (-e.priority, e.seq)
            )
        ]

    def remove_tx(self, tx: bytes) -> None:
        """Evict one tx (the post-commit recheck path): counted like every
        other non-commit removal so the gauges reconcile."""
        key = self.tx_key(tx)
        if key in self._entries:
            self._remove(key)
            self._tick_eviction("recheck")
            self._refresh_gauges()
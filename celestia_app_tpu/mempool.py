"""Prioritized mempool (celestia-core mempool v1 semantics).

Parity with the reference node defaults (app/default_overrides.go:258-284):
version "v1" prioritized mempool, TTL of 5 blocks, MaxTxBytes cap sized to
the biggest square (128^2 x 478).  Admission runs CheckTx first (the app
sets the priority = gas price x 1e6, app/ante/fee_checker.go:17); reaping
returns txs in priority order under a byte budget, the order PrepareProposal
receives them.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

DEFAULT_TTL_NUM_BLOCKS = 5
DEFAULT_MAX_TX_BYTES = 128 * 128 * 478  # ~7.8 MB
DEFAULT_MAX_POOL_BYTES = 4 * DEFAULT_MAX_TX_BYTES


@dataclass
class _Entry:
    tx: bytes
    priority: int
    height: int  # admission height (for TTL)
    seq: int  # FIFO tiebreak


class PriorityMempool:
    def __init__(
        self,
        ttl_num_blocks: int = DEFAULT_TTL_NUM_BLOCKS,
        max_tx_bytes: int = DEFAULT_MAX_TX_BYTES,
        max_pool_bytes: int = DEFAULT_MAX_POOL_BYTES,
    ):
        self.ttl = ttl_num_blocks
        self.max_tx_bytes = max_tx_bytes
        self.max_pool_bytes = max_pool_bytes
        self._entries: dict[bytes, _Entry] = {}
        self._seq = 0
        self._bytes = 0

    def __len__(self) -> int:
        return len(self._entries)

    def size_bytes(self) -> int:
        return self._bytes

    @staticmethod
    def tx_key(tx: bytes) -> bytes:
        return hashlib.sha256(tx).digest()

    def has_tx(self, tx: bytes) -> bool:
        """Is this exact tx resident? (gossip relay dedup)."""
        return self.tx_key(tx) in self._entries

    def insert(self, tx: bytes, priority: int, height: int) -> bool:
        """Admit a checked tx; False if duplicate, oversized, or the pool is
        full of higher-priority txs."""
        if len(tx) > self.max_tx_bytes:
            return False
        key = self.tx_key(tx)
        if key in self._entries:
            return False
        # Evict lowest-priority entries to make room (prioritized admission).
        while self._bytes + len(tx) > self.max_pool_bytes and self._entries:
            victim_key, victim = min(
                self._entries.items(), key=lambda kv: (kv[1].priority, -kv[1].seq)
            )
            if victim.priority >= priority:
                return False  # everything resident outranks the newcomer
            self._remove(victim_key)
        self._entries[key] = _Entry(tx, priority, height, self._seq)
        self._seq += 1
        self._bytes += len(tx)
        return True

    def _remove(self, key: bytes) -> None:
        e = self._entries.pop(key, None)
        if e is not None:
            self._bytes -= len(e.tx)

    def reap(self, max_bytes: int | None = None) -> list[bytes]:
        """Txs by (priority desc, FIFO) under a byte budget."""
        ordered = sorted(
            self._entries.values(), key=lambda e: (-e.priority, e.seq)
        )
        out: list[bytes] = []
        total = 0
        for e in ordered:
            if max_bytes is not None and total + len(e.tx) > max_bytes:
                continue
            out.append(e.tx)
            total += len(e.tx)
        return out

    def update(self, height: int, committed_txs: list[bytes]) -> None:
        """Post-commit maintenance: drop included txs, expire TTLs."""
        for tx in committed_txs:
            self._remove(self.tx_key(tx))
        expired = [
            k for k, e in self._entries.items() if height - e.height >= self.ttl
        ]
        for k in expired:
            self._remove(k)

    def resident_txs(self) -> list[bytes]:
        """All resident txs in (priority desc, FIFO) order — the order a
        proposer would take them (recheck runs in this order)."""
        return [
            e.tx for e in sorted(
                self._entries.values(), key=lambda e: (-e.priority, e.seq)
            )
        ]

    def remove_tx(self, tx: bytes) -> None:
        self._remove(self.tx_key(tx))

"""Sharded prioritized mempool (celestia-core mempool v1 semantics,
namespace-sharded admission, weighted-fair reaping, per-tenant QoS).

Parity with the reference node defaults (app/default_overrides.go:258-284):
version "v1" prioritized mempool, TTL of 5 blocks, MaxTxBytes cap sized to
the biggest square (128^2 x 478).  Admission runs CheckTx first (the app
sets the priority = gas price x 1e6, app/ante/fee_checker.go:17); reaping
returns txs under a byte budget, the order PrepareProposal receives them.

SHARDING ($CELESTIA_MEMPOOL_SHARDS, default 8; `0`/`global` pins the
frozen single-lock baseline): entries live in per-namespace shards —
namespace -> shard by stable hash, normal txs under the reserved `tx`
bucket — each behind its own lock, and the expensive per-admission work
(the sha256 tx key, the BlobTx namespace parse) runs OUTSIDE any lock,
so concurrent BroadcastTx admission stops serializing the way the old
one-big-lock path did (BENCH_MODE=mempool measures the A/B).  The
cross-shard paths — pool-pressure priority eviction, reap, update — take
the shard locks in index order, so their DECISIONS are identical to the
global baseline's: only the locking is sharded, never the semantics.

WEIGHTED-FAIR REAPING: when the byte budget BINDS (resident bytes exceed
the reap budget) and the pool is sharded, reap arbitrates the contended
budget by deficit round-robin across namespaces (quantum
$CELESTIA_MEMPOOL_QUANTUM bytes, default 64 KiB): each tenant's queue
stays in (priority desc, FIFO) order internally — priority is preserved
WITHIN a tenant — but tenants take turns filling the square, so one
whale namespace can no longer crowd a small tenant out of N consecutive
squares (the starvation test's invariant).  A tx larger than the quantum
accrues deficit over multiple rounds (classic DRR); empty tenants are
skipped without accruing; a tx that cannot fit the remaining budget is
skipped exactly like the baseline's skip-semantics.  When the budget
does NOT bind (every resident tx fits — the common case) the reap is
byte-identical to the frozen pure-priority baseline, as is every reap
under `$CELESTIA_MEMPOOL_SHARDS=0`.

QOS ADMISSION CONTROL ($CELESTIA_QOS, qos.py): per-tenant token-bucket
rate limits (txs/sec, bytes/sec) and resident byte quotas are enforced
at insert — the one admission seam all three RPC planes, the gossip
flood, and direct embedders share — raising QosThrottled (429 /
RESOURCE_EXHAUSTED, byte-identical payload on every plane).

Observability: every entry stores the submitting request's TraceContext
(trace/context.py), so the insert span, the reap row, and the block built
from the reap all share the submission's trace_id.  Pool health lives on
the `celestia_mempool_txs` / `celestia_mempool_size_bytes` gauges (plus
`celestia_mempool_shard_txs{shard}` on the sharded pool),
`celestia_mempool_evictions_total{reason=priority|ttl|recheck}`, and the
lifecycle histogram's `mempool_wait` / `total` phases.  The per-tenant
`celestia_mempool_namespace_{txs,size_bytes}` depth gauges SUM EXACTLY
across shards on every insert/reap/ttl/recheck/committed-drop path (the
PR 3 reconciliation invariant, re-pinned shard-aware); namespace labels
go through the top-N cardinality cap (trace/square_journal.py) once, at
admission.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import sys
import threading
import time
import weakref
import zlib
from dataclasses import dataclass, field

DEFAULT_TTL_NUM_BLOCKS = 5
DEFAULT_MAX_TX_BYTES = 128 * 128 * 478  # ~7.8 MB
DEFAULT_MAX_POOL_BYTES = 4 * DEFAULT_MAX_TX_BYTES
#: Default lock-stripe count of the sharded pool.
DEFAULT_SHARDS = 8
#: Default DRR quantum (bytes added to each tenant's deficit per round).
DEFAULT_REAP_QUANTUM = 64 * 1024

_WARNED: set[str] = set()


def _warn_once(key: str, msg: str) -> None:
    if key not in _WARNED:
        _WARNED.add(key)
        print(msg, file=sys.stderr)


def mempool_shards() -> int:
    """$CELESTIA_MEMPOOL_SHARDS: lock-stripe count of the sharded pool;
    `0` or `global` pins the frozen single-lock baseline rung (the
    measurable pre-PR behavior).  Malformed values warn loudly and fall
    back to the default — silently serving the baseline would disable
    both the concurrency win and the fairness arbitration."""
    raw = (os.environ.get("CELESTIA_MEMPOOL_SHARDS") or "").strip().lower()
    if raw in ("", "auto"):
        return DEFAULT_SHARDS
    if raw in ("0", "global"):
        return 0
    try:
        return max(0, int(raw))
    except ValueError:
        _warn_once(
            "shards",
            f"CELESTIA_MEMPOOL_SHARDS={raw!r} is not an integer or "
            f"'global'; using the default {DEFAULT_SHARDS} shards",
        )
        return DEFAULT_SHARDS


def reap_quantum() -> int:
    """$CELESTIA_MEMPOOL_QUANTUM: DRR bytes-per-tenant-per-round (>= 1)."""
    try:
        return max(
            1, int(os.environ.get("CELESTIA_MEMPOOL_QUANTUM", "")
                   or DEFAULT_REAP_QUANTUM)
        )
    except ValueError:
        return DEFAULT_REAP_QUANTUM


@dataclass
class _Entry:
    tx: bytes
    priority: int
    height: int  # admission height (for TTL)
    seq: int  # FIFO tiebreak
    ctx: object | None = None  # submitting request's TraceContext
    t_ins: float = field(default=0.0)  # perf_counter at admission
    reaped: bool = False  # mempool_wait observed (first reap only)
    # Submitting namespace label, already CAPPED at admission ("tx" for
    # normal txs, "other" past the top-N admission cap): capping once
    # here keeps every later gauge/counter refresh a plain dict walk.
    ns: str = "tx"

    def e2e_namespace(self) -> str | None:
        """The namespace the entry's e2e observations are attributed to
        (None for normal txs — they keep the unlabeled phase series)."""
        return self.ns if self.ns != "tx" else None


def _pools_owned_bytes() -> int:
    """Tx bytes resident across every live pool's shards — the mempool's
    contribution to the /device memory-ownership ledger (host RAM on
    every backend, but it is this process's biggest non-array holder)."""
    return sum(
        s.nbytes for pool in list(_ALL_POOLS) for s in pool._shards
    )


_ALL_POOLS: "weakref.WeakSet[PriorityMempool]" = weakref.WeakSet()

from celestia_app_tpu.trace.device_ledger import (  # noqa: E402
    register_owner as _register_owner,
)

_register_owner("mempool_shards", _pools_owned_bytes)


class _Shard:
    """One namespace shard: its own lock, entry map, byte + per-tenant
    depth accounting.  All mutation happens under `lock`; cross-shard
    operations acquire shard locks in index order (deadlock-free)."""

    __slots__ = ("lock", "entries", "nbytes", "ns_depth")

    def __init__(self):
        self.lock = threading.Lock()
        self.entries: dict[bytes, _Entry] = {}
        self.nbytes = 0
        # CAPPED namespace label -> [txs, bytes] for THIS shard; the
        # exposition gauges sum these across shards (zeroed tenants drop
        # after their aggregate lands on 0), so per-shard and per-process
        # accounting can never drift apart.
        self.ns_depth: dict[str, list[int]] = {}

    def add(self, key: bytes, e: _Entry) -> None:
        self.entries[key] = e
        self.nbytes += len(e.tx)
        agg = self.ns_depth.setdefault(e.ns, [0, 0])
        agg[0] += 1
        agg[1] += len(e.tx)

    def remove(self, key: bytes) -> _Entry | None:
        e = self.entries.pop(key, None)
        if e is not None:
            self.nbytes -= len(e.tx)
            agg = self.ns_depth.get(e.ns)
            if agg is not None:
                agg[0] -= 1
                agg[1] -= len(e.tx)
                if agg[0] <= 0 and agg[1] <= 0:
                    del self.ns_depth[e.ns]
        return e


class PriorityMempool:
    def __init__(
        self,
        ttl_num_blocks: int = DEFAULT_TTL_NUM_BLOCKS,
        max_tx_bytes: int = DEFAULT_MAX_TX_BYTES,
        max_pool_bytes: int = DEFAULT_MAX_POOL_BYTES,
        shards: int | None = None,
    ):
        self.ttl = ttl_num_blocks
        self.max_tx_bytes = max_tx_bytes
        self.max_pool_bytes = max_pool_bytes
        # Shard count pinned at construction (env read once): a live
        # pool's key->shard routing must never move under a mid-process
        # env flip.  0 = the frozen global-lock baseline, which runs the
        # same code over ONE shard whose lock covers the whole admission
        # (key hash + namespace parse included, exactly the old
        # serialization the sharded path exists to break).
        self.shards = mempool_shards() if shards is None else max(0, shards)
        self._shards = [_Shard() for _ in range(max(1, self.shards))]
        # tx key -> shard index (GIL-atomic single-op reads; mutated only
        # under the owning shard's lock): how the key-addressed paths
        # (has_tx / ctx_for / remove_tx / update) find an entry without
        # searching every shard.
        self._key_shard: dict[bytes, int] = {}
        self._seq = itertools.count()
        # Namespace labels currently published on the per-tenant gauges
        # (so a drained tenant lands on 0 exactly once, never a stale
        # positive); own lock — mutated from concurrent insert threads
        # while the full-refresh path iterates and replaces it.
        self._published_ns: set[str] = set()
        self._published_lock = threading.Lock()
        _ALL_POOLS.add(self)

    # --- shard routing -------------------------------------------------------
    def _shard_index(self, ns: str) -> int:
        if self.shards <= 0 or len(self._shards) == 1:
            return 0
        return zlib.crc32(ns.encode()) % len(self._shards)

    def _shard_of_key(self, key: bytes) -> _Shard | None:
        i = self._key_shard.get(key)
        return self._shards[i] if i is not None else None

    class _AllLocks:
        """Acquire every shard lock in index order (the cross-shard
        paths: pool-pressure eviction, reap snapshot, update)."""

        def __init__(self, shards):
            self._shards = shards

        def __enter__(self):
            for s in self._shards:
                s.lock.acquire()
            return self

        def __exit__(self, *exc):
            for s in reversed(self._shards):
                s.lock.release()

    def _all_locks(self) -> "PriorityMempool._AllLocks":
        return self._AllLocks(self._shards)

    def __len__(self) -> int:
        return sum(len(s.entries) for s in self._shards)

    def size_bytes(self) -> int:
        return sum(s.nbytes for s in self._shards)

    def namespace_bytes(self, ns: str) -> int:
        """Resident bytes of one (capped) namespace label across shards
        — the QoS byte-quota input."""
        total = 0
        for s in self._shards:
            agg = s.ns_depth.get(ns)
            if agg is not None:
                total += agg[1]
        return total

    @staticmethod
    def tx_key(tx: bytes) -> bytes:
        return hashlib.sha256(tx).digest()

    def has_tx(self, tx: bytes) -> bool:
        """Is this exact tx resident? (gossip relay dedup)."""
        return self.tx_key(tx) in self._key_shard

    def ctx_for(self, tx: bytes):
        """The TraceContext a resident tx was submitted under, if any —
        how a block adopts the trace of the request that fed it."""
        key = self.tx_key(tx)
        shard = self._shard_of_key(key)
        if shard is None:
            return None
        e = shard.entries.get(key)
        return e.ctx if e is not None else None

    # --- metrics plumbing ---------------------------------------------------
    def _gauges(self):
        """(txs, bytes, ns_txs, ns_bytes, shard_txs) gauge handles,
        cached per pool: the registry is process-global and never
        swapped, and handle lookup per admission is measurable next to a
        small tx's hash."""
        handles = self.__dict__.get("_gauge_handles")
        if handles is None:
            from celestia_app_tpu.trace.metrics import registry

            reg = registry()
            handles = self._gauge_handles = (
                reg.gauge("celestia_mempool_txs", "resident mempool txs"),
                reg.gauge("celestia_mempool_size_bytes",
                          "resident mempool bytes"),
                reg.gauge(
                    "celestia_mempool_namespace_txs",
                    "resident mempool txs per namespace (top-N capped, "
                    "summed across shards)",
                ),
                reg.gauge(
                    "celestia_mempool_namespace_size_bytes",
                    "resident mempool bytes per namespace (top-N capped, "
                    "summed across shards)",
                ),
                reg.gauge(
                    "celestia_mempool_shard_txs",
                    "resident mempool txs per namespace shard "
                    "(bounded by $CELESTIA_MEMPOOL_SHARDS)",
                ),
            )
        return handles

    def _refresh_gauges_for(self, ns: str, shard_idx: int) -> None:
        """The insert fast path's targeted refresh: totals, the touched
        tenant's cross-shard sums, the touched shard — exact (the sums
        are recomputed, never incremented blind) without re-walking every
        tenant per admission."""
        txs_g, bytes_g, ns_txs, ns_bytes, shard_txs = self._gauges()
        txs_g.set(len(self))
        bytes_g.set(self.size_bytes())
        n = b = 0
        for s in self._shards:
            agg = s.ns_depth.get(ns)
            if agg is not None:
                n += agg[0]
                b += agg[1]
        ns_txs.set(n, namespace=ns)
        ns_bytes.set(b, namespace=ns)
        if n:
            with self._published_lock:
                self._published_ns.add(ns)
        if self.shards > 0:
            shard_txs.set(
                len(self._shards[shard_idx].entries), shard=str(shard_idx)
            )

    def _refresh_gauges(self) -> None:
        txs_g, bytes_g, ns_txs, ns_bytes, shard_txs = self._gauges()
        txs_g.set(len(self))
        bytes_g.set(self.size_bytes())
        if self.shards > 0:
            for i, s in enumerate(self._shards):
                shard_txs.set(len(s.entries), shard=str(i))
        # Per-tenant depth, summed EXACTLY across shards (the PR 3
        # reconciliation invariant): keys are capped at admission
        # (distinct raw labels past the cap already share the `other`
        # entry), so this is a plain walk; a tenant whose aggregate hit
        # zero is published once at 0 and then dropped.
        totals: dict[str, list[int]] = {}
        for s in self._shards:
            for lbl, (n, b) in s.ns_depth.items():
                agg = totals.setdefault(lbl, [0, 0])
                agg[0] += n
                agg[1] += b
        for lbl, (n, b) in totals.items():
            ns_txs.set(n, namespace=lbl)
            ns_bytes.set(b, namespace=lbl)
        # Tenants that drained since the last refresh land on 0 (never a
        # stale positive sample).  Under the published-set lock: insert
        # threads add concurrently, and an unsynchronized subtract-and-
        # replace could both blow up mid-iteration and lose a racing add
        # (a tenant that then drained would keep a stale positive).
        with self._published_lock:
            for lbl in self._published_ns - set(totals):
                ns_txs.set(0, namespace=lbl)
                ns_bytes.set(0, namespace=lbl)
            self._published_ns = set(totals)

    def _tick_eviction(self, reason: str, n: int = 1, *,
                       namespace: str = "tx") -> None:
        from celestia_app_tpu.trace.metrics import registry
        from celestia_app_tpu.trace.square_journal import capped_namespace_label

        registry().counter(
            "celestia_mempool_evictions_total",
            "mempool removals that were not block inclusion",
        ).inc(n, reason=reason, namespace=capped_namespace_label(namespace))

    # --- mutation -----------------------------------------------------------
    def insert(self, tx: bytes, priority: int, height: int, ctx=None,
               ns: str | None = None) -> bool:
        """Admit a checked tx; False if duplicate, oversized, chaos-
        dropped, or the pool is full of higher-priority txs; raises
        qos.QosThrottled when the tenant is over a $CELESTIA_QOS limit.
        `ctx` is the submitting request's TraceContext (defaults to the
        thread's current one); `ns` is the tx's already-resolved
        namespace label, when the caller (the broadcast path) parsed the
        tx anyway."""
        from celestia_app_tpu.trace.context import current_context, trace_span
        from celestia_app_tpu.trace.tracer import trace_enabled

        if ctx is None:
            ctx = current_context()
        if not trace_enabled():
            # Muted-tracing fast path: no span context (new_context draws
            # urandom per span — measurable next to a small tx's hash);
            # the admission semantics are identical.
            return self._insert_refreshing(tx, priority, height, ctx, ns, {})
        with trace_span(
            "mempool_insert", ctx=ctx, layer="mempool",
            tx_bytes=len(tx), height=height,
        ) as sp:
            ok = self._insert_refreshing(tx, priority, height, ctx, ns, sp)
            if "result" not in sp:
                sp["result"] = "inserted" if ok else "rejected"
        return ok

    def _insert_refreshing(self, tx, priority, height, ctx, ns, sp) -> bool:
        """Admission + the matching gauge refresh: targeted (touched
        tenant + shard only) on the fast path, FULL when the admission
        evicted other tenants' residents (their gauges must land on the
        new truth, not stay stale)."""
        try:
            verdict = (
                self._insert_global(tx, priority, height, ctx, ns, sp)
                if self.shards <= 0
                else self._insert_sharded(tx, priority, height, ctx, ns, sp)
            )
        except Exception:
            sp["result"] = "throttled"
            raise
        ok, touched = verdict
        if touched is not None:
            self._refresh_gauges_for(*touched)
        elif ok:
            self._refresh_gauges()
        return ok

    def _insert_global(self, tx, priority, height, ctx, ns, sp):
        """The frozen baseline rung: ONE lock held across the whole
        admission — key hash, namespace parse, QoS, map mutation — which
        is exactly the serialization the pre-shard node paid (and the
        rung BENCH_MODE=mempool measures the sharded path against)."""
        from celestia_app_tpu import chaos

        if chaos.mempool_insert(shard=0):
            sp["result"] = "chaos_dropped"
            return False, None
        shard = self._shards[0]
        with shard.lock:
            key, label = self._resolve(tx, ctx, ns)
            # Duplicates and oversize reject BEFORE the QoS gate: a
            # gossip flood re-offering a resident tx is protocol
            # traffic and must not drain the tenant's token budget.
            if len(tx) > self.max_tx_bytes or key in shard.entries:
                return False, None
            self._qos_gate(label, len(tx))
            # An admission under pool pressure may evict OTHER tenants'
            # residents — that path takes the full gauge refresh.
            pressure = self.size_bytes() + len(tx) > self.max_pool_bytes
            ok = self._admit(shard, key, tx, priority, height, ctx, label)
        return ok, ((label, 0) if ok and not pressure else None)

    def _insert_sharded(self, tx, priority, height, ctx, ns, sp):
        """The sharded admission path: the per-tx sha256 + namespace
        parse run OUTSIDE any lock (that work dominates an admission and
        is what the old global lock serialized), then only the owning
        namespace shard's lock is taken.  Pool-pressure evictions — the
        rare cross-shard decision — fall to the all-locks path, where
        the decision logic is the same as the baseline's."""
        from celestia_app_tpu import chaos

        key, label = self._resolve(tx, ctx, ns)
        idx = self._shard_index(label)
        # The chaos seam fires per-shard with its own seeded RNG stream
        # (chaos/spec.py): injection sets stay interleaving-independent
        # even when admissions race across shards.
        if chaos.mempool_insert(shard=idx):
            sp["result"] = "chaos_dropped"
            return False, None
        # Oversize and already-resident rejections BEFORE the QoS gate
        # (the key-map read is GIL-atomic): gossip re-offers of resident
        # txs are protocol traffic and must not drain the tenant's token
        # budget.  A same-tx race past this pre-check is decided by
        # _admit's authoritative under-lock check; the rare loser
        # charges one token — bounded by the race, not by the flood.
        if len(tx) > self.max_tx_bytes or key in self._key_shard:
            return False, None
        self._qos_gate(label, len(tx))
        shard = self._shards[idx]
        if self.size_bytes() + len(tx) > self.max_pool_bytes:
            # Pool pressure: the eviction decision needs the global
            # lowest-priority view, so this path locks every shard (in
            # index order) and decides exactly like the baseline; the
            # caller then refreshes EVERY tenant's gauges (evicted
            # residents belong to other namespaces).
            return self._admit_evicting(idx, key, tx, priority, height,
                                        ctx, label), None
        with shard.lock:
            admitted = self._admit(shard, key, tx, priority, height, ctx,
                                   label, evict=False)
        if admitted is None:
            # Lost a race against concurrent fills: decide under all locks.
            return self._admit_evicting(idx, key, tx, priority, height,
                                        ctx, label), None
        return admitted, ((label, idx) if admitted else None)

    def _resolve(self, tx, ctx, ns) -> tuple[bytes, str]:
        """(tx key, capped namespace label) — the per-admission work the
        sharded path hoists outside every lock."""
        key = self.tx_key(tx)
        if ns is not None:  # caller-resolved raw label still needs the cap
            from celestia_app_tpu.trace.square_journal import (
                capped_namespace_label,
            )

            return key, capped_namespace_label(ns)
        return key, self._namespace_of(tx, ctx)

    def _qos_gate(self, label: str, nbytes: int) -> None:
        """Per-tenant admission control ($CELESTIA_QOS): one cached
        env-string compare when enforcement is off."""
        from celestia_app_tpu import qos

        enf = qos.enforcer()
        if enf is not None:
            enf.admit_tx(label, nbytes, self.namespace_bytes(label))

    def _admit(self, shard: _Shard, key, tx, priority, height, ctx, label,
               evict: bool = True) -> bool | None:
        """Admission under the caller-held shard lock.  With evict=False
        returns None instead of evicting when the pool is over budget
        (the sharded fast path escalates to the all-locks decision)."""
        if len(tx) > self.max_tx_bytes:
            return False
        if key in shard.entries:
            return False
        need = self.size_bytes() + len(tx) - self.max_pool_bytes
        if need > 0:
            if not evict:
                return None
            if not self._evict_locked(need, priority):
                return False  # infeasible: nothing was evicted
        shard.add(key, _Entry(
            tx, priority, height, next(self._seq), ctx,
            time.perf_counter(), ns=label,
        ))
        self._key_shard[key] = self._shards.index(shard)
        return True

    def _admit_evicting(self, idx, key, tx, priority, height, ctx,
                        label) -> bool:
        with self._all_locks():
            return bool(self._admit(
                self._shards[idx], key, tx, priority, height, ctx, label,
                evict=True,
            ))

    def _evict_locked(self, need: int, priority: int) -> bool:
        """Priority eviction under ALL shard locks (single-shard pools
        hold their one lock — same thing).  Feasibility is decided
        BEFORE anything is removed: evicting one-at-a-time and then
        discovering the next victim outranks the newcomer would have
        destroyed valid residents for an insert that admits nothing.
        The victim order is global (priority asc, LIFO tiebreak), so the
        decision is identical at every shard count."""
        victims = sorted(
            (
                (key, e, i)
                for i, s in enumerate(self._shards)
                for key, e in s.entries.items()
                if e.priority < priority
            ),
            key=lambda kv: (kv[1].priority, -kv[1].seq),
        )
        chosen, freed = [], 0
        for kv in victims:
            if freed >= need:
                break
            chosen.append(kv)
            freed += len(kv[1].tx)
        if freed < need:
            return False
        for victim_key, victim, i in chosen:
            self._shards[i].remove(victim_key)
            self._key_shard.pop(victim_key, None)
            self._tick_eviction("priority", namespace=victim.ns)
        return True

    @staticmethod
    def _namespace_of(tx: bytes, ctx) -> str:
        """The entry's CAPPED namespace label: the submit path already
        resolved the raw label into the trace baggage; fall back to
        parsing the tx (gossip relays and direct inserts arrive without
        baggage).  Capped exactly once, here at admission."""
        from celestia_app_tpu.trace.square_journal import (
            capped_namespace_label,
            tx_namespace_label,
        )

        baggage = getattr(ctx, "baggage", None)
        raw = (baggage or {}).get("namespace") or tx_namespace_label(tx)
        return capped_namespace_label(raw) if raw else "tx"

    def _remove_key(self, key: bytes) -> _Entry | None:
        """Remove under the owning shard's lock (key-addressed paths).
        The key->shard mapping is popped INSIDE the lock: popping after
        release could race a same-tx re-insert (gossip re-offer) and
        delete the mapping of the re-inserted LIVE entry, leaving it
        invisible to every key-addressed path until TTL."""
        shard = self._shard_of_key(key)
        if shard is None:
            return None
        with shard.lock:
            e = shard.remove(key)
            if e is not None:
                self._key_shard.pop(key, None)
        return e

    def _snapshot(self) -> list[_Entry]:
        """Every resident entry, snapshotted under the shard locks."""
        with self._all_locks():
            return [e for s in self._shards for e in s.entries.values()]

    def reap(self, max_bytes: int | None = None) -> list[bytes]:
        """Txs under a byte budget, the order PrepareProposal receives.

        Uncontended (everything fits, or the frozen global baseline):
        pure (priority desc, FIFO) order with skip-semantics — byte-
        identical to the pre-shard pool.  Contended AND sharded: deficit
        round-robin across namespaces (module docstring), priority order
        preserved within each tenant.

        Journaled: one `mempool_reap` span per call (count/bytes/skips/
        drr, joined to the first reaped tx's trace), plus one
        `mempool_wait` e2e observation per reaped tx (insert -> reap
        residency).
        """
        from celestia_app_tpu.trace.context import export_span, new_context
        from celestia_app_tpu.trace.spans import observe_e2e
        from celestia_app_tpu.trace.tracer import trace_enabled

        start_unix_ns = time.time_ns()
        t0 = time.perf_counter_ns()
        ordered = sorted(
            self._snapshot(), key=lambda e: (-e.priority, e.seq)
        )
        resident_bytes = sum(len(e.tx) for e in ordered)
        use_drr = (
            self.shards > 0
            and max_bytes is not None
            and resident_bytes > max_bytes
        )
        if use_drr:
            out, reaped_entries, skipped, total = self._drr_reap(
                ordered, max_bytes
            )
        else:
            out, reaped_entries = [], []
            total = skipped = 0
            for e in ordered:
                if max_bytes is not None and total + len(e.tx) > max_bytes:
                    skipped += 1
                    continue
                out.append(e.tx)
                reaped_entries.append(e)
                total += len(e.tx)
        elapsed_ns = time.perf_counter_ns() - t0
        if trace_enabled():
            # The span joins the trace of the first REAPED tx — the same
            # trace the block built from this reap adopts
            # (_block_trace_context), so the reap leg is never orphaned
            # onto a budget-skipped tx's trace.
            first_ctx = next(
                (e.ctx for e in reaped_entries if e.ctx is not None), None
            )
            ctx = first_ctx.child() if first_ctx is not None else new_context()
            export_span(
                "mempool_reap", ctx, start_unix_ns, elapsed_ns,
                {"layer": "mempool", "n_txs": len(out), "reap_bytes": total,
                 "skipped": skipped, "resident": len(ordered),
                 "drr": use_drr,
                 "tenants": len({e.ns for e in ordered})},
                e2e="reap",
            )
        now = time.perf_counter()
        for e in reaped_entries:
            # First reap only: a tx the proposer reaps but drops (filter
            # rejection, square overflow) is reaped again every block
            # until TTL, and re-observing its growing residency would let
            # duplicates dominate the histogram's tail.
            if e.t_ins and not e.reaped:
                observe_e2e("mempool_wait", now - e.t_ins,
                            namespace=e.e2e_namespace())
            e.reaped = True
        return out

    def _drr_reap(self, ordered: list[_Entry], max_bytes: int):
        """Deficit round-robin over per-namespace queues.

        `ordered` is the global (priority desc, FIFO) list, so each
        tenant's queue inherits priority order internally.  Per round
        each non-empty tenant accrues one quantum of deficit and serves
        queue-head txs while the deficit and the remaining global budget
        both allow; a head too big for the remaining BUDGET is skipped
        (popped from this reap's view, like the baseline's skip-and-
        continue); a head too big for the DEFICIT ends the tenant's turn
        and is retried next round with more deficit (classic DRR — this
        is how a tx larger than the quantum still gets served).  Empty
        tenants are skipped and their deficit reset, so idle tenants
        never accrue a burst claim."""
        from collections import deque

        queues: dict[str, deque] = {}
        for e in ordered:
            queues.setdefault(e.ns, deque()).append(e)
        names = sorted(queues)  # deterministic round-robin order
        quantum = reap_quantum()
        deficit = dict.fromkeys(names, 0)
        out: list[bytes] = []
        reaped: list[_Entry] = []
        skipped = total = 0
        while any(queues[ns] for ns in names):
            progress = False
            for ns in names:
                q = queues[ns]
                if not q:
                    deficit[ns] = 0  # idle tenants accrue no burst claim
                    continue
                deficit[ns] += quantum
                while q:
                    e = q[0]
                    if total + len(e.tx) > max_bytes:
                        q.popleft()
                        skipped += 1
                        progress = True
                        continue
                    if len(e.tx) > deficit[ns]:
                        break  # accrues more deficit next round
                    q.popleft()
                    deficit[ns] -= len(e.tx)
                    out.append(e.tx)
                    reaped.append(e)
                    total += len(e.tx)
                    progress = True
            if not progress and not any(
                q and len(q[0].tx) <= max_bytes - total for q in queues.values()
            ):
                break  # nothing left that could ever fit the budget
        return out, reaped, skipped, total

    def update(self, height: int, committed_txs: list[bytes]) -> None:
        """Post-commit maintenance: drop included txs, expire TTLs.

        Journaled (`mempool_update` row): committed drops and TTL expiries
        were previously silent.  Each committed tx with a known submission
        context closes its lifecycle on the e2e `total` phase
        (submit wall-clock -> this commit)."""
        from celestia_app_tpu.trace.spans import observe_e2e
        from celestia_app_tpu.trace.tracer import traced

        now_ns = time.time_ns()
        committed = 0
        for tx in committed_txs:
            e = self._remove_key(self.tx_key(tx))
            if e is None:
                continue
            committed += 1
            if e.ctx is not None and getattr(e.ctx, "start_unix_ns", 0):
                observe_e2e("total", (now_ns - e.ctx.start_unix_ns) / 1e9,
                            namespace=e.e2e_namespace())
        expired_by_ns: dict[str, int] = {}
        n_expired = 0
        with self._all_locks():
            for s in self._shards:
                expired = [
                    k for k, e in s.entries.items()
                    if height - e.height >= self.ttl
                ]
                for k in expired:
                    e = s.remove(k)
                    self._key_shard.pop(k, None)
                    expired_by_ns[e.ns] = expired_by_ns.get(e.ns, 0) + 1
                    n_expired += 1
        for ns, n in sorted(expired_by_ns.items()):
            self._tick_eviction("ttl", n, namespace=ns)
        traced().write(
            "mempool_update", height=height, committed=committed,
            expired=n_expired, resident=len(self),
        )
        self._refresh_gauges()

    def resident_txs(self) -> list[bytes]:
        """All resident txs in (priority desc, FIFO) order — the order a
        proposer would take them (recheck runs in this order)."""
        return [
            e.tx for e in sorted(
                self._snapshot(), key=lambda e: (-e.priority, e.seq)
            )
        ]

    def remove_tx(self, tx: bytes) -> None:
        """Evict one tx (the post-commit recheck path): counted like every
        other non-commit removal so the gauges reconcile."""
        e = self._remove_key(self.tx_key(tx))
        if e is not None:
            self._tick_eviction("recheck", namespace=e.ns)
            self._refresh_gauges()

"""Prioritized mempool (celestia-core mempool v1 semantics).

Parity with the reference node defaults (app/default_overrides.go:258-284):
version "v1" prioritized mempool, TTL of 5 blocks, MaxTxBytes cap sized to
the biggest square (128^2 x 478).  Admission runs CheckTx first (the app
sets the priority = gas price x 1e6, app/ante/fee_checker.go:17); reaping
returns txs in priority order under a byte budget, the order PrepareProposal
receives them.

Observability: every entry stores the submitting request's TraceContext
(trace/context.py), so the insert span, the reap row, and the block built
from the reap all share the submission's trace_id.  Pool health lives on
three Prometheus families — `celestia_mempool_txs` /
`celestia_mempool_size_bytes` gauges refreshed on every mutation, and
`celestia_mempool_evictions_total{reason=priority|ttl|recheck}` counting
every non-commit removal — and the lifecycle histogram gets the
`mempool_wait` (insert -> reap) and `total` (submit -> commit) phases.

Per-tenant accounting: each entry carries its submitting namespace label
(first blob's namespace for a BlobTx, the reserved `tx` bucket for
normal txs), kept reconciled through every admission and removal path —
insert, priority eviction, TTL expiry, recheck eviction, committed drop
— onto the `celestia_mempool_namespace_{txs,size_bytes}` depth gauges;
evictions carry the namespace too.  All namespace label values go
through the top-N cardinality cap (trace/square_journal.py), and the
e2e `mempool_wait`/`total` phases inherit the namespace from the
entry's TraceContext baggage.
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field

DEFAULT_TTL_NUM_BLOCKS = 5
DEFAULT_MAX_TX_BYTES = 128 * 128 * 478  # ~7.8 MB
DEFAULT_MAX_POOL_BYTES = 4 * DEFAULT_MAX_TX_BYTES


@dataclass
class _Entry:
    tx: bytes
    priority: int
    height: int  # admission height (for TTL)
    seq: int  # FIFO tiebreak
    ctx: object | None = None  # submitting request's TraceContext
    t_ins: float = field(default=0.0)  # perf_counter at admission
    reaped: bool = False  # mempool_wait observed (first reap only)
    # Submitting namespace label, already CAPPED at admission ("tx" for
    # normal txs, "other" past the top-N admission cap): capping once
    # here keeps every later gauge/counter refresh a plain dict walk.
    ns: str = "tx"

    def e2e_namespace(self) -> str | None:
        """The namespace the entry's e2e observations are attributed to
        (None for normal txs — they keep the unlabeled phase series)."""
        return self.ns if self.ns != "tx" else None


class PriorityMempool:
    def __init__(
        self,
        ttl_num_blocks: int = DEFAULT_TTL_NUM_BLOCKS,
        max_tx_bytes: int = DEFAULT_MAX_TX_BYTES,
        max_pool_bytes: int = DEFAULT_MAX_POOL_BYTES,
    ):
        self.ttl = ttl_num_blocks
        self.max_tx_bytes = max_tx_bytes
        self.max_pool_bytes = max_pool_bytes
        self._entries: dict[bytes, _Entry] = {}
        self._seq = 0
        self._bytes = 0
        # CAPPED namespace label -> [txs, bytes]; entries removed on zero
        # after the gauge refresh, so the dict only holds live tenants and
        # is bounded by the cap (top-N + `tx` + `other`) by construction.
        self._ns_depth: dict[str, list[int]] = {}

    def __len__(self) -> int:
        return len(self._entries)

    def size_bytes(self) -> int:
        return self._bytes

    @staticmethod
    def tx_key(tx: bytes) -> bytes:
        return hashlib.sha256(tx).digest()

    def has_tx(self, tx: bytes) -> bool:
        """Is this exact tx resident? (gossip relay dedup)."""
        return self.tx_key(tx) in self._entries

    def ctx_for(self, tx: bytes):
        """The TraceContext a resident tx was submitted under, if any —
        how a block adopts the trace of the request that fed it."""
        e = self._entries.get(self.tx_key(tx))
        return e.ctx if e is not None else None

    # --- metrics plumbing ---------------------------------------------------
    def _refresh_gauges(self) -> None:
        from celestia_app_tpu.trace.metrics import registry

        reg = registry()
        reg.gauge("celestia_mempool_txs", "resident mempool txs").set(
            len(self._entries)
        )
        reg.gauge(
            "celestia_mempool_size_bytes", "resident mempool bytes"
        ).set(self._bytes)
        # Per-tenant depth: keys are capped at admission (distinct raw
        # labels past the cap already share the `other` entry), so this is
        # a plain walk; zeroed tenants drop after their gauge lands on 0.
        ns_txs = reg.gauge(
            "celestia_mempool_namespace_txs",
            "resident mempool txs per namespace (top-N capped)",
        )
        ns_bytes = reg.gauge(
            "celestia_mempool_namespace_size_bytes",
            "resident mempool bytes per namespace (top-N capped)",
        )
        for lbl, (n, b) in self._ns_depth.items():
            ns_txs.set(n, namespace=lbl)
            ns_bytes.set(b, namespace=lbl)
        for lbl in [l for l, (n, _) in self._ns_depth.items() if n == 0]:
            del self._ns_depth[lbl]

    def _tick_eviction(self, reason: str, n: int = 1, *,
                       namespace: str = "tx") -> None:
        from celestia_app_tpu.trace.metrics import registry
        from celestia_app_tpu.trace.square_journal import capped_namespace_label

        registry().counter(
            "celestia_mempool_evictions_total",
            "mempool removals that were not block inclusion",
        ).inc(n, reason=reason, namespace=capped_namespace_label(namespace))

    # --- mutation -----------------------------------------------------------
    def insert(self, tx: bytes, priority: int, height: int, ctx=None,
               ns: str | None = None) -> bool:
        """Admit a checked tx; False if duplicate, oversized, or the pool is
        full of higher-priority txs.  `ctx` is the submitting request's
        TraceContext (defaults to the thread's current one); `ns` is the
        tx's already-resolved namespace label, when the caller (the
        broadcast path) parsed the tx anyway."""
        from celestia_app_tpu import chaos
        from celestia_app_tpu.trace.context import current_context, trace_span

        if ctx is None:
            ctx = current_context()
        with trace_span(
            "mempool_insert", ctx=ctx, layer="mempool",
            tx_bytes=len(tx), height=height,
        ) as sp:
            # Chaos mempool.insert seam: a transient admission drop — the
            # submitter's retry (or the gossip flood re-offering the tx)
            # is what gets it in, which is exactly the robustness a lossy
            # admission path requires.
            if chaos.mempool_insert():
                sp["result"] = "chaos_dropped"
                ok = False
            else:
                ok = self._insert(tx, priority, height, ctx, ns)
                sp["result"] = "inserted" if ok else "rejected"
        self._refresh_gauges()
        return ok

    def _insert(self, tx: bytes, priority: int, height: int, ctx,
                ns: str | None = None) -> bool:
        if len(tx) > self.max_tx_bytes:
            return False
        key = self.tx_key(tx)
        if key in self._entries:
            return False
        # Evict lowest-priority entries to make room (prioritized
        # admission).  Feasibility is decided BEFORE anything is removed:
        # evicting one-at-a-time and then discovering the next victim
        # outranks the newcomer would have destroyed valid residents for
        # an insert that admits nothing.
        need = self._bytes + len(tx) - self.max_pool_bytes
        if need > 0:
            victims = sorted(
                (kv for kv in self._entries.items()
                 if kv[1].priority < priority),
                key=lambda kv: (kv[1].priority, -kv[1].seq),
            )
            chosen, freed = [], 0
            for kv in victims:
                if freed >= need:
                    break
                chosen.append(kv)
                freed += len(kv[1].tx)
            if freed < need:
                return False  # infeasible: nothing was evicted
            for victim_key, victim in chosen:
                self._remove(victim_key)
                self._tick_eviction("priority", namespace=victim.ns)
        if ns is not None:  # caller-resolved raw label still needs the cap
            from celestia_app_tpu.trace.square_journal import (
                capped_namespace_label,
            )

            ns = capped_namespace_label(ns)
        self._entries[key] = _Entry(
            tx, priority, height, self._seq, ctx, time.perf_counter(),
            ns=ns if ns is not None else self._namespace_of(tx, ctx),
        )
        self._seq += 1
        self._bytes += len(tx)
        e = self._entries[key]
        agg = self._ns_depth.setdefault(e.ns, [0, 0])
        agg[0] += 1
        agg[1] += len(tx)
        return True

    @staticmethod
    def _namespace_of(tx: bytes, ctx) -> str:
        """The entry's CAPPED namespace label: the submit path already
        resolved the raw label into the trace baggage; fall back to
        parsing the tx (gossip relays and direct inserts arrive without
        baggage).  Capped exactly once, here at admission."""
        from celestia_app_tpu.trace.square_journal import (
            capped_namespace_label,
            tx_namespace_label,
        )

        baggage = getattr(ctx, "baggage", None)
        raw = (baggage or {}).get("namespace") or tx_namespace_label(tx)
        return capped_namespace_label(raw) if raw else "tx"

    def _remove(self, key: bytes) -> None:
        e = self._entries.pop(key, None)
        if e is not None:
            self._bytes -= len(e.tx)
            agg = self._ns_depth.get(e.ns)
            if agg is not None:
                agg[0] -= 1
                agg[1] -= len(e.tx)

    def reap(self, max_bytes: int | None = None) -> list[bytes]:
        """Txs by (priority desc, FIFO) under a byte budget.

        Journaled: one `mempool_reap` span per call (count/bytes/skips,
        joined to the first reaped tx's trace), plus one `mempool_wait`
        e2e observation per reaped tx (insert -> reap residency).
        """
        from celestia_app_tpu.trace.context import export_span, new_context
        from celestia_app_tpu.trace.spans import observe_e2e
        from celestia_app_tpu.trace.tracer import trace_enabled

        start_unix_ns = time.time_ns()
        t0 = time.perf_counter_ns()
        ordered = sorted(
            self._entries.values(), key=lambda e: (-e.priority, e.seq)
        )
        out: list[bytes] = []
        reaped_entries: list[_Entry] = []
        total = skipped = 0
        for e in ordered:
            if max_bytes is not None and total + len(e.tx) > max_bytes:
                skipped += 1
                continue
            out.append(e.tx)
            reaped_entries.append(e)
            total += len(e.tx)
        elapsed_ns = time.perf_counter_ns() - t0
        if trace_enabled():
            # The span joins the trace of the first REAPED tx — the same
            # trace the block built from this reap adopts
            # (_block_trace_context), so the reap leg is never orphaned
            # onto a budget-skipped tx's trace.
            first_ctx = next(
                (e.ctx for e in reaped_entries if e.ctx is not None), None
            )
            ctx = first_ctx.child() if first_ctx is not None else new_context()
            export_span(
                "mempool_reap", ctx, start_unix_ns, elapsed_ns,
                {"layer": "mempool", "n_txs": len(out), "reap_bytes": total,
                 "skipped": skipped, "resident": len(ordered)},
                e2e="reap",
            )
        now = time.perf_counter()
        for e in reaped_entries:
            # First reap only: a tx the proposer reaps but drops (filter
            # rejection, square overflow) is reaped again every block
            # until TTL, and re-observing its growing residency would let
            # duplicates dominate the histogram's tail.
            if e.t_ins and not e.reaped:
                observe_e2e("mempool_wait", now - e.t_ins,
                            namespace=e.e2e_namespace())
            e.reaped = True
        return out

    def update(self, height: int, committed_txs: list[bytes]) -> None:
        """Post-commit maintenance: drop included txs, expire TTLs.

        Journaled (`mempool_update` row): committed drops and TTL expiries
        were previously silent.  Each committed tx with a known submission
        context closes its lifecycle on the e2e `total` phase
        (submit wall-clock -> this commit)."""
        from celestia_app_tpu.trace.spans import observe_e2e
        from celestia_app_tpu.trace.tracer import traced

        now_ns = time.time_ns()
        committed = 0
        for tx in committed_txs:
            key = self.tx_key(tx)
            e = self._entries.get(key)
            if e is None:
                continue
            committed += 1
            if e.ctx is not None and getattr(e.ctx, "start_unix_ns", 0):
                observe_e2e("total", (now_ns - e.ctx.start_unix_ns) / 1e9,
                            namespace=e.e2e_namespace())
            self._remove(key)
        expired = [
            k for k, e in self._entries.items() if height - e.height >= self.ttl
        ]
        expired_by_ns: dict[str, int] = {}
        for k in expired:
            ns = self._entries[k].ns
            expired_by_ns[ns] = expired_by_ns.get(ns, 0) + 1
            self._remove(k)
        for ns, n in sorted(expired_by_ns.items()):
            self._tick_eviction("ttl", n, namespace=ns)
        traced().write(
            "mempool_update", height=height, committed=committed,
            expired=len(expired), resident=len(self._entries),
        )
        self._refresh_gauges()

    def resident_txs(self) -> list[bytes]:
        """All resident txs in (priority desc, FIFO) order — the order a
        proposer would take them (recheck runs in this order)."""
        return [
            e.tx for e in sorted(
                self._entries.values(), key=lambda e: (-e.priority, e.seq)
            )
        ]

    def remove_tx(self, tx: bytes) -> None:
        """Evict one tx (the post-commit recheck path): counted like every
        other non-commit removal so the gauges reconcile."""
        key = self.tx_key(tx)
        e = self._entries.get(key)
        if e is not None:
            self._remove(key)
            self._tick_eviction("recheck", namespace=e.ns)
            self._refresh_gauges()
"""txsim: the deterministic load generator.

Parity with reference test/txsim (run.go:37-124, blob.go, send.go):
composable sequences submit txs through a TxClient against a node; a master
seed makes the whole load pattern reproducible.  Each sequence owns one
account (the reference's AccountManager funds subaccounts; here keys map to
genesis accounts from the harness).
"""

from __future__ import annotations

import numpy as np

from celestia_app_tpu.shares.namespace import Namespace
from celestia_app_tpu.shares.sparse import Blob
from celestia_app_tpu.tx.messages import Coin, MsgSend
from celestia_app_tpu.user import TxClient


class BlobSequence:
    """Submits PFBs with random namespaces/sizes (test/txsim/blob.go)."""

    def __init__(
        self,
        blobs_per_pfb: tuple[int, int] = (1, 3),
        blob_size: tuple[int, int] = (100, 10_000),
    ):
        self.blobs_per_pfb = blobs_per_pfb
        self.blob_size = blob_size
        self.address: str | None = None

    def next(self, rng: np.random.Generator, client: TxClient):
        count = int(rng.integers(self.blobs_per_pfb[0], self.blobs_per_pfb[1] + 1))
        blobs = []
        for _ in range(count):
            ns = Namespace.v0(rng.integers(1, 256, 10, dtype=np.uint8).tobytes())
            size = int(rng.integers(self.blob_size[0], self.blob_size[1] + 1))
            blobs.append(Blob(ns, rng.integers(0, 256, size, dtype=np.uint8).tobytes()))
        # Namespaces within one PFB must be sorted for deterministic blob order.
        blobs.sort(key=lambda b: b.namespace.to_bytes())
        return ("pfb", blobs)


class SendSequence:
    """Round-robin MsgSends between the client's accounts (send.go)."""

    def __init__(self, amount: tuple[int, int] = (1, 1000)):
        self.amount = amount
        self.address: str | None = None

    def next(self, rng: np.random.Generator, client: TxClient):
        addrs = client.signer.addresses()
        to = addrs[int(rng.integers(0, len(addrs)))]
        amount = int(rng.integers(self.amount[0], self.amount[1] + 1))
        return ("send", to, amount)


class StakeSequence:
    """Delegate once, continuously claim rewards, and occasionally
    redelegate to a random other validator (test/txsim/stake.go: 1-in-10
    redelegation, MsgWithdrawDelegatorReward otherwise)."""

    def __init__(self, initial_stake: int = 1_000_000, validators: list[str] | None = None):
        self.initial_stake = initial_stake
        self.validators = validators  # None = query the node each round
        self.delegated_to: str | None = None
        self.address: str | None = None

    def _validator_addrs(self, node) -> list[str]:
        # node-agnostic: TestNode and RemoteNode both expose validators().
        return self.validators or [v["address"] for v in node.validators()]

    def next(self, rng: np.random.Generator, client: TxClient):
        if self.delegated_to is None:
            return ("delegate", None)
        if int(rng.integers(0, 10)) == 0:
            return ("redelegate", None)
        return ("claim", None)


def run(
    node, keys, sequences, blocks: int, seed: int = 42,
    use_feegrant: bool = False,
) -> dict:
    """Drive `sequences` for `blocks` blocks; returns submission stats.

    `use_feegrant` mirrors the reference AccountManager: the master (first)
    account grants every other account a fee allowance up front and then
    pays all their fees (test/txsim/account.go:238-239,318-330)."""
    rng = np.random.default_rng(seed)
    client = TxClient(node, keys)
    addrs = client.signer.addresses()
    if use_feegrant and len(addrs) > 1:
        from celestia_app_tpu.tx.messages import MsgGrantAllowance

        master = addrs[0]
        grants = [MsgGrantAllowance(master, a) for a in addrs[1:]]
        client.submit_tx(grants, master, gas=200_000)  # confirms inclusion
        client.fee_granter = master
    for i, seq in enumerate(sequences):
        seq.address = addrs[i % len(addrs)]

    stats = {"submitted": 0, "failed": 0, "blocks": 0}
    for _ in range(blocks):
        for seq in sequences:
            op = seq.next(rng, client)
            try:
                if op[0] == "pfb":
                    with client._lock:
                        client._broadcast_pfb(op[1], seq.address)
                elif op[0] == "send":
                    _, to, amount = op
                    msg = MsgSend(seq.address, to, (Coin("utia", amount),))
                    with client._lock:
                        client._broadcast_msgs([msg], seq.address, gas=200_000)
                elif op[0] in ("delegate", "redelegate"):
                    from celestia_app_tpu.tx.messages import (
                        MsgBeginRedelegate,
                        MsgDelegate,
                    )

                    vals = seq._validator_addrs(node)
                    if op[0] == "delegate":
                        target = vals[int(rng.integers(0, len(vals)))]
                        msg = MsgDelegate(
                            seq.address, target, Coin("utia", seq.initial_stake)
                        )
                    else:
                        others = [v for v in vals if v != seq.delegated_to]
                        if not others:
                            continue  # solo validator: nothing to redelegate to
                        target = others[int(rng.integers(0, len(others)))]
                        msg = MsgBeginRedelegate(
                            seq.address, seq.delegated_to,
                            Coin("utia", seq.initial_stake), target,
                        )
                    with client._lock:
                        client._broadcast_msgs([msg], seq.address, gas=200_000)
                    # Track only AFTER the broadcast succeeded: a rejected
                    # submission must not desync the sequence from chain
                    # state (it retries the same step next round).
                    seq.delegated_to = target
                elif op[0] == "claim":
                    from celestia_app_tpu.tx.messages import (
                        MsgWithdrawDelegatorReward,
                    )

                    msg = MsgWithdrawDelegatorReward(seq.address, seq.delegated_to)
                    with client._lock:
                        client._broadcast_msgs([msg], seq.address, gas=200_000)
                else:
                    continue  # noop round
                stats["submitted"] += 1
            except Exception:
                stats["failed"] += 1
        node.produce_block()
        stats["blocks"] += 1
    return stats

from celestia_app_tpu.txsim.run import BlobSequence, SendSequence, run

__all__ = ["BlobSequence", "SendSequence", "run"]

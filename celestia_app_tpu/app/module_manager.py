"""Versioned module manager (reference app/module/manager.go).

Every module declares the app-version range it is active in
(app/modules.go:96-189 VersionedModule list); when the signal-driven
upgrade bumps the app version, RunMigrations (manager.go:222) runs each
newly-active module's migration so state appears/disappears atomically with
the version change.  The reference's v1->v2 delta: x/signal and x/minfee
come alive, x/blobstream goes dormant (app/app.go:465-469).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from celestia_app_tpu.modules.minfee import MinFeeKeeper


@dataclass(frozen=True)
class VersionedModule:
    name: str
    from_version: int
    to_version: int  # inclusive
    # migrate(ctx, from_v, to_v) runs when the module becomes active or its
    # consensus version advances across an upgrade.
    migrate: Callable | None = None


def _migrate_minfee(ctx, from_v: int, to_v: int) -> None:
    # v2 introduces the on-chain network min gas price with its default
    # (x/minfee/params.go:20-26).
    keeper = MinFeeKeeper(ctx.store)
    keeper.set_network_min_gas_price(keeper.network_min_gas_price())


DEFAULT_MODULES = (
    VersionedModule("auth", 1, 99),
    VersionedModule("bank", 1, 99),
    VersionedModule("staking", 1, 99),
    VersionedModule("mint", 1, 99),
    VersionedModule("blob", 1, 99),
    VersionedModule("paramfilter", 1, 99),
    VersionedModule("tokenfilter", 1, 99),
    VersionedModule("blobstream", 1, 1),  # v1 only
    VersionedModule("signal", 2, 99),
    VersionedModule("minfee", 2, 99, migrate=_migrate_minfee),
)


class ModuleManager:
    def __init__(self, modules: tuple[VersionedModule, ...] = DEFAULT_MODULES):
        by_name: dict[str, VersionedModule] = {}
        for m in modules:
            if m.from_version > m.to_version:
                raise ValueError(f"module {m.name}: bad version range")
            if m.name in by_name:
                raise ValueError(f"duplicate module {m.name}")
            by_name[m.name] = m
        self.modules = modules

    def active(self, app_version: int) -> list[str]:
        return [
            m.name
            for m in self.modules
            if m.from_version <= app_version <= m.to_version
        ]

    def is_active(self, name: str, app_version: int) -> bool:
        return name in self.active(app_version)

    def run_migrations(self, ctx, from_version: int, to_version: int) -> list[str]:
        """Run migrations for modules newly active in (from, to]; returns
        the migrated module names (RunMigrations, manager.go:222)."""
        migrated = []
        for m in self.modules:
            newly_active = (
                m.from_version > from_version and m.from_version <= to_version
            )
            if newly_active and m.migrate is not None:
                m.migrate(ctx, from_version, to_version)
            if newly_active:
                migrated.append(m.name)
        return migrated

"""The ABCI application: proposal construction, validation, and execution.

Behavioral parity with the reference app package:

  PrepareProposal  app/prepare_proposal.go:22-91   filter -> build square ->
                                                   RS-extend -> DAH -> root
  ProcessProposal  app/process_proposal.go:24-158  decode/validate every tx,
                                                   reconstruct, compare root
  CheckTx          app/check_tx.go:16-54           BlobTx unwrap + ante
  Finalize/Commit  app/app.go:446-480              mint BeginBlock, tx
                                                   execution, signal-driven
                                                   upgrades, state commit

The square pipeline below FilterTxs runs on the TPU via the fused
extend+NMT+DAH program (da/eds.py) — the offload target of SURVEY §3.2.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from celestia_app_tpu.constants import (
    DEFAULT_GOV_MAX_SQUARE_SIZE,
    LATEST_VERSION,
    MAX_CODEC_SQUARE_SIZE,
    SQUARE_SIZE_UPPER_BOUND,
)
from celestia_app_tpu.app.ante import AnteError, run_ante
from celestia_app_tpu.app.gas import OutOfGas
from celestia_app_tpu.da import DataAvailabilityHeader, extend_shares, min_data_availability_header
from celestia_app_tpu.modules.blob.types import (
    BlobTxError,
    gas_to_consume,
    validate_blob_tx,
    validate_blob_txs_batched,
)
from celestia_app_tpu.modules.minfee import MinFeeKeeper
from celestia_app_tpu.modules.mint.minter import Minter
from celestia_app_tpu.modules.signal.keeper import SignalError, SignalKeeper
from celestia_app_tpu.square import SquareOverflow
from celestia_app_tpu.square import builder as square
from celestia_app_tpu.state.accounts import AuthKeeper, BankKeeper, FEE_COLLECTOR
from celestia_app_tpu.state.dec import Dec
from celestia_app_tpu.state.staking import StakingKeeper, Validator
from celestia_app_tpu.state.store import CommitStore, KVStore
from celestia_app_tpu.tx.envelopes import unmarshal_blob_tx
from celestia_app_tpu.tx.messages import (
    MsgAcknowledgement,
    MsgAuthzExec,
    MsgAuthzGrant,
    MsgAuthzRevoke,
    MsgBeginRedelegate,
    MsgCancelUnbondingDelegation,
    MsgCreatePeriodicVestingAccount,
    MsgCreatePermanentLockedAccount,
    MsgCreateVestingAccount,
    MsgDepositV1,
    MsgMultiSend,
    MsgSubmitEvidence,
    MsgSubmitProposalV1,
    MsgVerifyInvariant,
    MsgVoteV1,
    MsgVoteWeightedV1,
    MsgCreateValidator,
    MsgDelegate,
    MsgDeposit,
    MsgEditValidator,
    MsgGrantAllowance,
    MsgPayForBlobs,
    MsgRecvPacket,
    MsgRevokeAllowance,
    MsgFundCommunityPool,
    MsgSend,
    MsgSetWithdrawAddress,
    MsgSignalVersion,
    MsgSubmitProposal,
    MsgTimeout,
    MsgTransfer,
    MsgTryUpgrade,
    MsgUndelegate,
    MsgUnjail,
    MsgVote,
    MsgVoteWeighted,
    MsgWithdrawDelegatorReward,
    MsgWithdrawValidatorCommission,
)
from celestia_app_tpu.trace import trace_span, traced
from celestia_app_tpu.tx.sign import Tx


@dataclass(frozen=True)
class GenesisAccount:
    address: str
    balance: int  # utia
    pubkey: bytes = b""
    # Optional vesting schedule (x/auth/vesting; celestia mainnet genesis
    # carries vesting accounts): type 1 = continuous, 2 = delayed.
    vesting_type: int = 0
    original_vesting: int = 0
    vesting_start_ns: int = 0
    vesting_end_ns: int = 0


@dataclass(frozen=True)
class Genesis:
    chain_id: str
    genesis_time_ns: int
    accounts: tuple[GenesisAccount, ...] = ()
    validators: tuple[Validator, ...] = ()
    app_version: int = LATEST_VERSION
    gov_max_square_size: int = DEFAULT_GOV_MAX_SQUARE_SIZE
    # x/blobstream DataCommitmentWindow (types/genesis.go:29); 0 = default 400.
    data_commitment_window: int = 0
    # Consensus Block.MaxBytes; 0 derives gov_max_square_size^2 x 478 (the
    # reference's DefaultMaxBytes formula, initial_consts.go:10-14 — its
    # big-block e2e manifests raise this alongside the square cap).
    block_max_bytes: int = 0


@dataclass(frozen=True)
class BlockData:
    """PrepareProposal response payload (celestia-core BlockData fork fields,
    app/prepare_proposal.go:84-90)."""

    txs: tuple[bytes, ...]
    square_size: int
    hash: bytes  # the DAH data root


@dataclass
class TxResult:
    code: int  # 0 = ok
    log: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: list = field(default_factory=list)


class Ctx:
    """A branched state view for one proposal / tx / block."""

    def __init__(self, store: KVStore, height: int, time_ns: int, app_version: int):
        self.store = store
        self.height = height
        self.time_ns = time_ns
        self.app_version = app_version
        self.auth = AuthKeeper(store)
        self.bank = BankKeeper(store)
        self.staking = StakingKeeper(store)

    def branch(self) -> "Ctx":
        return Ctx(self.store.branch(), self.height, self.time_ns, self.app_version)

    def with_store(self, store) -> "Ctx":
        """Same coordinates over a different store view (e.g. gas-metered)."""
        return Ctx(store, self.height, self.time_ns, self.app_version)

    def send_spendable(self, sender: str, recipient: str, amount: int) -> None:
        """Transfer that cannot dip into still-vesting tokens."""
        from celestia_app_tpu.state.accounts import send_spendable

        send_spendable(self.auth, self.bank, sender, recipient, amount, self.time_ns)

    def assert_spendable(self, sender: str, amount: int) -> None:
        from celestia_app_tpu.state.accounts import assert_spendable

        assert_spendable(self.auth, self.bank, sender, amount, self.time_ns)


class App:
    """The celestia state machine with a TPU square pipeline."""

    def __init__(
        self,
        node_min_gas_price: Dec | None = None,
        v2_upgrade_height: int | None = None,
        ibc_token_filter: bool = True,
        square_size_upper_bound: int | None = None,
    ):
        self.cms = CommitStore()
        self.chain_id = ""
        self.app_version = LATEST_VERSION
        # Height-based v1->v2 upgrade (reference --v2-upgrade-height,
        # cmd/celestia-appd/cmd/root.go:40,142 consumed at app/app.go:458-470).
        self.v2_upgrade_height = v2_upgrade_height
        self.height = 0
        self.genesis_time_ns = 0
        self.last_block_time_ns = 0
        self.node_min_gas_price = node_min_gas_price or Dec.from_str("0.002")
        self.minter = Minter.default()
        # False models a non-celestia counterparty chain (the reference's
        # test/pfm/simapp.go) in IBC tests; celestia itself always filters.
        self.ibc_token_filter = ibc_token_filter
        # The versioned protocol hard cap (128 for v1/v2).  The reference's
        # big-block benchmark manifests override MaxSquareSize up to 512
        # (test/e2e/benchmark/throughput.go:15-54); this knob is that
        # override, clamped to what the DA kernels support.
        self.square_size_upper_bound = min(
            square_size_upper_bound or SQUARE_SIZE_UPPER_BOUND,
            MAX_CODEC_SQUARE_SIZE,
        )
        self._check_state: KVStore | None = None
        # Own-root memo: (square_size, sha256(square bytes)) -> DAH hash.
        # A data root is a pure function of the square bytes, and this
        # node recomputes the SAME square's root up to twice per block
        # (PrepareProposal, then ProcessProposal rebuilding the square
        # from the txs itself). Only self-computed results enter the memo
        # and Process still rebuilds the square from the raw txs, so the
        # proposer's claims are never trusted — identical bytes simply
        # skip the identical device pipeline. Bounded FIFO.
        self._own_roots: dict[tuple[int, bytes], bytes] = {}

    # --- keeper views over committed state ---------------------------------
    @property
    def minfee(self) -> MinFeeKeeper:
        return MinFeeKeeper(self.cms.working)

    @property
    def gov_max_square_size(self) -> int:
        """On-chain x/blob param (read at square_size.go:20-22)."""
        from celestia_app_tpu.modules.blob.params import BlobParamsKeeper

        return BlobParamsKeeper(self.cms.working).gov_max_square_size()

    @property
    def gas_per_blob_byte(self) -> int:
        from celestia_app_tpu.modules.blob.params import BlobParamsKeeper

        return BlobParamsKeeper(self.cms.working).gas_per_blob_byte()

    @property
    def signal(self) -> SignalKeeper:
        return SignalKeeper(self.cms.working, StakingKeeper(self.cms.working))

    def max_effective_square_size(self) -> int:
        """min(gov, hard cap) — reference app/square_size.go:9-23."""
        return min(self.gov_max_square_size, self.square_size_upper_bound)

    # --- genesis ------------------------------------------------------------
    def init_chain(self, genesis: Genesis) -> None:
        if self.height != 0:
            raise RuntimeError("chain already initialized")
        self.chain_id = genesis.chain_id
        self.app_version = genesis.app_version
        self.genesis_time_ns = genesis.genesis_time_ns
        self.last_block_time_ns = genesis.genesis_time_ns
        from celestia_app_tpu.modules.blob.params import BlobParamsKeeper

        BlobParamsKeeper(self.cms.working).set_gov_max_square_size(
            genesis.gov_max_square_size
        )
        if genesis.data_commitment_window:
            from celestia_app_tpu.modules.blobstream.keeper import (
                set_data_commitment_window,
            )

            set_data_commitment_window(
                self.cms.working, genesis.data_commitment_window
            )
        from celestia_app_tpu.constants import CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
        from celestia_app_tpu.modules.consensus_params import ConsensusParamsKeeper

        ConsensusParamsKeeper(self.cms.working).set_block_max_bytes(
            genesis.block_max_bytes
            or genesis.gov_max_square_size**2 * CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
        )
        ctx = Ctx(self.cms.working, 0, genesis.genesis_time_ns, self.app_version)
        for acc in genesis.accounts:
            a = ctx.auth.create_account(acc.address, acc.pubkey)
            if acc.vesting_type:
                a.vesting_type = acc.vesting_type
                a.original_vesting = acc.original_vesting
                a.vesting_start_ns = acc.vesting_start_ns or genesis.genesis_time_ns
                a.vesting_end_ns = acc.vesting_end_ns
            ctx.auth.set_account(a)
            if acc.balance:
                ctx.bank.mint(acc.address, acc.balance)
        from celestia_app_tpu.modules.distribution import DistributionKeeper
        from celestia_app_tpu.state.staking import POWER_REDUCTION

        dist = DistributionKeeper(ctx.store)
        for v in genesis.validators:
            ctx.staking.set_validator(v)
            # A genesis validator's declared power is a notional self-bond
            # (no escrowed delegation backs it); register it with
            # distribution so its reward share accrues to the operator.
            dist.set_notional(v.address, v.power * POWER_REDUCTION)
        # x/crisis: genesis invariant assertion (the reference runs module
        # invariants at genesis unless skipGenesisInvariants).
        from celestia_app_tpu.modules.crisis import assert_invariants

        assert_invariants(self.cms.working)
        self.cms.commit(0)
        self._check_state = None

    # --- CheckTx (mempool admission, app/check_tx.go:16-54) ----------------
    def check_tx(self, raw: bytes) -> TxResult:
        if self._check_state is None:
            self._check_state = self.cms.working.branch()
        ctx = Ctx(
            self._check_state, self.height + 1, self.last_block_time_ns, self.app_version
        )
        from celestia_app_tpu.trace.metrics import registry

        checked = registry().counter(
            "celestia_checktx_total", "CheckTx admissions by result"
        )
        btx = unmarshal_blob_tx(raw)
        inner = raw
        if btx is not None:
            try:
                validate_blob_tx(btx)
            except BlobTxError as e:
                checked.inc(result="rejected")
                return TxResult(code=11, log=str(e))
            inner = btx.tx
        try:
            tx = Tx.unmarshal(inner)
            res = run_ante(self, ctx, tx, is_check_tx=True, tx_bytes=inner)
        except OutOfGas as e:
            checked.inc(result="rejected")
            return TxResult(code=11, log=str(e))  # sdk ErrOutOfGas
        except (AnteError, ValueError) as e:
            checked.inc(result="rejected")
            return TxResult(code=1, log=str(e))
        checked.inc(result="accepted")
        return TxResult(code=0, gas_wanted=res.gas_wanted, events=[("priority", res.priority)])

    # --- PrepareProposal (app/prepare_proposal.go:22-91) --------------------
    def prepare_proposal(self, raw_txs: list[bytes]) -> BlockData:
        # telemetry.MeasureSince parity (prepare_proposal.go:23); joins
        # the block's trace when the caller set one (trace/context.py).
        with trace_span("prepare_proposal", layer="app",
                        height=self.height + 1, n_txs=len(raw_txs)):
            raw_txs = self._cap_block_bytes(raw_txs)
            filtered = self._filter_txs(raw_txs)
            sq, kept = square.build(filtered, self.max_effective_square_size())
            if sq.is_empty():
                dah = min_data_availability_header()
                return BlockData(tuple(kept), 1, dah.hash())
            with trace_span("square_pipeline", layer="device", e2e="dispatch",
                            k=sq.size, phase="prepare"):
                root = self._square_root(sq.size, sq.share_bytes())
            return BlockData(tuple(kept), sq.size, root)

    def _cap_block_bytes(self, raw_txs: list[bytes]) -> list[bytes]:
        """Keep the prefix of candidate txs fitting the on-chain
        Block.MaxBytes consensus param (the reference's celestia-core reaps
        the mempool under this cap before PrepareProposal sees it)."""
        from celestia_app_tpu.modules.consensus_params import ConsensusParamsKeeper

        max_bytes = ConsensusParamsKeeper(self.cms.working).block_max_bytes()
        kept, total = [], 0
        for raw in raw_txs:
            if total + len(raw) > max_bytes:
                break  # prefix semantics: a later small tx must not jump
                # an earlier large one (sequence gaps would drop it anyway)
            total += len(raw)
            kept.append(raw)
        return kept

    def _filter_txs(self, raw_txs: list[bytes]) -> list[bytes]:
        """FilterTxs (app/validate_txs.go:32): separate tx classes, then
        ante-validate in BLOCK order (normal txs before blob txs,
        validate_txs.go:14,31-36) on one branched state, dropping failures.
        Validating in block order matters: a signer's sequence must advance
        in the order txs execute, not the order they arrived."""
        ctx = Ctx(
            self.cms.working.branch(),
            self.height + 1,
            self.last_block_time_ns,
            self.app_version,
        )
        classified = [(raw, unmarshal_blob_tx(raw)) for raw in raw_txs]
        normal: list[bytes] = []
        blob: list[bytes] = []
        for raw, btx in classified:
            if btx is not None:
                continue
            try:
                tx = Tx.unmarshal(raw)
                if any(isinstance(m, MsgPayForBlobs) for m in tx.msgs()):
                    continue  # PFB outside a BlobTx is invalid
                run_ante(self, ctx, tx, is_check_tx=False, tx_bytes=raw)
                normal.append(raw)
            except (AnteError, ValueError, OutOfGas):
                continue
        blob_entries = [(raw, btx) for raw, btx in classified if btx is not None]
        validated = validate_blob_txs_batched([b for _, b in blob_entries])
        for (raw, btx), v in zip(blob_entries, validated):
            if isinstance(v, BlobTxError):
                continue
            try:
                run_ante(
                    self, ctx, Tx.unmarshal(btx.tx), is_check_tx=False, tx_bytes=btx.tx
                )
                blob.append(raw)
            except (AnteError, ValueError, OutOfGas):
                continue
        return normal + blob

    def speculate_proposal(
        self, data: BlockData, height: int | None = None,
        round_: int | None = None,
    ) -> bool:
        """Enqueue the proposed square's extension SPECULATIVELY (the PR 9
        seam's consensus call site, $CELESTIA_PIPE_SPECULATE): called by
        the round-machine driver the moment a proposal's payload is known
        to be the proposer's signed content, so the device dispatch is in
        flight while the prevote window's host work runs — the LastCommit
        signature batch, ante validation, blob-commitment checks — and
        process_proposal's root derivation claims the finished result
        instead of dispatching cold.  Best-effort by contract: any
        mismatch (a round change re-proposed different bytes) discards
        the claim and compute() runs normally; never raises into the
        consensus path."""
        import numpy as np

        from celestia_app_tpu.constants import SHARE_SIZE
        from celestia_app_tpu.da.eds import speculation_enabled, speculator

        if not speculation_enabled():
            return False
        try:
            sq = square.construct(
                list(data.txs), self.max_effective_square_size()
            )
            if sq.is_empty() or sq.size != data.square_size:
                return False
            shares = sq.share_bytes()
            k = sq.size
            ods = np.frombuffer(
                b"".join(shares), dtype=np.uint8
            ).reshape(k, k, SHARE_SIZE)
            return speculator().speculate(ods, height=height, round_=round_)
        except Exception:  # chaos-ok: speculation is best-effort by contract
            return False

    # --- ProcessProposal (app/process_proposal.go:24-158) -------------------
    def process_proposal(self, data: BlockData) -> bool:
        from celestia_app_tpu.trace.metrics import registry

        outcomes = registry().counter(
            "celestia_process_proposal_total", "ProcessProposal verdicts"
        )
        with trace_span("process_proposal", layer="app",
                        height=self.height + 1, n_txs=len(data.txs)):
            try:
                ok = self._process_proposal(data)
            except Exception:
                # recover() -> reject (process_proposal.go:29-35); counted like
                # the reference's rejection telemetry (process_proposal.go:32).
                traced().write("process_proposal_rejections", height=self.height + 1)
                outcomes.inc(result="panic_reject")
                return False
            outcomes.inc(result="accepted" if ok else "rejected")
            return ok

    def _process_proposal(self, data: BlockData) -> bool:
        # Block.MaxBytes is consensus law, not proposer advice: an oversize
        # block is rejected validator-side (celestia-core enforces this
        # around the reference app; here the app is the enforcement point).
        from celestia_app_tpu.modules.consensus_params import ConsensusParamsKeeper

        if sum(len(t) for t in data.txs) > ConsensusParamsKeeper(
            self.cms.working
        ).block_max_bytes():
            return False
        ctx = Ctx(
            self.cms.working.branch(),
            self.height + 1,
            self.last_block_time_ns,
            self.app_version,
        )
        classified = [(raw, unmarshal_blob_tx(raw)) for raw in data.txs]
        # Hot loop (3): every blob's commitment recomputed, batched on device.
        validated = iter(
            validate_blob_txs_batched([b for _, b in classified if b is not None])
        )
        for raw, btx in classified:
            if btx is None:
                tx = Tx.unmarshal(raw)
                if any(isinstance(m, MsgPayForBlobs) for m in tx.msgs()):
                    return False  # PFB must ride in a BlobTx (:77-88)
                run_ante(self, ctx, tx, is_check_tx=False, tx_bytes=raw)
            else:
                v = next(validated)
                if isinstance(v, BlobTxError):
                    raise v
                run_ante(
                    self, ctx, Tx.unmarshal(btx.tx), is_check_tx=False, tx_bytes=btx.tx
                )

        sq = square.construct(list(data.txs), self.max_effective_square_size())
        if sq.size != data.square_size:
            return False  # square-size equality (:133)
        if sq.is_empty():
            return min_data_availability_header().hash() == data.hash
        # Root equality (:152) over the square REBUILT from the raw txs
        # above — the own-root memo only skips re-running the pipeline on
        # bytes this node already extended (its own Prepare, usually).
        return self._square_root(sq.size, sq.share_bytes()) == data.hash

    @staticmethod
    def _square_key(size: int, share_bytes: list[bytes]) -> tuple:
        import hashlib

        digest = hashlib.sha256()
        for s in share_bytes:
            digest.update(s)
        return (size, digest.digest())

    def square_eds(self, size: int, share_bytes: list[bytes]):
        """The extended square for a built square's shares — the serve
        plane's rebuild source (rpc/server._rebuild_eds): when the
        content matches the square this app just extended, the SAME
        device-resident handle comes back with zero extra extensions;
        otherwise (a cache-miss rebuild of an old height) it extends
        fresh.  Deliberately NOT a `_last_eds` writer: that slot belongs
        to the consensus path (_square_root), and a concurrent read-side
        rebuild overwriting it would displace the just-extended square
        right before the commit hook retains it.  The rebuild's caller
        admits the result to the forest cache, so repeats are already
        covered there."""
        key = self._square_key(size, share_bytes)
        last = getattr(self, "_last_eds", None)
        if last is not None and last[0] == key:
            return last[1]
        return extend_shares(share_bytes)

    def last_eds_for_root(self, data_root: bytes):
        """The freshest extended square IF its DAH hash is `data_root` —
        how the serving plane's commit hook retains the just-committed
        height without reconstructing the square (no second layout
        solve, no duplicate square-journal row, no device work)."""
        last = getattr(self, "_last_eds", None)
        if last is not None and last[2] == data_root:
            return last[1]
        return None

    def _square_root(self, size: int, share_bytes: list[bytes]) -> bytes:
        """DAH hash of a built square, memoized on the square's content."""
        key = self._square_key(size, share_bytes)
        cached = self._own_roots.get(key)
        if cached is not None:
            return cached
        eds = extend_shares(share_bytes)
        root = DataAvailabilityHeader.from_eds(eds).hash()
        from celestia_app_tpu.serve import serve_heights

        if serve_heights() > 0:
            # Keep the freshest EDS handle alive for the serve plane's
            # commit-time retention (ONE handle; the forest cache owns
            # longer-term residency).  Gated so a node with serving
            # disabled holds no extra device memory.
            self._last_eds = (key, eds, root)
        while len(self._own_roots) >= 4:
            self._own_roots.pop(next(iter(self._own_roots)))
        self._own_roots[key] = root
        return root

    # --- block execution ----------------------------------------------------
    def finalize_block(
        self,
        time_ns: int,
        txs: list[bytes],
        last_commit_signers: set[str] | None = None,
        evidence: tuple = (),
    ) -> list[TxResult]:
        """Execute one block.  `last_commit_signers` is the set of operator
        addresses whose precommits made the previous block's commit (ABCI
        RequestBeginBlock.LastCommitInfo) — None skips liveness tracking
        (harnesses without a consensus plane).  `evidence` carries
        consensus.votes.Equivocation records (ByzantineValidators)."""
        height = self.height + 1
        block_store = self.cms.working.branch()
        ctx = Ctx(block_store, height, time_ns, self.app_version)

        self._begin_block(ctx, time_ns, last_commit_signers, evidence)
        results = [self._deliver_tx(ctx, raw) for raw in txs]
        self._end_block(ctx, height)
        from celestia_app_tpu.trace.metrics import registry

        delivered = registry().counter(
            "celestia_txs_delivered_total", "delivered txs by result code"
        )
        for r in results:
            delivered.inc(code=str(r.code))

        self.cms.working.write_back(block_store)
        self.height = height
        self.last_block_time_ns = time_ns
        return results

    def commit(self) -> bytes:
        app_hash = self.cms.commit(self.height)
        self._check_state = None  # reset mempool check state each block
        from celestia_app_tpu.trace.metrics import registry

        registry().gauge("celestia_block_height", "last committed height").set(
            self.height
        )
        return app_hash

    def _begin_block(
        self,
        ctx: Ctx,
        time_ns: int,
        last_commit_signers: set[str] | None = None,
        evidence: tuple = (),
    ) -> None:
        """x/mint BeginBlocker (x/mint/abci.go:14-20), then x/distribution's
        (sdk begin-block order: mint before distribution, so this block's
        provision and the previous block's tx fees sweep together), then
        x/evidence + x/slashing liveness."""
        supply = ctx.bank.supply()
        self.minter.update(self.genesis_time_ns, time_ns, supply)
        prev = (
            self.minter.previous_block_time_ns
            if self.minter.previous_block_time_ns is not None
            else self.last_block_time_ns
        )
        provision = self.minter.calculate_block_provision(time_ns, prev)
        if provision > 0:
            ctx.bank.mint(FEE_COLLECTOR, provision)
        self.minter.previous_block_time_ns = time_ns
        from celestia_app_tpu.modules.distribution import DistributionKeeper

        dist = DistributionKeeper(ctx.store)
        dist.allocate(ctx.bank, ctx.staking)

        if evidence or last_commit_signers is not None:
            from celestia_app_tpu.modules.slashing import SlashingKeeper

            slashing = SlashingKeeper(ctx.store)
            # x/evidence BeginBlocker: punish equivocations first (sdk
            # begin-block order: evidence before slashing liveness).
            for ev in evidence:
                try:
                    slashing.handle_equivocation(
                        ctx.staking, ctx.bank, dist,
                        self.chain_id, ev.vote_a, ev.vote_b,
                        current_height=ctx.height,
                    )
                except ValueError:
                    continue  # invalid evidence is dropped, not fatal
            if last_commit_signers is not None:
                for v in ctx.staking.bonded_validators():
                    slashing.handle_validator_signature(
                        ctx.staking, ctx.bank, dist,
                        v.address, v.address in last_commit_signers, time_ns,
                    )

    def simulate_tx(self, raw: bytes) -> TxResult:
        """cosmos.tx.v1beta1.Service/Simulate: run the tx (ante + msgs)
        against a throwaway branch of committed state at the next height
        — signature verification and the gas limit are waived (sdk
        Simulate), gas_used is the real metered consumption, and no state
        survives."""
        ctx = Ctx(
            self.cms.working.branch(), self.height + 1,
            self.last_block_time_ns, self.app_version,
        )
        return self._deliver_tx(ctx, raw, simulate=True)

    def _deliver_tx(
        self, block_ctx: Ctx, raw: bytes, simulate: bool = False
    ) -> TxResult:
        # Imported BEFORE the first try: a function-level import makes the
        # name local for the WHOLE function, so the first `except OutOfGas`
        # would otherwise raise UnboundLocalError whenever the ante phase
        # fails (latent until Simulate started feeding garbage txs here).
        from celestia_app_tpu.app.gas import GasKVStore, OutOfGas

        btx = unmarshal_blob_tx(raw)
        inner = btx.tx if btx is not None else raw
        tx_ctx = block_ctx.branch()
        try:
            tx = Tx.unmarshal(inner)
            ante_res = run_ante(
                self, tx_ctx, tx, is_check_tx=False, tx_bytes=inner,
                simulate=simulate,
            )
        except OutOfGas as e:
            return TxResult(code=11, log=str(e))  # sdk ErrOutOfGas, either phase
        except (AnteError, ValueError) as e:
            return TxResult(code=1, log=str(e))

        # The tx's SINGLE gas meter (sdk runTx) carries from the ante chain
        # into execution: store access during message handling is charged
        # the KVStore schedule, and blob gas consumes against the same
        # limit (closes the round-2 store-gas PARITY deviation).
        meter = ante_res.meter
        events: list = []
        # Messages run on their own branch (baseapp runMsgs' cache): a failed
        # execution rolls back msg effects ONLY — the ante effects (fee
        # deduction, sequence bump) stay committed, so a failed tx still pays
        # its fee and cannot be replayed (msCache.Write() precedes runMsgs).
        msg_ctx = tx_ctx.branch()
        exec_ctx = msg_ctx.with_store(GasKVStore(msg_ctx.store, meter))
        try:
            for msg in tx.msgs():
                # Simulate runs on an infinite meter, so consumption can
                # legitimately exceed the fee's nominal gas_wanted — the
                # remaining-gas argument must not go negative there.
                remaining = (
                    (1 << 62) if simulate
                    else ante_res.gas_wanted - meter.consumed
                )
                used, evts = self._handle_msg(exec_ctx, msg, remaining)
                if used:
                    meter.consume(used, "execution")
                events.extend(evts)
        except OutOfGas as e:
            block_ctx.store.write_back(tx_ctx.store)  # ante effects persist
            return TxResult(
                code=11,  # sdk ErrOutOfGas
                log=str(e), gas_wanted=ante_res.gas_wanted,
                gas_used=meter.consumed,
            )
        except Exception as e:
            from celestia_app_tpu.modules.crisis import InvariantBroken

            if isinstance(e, InvariantBroken):
                # x/crisis: a broken invariant HALTS the chain (the sdk
                # panics in the crisis msg server) — converting it into a
                # failed tx would let a corrupted state keep committing.
                raise
            block_ctx.store.write_back(tx_ctx.store)  # ante effects persist
            return TxResult(
                code=2, log=str(e), gas_wanted=ante_res.gas_wanted,
                gas_used=meter.consumed,
            )
        tx_ctx.store.write_back(msg_ctx.store)
        block_ctx.store.write_back(tx_ctx.store)
        return TxResult(
            code=0, gas_wanted=ante_res.gas_wanted, gas_used=meter.consumed,
            events=events,
        )

    def _handle_msg(self, ctx: Ctx, msg, gas_remaining: int):
        if isinstance(msg, MsgSend):
            total = sum(c.amount for c in msg.amount if c.denom == "utia")
            ctx.send_spendable(msg.from_address, msg.to_address, total)
            # The sdk bank keeper creates the recipient account on first
            # receive (x/bank SendCoins -> SetAccount): a freshly funded
            # address — a multisig, say — must exist before it can sign.
            ctx.auth.get_or_create(msg.to_address)
            return 0, [("transfer", msg.from_address, msg.to_address, total)]
        if isinstance(msg, MsgSubmitEvidence):
            # Reference behavior: the evidence keeper has NO router
            # (app/app.go:348-353 never calls SetRouter), so tx-submitted
            # evidence never succeeds — equivocation evidence arrives via
            # ABCI ByzantineValidators, not txs.  Error text follows the
            # sdk's registered ErrNoEvidenceHandlerExists ("unregistered
            # handler for evidence type"); the reference's exact
            # nil-router failure shape is unverifiable in-image.
            raise ValueError(
                "unregistered handler for evidence type: "
                f"{msg.evidence.type_url}"
            )
        if isinstance(msg, MsgVerifyInvariant):
            from celestia_app_tpu.modules.crisis import INVARIANTS

            name = f"{msg.invariant_module_name}/{msg.invariant_route}"
            check = next((c for n, c in INVARIANTS if n == name), None)
            if check is None:
                raise ValueError(f"unknown invariant {name}")
            # ConstantFee: 1000utia to the fee collector (reference
            # default_overrides.go:120) — on-chain invariant checks are
            # priced so they cannot be spammed for free.
            ctx.send_spendable(msg.sender, FEE_COLLECTOR, 1000)
            # On an UNMETERED branch: the sdk runs AssertInvariants under
            # an infinite gas meter (a full-state audit must not die on
            # the tx's gas limit), and some checks settle intermediate
            # state that must not leak into consensus state.  A broken
            # invariant raises InvariantBroken, which deliver()
            # deliberately does NOT convert to a tx error — the chain
            # halts (sdk panic).
            store = (
                ctx.store.unwrap() if hasattr(ctx.store, "unwrap") else ctx.store
            )
            check(store.branch())
            return 0, [(
                "cosmos.crisis.v1beta1.EventInvariantChecked", name,
            )]
        if isinstance(msg, (
            MsgCreateVestingAccount,
            MsgCreatePeriodicVestingAccount,
            MsgCreatePermanentLockedAccount,
        )):
            from celestia_app_tpu.state.accounts import (
                VESTING_CONTINUOUS,
                VESTING_DELAYED,
                VESTING_PERIODIC,
                VESTING_PERMANENT,
            )

            if ctx.auth.get_account(msg.to_address) is not None:
                # sdk vesting msg server: the target must be brand new.
                raise ValueError(f"account {msg.to_address} already exists")
            acc = ctx.auth.get_or_create(msg.to_address)
            if isinstance(msg, MsgCreateVestingAccount):
                total = sum(c.amount for c in msg.amount if c.denom == "utia")
                acc.vesting_type = (
                    VESTING_DELAYED if msg.delayed else VESTING_CONTINUOUS
                )
                # Continuous vesting starts at the block time (sdk
                # NewContinuousVestingAccount with ctx.BlockTime); delayed
                # ignores the start.
                acc.vesting_start_ns = ctx.time_ns
                acc.vesting_end_ns = msg.end_time * 10**9
            elif isinstance(msg, MsgCreatePeriodicVestingAccount):
                total = msg.total()
                acc.vesting_type = VESTING_PERIODIC
                # Periodic vesting starts at the MSG's start_time (sdk
                # NewPeriodicVestingAccount takes it verbatim).
                acc.vesting_start_ns = msg.start_time * 10**9
                acc.vesting_periods = tuple(
                    (p.length * 10**9,
                     sum(c.amount for c in p.amount if c.denom == "utia"))
                    for p in msg.vesting_periods
                )
                acc.vesting_end_ns = acc.vesting_start_ns + sum(
                    length for length, _ in acc.vesting_periods
                )
            else:
                total = sum(c.amount for c in msg.amount if c.denom == "utia")
                acc.vesting_type = VESTING_PERMANENT
            acc.original_vesting = total
            ctx.auth.set_account(acc)
            ctx.send_spendable(msg.from_address, msg.to_address, total)
            return 0, [(
                "cosmos.vesting.v1beta1.EventCreateVestingAccount",
                msg.to_address, total, acc.vesting_type,
            )]
        if isinstance(msg, MsgMultiSend):
            # Single input (enforced by ValidateBasic, see tx/messages.py),
            # fanned out to every output; recipients are created on first
            # receive like the MsgSend path.
            src = msg.inputs[0].address
            events = []
            for out in msg.outputs:
                total = sum(c.amount for c in out.coins if c.denom == "utia")
                ctx.send_spendable(src, out.address, total)
                ctx.auth.get_or_create(out.address)
                events.append(("transfer", src, out.address, total))
            return 0, events
        if isinstance(msg, MsgAuthzExec):
            return self._handle_authz_exec(ctx, msg, gas_remaining)
        if isinstance(msg, (MsgAuthzGrant, MsgAuthzRevoke)):
            from celestia_app_tpu.modules.authz import AuthzError, AuthzKeeper, Grant

            authz = AuthzKeeper(ctx.store)
            try:
                if isinstance(msg, MsgAuthzGrant):
                    authz.grant(
                        msg.granter, msg.grantee,
                        Grant(msg.msg_type_url, msg.spend_limit, msg.expiration_ns),
                    )
                    return 0, [("cosmos.authz.v1beta1.EventGrant",
                                msg.granter, msg.grantee, msg.msg_type_url)]
                authz.revoke(msg.granter, msg.grantee, msg.msg_type_url)
                return 0, [("cosmos.authz.v1beta1.EventRevoke",
                            msg.granter, msg.grantee, msg.msg_type_url)]
            except AuthzError as e:
                raise ValueError(str(e)) from e
        if isinstance(msg, (MsgGrantAllowance, MsgRevokeAllowance)):
            from celestia_app_tpu.modules.feegrant import (
                Allowance,
                FeegrantError,
                FeegrantKeeper,
            )

            feegrant = FeegrantKeeper(ctx.store)
            try:
                if isinstance(msg, MsgGrantAllowance):
                    feegrant.grant(
                        msg.granter, msg.grantee,
                        Allowance(
                            spend_limit=msg.spend_limit,
                            expiration_ns=msg.expiration_ns,
                            allowed_msgs=msg.allowed_msgs,
                        ),
                    )
                    return 0, [("cosmos.feegrant.v1beta1.EventSetFeeGrant",
                                msg.granter, msg.grantee)]
                feegrant.revoke(msg.granter, msg.grantee)
                return 0, [("cosmos.feegrant.v1beta1.EventRevokeFeeGrant",
                            msg.granter, msg.grantee)]
            except FeegrantError as e:
                raise ValueError(str(e)) from e
        if isinstance(msg, MsgPayForBlobs):
            # keeper.PayForBlobs (x/blob/keeper/keeper.go:43-57): consume
            # shares x 512 x gasPerBlobByte, emit the event.
            gas = gas_to_consume(msg.blob_sizes, self.gas_per_blob_byte)
            if gas > gas_remaining:
                raise ValueError(
                    f"out of gas: blob gas {gas} > remaining {gas_remaining}"
                )
            return gas, [("celestia.blob.v1.EventPayForBlobs", msg.signer, msg.blob_sizes)]
        if isinstance(msg, MsgSignalVersion):
            keeper = SignalKeeper(ctx.store, ctx.staking)
            keeper.signal_version(msg.validator_address, msg.version, self.app_version)
            return 0, []
        if isinstance(msg, MsgTryUpgrade):
            keeper = SignalKeeper(ctx.store, ctx.staking)
            keeper.try_upgrade(ctx.height, self.app_version)
            return 0, []
        if isinstance(msg, (MsgTransfer, MsgRecvPacket, MsgAcknowledgement, MsgTimeout)):
            return self._handle_ibc_msg(ctx, msg)
        if isinstance(msg, (MsgCreateValidator, MsgEditValidator)):
            from celestia_app_tpu.modules.distribution import (
                DistributionError,
                DistributionKeeper,
            )
            from celestia_app_tpu.state.dec import Dec as _Dec
            from celestia_app_tpu.state.staking import StakingError

            dist = DistributionKeeper(ctx.store)
            try:
                if isinstance(msg, MsgCreateValidator):
                    self._track_vesting_delegation(
                        ctx, msg.delegator_address, msg.value.amount
                    )
                    ctx.staking.create_validator(
                        ctx.bank, dist, msg.validator_address, msg.pubkey,
                        msg.delegator_address, msg.value.amount,
                        _Dec.from_str(msg.commission_rate or "0").raw,
                        msg.min_self_delegation,
                    )
                    # The bounds the operator declared bind every later edit.
                    dist.set_commission_bounds(
                        msg.validator_address,
                        _Dec.from_str(msg.commission_max_rate or "1"),
                        _Dec.from_str(msg.commission_max_change_rate or "1"),
                    )
                    return 0, [("cosmos.staking.v1beta1.EventCreateValidator",
                                msg.validator_address, msg.value.amount)]
                if not ctx.staking.has_validator(msg.validator_address):
                    raise ValueError(f"no validator {msg.validator_address}")
                if msg.commission_rate:
                    dist.change_commission_rate(
                        msg.validator_address, _Dec.from_str(msg.commission_rate)
                    )
                return 0, [("cosmos.staking.v1beta1.EventEditValidator",
                            msg.validator_address)]
            except (StakingError, DistributionError) as e:
                raise ValueError(str(e)) from e
        if isinstance(msg, (MsgDelegate, MsgUndelegate, MsgBeginRedelegate)):
            if msg.amount.denom != "utia":  # x/staking ErrBadDenom
                raise ValueError(
                    f"invalid bond denom {msg.amount.denom!r}, expected utia"
                )
            amount = msg.amount.amount
            # Settle pending rewards before the stake changes (the sdk's
            # BeforeDelegationSharesModified hook; x/distribution hooks.go).
            from celestia_app_tpu.modules.distribution import DistributionKeeper

            dist = DistributionKeeper(ctx.store)
            dist.settle(ctx.staking, msg.delegator_address, msg.validator_address)
            if isinstance(msg, MsgBeginRedelegate):
                dist.settle(
                    ctx.staking, msg.delegator_address, msg.validator_dst_address
                )
            if isinstance(msg, MsgDelegate):
                self._track_vesting_delegation(ctx, msg.delegator_address, amount)
                ctx.staking.delegate(
                    ctx.bank, msg.delegator_address, msg.validator_address, amount
                )
                return 0, [("cosmos.staking.v1beta1.EventDelegate",
                            msg.validator_address, amount)]
            if isinstance(msg, MsgUndelegate):
                # No vesting bookkeeping here: the tokens return at
                # unbonding COMPLETION (end blocker), and that's when the
                # lock re-encumbers them (sdk TrackUndelegation runs at
                # CompleteUnbonding) — untracking now would freeze the
                # account's liquid funds for the whole unbonding window.
                completion = ctx.staking.undelegate(
                    ctx.bank, msg.delegator_address, msg.validator_address,
                    amount, ctx.time_ns, height=ctx.height,
                )
                # An operator undelegating below its declared
                # min_self_delegation is jailed (sdk Undelegate's
                # jailValidator path): no skin in the game, no vote.
                min_self = ctx.staking.min_self_delegation(msg.validator_address)
                if (
                    msg.delegator_address == msg.validator_address
                    and min_self
                    and ctx.staking.delegation(
                        msg.delegator_address, msg.validator_address
                    ) < min_self
                    and not ctx.staking.is_jailed(msg.validator_address)
                ):
                    ctx.staking.jail(msg.validator_address)
                return 0, [("cosmos.staking.v1beta1.EventUnbond",
                            msg.validator_address, amount, completion)]
            ctx.staking.begin_redelegate(
                msg.delegator_address, msg.validator_address,
                msg.validator_dst_address, amount,
            )
            # Same skin-in-the-game rule as the undelegate path: an operator
            # redelegating its self-bond below min_self_delegation is jailed
            # (sdk BeginRedelegate jails the source validator too).
            min_self = ctx.staking.min_self_delegation(msg.validator_address)
            if (
                msg.delegator_address == msg.validator_address
                and min_self
                and ctx.staking.delegation(
                    msg.delegator_address, msg.validator_address
                ) < min_self
                and not ctx.staking.is_jailed(msg.validator_address)
            ):
                ctx.staking.jail(msg.validator_address)
            return 0, [("cosmos.staking.v1beta1.EventRedelegate",
                        msg.validator_address, msg.validator_dst_address, amount)]
        if isinstance(msg, MsgCancelUnbondingDelegation):
            from celestia_app_tpu.modules.distribution import DistributionKeeper
            from celestia_app_tpu.state.staking import StakingError

            # Settle pending rewards before shares change (the same
            # BeforeDelegationSharesModified hook the delegate path runs).
            DistributionKeeper(ctx.store).settle(
                ctx.staking, msg.delegator_address, msg.validator_address
            )
            try:
                ctx.staking.cancel_unbonding(
                    ctx.bank, msg.delegator_address, msg.validator_address,
                    msg.amount.amount, msg.creation_height, ctx.time_ns,
                )
            except StakingError as e:
                raise ValueError(str(e)) from e
            return 0, [(
                "cosmos.staking.v1beta1.EventCancelUnbondingDelegation",
                msg.validator_address, msg.amount.amount, msg.creation_height,
            )]
        if isinstance(msg, MsgUnjail):
            from celestia_app_tpu.modules.slashing import (
                SlashingError,
                SlashingKeeper,
            )

            try:
                SlashingKeeper(ctx.store).unjail(
                    ctx.staking, msg.validator_address, ctx.time_ns
                )
            except SlashingError as e:
                raise ValueError(str(e)) from e
            return 0, [("cosmos.slashing.v1beta1.EventUnjail", msg.validator_address)]
        if isinstance(
            msg,
            (
                MsgWithdrawDelegatorReward,
                MsgWithdrawValidatorCommission,
                MsgSetWithdrawAddress,
                MsgFundCommunityPool,
            ),
        ):
            from celestia_app_tpu.modules.distribution import (
                DistributionError,
                DistributionKeeper,
            )

            dist = DistributionKeeper(ctx.store)
            try:
                if isinstance(msg, MsgWithdrawDelegatorReward):
                    paid = dist.withdraw_rewards(
                        ctx.bank, ctx.staking,
                        msg.delegator_address, msg.validator_address,
                    )
                    return 0, [(
                        "cosmos.distribution.v1beta1.EventWithdrawRewards",
                        msg.validator_address, paid,
                    )]
                if isinstance(msg, MsgWithdrawValidatorCommission):
                    paid = dist.withdraw_commission(ctx.bank, msg.validator_address)
                    return 0, [(
                        "cosmos.distribution.v1beta1.EventWithdrawCommission", paid,
                    )]
                if isinstance(msg, MsgSetWithdrawAddress):
                    dist.set_withdraw_address(
                        msg.delegator_address, msg.withdraw_address
                    )
                    return 0, []
                total = sum(c.amount for c in msg.amount if c.denom == "utia")
                ctx.assert_spendable(msg.depositor, total)
                dist.fund_community_pool(ctx.bank, msg.depositor, total)
                return 0, [(
                    "cosmos.distribution.v1beta1.EventFundCommunityPool", total,
                )]
            except DistributionError as e:
                raise ValueError(str(e)) from e
        if isinstance(msg, (
            MsgSubmitProposal, MsgSubmitProposalV1,
            MsgVote, MsgVoteV1, MsgVoteWeighted, MsgVoteWeightedV1,
            MsgDeposit, MsgDepositV1,
        )):
            from celestia_app_tpu.modules.gov import GovKeeper, ParamChange

            gov = GovKeeper(ctx.store, ctx.staking, ctx.bank)
            if isinstance(msg, MsgSubmitProposalV1):
                # gov v1: the single MsgExecLegacyContent's Content maps
                # onto the same proposal shape the v1beta1 surface takes
                # (the gov router executes legacy Content only).
                from celestia_app_tpu.tx.messages import _parse_gov_content

                exec_msg = msg.legacy_content()
                (
                    _title, _desc, v1_changes, spend_recipient, spend_amount,
                ) = _parse_gov_content(exec_msg.content)
                msg = MsgSubmitProposal(
                    _title, _desc, v1_changes, msg.initial_deposit,
                    msg.proposer, spend_recipient, spend_amount,
                )
            if isinstance(msg, MsgSubmitProposal):
                deposit = sum(c.amount for c in msg.initial_deposit if c.denom == "utia")
                ctx.assert_spendable(msg.proposer, deposit)
                spend = None
                if msg.spend_recipient:
                    spend = (
                        msg.spend_recipient,
                        sum(c.amount for c in msg.spend_amount if c.denom == "utia"),
                    )
                pid = gov.submit(
                    msg.proposer,
                    [ParamChange(c.subspace, c.key, c.value) for c in msg.changes],
                    deposit,
                    ctx.time_ns,
                    spend=spend,
                )
                return 0, [("cosmos.gov.v1beta1.EventSubmitProposal", pid)]
            if isinstance(msg, (MsgVote, MsgVoteV1)):
                gov.vote(msg.proposal_id, msg.voter, msg.option, ctx.time_ns)
                return 0, [("cosmos.gov.v1beta1.EventVote", msg.proposal_id, msg.voter)]
            if isinstance(msg, (MsgVoteWeighted, MsgVoteWeightedV1)):
                from celestia_app_tpu.modules.gov import VoteOption
                from celestia_app_tpu.state.dec import Dec

                gov.vote_weighted(
                    msg.proposal_id, msg.voter,
                    [(VoteOption(o), Dec.from_str(w)) for o, w in msg.options],
                    ctx.time_ns,
                )
                return 0, [("cosmos.gov.v1beta1.EventVote", msg.proposal_id, msg.voter)]
            deposit = sum(c.amount for c in msg.amount if c.denom == "utia")
            ctx.assert_spendable(msg.depositor, deposit)
            gov.deposit(msg.proposal_id, msg.depositor, deposit, ctx.time_ns)
            return 0, [("cosmos.gov.v1beta1.EventDeposit", msg.proposal_id, deposit)]
        raise ValueError(f"no handler for {type(msg).__name__}")

    @staticmethod
    def _track_vesting_delegation(ctx: Ctx, delegator: str, amount: int) -> None:
        """Vesting bookkeeping BEFORE a staking escrow moves: delegations
        (incl. a create-validator self-bond) consume locked tokens first
        (sdk TrackDelegation), so a vesting account's later-received
        liquid funds stay spendable."""
        acc = ctx.auth.get_account(delegator)
        if acc is not None and acc.vesting_type:
            acc.track_delegation(amount, ctx.time_ns)
            ctx.auth.set_account(acc)

    def _handle_authz_exec(self, ctx: Ctx, msg, gas_remaining: int):
        """MsgExec (sdk authz DispatchActions): each inner msg's signer is
        the GRANTER; the grant (granter -> grantee=tx signer, msg type) is
        checked-and-consumed, then the msg runs through the normal
        handlers.  PFBs cannot ride in an exec (blobs only travel in
        BlobTxs), matching the reference's gatekeeping."""
        from celestia_app_tpu.modules.authz import AuthzError, AuthzKeeper

        authz = AuthzKeeper(ctx.store)
        gas_total, events = 0, []
        for inner in msg.inner_msgs():
            if isinstance(inner, (MsgPayForBlobs, MsgAuthzExec)):
                raise ValueError(
                    f"{type(inner).__name__} cannot be nested in MsgExec"
                )
            granter = getattr(inner, "signer", None) or getattr(
                inner, "from_address", None
            )
            if not granter:
                raise ValueError(
                    f"cannot determine granter for {type(inner).__name__}"
                )
            try:
                authz.accept(granter, msg.grantee, inner, ctx.time_ns)
            except AuthzError as e:
                raise ValueError(str(e)) from e
            used, evts = self._handle_msg(ctx, inner, gas_remaining - gas_total)
            gas_total += used
            events.extend(evts)
        return gas_total, events

    def _handle_ibc_msg(self, ctx: Ctx, msg):
        """Transfer sends + the three relay callbacks through the versioned
        middleware stack (tokenfilter > PFM [v2] > transfer,
        app/app.go:329-346)."""
        from celestia_app_tpu.modules.ibc import (
            ChannelKeeper,
            Height,
            TransferKeeper,
            build_transfer_stack,
        )

        from celestia_app_tpu.modules.ibc.transfer import ack_is_error

        channels = ChannelKeeper(ctx.store)
        if isinstance(msg, MsgTransfer):
            if msg.token.denom == "utia":
                # Escrow is an outflow: vesting tokens cannot leave via IBC.
                ctx.assert_spendable(msg.sender, msg.token.amount)
            keeper = TransferKeeper(channels, ctx.bank)
            packet = keeper.send_transfer(
                source_channel=msg.source_channel,
                sender=msg.sender,
                receiver=msg.receiver,
                denom=msg.token.denom,
                amount=msg.token.amount,
                timeout_height=Height(
                    msg.timeout_revision_number, msg.timeout_revision_height
                ),
                timeout_timestamp_ns=msg.timeout_timestamp_ns,
                memo=msg.memo,
                source_port=msg.source_port,
            )
            return 0, [("ibc.send_packet", packet.marshal().hex())]
        if isinstance(msg, MsgRecvPacket):
            packet = msg.packet()
            # Redundant relays are no-op successes in DeliverTx (ibc-go
            # ErrNoOpMsg), so a racing relayer's batched siblings survive.
            if channels.has_receipt(packet):
                return 0, [("ibc.noop", "recv", packet.sequence)]
            dest_chan = channels.channel(
                packet.destination_port, packet.destination_channel
            )
            if dest_chan.connection_id:
                # Connection-backed channel: the packet commitment must be
                # PROVEN in the sender's state through the light client.
                from celestia_app_tpu.modules.ibc.handshake import verify_recv_proof

                verify_recv_proof(
                    ctx.store, dest_chan, packet,
                    msg.state_proof(), msg.proof_height,
                )
            channels.recv_packet(packet, ctx.height, ctx.time_ns)
            # The app callback runs on a cache; its state lands only when
            # the ack is a success (ibc-go msg_server.go RecvPacket's
            # cacheCtx) — an error ack must not leave minted vouchers or
            # half-done forwards behind.  The destination port routes to
            # the app module (ibc-go's port router): transfer or icahost.
            recv_ctx = ctx.branch()
            from celestia_app_tpu.modules.ibc.ica import ICA_HOST_PORT

            if packet.destination_port == ICA_HOST_PORT:
                if self.app_version < 2:
                    raise ValueError(
                        "icahost is a v2 module (app/modules.go:185-187)"
                    )
                from celestia_app_tpu.modules.ibc.ica import (
                    ICAHostKeeper,
                    ICAHostModule,
                )

                ica = ICAHostModule(
                    ICAHostKeeper(recv_ctx.store), self._handle_msg
                )
                ack, recv_events = ica.on_recv_packet(recv_ctx, packet)
            else:
                recv_keeper = TransferKeeper(
                    ChannelKeeper(recv_ctx.store), recv_ctx.bank
                )
                stack = build_transfer_stack(
                    self.app_version, recv_keeper,
                    token_filter=self.ibc_token_filter,
                )
                ack = stack.on_recv_packet(recv_ctx, packet)
                # Middleware (PFM) may have sent onward packets during recv.
                recv_events = [
                    ("ibc.send_packet", p.marshal().hex()) for p in recv_keeper.sent
                ]
            events = [("ibc.write_acknowledgement", packet.marshal().hex(), ack.hex())]
            if not ack_is_error(ack):
                ctx.store.write_back(recv_ctx.store)
                events += recv_events
            channels.write_acknowledgement(packet, ack)
            return 0, events
        from celestia_app_tpu.modules.ibc.ica import (
            CONTROLLER_PORT_PREFIX,
            ICA_HOST_PORT,
        )

        def _ica_port(port: str) -> bool:
            # Port routing (ibc-go's router): the ONLY non-transfer app
            # here is ICA; every other port belongs to the transfer app
            # (send_transfer escrows for arbitrary ports, so the refund
            # callbacks must fire for them too).
            return port == ICA_HOST_PORT or port.startswith(CONTROLLER_PORT_PREFIX)

        keeper = TransferKeeper(channels, ctx.bank)
        stack = build_transfer_stack(
            self.app_version, keeper, token_filter=self.ibc_token_filter
        )
        if isinstance(msg, MsgAcknowledgement):
            packet = msg.packet()
            if channels.packet_commitment(
                packet.source_port, packet.source_channel, packet.sequence
            ) is None:
                return 0, [("ibc.noop", "ack", packet.sequence)]
            src_chan = channels.channel(packet.source_port, packet.source_channel)
            if src_chan.connection_id:
                from celestia_app_tpu.modules.ibc.handshake import verify_ack_proof

                verify_ack_proof(
                    ctx.store, src_chan, packet, msg.acknowledgement,
                    msg.state_proof(), msg.proof_height,
                )
            channels.acknowledge_packet(packet)
            # Only ICA acks bypass the transfer app's refund-on-error
            # callback; an ICA controller's ack just clears the commitment.
            if not _ica_port(packet.source_port):
                stack.on_acknowledgement_packet(ctx, packet, msg.acknowledgement)
            return 0, [("ibc.acknowledge_packet", packet.sequence)]
        packet = msg.packet()  # MsgTimeout
        if channels.packet_commitment(
            packet.source_port, packet.source_channel, packet.sequence
        ) is None:
            return 0, [("ibc.noop", "timeout", packet.sequence)]
        src_chan = channels.channel(packet.source_port, packet.source_channel)
        if src_chan.connection_id:
            # Proven non-receipt on the counterparty at the proof height;
            # the timestamp bound comes from the counterparty's ATTESTED
            # consensus time at that height, never the local clock (a
            # lagging local clock would otherwise let the sender refund
            # escrow while the receiver could still accept the packet).
            from celestia_app_tpu.modules.ibc.handshake import (
                counterparty_proof_time,
                verify_timeout_proof,
            )

            verify_timeout_proof(
                ctx.store, src_chan, packet, msg.state_proof(), msg.proof_height
            )
            proof_time_ns = counterparty_proof_time(
                ctx.store, src_chan, msg.proof_height
            )
        else:
            # Harness-direct channels (no connection/client): trusted mode.
            proof_time_ns = ctx.time_ns
        channels.timeout_packet(packet, msg.proof_height, proof_time_ns)
        if not _ica_port(packet.source_port):
            stack.on_timeout_packet(ctx, packet)
        return 0, [("ibc.timeout_packet", packet.sequence)]

    def _end_block(self, ctx: Ctx, height: int) -> None:
        """Gov clocks + blobstream (v1 only) + height/signal upgrades
        (app/app.go:458-477)."""
        from celestia_app_tpu.modules.gov import GovKeeper

        GovKeeper(ctx.store, ctx.staking, ctx.bank).end_blocker(ctx.time_ns)
        # Matured unbonding delegations release back to delegators
        # (x/staking EndBlocker's unbonding queue); returning tokens
        # re-encumber a vesting account's lock (sdk TrackUndelegation at
        # CompleteUnbonding).
        for delegator, amount in ctx.staking.complete_unbondings(
            ctx.bank, ctx.time_ns
        ):
            acc = ctx.auth.get_account(delegator)
            if acc is not None and acc.vesting_type:
                acc.track_undelegation(amount)
                ctx.auth.set_account(acc)
        if self.app_version == 1:
            from celestia_app_tpu.modules.blobstream.keeper import BlobstreamKeeper

            BlobstreamKeeper(ctx.store, ctx.staking).end_blocker(height, ctx.time_ns)
        if (
            self.app_version == 1
            and self.v2_upgrade_height is not None
            and height >= self.v2_upgrade_height
        ):
            from celestia_app_tpu.app.module_manager import ModuleManager

            ModuleManager().run_migrations(ctx, 1, 2)
            self.app_version = 2
            return
        if self.app_version >= 2:
            keeper = SignalKeeper(ctx.store, ctx.staking)
            up = keeper.should_upgrade(height)
            if up is not None:
                from celestia_app_tpu.app.module_manager import ModuleManager

                ModuleManager().run_migrations(ctx, self.app_version, up.app_version)
                self.app_version = up.app_version
                keeper.reset_tally()

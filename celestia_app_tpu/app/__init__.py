from celestia_app_tpu.app.app import (
    App,
    BlockData,
    Genesis,
    GenesisAccount,
    TxResult,
)
from celestia_app_tpu.app.ante import AnteError, run_ante

__all__ = [
    "App",
    "BlockData",
    "Genesis",
    "GenesisAccount",
    "TxResult",
    "AnteError",
    "run_ante",
]

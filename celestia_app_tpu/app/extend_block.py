"""Library entry turning block data into an EDS (reference app/extend_block.go).

Used by the consensus layer (the reference's celestia-core fork calls
ExtendBlock on every committed block) and by availability tooling: rebuild
the square from the block's txs and erasure-extend it on the device.
"""

from __future__ import annotations

from celestia_app_tpu.constants import SQUARE_SIZE_UPPER_BOUND
from celestia_app_tpu.da import ExtendedDataSquare, extend_shares
from celestia_app_tpu.square import builder as square


def extend_block(
    raw_txs: list[bytes],
    gov_max_square_size: int = SQUARE_SIZE_UPPER_BOUND,
    square_size_upper_bound: int = SQUARE_SIZE_UPPER_BOUND,
    construction: str | None = None,
) -> ExtendedDataSquare | None:
    """coretypes.Data -> EDS (extend_block.go:14-26); None for empty blocks.

    `square_size_upper_bound` must match the chain's hard cap: a chain run
    under the benchmark-manifest override (App(square_size_upper_bound=512))
    commits squares wider than the versioned 128 default, and a clamp here
    would rebuild a DIFFERENT square with a different data root.

    The extension rides the fused/staged device seam (kernels/fused) and
    the RS construction seam: a consensus caller passes `construction` to
    pin the generator for the block's lifetime, so a mid-block
    $CELESTIA_RS_CONSTRUCTION flip can never extend with one generator and
    verify with another.  Outputs are byte-identical on every path.
    """
    if is_empty_block(raw_txs):
        return None
    sq = square.construct(
        raw_txs, min(gov_max_square_size, square_size_upper_bound)
    )
    from celestia_app_tpu.trace import traced

    # The span records the host-side cost of the whole rebuild+extend (the
    # journal row for the device half comes from ExtendedDataSquare.compute
    # inside extend_shares); no sync beyond what compute already does.
    with traced().span("extend_block", k=sq.size, n_txs=len(raw_txs)):
        return extend_shares(sq.share_bytes(), construction)


def is_empty_block(raw_txs: list[bytes]) -> bool:
    """extend_block.go:30 IsEmptyBlock: no txs means the minimal square."""
    return len(raw_txs) == 0

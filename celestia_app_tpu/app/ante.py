"""The ante handler chain: every admission check a tx passes before execution.

Behavioral parity with reference app/ante/ante.go:15-82, decorator by
decorator and in the reference's order (the per-decorator map with its
rejection tests lives in PARITY.md §ante):

   1 HandlePanicDecorator        -> run_ante's catch-all reject
   2 MsgVersioningGateKeeper     -> allowed_msg_types version gate
   3 SetUpContextDecorator       -> GasMeter(fee.gas_limit)
   4 ExtensionOptionsDecorator   -> reject critical extension options
   5 ValidateBasicDecorator      -> per-msg validate_basic + sig presence
   6 TxTimeoutHeightDecorator    -> reject past-timeout txs
   7 ValidateMemoDecorator       -> memo <= 256 chars
   8 ConsumeGasForTxSizeDecorator-> 10 gas per tx byte
   9 DeductFeeDecorator          -> ValidateTxFee (network+node min gas
                                    price, priority = gas price x 1e6,
                                    fee_checker.go:17,31-60) + deduction
  10 SetPubKeyDecorator          -> stores the pubkey on first use
  11 ValidateSigCountDecorator   -> single-signer rule (see PARITY: the
                                    sdk allows up to 7 multisig keys; this
                                    framework pins exactly one signer)
  12 SigGasConsumeDecorator      -> 1000 gas per secp256k1 signature
  13 SigVerificationDecorator    -> sequence match + DIRECT verification
  14 MinGasPFBDecorator          -> gas limit covers blob gas
  15 MaxTotalBlobSizeDecorator   -> v1 blob byte cap
  16 BlobShareDecorator          -> v2 blob share cap
  17 GovProposalDecorator        -> MsgSubmitProposal needs >= 1 message
  18 IncrementSequenceDecorator  -> sequence bump
  19 RedundantRelayDecorator     -> IBC relay dedup (modules/ibc)
"""

from __future__ import annotations

from dataclasses import dataclass, field

from celestia_app_tpu.app.gas import (
    GasKVStore,
    GasMeter,
    MAX_MEMO_CHARACTERS,
    OutOfGas,
    SIG_VERIFY_COST_SECP256K1,
    TX_SIG_LIMIT,
    TX_SIZE_COST_PER_BYTE,
)
from celestia_app_tpu.constants import CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
from celestia_app_tpu.shares.sparse import sparse_shares_needed
from celestia_app_tpu.state.accounts import FEE_COLLECTOR
from celestia_app_tpu.state.dec import Dec
from celestia_app_tpu.tx.messages import (
    MsgAcknowledgement,
    MsgAuthzExec,
    MsgAuthzGrant,
    MsgAuthzRevoke,
    MsgBeginRedelegate,
    MsgCancelUnbondingDelegation,
    MsgCreateValidator,
    MsgDelegate,
    MsgDeposit,
    MsgEditValidator,
    MsgFundCommunityPool,
    MsgCreatePeriodicVestingAccount,
    MsgCreatePermanentLockedAccount,
    MsgCreateVestingAccount,
    MsgDepositV1,
    MsgGrantAllowance,
    MsgMultiSend,
    MsgSubmitEvidence,
    MsgSubmitProposalV1,
    MsgVerifyInvariant,
    MsgVoteV1,
    MsgVoteWeightedV1,
    MsgRevokeAllowance,
    MsgPayForBlobs,
    MsgRecvPacket,
    MsgSend,
    MsgSetWithdrawAddress,
    MsgSignalVersion,
    MsgSubmitProposal,
    MsgTimeout,
    MsgTransfer,
    MsgTryUpgrade,
    MsgUndelegate,
    MsgUnjail,
    MsgVote,
    MsgVoteWeighted,
    MsgWithdrawDelegatorReward,
    MsgWithdrawValidatorCommission,
)
from celestia_app_tpu.tx.sign import Tx

PRIORITY_SCALING_FACTOR = 1_000_000  # fee_checker.go:17


class AnteError(ValueError):
    """Tx rejected by the ante chain."""


# appVersion -> allowed msg types (MsgVersioningGateKeeper,
# app/ante/msg_gatekeeper.go:18-42: signal msgs are v2+; gov and IBC msgs
# exist in every version, as x/gov and ibc are wired for v1 and v2 in
# app/modules.go:96-189).
_V1_MSGS = {
    MsgSend, MsgMultiSend, MsgPayForBlobs, MsgSubmitProposal, MsgVote,
    MsgVoteWeighted, MsgDeposit,
    MsgTransfer, MsgRecvPacket, MsgAcknowledgement, MsgTimeout,
    MsgDelegate, MsgUndelegate, MsgBeginRedelegate,
    MsgCancelUnbondingDelegation,
    MsgCreateValidator, MsgEditValidator,
    MsgWithdrawDelegatorReward, MsgWithdrawValidatorCommission,
    MsgSetWithdrawAddress, MsgFundCommunityPool, MsgUnjail,
    MsgGrantAllowance, MsgRevokeAllowance,
    MsgAuthzGrant, MsgAuthzExec, MsgAuthzRevoke,
    MsgCreateVestingAccount, MsgVerifyInvariant, MsgSubmitEvidence,
    MsgCreatePeriodicVestingAccount, MsgCreatePermanentLockedAccount,
    MsgSubmitProposalV1, MsgVoteV1, MsgVoteWeightedV1, MsgDepositV1,
}
_V2_MSGS = _V1_MSGS | {MsgSignalVersion, MsgTryUpgrade}


def allowed_msg_types(app_version: int) -> set[type]:
    return _V1_MSGS if app_version <= 1 else _V2_MSGS


@dataclass
class AnteResult:
    priority: int = 0
    gas_wanted: int = 0
    gas_consumed: int = 0  # meter reading after the chain (size+sig+store gas)
    signer: str = ""
    events: list = field(default_factory=list)
    # The tx's single gas meter (sdk runTx): execution continues on it so
    # store access during message handling is charged too.
    meter: GasMeter | None = None


def run_ante(
    app,
    ctx,
    tx: Tx,
    *,
    is_check_tx: bool,
    simulate: bool = False,
    tx_bytes: bytes | None = None,
) -> AnteResult:
    """Run the full chain against `ctx` (a branched state view).

    Raises AnteError on any rejection; mutates ctx state (sequence bump,
    fee deduction) on success, exactly like the reference chain.
    `tx_bytes` is the delivered tx encoding (the inner tx for a BlobTx),
    metered by ConsumeGasForTxSizeDecorator; None skips size gas (some
    internal callers have no wire encoding).

    The chain runs on a per-tx branch of `ctx` that is written back only on
    success (baseapp runTx's cacheTxContext around the ante handler): a
    rejection after fee deduction must not leave the fee deducted in a
    shared check/filter state.
    """
    tx_ctx = ctx.branch()
    try:
        res = _run(
            app, tx_ctx, tx, is_check_tx=is_check_tx, simulate=simulate,
            tx_bytes=tx_bytes,
        )
    except AnteError:
        raise
    except OutOfGas:
        # Gas exhaustion keeps its type: baseapp runTx returns sdk
        # ErrOutOfGas (code 11) whether the meter ran out in the ante
        # chain or in execution — check/deliver map it to code 11 there.
        raise
    except Exception as e:  # HandlePanicDecorator: panic -> reject, not crash
        raise AnteError(f"internal error in ante chain: {e!r}") from e
    ctx.store.write_back(tx_ctx.store)
    return res


def _run(
    app, ctx, tx: Tx, *, is_check_tx: bool, simulate: bool, tx_bytes: bytes | None
) -> AnteResult:
    from celestia_app_tpu.tx.messages import decode_msg

    body = tx.body  # parsed once; msgs() would re-unmarshal the body
    msgs = [decode_msg(m) for m in body.messages]  # raises on unknown type
    if not msgs:
        raise AnteError("tx has no messages")

    # --- 2: msg version gating ---------------------------------------------
    # Nested authz msgs are gated too (the reference's MsgVersioningGateKeeper
    # unpacks MsgExec, msg_gatekeeper.go).
    allowed = allowed_msg_types(ctx.app_version)
    to_gate = list(msgs)
    for m in msgs:
        if isinstance(m, MsgAuthzExec):
            to_gate.extend(m.inner_msgs())
    for m in to_gate:
        if type(m) not in allowed:
            raise AnteError(
                f"message {type(m).__name__} not allowed at app version {ctx.app_version}"
            )

    # --- 3: gas meter setup (SetUpContextDecorator) --------------------------
    auth = tx.auth_info
    fee = auth.fee
    if fee.gas_limit == 0 and not simulate:
        # Simulate waives the limit entirely (sdk SetUpContextDecorator
        # installs an infinite meter): cosmjs's simulate() sends
        # gasLimit=0 by construction.
        raise AnteError("gas limit must be positive")
    meter = GasMeter(None if simulate else fee.gas_limit)
    # Every store access from here on is charged the sdk KVStore gas
    # schedule (gaskv wrapping in baseapp's runTx context).
    ctx = ctx.with_store(GasKVStore(ctx.store, meter))

    # --- 4: extension options (RejectExtensionOptionsDecorator: any critical
    # extension option rejects; non-critical ones pass by definition) ---------
    if body.extension_options:
        raise AnteError("unknown extension options")

    # --- 5: ValidateBasic --------------------------------------------------
    if not tx.signatures or any(not s for s in tx.signatures):
        raise AnteError("tx must contain signatures")
    for m in msgs:
        vb = getattr(m, "validate_basic", None)
        if vb is not None:
            try:
                vb()
            except ValueError as e:
                raise AnteError(str(e)) from e

    # --- 6: timeout height ---------------------------------------------------
    if body.timeout_height and ctx.height > body.timeout_height:
        raise AnteError(
            f"tx timeout height {body.timeout_height} exceeded, block height {ctx.height}"
        )

    # --- 7: memo length ------------------------------------------------------
    if len(body.memo) > MAX_MEMO_CHARACTERS:
        raise AnteError(
            f"maximum number of characters is {MAX_MEMO_CHARACTERS} "
            f"but received {len(body.memo)}"
        )

    # --- 8: tx size gas ------------------------------------------------------
    if tx_bytes is not None:
        meter.consume(len(tx_bytes) * TX_SIZE_COST_PER_BYTE, "txSize")

    # --- 9: fee checks (ValidateTxFee) + deduction ---------------------------
    fee_utia = sum(c.amount for c in fee.amount if c.denom == "utia")
    # gas_limit can be 0 only under Simulate (checked at step 3), where
    # the min-gas-price comparisons are skipped anyway.
    gas_price = (
        Dec(0) if fee.gas_limit == 0
        else Dec.from_fraction(fee_utia, fee.gas_limit)
    )
    # Error strings follow the sdk wording so clients can parse the required
    # fee and retry (app/errors/insufficient_gas_price.go:23).
    net_min = app.minfee.network_min_gas_price()
    if gas_price < net_min and not simulate:
        required = net_min.mul_int(fee.gas_limit).ceil_int()
        raise AnteError(
            f"insufficient fees; got: {fee_utia}utia required: {required}utia"
        )
    if is_check_tx and not simulate:
        node_min = app.node_min_gas_price
        if gas_price < node_min:
            required = node_min.mul_int(fee.gas_limit).ceil_int()
            raise AnteError(
                f"insufficient fees; got: {fee_utia}utia required: {required}utia"
            )
    priority = gas_price.mul_int(PRIORITY_SCALING_FACTOR).truncate_int()

    # Resolve the signer before moving money (DeductFee needs the fee payer —
    # the first signer, pkg/user single-signer rule).  The one signer may
    # be a threshold multisig (LegacyAminoPubKey): the sdk default ante
    # admits <= TxSigLimit = 7 sub-keys (NewValidateSigCountDecorator,
    # app/ante/ante.go:15-82).
    from celestia_app_tpu.tx.multisig import MultisigPubKey

    if len(auth.signer_infos) != 1 or len(tx.signatures) != 1:
        raise AnteError("exactly one signer required")
    info = auth.signer_infos[0]
    is_multisig = isinstance(info.public_key, MultisigPubKey)
    sub_keys = len(info.public_key.public_keys) if is_multisig else 1
    if sub_keys > TX_SIG_LIMIT:
        raise AnteError(
            f"signatures: {sub_keys}, limit: {TX_SIG_LIMIT}"
        )
    signer_addr = info.public_key.address()
    acc = ctx.auth.get_account(signer_addr)
    if acc is None:
        raise AnteError(f"account {signer_addr} not found")
    # Fee deduction precedes signature verification in the reference chain
    # (DeductFeeDecorator at ante.go:46-49 vs SigVerification at :60-63), so
    # an underfunded fee payer surfaces as insufficient funds even when the
    # signature is also bad.  The branch is discarded on rejection.
    # Fee.granter routes payment through an x/feegrant allowance (the sdk's
    # DeductFeeDecorator feegrant path; txsim's master account pays its
    # sub-accounts' fees this way, test/txsim/account.go:238-239).
    # An explicit Fee.payer must be the signer: honoring a third-party
    # payer would charge an account that never signed (the sdk requires
    # the payer to be a tx signer; with single-signer txs that means the
    # signer itself).  Silently ignoring the field would debit the wrong
    # account from the client's point of view.
    if fee.payer and fee.payer != signer_addr:
        raise AnteError(
            f"fee payer {fee.payer} must be the tx signer {signer_addr}"
        )
    fee_payer = signer_addr
    if fee.granter:
        from celestia_app_tpu.modules.feegrant import FeegrantError, FeegrantKeeper

        try:
            FeegrantKeeper(ctx.store).use_grant(
                fee.granter, signer_addr, fee_utia,
                [type(m).TYPE_URL for m in msgs], ctx.time_ns,
            )
        except FeegrantError as e:
            raise AnteError(str(e)) from e
        fee_payer = fee.granter
    if fee_utia:
        try:
            # Vesting-aware: fees cannot spend still-vesting tokens.
            ctx.send_spendable(fee_payer, FEE_COLLECTOR, fee_utia)
        except ValueError as e:
            raise AnteError(str(e)) from e

    # --- 10-13: pubkey, sig count, sig gas, sig verification -----------------
    for m in msgs:
        expected = getattr(m, "signer", None) or getattr(m, "from_address", None) or getattr(
            m, "validator_address", None
        )
        if expected and expected != signer_addr:
            raise AnteError(f"message signer {expected} != tx signer {signer_addr}")
    # Sig gas per participating sub-signature (the sdk's
    # ConsumeMultisignatureVerificationGas; 1 for a plain key).
    n_sigs = (
        sum(1 for b in (info.mode_bits or ()) if b) if is_multisig else 1
    )
    meter.consume(
        SIG_VERIFY_COST_SECP256K1 * max(n_sigs, 1), "ante verify: secp256k1"
    )
    if info.sequence != acc.sequence:
        raise AnteError(
            f"account sequence mismatch, expected {acc.sequence}, got {info.sequence}"
        )
    if not simulate and not tx.verify_signature(app.chain_id, acc.account_number):
        raise AnteError("signature verification failed")

    # --- 14-16: x/blob ante --------------------------------------------------
    for m in msgs:
        if isinstance(m, MsgPayForBlobs):
            if not simulate:
                # MinGasPFBDecorator reads the meter's limit, which is
                # infinite under Simulate — a placeholder fee gas limit
                # must not fail the estimation call.
                _check_pfb_gas(m, fee.gas_limit, app.gas_per_blob_byte)
            _check_blob_shares(m, app.gov_max_square_size, ctx.app_version)

    # --- 17: gov proposals ---------------------------------------------------
    _check_gov_proposals(msgs)

    # --- 19: redundant IBC relays (CheckTx only, as the reference's
    # RedundantRelayDecorator protects the mempool without affecting
    # consensus) ---------------------------------------------------------------
    if is_check_tx:
        _check_redundant_relays(ctx, msgs)

    # --- 18: sequence increment + pubkey persistence -------------------------
    if acc.pubkey == b"":
        # Multisig keys persist their proto value bytes (sdk stores the
        # whole LegacyAminoPubKey on the account the same way).
        acc.pubkey = (
            info.public_key.value_bytes()
            if is_multisig
            else info.public_key.bytes
        )
    acc.sequence += 1
    ctx.auth.set_account(acc)

    return AnteResult(
        priority=priority,
        gas_wanted=fee.gas_limit,
        gas_consumed=meter.consumed,
        signer=signer_addr,
        meter=meter,
    )


def _check_gov_proposals(msgs: list) -> None:
    """GovProposalDecorator (app/ante/gov.go): a MsgSubmitProposal with no
    inner messages is rejected before it can reach the gov keeper.  The
    v1 msg needs no branch here: MsgSubmitProposalV1.validate_basic (ante
    step 5) already rejects anything but exactly one legacy-content
    message, which subsumes the empty case."""
    for m in msgs:
        if (
            isinstance(m, MsgSubmitProposal)
            and not m.changes
            and not m.spend_recipient
        ):
            raise AnteError("proposal must contain at least one message")


def _check_redundant_relays(ctx, msgs: list) -> None:
    """RedundantRelayDecorator (ibc-go core/ante): a CheckTx-only guard —
    if the tx carries relay messages and EVERY one of them is a no-op
    (packet already received / already acked or timed out), reject so
    redundant relays never occupy the mempool."""
    from celestia_app_tpu.modules.ibc.core import ChannelKeeper

    relay_msgs = [
        m for m in msgs if isinstance(m, (MsgRecvPacket, MsgAcknowledgement, MsgTimeout))
    ]
    if not relay_msgs:
        return
    channels = ChannelKeeper(ctx.store)
    for m in relay_msgs:
        packet = m.packet()
        if isinstance(m, MsgRecvPacket):
            if not channels.has_receipt(packet):
                return  # at least one effective message
        else:  # ack / timeout: effective iff the commitment still exists
            if channels.packet_commitment(
                packet.source_port, packet.source_channel, packet.sequence
            ) is not None:
                return
    raise AnteError("tx contains only redundant IBC relay messages")


def _check_pfb_gas(msg: MsgPayForBlobs, gas_limit: int, gas_per_blob_byte: int) -> None:
    """MinGasPFBDecorator: the gas limit must cover the blob gas."""
    from celestia_app_tpu.modules.blob.types import gas_to_consume

    needed = gas_to_consume(msg.blob_sizes, gas_per_blob_byte)
    if gas_limit < needed:
        raise AnteError(
            f"gas limit {gas_limit} insufficient for blobs needing {needed}"
        )


def _check_blob_shares(
    msg: MsgPayForBlobs, gov_max_square_size: int, app_version: int
) -> None:
    """BlobShareDecorator (v2) / MaxTotalBlobSize (v1): blobs must be able to
    fit a square at all."""
    cap = gov_max_square_size * gov_max_square_size
    if app_version <= 1:
        # v1: bound total blob *bytes* by the square capacity
        # (x/blob/ante/max_total_blob_size_ante.go:25).
        max_bytes = cap * CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
        if sum(msg.blob_sizes) > max_bytes:
            raise AnteError(f"total blob size exceeds {max_bytes} bytes")
    else:
        shares = sum(sparse_shares_needed(s) for s in msg.blob_sizes)
        if shares > cap:
            raise AnteError(
                f"blobs need {shares} shares > square capacity {cap}"
            )

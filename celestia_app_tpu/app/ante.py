"""The ante handler chain: every admission check a tx passes before execution.

Behavioral parity with reference app/ante/ante.go:15-82 (the 17-decorator
chain), collapsed to the decorators with observable behavior in this
framework:

  * panic containment (HandlePanicDecorator, app/ante/panic.go)
  * message-version gating (MsgVersioningGateKeeper, app/ante/msg_gatekeeper.go)
  * fee validation: gas price >= max(node min [CheckTx only], network min),
    priority = gas price x 1e6 (ValidateTxFee, app/ante/fee_checker.go:31-60)
  * signature + account checks: pubkey, account number, sequence, DIRECT
    mode verification (sdk SigVerificationDecorator analog)
  * fee deduction to the fee collector
  * x/blob ante: MinGasPFBDecorator + BlobShareDecorator
    (x/blob/ante/ante.go:25, blob_share_decorator.go:27)
  * sequence increment
"""

from __future__ import annotations

from dataclasses import dataclass, field

from celestia_app_tpu.constants import CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
from celestia_app_tpu.shares.sparse import sparse_shares_needed
from celestia_app_tpu.state.accounts import FEE_COLLECTOR
from celestia_app_tpu.state.dec import Dec
from celestia_app_tpu.tx.messages import (
    MsgPayForBlobs,
    MsgSend,
    MsgSignalVersion,
    MsgTryUpgrade,
)
from celestia_app_tpu.tx.sign import Tx

PRIORITY_SCALING_FACTOR = 1_000_000  # fee_checker.go:17


class AnteError(ValueError):
    """Tx rejected by the ante chain."""


# appVersion -> allowed msg types (MsgVersioningGateKeeper,
# app/ante/msg_gatekeeper.go:18-42: signal msgs are v2+).
_V1_MSGS = {MsgSend, MsgPayForBlobs}
_V2_MSGS = _V1_MSGS | {MsgSignalVersion, MsgTryUpgrade}


def allowed_msg_types(app_version: int) -> set[type]:
    return _V1_MSGS if app_version <= 1 else _V2_MSGS


@dataclass
class AnteResult:
    priority: int = 0
    gas_wanted: int = 0
    signer: str = ""
    events: list = field(default_factory=list)


def run_ante(
    app,
    ctx,
    tx: Tx,
    *,
    is_check_tx: bool,
    simulate: bool = False,
) -> AnteResult:
    """Run the full chain against `ctx` (a branched state view).

    Raises AnteError on any rejection; mutates ctx state (sequence bump,
    fee deduction) on success, exactly like the reference chain.
    """
    try:
        return _run(app, ctx, tx, is_check_tx=is_check_tx, simulate=simulate)
    except AnteError:
        raise
    except Exception as e:  # HandlePanicDecorator: panic -> reject, not crash
        raise AnteError(f"internal error in ante chain: {e!r}") from e


def _run(app, ctx, tx: Tx, *, is_check_tx: bool, simulate: bool) -> AnteResult:
    msgs = tx.msgs()  # raises on unknown type: unregistered msgs are rejected
    if not msgs:
        raise AnteError("tx has no messages")

    # --- msg version gating ----------------------------------------------
    allowed = allowed_msg_types(ctx.app_version)
    for m in msgs:
        if type(m) not in allowed:
            raise AnteError(
                f"message {type(m).__name__} not allowed at app version {ctx.app_version}"
            )

    # --- fee checks (ValidateTxFee) ---------------------------------------
    auth = tx.auth_info
    fee = auth.fee
    if fee.gas_limit == 0:
        raise AnteError("gas limit must be positive")
    fee_utia = sum(c.amount for c in fee.amount if c.denom == "utia")
    gas_price = Dec.from_fraction(fee_utia, fee.gas_limit)
    # Error strings follow the sdk wording so clients can parse the required
    # fee and retry (app/errors/insufficient_gas_price.go:23).
    net_min = app.minfee.network_min_gas_price()
    if gas_price < net_min and not simulate:
        required = net_min.mul_int(fee.gas_limit).ceil_int()
        raise AnteError(
            f"insufficient fees; got: {fee_utia}utia required: {required}utia"
        )
    if is_check_tx and not simulate:
        node_min = app.node_min_gas_price
        if gas_price < node_min:
            required = node_min.mul_int(fee.gas_limit).ceil_int()
            raise AnteError(
                f"insufficient fees; got: {fee_utia}utia required: {required}utia"
            )
    priority = gas_price.mul_int(PRIORITY_SCALING_FACTOR).truncate_int()

    # --- x/blob ante -------------------------------------------------------
    for m in msgs:
        if isinstance(m, MsgPayForBlobs):
            _check_pfb_gas(m, fee.gas_limit, app.gas_per_blob_byte)
            _check_blob_shares(m, app.gov_max_square_size, ctx.app_version)

    # --- account + signature -----------------------------------------------
    if len(auth.signer_infos) != 1 or len(tx.signatures) != 1:
        raise AnteError("exactly one signer required")
    info = auth.signer_infos[0]
    signer_addr = info.public_key.address()
    acc = ctx.auth.get_account(signer_addr)
    if acc is None:
        raise AnteError(f"account {signer_addr} not found")
    if info.sequence != acc.sequence:
        raise AnteError(
            f"account sequence mismatch, expected {acc.sequence}, got {info.sequence}"
        )
    for m in msgs:
        expected = getattr(m, "signer", None) or getattr(m, "from_address", None) or getattr(
            m, "validator_address", None
        )
        if expected and expected != signer_addr:
            raise AnteError(f"message signer {expected} != tx signer {signer_addr}")
    if not simulate and not tx.verify_signature(app.chain_id, acc.account_number):
        raise AnteError("signature verification failed")

    # --- fee deduction + sequence increment --------------------------------
    if fee_utia:
        try:
            ctx.bank.send(signer_addr, FEE_COLLECTOR, fee_utia)
        except ValueError as e:
            raise AnteError(str(e)) from e
    if acc.pubkey == b"":
        acc.pubkey = info.public_key.bytes
    acc.sequence += 1
    ctx.auth.set_account(acc)

    return AnteResult(priority=priority, gas_wanted=fee.gas_limit, signer=signer_addr)


def _check_pfb_gas(msg: MsgPayForBlobs, gas_limit: int, gas_per_blob_byte: int) -> None:
    """MinGasPFBDecorator: the gas limit must cover the blob gas."""
    from celestia_app_tpu.modules.blob.types import gas_to_consume

    needed = gas_to_consume(msg.blob_sizes, gas_per_blob_byte)
    if gas_limit < needed:
        raise AnteError(
            f"gas limit {gas_limit} insufficient for blobs needing {needed}"
        )


def _check_blob_shares(
    msg: MsgPayForBlobs, gov_max_square_size: int, app_version: int
) -> None:
    """BlobShareDecorator (v2) / MaxTotalBlobSize (v1): blobs must be able to
    fit a square at all."""
    cap = gov_max_square_size * gov_max_square_size
    if app_version <= 1:
        # v1: bound total blob *bytes* by the square capacity
        # (x/blob/ante/max_total_blob_size_ante.go:25).
        max_bytes = cap * CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
        if sum(msg.blob_sizes) > max_bytes:
            raise AnteError(f"total blob size exceeds {max_bytes} bytes")
    else:
        shares = sum(sparse_shares_needed(s) for s in msg.blob_sizes)
        if shares > cap:
            raise AnteError(
                f"blobs need {shares} shares > square capacity {cap}"
            )

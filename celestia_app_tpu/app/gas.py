"""Tx gas metering (the SDK gas meter the ante chain sets up).

Reference: ante.NewSetUpContextDecorator installs a sdk.GasMeter limited to
the tx's gas limit (app/ante/ante.go:33-34); ConsumeGasForTxSizeDecorator
charges TxSizeCostPerByte per tx byte and SigGasConsumeDecorator charges
the secp256k1 verification cost (ante.go:43-45,55-57), both against that
meter, with overflow surfacing as an out-of-gas rejection.  Constants are
the cosmos-sdk x/auth defaults the reference chain runs with.
"""

from __future__ import annotations

# x/auth defaults (sdk auth/types/params.go), unchanged by celestia-app.
TX_SIZE_COST_PER_BYTE = 10
SIG_VERIFY_COST_SECP256K1 = 1000
MAX_MEMO_CHARACTERS = 256
TX_SIG_LIMIT = 7


class OutOfGas(Exception):
    """Gas consumption exceeded the meter's limit."""

    def __init__(self, descriptor: str, limit: int):
        super().__init__(f"out of gas in location: {descriptor}; gasLimit: {limit}")
        self.descriptor = descriptor
        self.limit = limit


class GasMeter:
    """Monotonic counter with a hard limit (sdk store/types/gas.go).

    A `limit` of None gives an infinite meter (simulation mode).
    """

    def __init__(self, limit: int | None):
        self.limit = limit
        self.consumed = 0

    def consume(self, amount: int, descriptor: str) -> None:
        if amount < 0:
            raise ValueError(f"negative gas amount for {descriptor}")
        self.consumed += amount
        if self.limit is not None and self.consumed > self.limit:
            raise OutOfGas(descriptor, self.limit)

    def remaining(self) -> int | None:
        return None if self.limit is None else max(0, self.limit - self.consumed)

"""Tx gas metering (the SDK gas meter the ante chain sets up).

Reference: ante.NewSetUpContextDecorator installs a sdk.GasMeter limited to
the tx's gas limit (app/ante/ante.go:33-34); ConsumeGasForTxSizeDecorator
charges TxSizeCostPerByte per tx byte and SigGasConsumeDecorator charges
the secp256k1 verification cost (ante.go:43-45,55-57), both against that
meter, with overflow surfacing as an out-of-gas rejection.  Constants are
the cosmos-sdk x/auth defaults the reference chain runs with.
"""

from __future__ import annotations

# x/auth defaults (sdk auth/types/params.go), unchanged by celestia-app.
TX_SIZE_COST_PER_BYTE = 10
SIG_VERIFY_COST_SECP256K1 = 1000
MAX_MEMO_CHARACTERS = 256
TX_SIG_LIMIT = 7


class OutOfGas(Exception):
    """Gas consumption exceeded the meter's limit."""

    def __init__(self, descriptor: str, limit: int):
        super().__init__(f"out of gas in location: {descriptor}; gasLimit: {limit}")
        self.descriptor = descriptor
        self.limit = limit


class GasMeter:
    """Monotonic counter with a hard limit (sdk store/types/gas.go).

    A `limit` of None gives an infinite meter (simulation mode).
    """

    def __init__(self, limit: int | None):
        self.limit = limit
        self.consumed = 0

    def consume(self, amount: int, descriptor: str) -> None:
        if amount < 0:
            raise ValueError(f"negative gas amount for {descriptor}")
        self.consumed += amount
        if self.limit is not None and self.consumed > self.limit:
            raise OutOfGas(descriptor, self.limit)

    def remaining(self) -> int | None:
        return None if self.limit is None else max(0, self.limit - self.consumed)


# sdk store/types/gas.go KVGasConfig() — the schedule every KVStore access
# inside a tx is charged under (gaskv.Store).  The reference chain runs the
# unmodified defaults.
READ_COST_FLAT = 1000
READ_COST_PER_BYTE = 3
WRITE_COST_FLAT = 2000
WRITE_COST_PER_BYTE = 30
HAS_COST = 1000
DELETE_COST = 1000
ITER_NEXT_COST_FLAT = 30


class GasKVStore:
    """gaskv.Store: a KVStore view that charges a GasMeter per access.

    Duck-types the KVStore surface keepers consume (get/set/delete/has/
    iterate/branch/write_back).  Charges follow sdk store/gaskv/store.go:
    Get = ReadCostFlat + ReadCostPerByte*(len(key)+len(value));
    Set = WriteCostFlat + WriteCostPerByte*(len(key)+len(value));
    Has = HasCost; Delete = DeleteCost; each iterated entry =
    IterNextCostFlat + ReadCostPerByte*(len(key)+len(value)).
    Closes the round-2 PARITY gas deviation ("store-access gas is not
    charged") — VERDICT r2 missing #5.
    """

    def __init__(self, inner, meter: GasMeter):
        self._inner = inner
        self._meter = meter

    def get(self, key: bytes) -> bytes | None:
        self._meter.consume(READ_COST_FLAT, "ReadFlat")
        value = self._inner.get(key)
        self._meter.consume(
            READ_COST_PER_BYTE * (len(key) + (len(value) if value else 0)),
            "ReadPerByte",
        )
        return value

    def set(self, key: bytes, value: bytes) -> None:
        self._meter.consume(WRITE_COST_FLAT, "WriteFlat")
        self._meter.consume(
            WRITE_COST_PER_BYTE * (len(key) + len(value)), "WritePerByte"
        )
        self._inner.set(key, value)

    def delete(self, key: bytes) -> None:
        self._meter.consume(DELETE_COST, "Delete")
        self._inner.delete(key)

    def has(self, key: bytes) -> bool:
        self._meter.consume(HAS_COST, "Has")
        return self._inner.has(key)

    def iterate(self, prefix: bytes) -> list[tuple[bytes, bytes]]:
        # The flat-dict store scans the prefix eagerly (unlike the sdk's
        # lazy IAVL iterator), so the scan itself cannot be interrupted
        # mid-way; gas is still charged per entry so OutOfGas fires at
        # the same consumption point and the tx is rejected
        # deterministically — the meter bounds what a tx can COMMIT, the
        # store's own cost model bounds the scan.
        out = self._inner.iterate(prefix)
        for k, v in out:
            self._meter.consume(
                ITER_NEXT_COST_FLAT + READ_COST_PER_BYTE * (len(k) + len(v)),
                "IterNext",
            )
        return out

    def branch(self) -> "GasKVStore":
        """A branch whose accesses stay metered (keepers branch freely)."""
        return GasKVStore(self._inner.branch(), self._meter)

    def write_back(self, branch) -> None:
        inner = branch._inner if isinstance(branch, GasKVStore) else branch
        self._inner.write_back(inner)

    def unwrap(self):
        """The unmetered store underneath (write_back by outer callers)."""
        return self._inner

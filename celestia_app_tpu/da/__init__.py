"""Data-availability layer: extended data squares + DA headers.

TPU-native replacement of reference pkg/da (ExtendShares,
NewDataAvailabilityHeader, data_availability_header.go:44-108) and the
rsmt2d/nmt composition behind it: one fused jitted pipeline takes the ODS and
returns the EDS, all row/column NMT roots, and the data root.
"""

from celestia_app_tpu.da.eds import ExtendedDataSquare, extend_shares
from celestia_app_tpu.da.dah import (
    DataAvailabilityHeader,
    min_data_availability_header,
)
from celestia_app_tpu.da.repair import IrrecoverableSquare, RootMismatch, repair

__all__ = [
    "ExtendedDataSquare",
    "extend_shares",
    "DataAvailabilityHeader",
    "min_data_availability_header",
    "IrrecoverableSquare",
    "RootMismatch",
    "repair",
]

"""DataAvailabilityHeader: row/col NMT roots + the data root.

Parity with reference pkg/da/data_availability_header.go:
  NewDataAvailabilityHeader :44-63, Hash :92-108 (merkle over rowRoots ||
  colRoots), ValidateBasic :134, MinDataAvailabilityHeader :179.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from celestia_app_tpu.constants import (
    MAX_CODEC_SQUARE_SIZE,
    NMT_NODE_SIZE,
    SHARE_SIZE,
)
from celestia_app_tpu import merkle
from celestia_app_tpu.da.eds import ExtendedDataSquare, extend_shares
from celestia_app_tpu.shares.share import padding_share
from celestia_app_tpu.shares.namespace import TAIL_PADDING_NAMESPACE

_MIN_EDS_WIDTH = 2
_MAX_EDS_WIDTH = 2 * MAX_CODEC_SQUARE_SIZE


@dataclass
class DataAvailabilityHeader:
    row_roots: list[bytes] = field(default_factory=list)
    column_roots: list[bytes] = field(default_factory=list)

    @classmethod
    def from_eds(cls, eds: ExtendedDataSquare) -> "DataAvailabilityHeader":
        return cls(row_roots=eds.row_roots(), column_roots=eds.col_roots())

    def hash(self) -> bytes:
        """Data root: merkle root over row roots then column roots."""
        return merkle.hash_from_byte_slices(self.row_roots + self.column_roots)

    def marshal(self) -> bytes:
        """Proto wire form (proto/celestia/core/v1/da: row_roots=1,
        column_roots=2); byte-compatibility pinned by tests/test_proto_wire.py."""
        from celestia_app_tpu.encoding.proto import encode_bytes_field

        out = b""
        for r in self.row_roots:
            out += encode_bytes_field(1, r)
        for c in self.column_roots:
            out += encode_bytes_field(2, c)
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "DataAvailabilityHeader":
        from celestia_app_tpu.encoding.proto import WIRE_LEN, decode_fields

        rows, cols = [], []
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                rows.append(val)
            elif num == 2 and wt == WIRE_LEN:
                cols.append(val)
        return cls(rows, cols)

    def validate_basic(self) -> None:
        nr, nc = len(self.row_roots), len(self.column_roots)
        if nr != nc:
            raise ValueError(f"row/col root count mismatch: {nr} vs {nc}")
        if nr < _MIN_EDS_WIDTH:
            raise ValueError(f"too few roots: {nr} < {_MIN_EDS_WIDTH}")
        if nr > _MAX_EDS_WIDTH:
            raise ValueError(f"too many roots: {nr} > {_MAX_EDS_WIDTH}")
        for r in self.row_roots + self.column_roots:
            if len(r) != NMT_NODE_SIZE:
                raise ValueError(f"malformed root length {len(r)}")

    def square_size(self) -> int:
        """ODS width implied by this header."""
        return len(self.row_roots) // 2

    def equals(self, other: "DataAvailabilityHeader") -> bool:
        return (
            self.row_roots == other.row_roots
            and self.column_roots == other.column_roots
        )


def min_data_availability_header() -> DataAvailabilityHeader:
    """DAH of the minimal (1x1 tail-padding) square - the empty block's root
    (reference pkg/da/data_availability_header.go:179)."""
    share = padding_share(TAIL_PADDING_NAMESPACE).raw
    assert len(share) == SHARE_SIZE
    eds = extend_shares([share])
    return DataAvailabilityHeader.from_eds(eds)

"""Erasure repair: reconstruct a full EDS from >= 25% of its shares.

Capability parity with rsmt2d.ExtendedDataSquare.Repair (SURVEY §2.2 —
celestia-app itself never calls Repair, but it is part of the rsmt2d surface
this framework replaces; BASELINE config 4 benchmarks a quadrant erasure).

TPU-first shape: rows (then columns) sharing one erasure pattern are decoded
together — the recover matrix R depends only on which positions survive, so
each pattern group is ONE bit-matmul `full = R_bits @ known_bits` on the
MXU (kernels/rs.py decode_axis_fn).  A quadrant loss therefore repairs in a
single batched matmul per axis instead of 2k independent codec calls.
Verification recomputes all 4k NMT roots with the fused pipeline and
compares against the DAH.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from celestia_app_tpu.constants import SHARE_SIZE
from celestia_app_tpu.da.dah import DataAvailabilityHeader
from celestia_app_tpu.da.eds import ExtendedDataSquare, jit_pipeline
from celestia_app_tpu.gf import codec_for_width
from celestia_app_tpu.kernels.rs import decode_axis_fn


class IrrecoverableSquare(ValueError):
    """Not enough shares to reconstruct the square."""


class RootMismatch(ValueError):
    """Repaired square does not match the DataAvailabilityHeader."""


def _decode_axis_groups(
    data: np.ndarray, present: np.ndarray, codec, decode
) -> tuple[np.ndarray, np.ndarray, bool]:
    """Decode every axis line (row of `data`) with >= k surviving shares.

    data: (L, 2k, S); present: (L, 2k) bool.  Returns (data, present,
    progressed) with repaired lines filled in and marked present.
    """
    n = data.shape[1]
    k = n // 2
    incomplete = ~present.all(axis=1)
    counts = present.sum(axis=1)
    solvable = incomplete & (counts >= k)
    if not solvable.any():
        return data, present, False

    # Group solvable lines by erasure pattern: one recover matrix (and one
    # batched device matmul) per pattern.
    patterns: dict[bytes, list[int]] = {}
    for i in np.nonzero(solvable)[0]:
        patterns.setdefault(present[i].tobytes(), []).append(int(i))
    for pat, lines in patterns.items():
        mask = np.frombuffer(pat, dtype=bool)
        known_pos = np.nonzero(mask)[0][:k]
        R = codec.recover_matrix(known_pos)
        R_bits = jnp.asarray(codec.field.expand_bit_matrix(R))
        known = jnp.asarray(data[lines][:, known_pos], dtype=jnp.uint8)
        full = np.asarray(decode(known, R_bits))  # (len(lines), 2k, S)
        # Fill only the missing positions: surviving shares stay authoritative
        # so the final consistency check can reject inconsistent survivor sets.
        sub = data[lines]
        sub[:, ~mask] = full[:, ~mask]
        data[lines] = sub
        present[lines] = True
    return data, present, True


def repair(
    shares: np.ndarray,
    present: np.ndarray,
    dah: DataAvailabilityHeader | None = None,
) -> ExtendedDataSquare:
    """Reconstruct the full EDS.

    shares: (2k, 2k, SHARE_SIZE) uint8 with arbitrary bytes at missing
    positions; present: (2k, 2k) bool availability mask.  If `dah` is given,
    the repaired square's roots must match it (the Repair contract: a light
    node verifies what it reconstructs).
    """
    data = np.array(shares, dtype=np.uint8, copy=True)
    present = np.array(present, dtype=bool, copy=True)
    n = data.shape[0]
    if data.shape != (n, n, SHARE_SIZE) or n % 2:
        raise ValueError(f"bad EDS shape {data.shape}")
    k = n // 2
    codec = codec_for_width(k)
    decode = decode_axis_fn(k)

    # Alternate row/column sweeps until complete: a line solved along one
    # axis contributes shares to crossing lines of the other axis (same
    # iterative strategy as rsmt2d's solveCrossword).
    while not present.all():
        data, present, row_prog = _decode_axis_groups(data, present, codec, decode)
        data_t = np.ascontiguousarray(data.transpose(1, 0, 2))
        present_t = np.ascontiguousarray(present.T)
        data_t, present_t, col_prog = _decode_axis_groups(
            data_t, present_t, codec, decode
        )
        data = np.ascontiguousarray(data_t.transpose(1, 0, 2))
        present = present_t.T
        if not (row_prog or col_prog):
            raise IrrecoverableSquare(
                f"stuck with {int((~present).sum())} missing shares"
            )

    # Re-run the fused extension+roots pipeline on the recovered ODS: this
    # both re-derives parity (rejecting inconsistent survivor sets) and
    # yields the roots for DAH verification.
    eds = ExtendedDataSquare.compute(data[:k, :k])
    if not np.array_equal(eds.squared(), data):
        raise RootMismatch("recovered shares are not a consistent codeword")
    if dah is not None:
        got = DataAvailabilityHeader.from_eds(eds)
        if not got.equals(dah):
            raise RootMismatch("repaired square does not match the DAH")
    return eds

"""Erasure repair: reconstruct a full EDS from >= 25% of its shares.

Capability parity with rsmt2d.ExtendedDataSquare.Repair (SURVEY §2.2 —
celestia-app itself never calls Repair, but it is part of the rsmt2d surface
this framework replaces; BASELINE config 4 benchmarks a quadrant erasure).

TPU-first shape (round-3 rework; the round-2 version round-tripped every
stage through the host and ran 10x slower than the extend path):

  * the damaged EDS ships to HBM ONCE; every sweep, the re-extension, and
    the survivor-consistency check run device-resident, and only the
    roots come back to the host for DAH comparison (shares are pulled
    lazily via the returned ExtendedDataSquare, as rsmt2d callers do);
  * rows (then columns) sharing one erasure pattern are decoded together:
    the recover matrix R depends only on which positions survive, so each
    pattern group is ONE bit-matmul `full = R_bits @ known_bits` on the
    MXU (kernels/rs.py encode_axis with the group's R_bits as input — no
    recompile per pattern, one compile per (k, axis));
  * R_bits and the host-side Gaussian elimination behind it are cached
    per (k, pattern, construction), so repeated repairs of the same erasure shape (the
    benchmark loop, retrying light nodes) skip both the O(k^3) host solve
    and the h2d upload of the expanded matrix.

Verification recomputes all 4k NMT roots with the fused pipeline and
compares against the DAH; surviving shares stay authoritative, so an
inconsistent survivor set is rejected on device (RootMismatch), matching
rsmt2d's Repair contract.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from celestia_app_tpu.constants import SHARE_SIZE
from celestia_app_tpu.da.dah import DataAvailabilityHeader
from celestia_app_tpu.da.eds import ExtendedDataSquare, jit_pipeline
from celestia_app_tpu.gf import codec_for_width
from celestia_app_tpu.gf.rs import active_construction
from celestia_app_tpu.kernels.rs import encode_axis


class IrrecoverableSquare(ValueError):
    """Not enough shares to reconstruct the square."""


class RootMismatch(ValueError):
    """Repaired square does not match the DataAvailabilityHeader."""


def _put_private(x: np.ndarray, sharding=None):
    """device_put from a PRIVATE host copy.

    The CPU backend may zero-copy alias suitably-aligned numpy buffers
    into device arrays, and repair() mutates `present_host` in place while
    async dispatches are still in flight — uploading the live buffer is a
    data race (the round-3 nondeterministic RootMismatch).  A fresh copy
    is owned solely by the returned device array.
    """
    arr = np.array(x, copy=True)
    if sharding is not None:
        return jax.device_put(arr, sharding)
    return jax.device_put(arr)


@lru_cache(maxsize=64)
def _recover_bits_device(k: int, pattern: bytes, construction: str):
    """Device-resident bit-expanded recover matrix for one erasure
    pattern of a width-2k axis line.  Cached per (k, pattern,
    construction): the host Gaussian elimination is O(k^3) and the
    expanded matrix is the largest h2d transfer of a repair."""
    codec = codec_for_width(k, construction)
    mask = np.frombuffer(pattern, dtype=bool)
    known_pos = np.nonzero(mask)[0][:k]
    R = codec.recover_matrix(known_pos)
    R_bits = jax.device_put(jnp.asarray(codec.field.expand_bit_matrix(R)))
    known_idx = jax.device_put(jnp.asarray(known_pos, dtype=jnp.int32))
    return R_bits, known_idx


@lru_cache(maxsize=None)
def _jit_sweep(k: int, axis: int, construction: str):
    """One decode of up to 2k same-pattern lines along `axis`.

    data: (2k, 2k, S) uint8 (device); present: (2k, 2k) bool;
    line_idx: (2k,) int32 — group lines, padded with the out-of-range
    sentinel 2k (gathers clamp, and the scatter drops the padded writes
    via mode="drop", so padding lanes never touch the square);
    known_idx: (k,) int32; R_bits: (2k*m, k*m).
    Returns data with the group's lines decoded, survivors untouched.
    """
    codec = codec_for_width(k, construction)
    m = codec.field.m

    def sweep(data, present, line_idx, known_idx, R_bits):
        if axis == 0:
            rows = data[line_idx]  # (L, 2k, S); padded lanes clamp
            known = jnp.take(rows, known_idx, axis=1)  # (L, k, S)
            full = encode_axis(known, R_bits, m, contract_axis=1)  # (L, 2k, S)
            pm = present[jnp.clip(line_idx, 0, 2 * k - 1)][..., None]
            mixed = jnp.where(pm, rows, full)
            return data.at[line_idx].set(mixed, mode="drop")
        cols = data[:, line_idx]  # (2k, L, S)
        known = jnp.take(data, known_idx, axis=0)[:, line_idx]  # (k, L, S)
        full = encode_axis(known, R_bits, m, contract_axis=0)  # (2k, L, S)
        pm = present[:, jnp.clip(line_idx, 0, 2 * k - 1)][..., None]
        mixed = jnp.where(pm, cols, full)
        return data.at[:, line_idx].set(mixed, mode="drop")

    return jax.jit(sweep)


def repair(
    shares: np.ndarray,
    present: np.ndarray,
    dah: DataAvailabilityHeader | None = None,
) -> ExtendedDataSquare:
    """Reconstruct the full EDS.

    shares: (2k, 2k, SHARE_SIZE) uint8 with arbitrary bytes at missing
    positions; present: (2k, 2k) bool availability mask.  If `dah` is given,
    the repaired square's roots must match it (the Repair contract: a light
    node verifies what it reconstructs).
    """
    shares = np.asarray(shares, dtype=np.uint8)
    present_host = np.array(present, dtype=bool, copy=True)
    n = shares.shape[0]
    if shares.shape != (n, n, SHARE_SIZE) or n % 2:
        raise ValueError(f"bad EDS shape {shares.shape}")
    k = n // 2
    construction = active_construction()

    # `shares` is never mutated here and repair() blocks on the consistency
    # check before returning, so a plain (possibly zero-copy) upload is
    # safe; only the in-place-mutated masks need private copies.
    damaged = jax.device_put(jnp.asarray(shares))
    present_orig = _put_private(present_host)
    data = damaged

    # Alternate row/column sweeps until complete: a line solved along one
    # axis contributes shares to crossing lines of the other axis (same
    # iterative strategy as rsmt2d's solveCrossword).  Orchestration is
    # host-side (pattern discovery over the small bool mask); all share
    # bytes stay in HBM.
    while not present_host.all():
        progressed = False
        for axis in (0, 1):
            pm = present_host if axis == 0 else present_host.T
            incomplete = ~pm.all(axis=1)
            solvable = incomplete & (pm.sum(axis=1) >= k)
            if not solvable.any():
                continue
            patterns: dict[bytes, list[int]] = {}
            for i in np.nonzero(solvable)[0]:
                patterns.setdefault(pm[i].tobytes(), []).append(int(i))
            present_dev = _put_private(present_host)
            for pat, lines in patterns.items():
                R_bits, known_idx = _recover_bits_device(k, pat, construction)
                padded = lines + [2 * k] * (2 * k - len(lines))
                line_idx = jnp.asarray(padded, dtype=jnp.int32)
                data = _jit_sweep(k, axis, construction)(
                    data, present_dev, line_idx, known_idx, R_bits
                )
                if axis == 0:
                    present_host[lines, :] = True
                else:
                    present_host[:, lines] = True
                progressed = True
        if not progressed:
            raise IrrecoverableSquare(
                f"stuck with {int((~present_host).sum())} missing shares"
            )

    # Re-run the fused extension+roots pipeline on the recovered ODS: this
    # both re-derives parity and yields the roots for DAH verification.
    ods = data[:k, :k]
    # Use the construction captured at entry: re-resolving the env var here
    # would let a mid-repair flip decode with one generator and verify with
    # another.
    eds, rr, cr, droot = jit_pipeline(k, construction)(ods)
    # Survivors are authoritative: the recomputed codeword must reproduce
    # every share that was present in the input (device-side check; only
    # one bool crosses back to the host).
    consistent = jnp.all((eds == damaged) | ~present_orig[..., None])
    if not bool(consistent):
        raise RootMismatch("recovered shares are not a consistent codeword")
    out = ExtendedDataSquare(eds, rr, cr, droot, k)
    if dah is not None:
        got = DataAvailabilityHeader.from_eds(out)
        if not got.equals(dah):
            raise RootMismatch("repaired square does not match the DAH")
    return out

"""Erasure repair: reconstruct a full EDS from >= 25% of its shares.

Capability parity with rsmt2d.ExtendedDataSquare.Repair (SURVEY §2.2 —
celestia-app itself never calls Repair, but it is part of the rsmt2d surface
this framework replaces; BASELINE config 4 benchmarks a quadrant erasure).

TPU-first shape (round-4 rework; the ISSUE-10 batched-repair tentpole —
repair is exactly the code that runs when the network is under a
data-availability attack, so it must run at device speed, not at
per-dispatch-overhead speed):

  * the damaged EDS ships to HBM ONCE; every sweep, the re-extension, and
    the survivor-consistency check run device-resident, and only the
    roots come back to the host for DAH comparison (shares are pulled
    lazily via the returned ExtendedDataSquare, as rsmt2d callers do);
  * one device program per sweep: every solvable erasure-pattern group's
    recover matrix is stacked into ONE (G, O*m, k*m) `R_bits` tensor and
    the whole sweep runs as one vmapped bit-matmul over groups
    (kernels/rs.encode_axis under jax.vmap), writing ONLY the missing
    positions — survivors are never touched, the decode matmul is half
    the legacy size (O missing outputs instead of all 2k), and lanes pad
    to the group's real size (power-of-two bucketed for jit-cache
    stability) instead of always 2k;
  * repair decodes what the OUTPUT needs, not everything: the returned
    square is the re-extension of the recovered ODS, so parity lines are
    decoded only when the crossword needs them to unlock a data line —
    a pure-parity erasure (the benchmark's quadrant) does zero decode
    sweeps and costs exactly one re-extension;
  * the sweep dispatch and the re-extension both ride
    chaos/degrade.guarded_dispatch, so a repair-path fault steps the
    same fused -> staged -> host ladder as every other dispatch: the
    batched sweep is the fused-family rung, the legacy per-pattern-group
    jitted sweep is the staged rung, and the same per-group sweep run
    eagerly is the host floor — all three bit-identical;
  * R_bits and the host-side Gaussian elimination behind it are cached
    per (k, pattern, construction) — and the whole stacked sweep input
    per (k, axis, patterns, lines) — so repeated repairs of the same
    erasure shape (the benchmark loop, retrying light nodes) skip the
    O(k^3) host solve, the stacking, and the h2d upload.

$CELESTIA_REPAIR_SWEEP pins the lowering: "batched" (default) or
"grouped" — the frozen pre-batching algorithm (decode every line until
the full square is present, one dispatch per pattern group), kept as the
measurable baseline and regression twin; tests pin the two byte-identical.

Verification recomputes all 4k NMT roots with the fused pipeline and
compares against the DAH; surviving shares stay authoritative, so an
inconsistent survivor set is rejected on device (RootMismatch), matching
rsmt2d's Repair contract.  A RootMismatch is also an ADVERSARY DETECTION
(a wrong-root or malformed-square attack surfaces exactly here), so it
fires the `root_mismatch` flight-recorder trigger before raising.
"""

from __future__ import annotations

import os
import weakref
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from celestia_app_tpu.constants import SHARE_SIZE
from celestia_app_tpu.da.dah import DataAvailabilityHeader
from celestia_app_tpu.da.eds import ExtendedDataSquare, _pipeline_for_mode
from celestia_app_tpu.gf import codec_for_width
from celestia_app_tpu.gf.rs import active_construction
from celestia_app_tpu.kernels.rs import encode_axis


class IrrecoverableSquare(ValueError):
    """Not enough shares to reconstruct the square."""


class RootMismatch(ValueError):
    """Repaired square does not match the DataAvailabilityHeader."""


def repair_sweep_mode() -> str:
    """$CELESTIA_REPAIR_SWEEP: "batched" (default) or "grouped" (the
    pre-batching per-pattern-group baseline, kept in-tree so the bench
    can measure the speedup and the tests can pin byte-identity)."""
    return (
        "grouped"
        if os.environ.get("CELESTIA_REPAIR_SWEEP", "") == "grouped"
        else "batched"
    )


def _root_mismatch_detected(reason: str, height: int | None = None,
                            **context) -> None:
    """Every repair rejection is an adversary-detection event: tick the
    detection counter and black-box the moment (the survivor set and the
    DAH that disagreed are in the trace tables right now).  When the
    caller knows WHICH height's repair was rejected, the signal also
    feeds the healing loop (serve/heal.py) — an engine already healing
    that height ignores its own rejection, so the wire cannot recurse."""
    from celestia_app_tpu.chaos.adversary import detections
    from celestia_app_tpu.trace.flight_recorder import note_trigger

    detections().inc(kind="root_mismatch")
    if height is not None:
        context["height"] = height
        from celestia_app_tpu.serve import heal

        heal.note_detection("root_mismatch", height)
    note_trigger("root_mismatch", reason=reason, **context)


def _put_private(x: np.ndarray, sharding=None):
    """device_put from a PRIVATE host copy.

    The CPU backend may zero-copy alias suitably-aligned numpy buffers
    into device arrays, and repair() mutates `present_host` in place while
    async dispatches are still in flight — uploading the live buffer is a
    data race (the round-3 nondeterministic RootMismatch).  A fresh copy
    is owned solely by the returned device array.
    """
    arr = np.array(x, copy=True)
    if sharding is not None:
        return jax.device_put(arr, sharding)
    return jax.device_put(arr)


#: Live recover-matrix arrays by cache key — a WEAK view over what the
#: bounded lru caches above/below still hold, so the ownership ledger's
#: figure falls when an entry evicts (trace/device_ledger.py).
_RECOVER_CACHE_ARRAYS: "weakref.WeakValueDictionary" = (
    weakref.WeakValueDictionary()
)


def _recover_cache_bytes() -> int:
    """Bytes held by the recover-matrix caches (the ownership-ledger
    callback): sums the bit-expanded matrices still alive."""
    return sum(
        int(getattr(a, "nbytes", 0) or 0)
        for a in _RECOVER_CACHE_ARRAYS.values()
    )


from celestia_app_tpu.trace.device_ledger import register_owner as _register_owner  # noqa: E402

_register_owner("repair_recover_cache", _recover_cache_bytes)


@lru_cache(maxsize=64)
def _recover_bits_device(k: int, pattern: bytes, construction: str):
    """Device-resident bit-expanded recover matrix for one erasure
    pattern of a width-2k axis line.  Cached per (k, pattern,
    construction): the host Gaussian elimination is O(k^3) and the
    expanded matrix is the largest h2d transfer of a repair."""
    codec = codec_for_width(k, construction)
    mask = np.frombuffer(pattern, dtype=bool)
    known_pos = np.nonzero(mask)[0][:k]
    R = codec.recover_matrix(known_pos)
    R_bits = jax.device_put(jnp.asarray(codec.field.expand_bit_matrix(R)))
    known_idx = jax.device_put(jnp.asarray(known_pos, dtype=jnp.int32))
    _RECOVER_CACHE_ARRAYS[(k, pattern, construction, "device")] = R_bits
    return R_bits, known_idx


@lru_cache(maxsize=128)
def _recover_bits_missing(k: int, pattern: bytes, construction: str):
    """HOST-side missing-rows-only recover matrix for one pattern:
    (R_miss_bits (miss*m, k*m) uint8, known_pos (k,), miss_pos (miss,)).

    The batched sweep writes only the missing positions, so it slices
    the (2k, k) GF recover matrix down to the missing rows BEFORE
    bit-expansion — half the matmul of the full-line decode for a
    quadrant-shaped pattern, and the survivors are never rewritten.
    Host arrays: the per-sweep stacker pads and uploads them as one
    tensor (cached per stack in _stacked_sweep_inputs)."""
    codec = codec_for_width(k, construction)
    mask = np.frombuffer(pattern, dtype=bool)
    known_pos = np.nonzero(mask)[0][:k]
    miss_pos = np.nonzero(~mask)[0]
    R = codec.recover_matrix(known_pos)  # (2k, k) over GF
    R_miss_bits = codec.field.expand_bit_matrix(R[miss_pos])
    _RECOVER_CACHE_ARRAYS[(k, pattern, construction, "missing")] = R_miss_bits
    return (
        R_miss_bits,
        known_pos.astype(np.int32),
        miss_pos.astype(np.int32),
    )


def _bucket(n: int) -> int:
    """Next power of two >= n: pads the batched sweep's group/lane/output
    axes so the jit cache sees O(log^3) shapes instead of one compile per
    erasure pattern census."""
    return 1 << max(0, int(n) - 1).bit_length()


@lru_cache(maxsize=64)
def _stacked_sweep_inputs(
    k: int,
    construction: str,
    patterns: tuple[bytes, ...],
    lines: tuple[tuple[int, ...], ...],
):
    """Device tensors for one batched sweep over `patterns[g]` decoding
    `lines[g]`: (line_idx (G,M), known_idx (G,k), miss_idx (G,O),
    R_stack (G, O*m, k*m)) with G/M/O power-of-two bucketed and padded
    with the out-of-range sentinel 2k (gathers clamp; the scatter drops
    sentinel writes via mode="drop").  Cached per erasure shape: the
    benchmark loop and a retrying light node repair the same pattern
    census repeatedly and skip the stacking + upload entirely."""
    codec = codec_for_width(k, construction)
    m = codec.field.m
    n = 2 * k
    per_group = [
        _recover_bits_missing(k, pat, construction) for pat in patterns
    ]
    G = _bucket(len(patterns))
    M = _bucket(max(len(ls) for ls in lines))
    O = _bucket(max(len(mp) for _, _, mp in per_group))
    line_idx = np.full((G, M), n, dtype=np.int32)
    known_idx = np.zeros((G, k), dtype=np.int32)
    miss_idx = np.full((G, O), n, dtype=np.int32)
    R_stack = np.zeros((G, O * m, k * m), dtype=np.uint8)
    for g, (ls, (R_miss, known_pos, miss_pos)) in enumerate(
        zip(lines, per_group)
    ):
        line_idx[g, : len(ls)] = ls
        known_idx[g] = known_pos
        miss_idx[g, : len(miss_pos)] = miss_pos
        R_stack[g, : len(miss_pos) * m] = R_miss
    return (
        jax.device_put(jnp.asarray(line_idx)),
        jax.device_put(jnp.asarray(known_idx)),
        jax.device_put(jnp.asarray(miss_idx)),
        jax.device_put(jnp.asarray(R_stack)),
    )


@lru_cache(maxsize=None)
def _jit_batched_sweep(k: int, axis: int, construction: str,
                       G: int, M: int, O: int):
    """ONE device program decoding every pattern group of a sweep.

    data: (2k, 2k, S) uint8; line_idx: (G, M) int32 (sentinel 2k);
    known_idx: (G, k); miss_idx: (G, O) (sentinel 2k);
    R_stack: (G, O*m, k*m).  vmap over the group axis; each lane gathers
    its group's known shares, runs the missing-rows bit-matmul
    (kernels/rs.encode_axis), and one scatter writes every decoded
    (line, missing-position) cell — sentinel-padded lanes/outputs drop.
    Survivor positions are never written: they stay authoritative bytes.
    """
    codec = codec_for_width(k, construction)
    m = codec.field.m

    def sweep(data, line_idx, known_idx, miss_idx, R_stack):
        if axis == 0:
            def one(lidx, kidx, Rb):
                rows = data[lidx]  # (M, 2k, S); sentinel lanes clamp
                known = jnp.take(rows, kidx, axis=1)  # (M, k, S)
                return encode_axis(known, Rb, m, contract_axis=1)  # (M, O, S)

            dec = jax.vmap(one)(line_idx, known_idx, R_stack)  # (G, M, O, S)
            return data.at[
                line_idx[:, :, None], miss_idx[:, None, :]
            ].set(dec, mode="drop")
        def one(lidx, kidx, Rb):
            known = jnp.take(data, kidx, axis=0)[:, lidx]  # (k, M, S)
            return encode_axis(known, Rb, m, contract_axis=0)  # (O, M, S)

        dec = jax.vmap(one)(line_idx, known_idx, R_stack)  # (G, O, M, S)
        return data.at[
            miss_idx[:, :, None], line_idx[:, None, :]
        ].set(dec, mode="drop")

    from celestia_app_tpu.trace.device_ledger import track

    return track(
        jax.jit(sweep),
        "repair_batched_sweep",
        k=k, construction=construction, mode="batched", batch=G,
    )


def _sweep_fn(k: int, axis: int, construction: str):
    """Body of the legacy per-pattern-group sweep — one decode of up to
    2k same-pattern lines along `axis`.  `_jit_sweep` compiles it (the
    staged rung); the host floor runs it eagerly, op by op.

    data: (2k, 2k, S) uint8 (device); present: (2k, 2k) bool;
    line_idx: (2k,) int32 — group lines, padded with the out-of-range
    sentinel 2k (gathers clamp, and the scatter drops the padded writes
    via mode="drop", so padding lanes never touch the square);
    known_idx: (k,) int32; R_bits: (2k*m, k*m).
    Returns data with the group's lines decoded, survivors untouched.
    """
    codec = codec_for_width(k, construction)
    m = codec.field.m

    def sweep(data, present, line_idx, known_idx, R_bits):
        if axis == 0:
            rows = data[line_idx]  # (L, 2k, S); padded lanes clamp
            known = jnp.take(rows, known_idx, axis=1)  # (L, k, S)
            full = encode_axis(known, R_bits, m, contract_axis=1)  # (L, 2k, S)
            pm = present[jnp.clip(line_idx, 0, 2 * k - 1)][..., None]
            mixed = jnp.where(pm, rows, full)
            return data.at[line_idx].set(mixed, mode="drop")
        cols = data[:, line_idx]  # (2k, L, S)
        known = jnp.take(data, known_idx, axis=0)[:, line_idx]  # (k, L, S)
        full = encode_axis(known, R_bits, m, contract_axis=0)  # (2k, L, S)
        pm = present[:, jnp.clip(line_idx, 0, 2 * k - 1)][..., None]
        mixed = jnp.where(pm, cols, full)
        return data.at[:, line_idx].set(mixed, mode="drop")

    return sweep


@lru_cache(maxsize=None)
def _jit_sweep(k: int, axis: int, construction: str):
    """The compiled legacy sweep (grouped baseline + staged ladder rung)."""
    from celestia_app_tpu.trace.device_ledger import track

    return track(
        jax.jit(_sweep_fn(k, axis, construction)),
        "repair_sweep", k=k, construction=construction, mode="staged",
    )


def _grouped_sweep_callable(
    k: int,
    axis: int,
    construction: str,
    patterns: dict[bytes, list[int]],
    present_host: np.ndarray,
    *,
    eager: bool,
):
    """f(data) -> data running every pattern group through the legacy
    per-group sweep — jitted on the staged rung, eager on the host floor
    (the repo's "host" contract: same ops, no compiled dispatch)."""
    n = 2 * k
    present_dev = _put_private(present_host)
    fn = _sweep_fn(k, axis, construction) if eager else _jit_sweep(
        k, axis, construction
    )

    def run(data):
        for pat, lines in patterns.items():
            R_bits, known_idx = _recover_bits_device(k, pat, construction)
            padded = lines + [n] * (n - len(lines))
            line_idx = jnp.asarray(padded, dtype=jnp.int32)
            data = fn(data, present_dev, line_idx, known_idx, R_bits)
        return data

    return run


def _sweep_for_mode(
    mode: str,
    k: int,
    axis: int,
    construction: str,
    patterns: dict[bytes, list[int]],
    present_host: np.ndarray,
):
    """Resolve one sweep's callable for a ladder rung — the repair-path
    face of chaos/degrade.guarded_dispatch's `resolve`: the batched
    single-dispatch program on the fused-family rungs, the per-group
    jitted sweep on staged, the same per-group sweep eager on the host
    floor.  All three produce byte-identical squares."""
    if mode in ("fused", "fused_epi"):
        pats = tuple(patterns)
        lines = tuple(tuple(patterns[p]) for p in pats)
        line_idx, known_idx, miss_idx, R_stack = _stacked_sweep_inputs(
            k, construction, pats, lines
        )
        jitted = _jit_batched_sweep(
            k, axis, construction,
            line_idx.shape[0], line_idx.shape[1], miss_idx.shape[1],
        )
        return lambda data: jitted(
            data, line_idx, known_idx, miss_idx, R_stack
        )
    return _grouped_sweep_callable(
        k, axis, construction, patterns, present_host,
        eager=(mode == "host"),
    )


def _solvable_groups(
    present_host: np.ndarray, k: int, axis: int, *, data_only: bool
) -> dict[bytes, list[int]]:
    """Pattern -> lines for one sweep.  `data_only` restricts to lines
    that recover at least one missing ODS position (the output is the
    re-extension of the recovered ODS, so parity-only lines are decoded
    only when a full round stalls and the crossword needs them)."""
    pm = present_host if axis == 0 else present_host.T
    incomplete = ~pm.all(axis=1)
    solvable = incomplete & (pm.sum(axis=1) >= k)
    if data_only:
        data_missing = ~pm[:, :k].all(axis=1)
        data_missing[k:] = False  # lines >= k are pure parity
        solvable = solvable & data_missing
    patterns: dict[bytes, list[int]] = {}
    for i in np.nonzero(solvable)[0]:
        patterns.setdefault(pm[i].tobytes(), []).append(int(i))
    return patterns


def _solve_batched(data, present_host: np.ndarray, k: int, construction: str):
    """Crossword solve to ODS completion, one guarded device program per
    sweep.  Decodes data-bearing lines first; when a full (row, column)
    round makes no data progress, falls back to every solvable line so a
    recovered parity line can unlock a starved data line — the same
    fixpoint the legacy solve reaches, terminated as soon as the ODS is
    whole (everything else re-derives from it)."""
    from celestia_app_tpu.chaos.degrade import guarded_dispatch

    def sweep_round(data, *, data_only: bool) -> tuple:
        progressed = False
        for axis in (0, 1):
            patterns = _solvable_groups(
                present_host, k, axis, data_only=data_only
            )
            if not patterns:
                continue
            _, data = guarded_dispatch(
                lambda m: _sweep_for_mode(
                    m, k, axis, construction, patterns, present_host
                ),
                data,
            )
            for lines in patterns.values():
                if axis == 0:
                    present_host[lines, :] = True
                else:
                    present_host[:, lines] = True
            progressed = True
        return progressed, data

    while not present_host[:k, :k].all():
        progressed, data = sweep_round(data, data_only=True)
        if not present_host[:k, :k].all() and not progressed:
            progressed, data = sweep_round(data, data_only=False)
        if not progressed:
            raise IrrecoverableSquare(
                f"stuck with {int((~present_host[:k, :k]).sum())} "
                "missing ODS shares"
            )
    return data


def _solve_grouped(data, present_host: np.ndarray, k: int, construction: str):
    """The frozen pre-batching solve ($CELESTIA_REPAIR_SWEEP=grouped):
    alternate row/column sweeps until the FULL square is present, one
    jitted dispatch per erasure-pattern group — the measurable baseline
    the batched path is pinned byte-identical to (and >= 2x faster
    than, per the ISSUE-10 acceptance bar)."""
    while not present_host.all():
        progressed = False
        for axis in (0, 1):
            patterns = _solvable_groups(
                present_host, k, axis, data_only=False
            )
            if not patterns:
                continue
            present_dev = _put_private(present_host)
            for pat, lines in patterns.items():
                R_bits, known_idx = _recover_bits_device(k, pat, construction)
                padded = lines + [2 * k] * (2 * k - len(lines))
                line_idx = jnp.asarray(padded, dtype=jnp.int32)
                data = _jit_sweep(k, axis, construction)(
                    data, present_dev, line_idx, known_idx, R_bits
                )
                if axis == 0:
                    present_host[lines, :] = True
                else:
                    present_host[:, lines] = True
                progressed = True
        if not progressed:
            raise IrrecoverableSquare(
                f"stuck with {int((~present_host).sum())} missing shares"
            )
    return data


def repair(
    shares: np.ndarray,
    present: np.ndarray,
    dah: DataAvailabilityHeader | None = None,
    *,
    height: int | None = None,
) -> ExtendedDataSquare:
    """Reconstruct the full EDS.

    shares: (2k, 2k, SHARE_SIZE) uint8 with arbitrary bytes at missing
    positions; present: (2k, 2k) bool availability mask.  If `dah` is given,
    the repaired square's roots must match it (the Repair contract: a light
    node verifies what it reconstructs).  `height`, when the caller knows
    it, stamps rejection events with the chain coordinate so the healing
    loop can subscribe to them.
    """
    from celestia_app_tpu.chaos.degrade import guarded_dispatch

    shares = np.asarray(shares, dtype=np.uint8)
    present_host = np.array(present, dtype=bool, copy=True)
    n = shares.shape[0]
    if shares.shape != (n, n, SHARE_SIZE) or n % 2:
        raise ValueError(f"bad EDS shape {shares.shape}")
    k = n // 2
    construction = active_construction()

    # `shares` is never mutated here and repair() blocks on the consistency
    # check before returning, so a plain (possibly zero-copy) upload is
    # safe; only the in-place-mutated masks need private copies.
    damaged = jax.device_put(jnp.asarray(shares))
    present_orig = _put_private(present_host)

    if repair_sweep_mode() == "grouped":
        data = _solve_grouped(damaged, present_host, k, construction)
    else:
        data = _solve_batched(damaged, present_host, k, construction)

    # Re-run the fused extension+roots pipeline on the recovered ODS: this
    # both re-derives parity and yields the roots for DAH verification.
    ods = data[:k, :k]
    # Use the construction captured at entry: re-resolving the env var here
    # would let a mid-repair flip decode with one generator and verify with
    # another.  guarded_dispatch: a re-extension fault steps the same
    # fused -> staged -> host ladder as every other extend+DAH dispatch.
    _, (eds, rr, cr, droot) = guarded_dispatch(
        lambda m: _pipeline_for_mode(m, k, construction), ods
    )
    # Survivors are authoritative: the recomputed codeword must reproduce
    # every share that was present in the input (device-side check; only
    # one bool crosses back to the host).
    consistent = jnp.all((eds == damaged) | ~present_orig[..., None])
    if not bool(consistent):
        _root_mismatch_detected("inconsistent_survivors", height=height, k=k)
        raise RootMismatch("recovered shares are not a consistent codeword")
    out = ExtendedDataSquare(eds, rr, cr, droot, k)
    if dah is not None:
        got = DataAvailabilityHeader.from_eds(out)
        if not got.equals(dah):
            _root_mismatch_detected("dah_mismatch", height=height, k=k)
            raise RootMismatch("repaired square does not match the DAH")
    return out

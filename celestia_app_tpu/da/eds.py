"""Extended data square: the 2k x 2k erasure-coded share matrix.

Replaces rsmt2d.ExtendedDataSquare as consumed by the reference
(pkg/da/data_availability_header.go:65-75): construction fuses the RS
extension and all 4k NMT roots into one jitted device program per square
size; accessors mirror the rsmt2d surface (Row, Col, FlattenedODS, quadrant
namespace rules from pkg/wrapper/nmt_wrapper.go:93-114).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from celestia_app_tpu.constants import (
    MAX_CODEC_SQUARE_SIZE,
    NAMESPACE_SIZE,
    PARITY_NAMESPACE_BYTES,
    SHARE_SIZE,
)
from celestia_app_tpu.gf.rs import active_construction
from celestia_app_tpu.kernels.merkle import merkle_root_pow2
from celestia_app_tpu.kernels.nmt import leaf_digests, tree_roots_from_digests
from celestia_app_tpu.kernels.rs import extend_square_fn


def leaf_namespaces(eds: jnp.ndarray, k: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-leaf namespaces for row trees and column trees.

    Q0 leaves carry the share's own namespace; every parity leaf (row >= k or
    col >= k) carries the parity namespace 0xFF^29.
    Returns (row_ns, col_ns): (2k, 2k, 29) each, row-tree-major and
    col-tree-major respectively.
    """
    n = eds.shape[0]
    share_ns = eds[..., :NAMESPACE_SIZE]  # (2k, 2k, 29)
    idx = jnp.arange(n)
    q0 = (idx[:, None] < k) & (idx[None, :] < k)  # (2k, 2k)
    parity = jnp.frombuffer(PARITY_NAMESPACE_BYTES, dtype=jnp.uint8)
    row_ns = jnp.where(q0[..., None], share_ns, parity)
    col_ns = row_ns.transpose(1, 0, 2)
    return row_ns, col_ns


def roots_fn(k: int):
    """The hashing half of the pipeline: eds (2k,2k,S) -> (row_roots,
    col_roots, droot).  Factored out so the bench decomposition can time
    NMT+DAH separately from the RS extension."""

    def roots(eds: jnp.ndarray):
        row_ns, _ = leaf_namespaces(eds, k)
        # The leaf digest at (i, j) is identical for the row-i tree and the
        # col-j tree (same namespace, same share), so hash the (2k, 2k) leaf
        # grid once and feed the column reduction its transpose.  Leaf hashes
        # are 9 SHA-256 blocks each vs 3 for inner nodes — this halves the
        # dominant hash cost.
        mins, maxs, hashes = leaf_digests(row_ns, eds)
        row_roots = tree_roots_from_digests(mins, maxs, hashes)  # (2k, 90)
        col_roots = tree_roots_from_digests(
            mins.transpose(1, 0, 2), maxs.transpose(1, 0, 2),
            hashes.transpose(1, 0, 2),
        )
        droot = merkle_root_pow2(jnp.concatenate([row_roots, col_roots], axis=0))
        return row_roots, col_roots, droot

    return roots


def _pipeline(k: int, construction: str):
    """Staged lowering: ods (k,k,512) -> (eds, row_roots (2k,90),
    col_roots (2k,90), droot (32,)) as extend-then-hash.  Kept as the
    bench A/B partner of kernels/fused.extend_and_dah_fn (bit-identical;
    the `parts` autotuner row measures both and seats the winner)."""
    extend = extend_square_fn(k, construction)
    roots = roots_fn(k)

    def run(ods: jnp.ndarray):
        eds = extend(ods)
        row_roots, col_roots, droot = roots(eds)
        return eds, row_roots, col_roots, droot

    return run


_STAGED_BUILT: set[tuple] = set()


@lru_cache(maxsize=None)
def _jit_pipeline(k: int, construction: str):
    _STAGED_BUILT.add((k, construction))
    from celestia_app_tpu.trace.device_ledger import track
    from celestia_app_tpu.trace.journal import note_jit_build

    note_jit_build("staged_pipeline")
    return track(
        jax.jit(_pipeline(k, construction)),
        "staged_pipeline", k=k, construction=construction, mode="staged",
    )


@lru_cache(maxsize=None)
def _host_pipeline(k: int, construction: str):
    """The degradation floor: the staged composition executed EAGERLY —
    no jitted program, every op its own dispatch.  Slow, but it removes
    compiled-program execution from the failure surface entirely, and it
    is bit-identical to both jitted lowerings (same ops, same order)."""
    fn = _pipeline(k, construction)

    def run(ods):
        return fn(jnp.asarray(ods))

    return run


def pipeline_cache_state(
    k: int, construction: str | None = None, *, owned: bool = False
) -> str:
    """"hit" when the jit wrapper the active seam would dispatch for
    (k, construction) is already built this process, else "miss" — the
    block journal's compile column, readable without building anything."""
    from celestia_app_tpu.kernels.fused import is_built, pipeline_mode_for_k

    construction = construction or active_construction()
    mode = pipeline_mode_for_k(k)
    if mode == "sharded_panel":
        from celestia_app_tpu.kernels.panel_sharded import is_sharded_warm

        return "hit" if is_sharded_warm(k, construction) else "miss"
    if mode == "panel":
        from celestia_app_tpu.kernels.panel import is_warm

        return "hit" if is_warm(k, construction) else "miss"
    if mode in ("fused", "fused_epi"):
        return "hit" if is_built(
            k, construction, donate=owned, epilogue=(mode == "fused_epi")
        ) else "miss"
    if mode == "host":
        return "hit"  # eager: nothing compiles, nothing can miss
    return "hit" if (k, construction) in _STAGED_BUILT else "miss"


def jit_pipeline(k: int, construction: str | None = None):
    """Cached single-dispatch pipeline, keyed on (k, RS construction) so an
    env-var flip mid-process never serves a stale-generator compile.
    Callers that must stay on one construction across several dispatches
    (repair's decode/verify pair, a live BlockPipeline) pass it explicitly.

    Routes through the fused/staged seam (kernels/fused.pipeline_mode —
    $CELESTIA_PIPE_FUSED): both lowerings are bit-identical, so the choice
    is a perf detail, never a correctness hazard.  This entry never
    donates its argument — callers that own their upload use
    jit_extend_and_dah(..., donate=True) directly (compute(), the block
    pipeline's feeder).

    Per-k: the panel-streamed lowering ($CELESTIA_PIPE_PANEL,
    kernels/panel.py) engages only for the square sizes its seam names,
    so the mode is resolved per square size (pipeline_mode_for_k)."""
    from celestia_app_tpu.kernels.fused import pipeline_mode_for_k

    construction = construction or active_construction()
    return _pipeline_for_mode(pipeline_mode_for_k(k), k, construction,
                              owned=False)


def _pipeline_for_mode(
    mode: str, k: int, construction: str | None = None, *, owned: bool = False
):
    """Resolve the pipeline callable for an EXPLICIT mode — the ladder-
    and retry-aware dispatch path (chaos/degrade.guarded_dispatch) re-
    resolves through here when the mode moves mid-retry."""
    from celestia_app_tpu.kernels.fused import jit_extend_and_dah

    construction = construction or active_construction()
    if mode == "sharded_panel":
        from celestia_app_tpu.kernels.panel_sharded import (
            sharded_panel_pipeline,
        )

        # Host-driven like the panel runner (input never donated), with
        # each step dispatched as ONE mesh-wide program; the EDS output
        # stays row-sharded under the committed extend-mesh layout.
        return sharded_panel_pipeline(k, construction)
    if mode == "panel":
        from celestia_app_tpu.kernels.panel import panel_pipeline

        # Host-driven loop of small jitted programs: the panel runner
        # never donates its input (only its internal accumulator), so
        # the owned/unowned distinction collapses here.
        return panel_pipeline(k, construction)
    if mode in ("fused", "fused_epi"):
        return jit_extend_and_dah(
            k, construction, donate=owned, epilogue=(mode == "fused_epi")
        )
    if mode == "host":
        return _host_pipeline(k, construction)
    return _jit_pipeline(k, construction)


def _owned_input_pipeline(k: int, construction: str | None = None):
    """The pipeline for a caller that OWNS its input buffer (a fresh
    upload): the donating fused program when the seam says fused, the
    staged jit otherwise.  compute() and warmup() both resolve through
    here so a server's warmed compile is exactly the one its blocks run."""
    from celestia_app_tpu.kernels.fused import pipeline_mode_for_k

    return _pipeline_for_mode(pipeline_mode_for_k(k), k, construction,
                              owned=True)


def _panel_fields(mode: str, k: int) -> dict:
    """Journal extras for a panel-streamed dispatch: how many panels the
    square streamed through (the per-dispatch panel-count instrument the
    giant-square memory model is judged by, next to the peak-bytes gauge
    journal.record refreshes).  Sharded dispatches additionally carry
    the mesh width (`shards`) and report their per-device step count."""
    if mode == "sharded_panel":
        from celestia_app_tpu.kernels.panel_sharded import (
            shards_for_k,
            sharded_panel_count,
        )

        return {"panels": sharded_panel_count(k), "shards": shards_for_k(k)}
    if mode != "panel":
        return {}
    from celestia_app_tpu.kernels.panel import panel_count

    return {"panels": panel_count(k)}


# --- batched (multi-square) pipeline ----------------------------------------


@lru_cache(maxsize=None)
def _jit_pipeline_batched(k: int, construction: str, batch: int):
    """vmap of the STAGED composition over a (batch, k, k, S) stack — the
    batched twin of _jit_pipeline, the ladder rung batched dispatch falls
    to when the fused family is degraded."""
    from celestia_app_tpu.trace.device_ledger import track
    from celestia_app_tpu.trace.journal import note_jit_build

    note_jit_build("staged_pipeline_batched")
    return track(
        jax.jit(jax.vmap(_pipeline(k, construction))),
        "staged_pipeline_batched",
        k=k, construction=construction, mode="staged", batch=batch,
    )


def _host_pipeline_batched(k: int, construction: str):
    """The batched degradation floor: each square through the eager host
    pipeline one by one (no compiled program at all), outputs stacked to
    the batched shape.  Exactly what "the unbatched rung" means at the
    bottom of the ladder."""
    run_one = _host_pipeline(k, construction)

    def run(odss):
        outs = [run_one(odss[b]) for b in range(odss.shape[0])]
        return tuple(
            jnp.stack([o[i] for o in outs]) for i in range(4)
        )

    return run


def _batched_pipeline_for_mode(
    mode: str, k: int, batch: int, construction: str | None = None,
    *, owned: bool = False,
):
    """The batched pipeline callable for an EXPLICIT mode: f(odss) with
    odss (batch, k, k, S) -> (eds, row_roots, col_roots, droots), each
    output carrying the leading batch axis.  Keyed per (k, batch, mode)
    through the underlying jit caches; fused_epi folds into the fused
    batched program (the epilogue tile schedule is per-square — see
    kernels/fused.py) so the ladder's batched modes are fused / staged /
    host."""
    from celestia_app_tpu.kernels.fused import jit_extend_and_dah_batched

    construction = construction or active_construction()
    if mode in ("fused", "fused_epi"):
        return jit_extend_and_dah_batched(
            k, batch, construction, donate=owned
        )
    if mode == "host":
        return _host_pipeline_batched(k, construction)
    return _jit_pipeline_batched(k, construction, batch)


def jit_pipeline_batched(k: int, batch: int, construction: str | None = None):
    """Cached batched pipeline for the ACTIVE mode — the multi-square
    analog of jit_pipeline.  Non-donating; the BlockPipeline dispatcher
    (which owns its uploads) resolves owned=True via
    _batched_pipeline_for_mode directly."""
    from celestia_app_tpu.kernels.fused import pipeline_mode

    return _batched_pipeline_for_mode(
        pipeline_mode(), k, batch, construction, owned=False
    )


# --- speculative extend ------------------------------------------------------
#
# $CELESTIA_PIPE_SPECULATE=on arms cross-height speculation: a caller that
# can SEE the next proposal early (a proposer assembling height h+1 while
# height h is still gathering precommits) starts its extend+DAH dispatch
# ahead of adoption and the eventual compute() claims the in-flight result
# instead of dispatching again.  Correctness-free by construction: a claim
# only hits when the claimed ODS bytes (and RS construction) are EXACTLY
# what was speculated — a round change that re-proposes different content
# digests differently and the entry is discarded, costing one wasted
# dispatch and nothing else.  Every lowering is bit-identical (the chaos
# ladder's standing proof), so even a ladder step between speculate and
# claim cannot change a byte.


def speculation_enabled() -> bool:
    """$CELESTIA_PIPE_SPECULATE: "on"/"1" arms the speculative-extend
    seam (default off — speculation trades wasted dispatches for
    latency, a choice the operator makes)."""
    import os

    return os.environ.get("CELESTIA_PIPE_SPECULATE", "").lower() in (
        "on", "1", "true",
    )


def _speculation_counter():
    from celestia_app_tpu.trace.metrics import registry

    return registry().counter(
        "celestia_speculation_total",
        "speculative extends by outcome: hit (claimed) / discard "
        "(content or construction changed before adoption, e.g. a round "
        "change re-proposed the square)",
    )


class SpeculativeExtender:
    """One in-flight speculative extend (the next proposal's square).

    `speculate()` digests the candidate ODS, dispatches the owned-input
    pipeline asynchronously (JAX dispatch is an async enqueue — this
    returns as soon as the program is queued), and parks the device
    handles.  `claim()` returns the finished ExtendedDataSquare iff the
    claimed bytes match the speculated digest; any mismatch — a round
    change, a construction flip — discards the entry and the caller
    computes normally.  `discard()` is the explicit round-change hook.

    Holds at most ONE entry: speculation is about the block after the one
    in consensus, and a second speculate() before the first resolves
    replaces (and counts as discarding) the stale one.
    """

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._entry: dict | None = None

    @staticmethod
    def _digest(ods: np.ndarray) -> bytes:
        import hashlib

        return hashlib.sha256(np.ascontiguousarray(ods).tobytes()).digest()

    def speculate(
        self,
        ods: np.ndarray,
        *,
        height: int | None = None,
        round_: int | None = None,
        construction: str | None = None,
    ) -> bool:
        """Start extending `ods` ahead of adoption; False when the seam
        is off (callers need no second gate).  Rides guarded_dispatch so
        a speculative fault walks the same retry/ladder path a real
        dispatch would — and can never raise into the consensus loop that
        merely HOPED to save latency."""
        if not speculation_enabled():
            return False
        from celestia_app_tpu.chaos.degrade import guarded_dispatch

        k = ods.shape[0]
        construction = construction or active_construction()
        digest = self._digest(ods)
        try:
            from celestia_app_tpu.kernels.fused import pipeline_mode_for_k

            if pipeline_mode_for_k(k) in ("panel", "sharded_panel"):
                # Same panel-granular staging as compute(): the runner
                # uploads one row panel (or one mesh-wide panel step) at
                # a time out of the host copy.
                x = np.ascontiguousarray(ods, dtype=np.uint8)
            else:
                x = jnp.asarray(ods, dtype=jnp.uint8)
            mode, out = guarded_dispatch(
                lambda m: _pipeline_for_mode(m, k, construction, owned=True),
                x,
                refresh=lambda: jnp.asarray(ods, dtype=jnp.uint8),
                k=k,
            )
        except Exception:  # chaos-ok: speculation is best-effort by contract
            return False
        with self._lock:
            if self._entry is not None:
                _speculation_counter().inc(outcome="discard")
            self._entry = {
                "digest": digest, "height": height, "round": round_,
                "k": k, "construction": construction, "mode": mode,
                "outputs": out,
            }
        return True

    def claim(
        self, ods: np.ndarray, construction: str | None = None
    ) -> tuple["ExtendedDataSquare", str] | None:
        """(eds, mode) when the in-flight speculation is EXACTLY the
        square being adopted (bytes + construction), else None — with the
        mismatched entry discarded (the round-change outcome)."""
        with self._lock:
            entry, self._entry = self._entry, None
        if entry is None:
            return None
        construction = construction or active_construction()
        if (
            entry["k"] != ods.shape[0]
            or entry["construction"] != construction
            or entry["digest"] != self._digest(ods)
        ):
            _speculation_counter().inc(outcome="discard")
            return None
        _speculation_counter().inc(outcome="hit")
        eds, rr, cr, droot = entry["outputs"]
        return (
            ExtendedDataSquare(eds, rr, cr, droot, entry["k"]),
            entry["mode"],
        )

    def discard(self) -> bool:
        """Drop the in-flight entry (the explicit round-change signal);
        True when there was one."""
        with self._lock:
            entry, self._entry = self._entry, None
        if entry is None:
            return False
        _speculation_counter().inc(outcome="discard")
        return True

    def pending(self) -> bool:
        with self._lock:
            return self._entry is not None


_SPECULATOR = SpeculativeExtender()


def _speculator_owned_bytes() -> int:
    """Device bytes parked by the in-flight speculation (the outputs
    claim() would adopt) — the ownership-ledger callback; 0 when no
    speculation is pending."""
    with _SPECULATOR._lock:
        entry = _SPECULATOR._entry
    if entry is None:
        return 0
    return sum(
        int(getattr(arr, "nbytes", 0) or 0) for arr in entry["outputs"]
    )


from celestia_app_tpu.trace.device_ledger import register_owner as _register_owner  # noqa: E402

_register_owner("speculative_extend", _speculator_owned_bytes)


def speculator() -> SpeculativeExtender:
    """The process-wide speculative extender (one in-flight next-block
    speculation per process, like the consensus loop it serves)."""
    return _SPECULATOR


def warmup_sizes(upto: int) -> list[int]:
    """The upto=N expansion: every power of two 1..upto (pure, so the
    contract is testable without paying the compiles)."""
    sizes = [1 << i for i in range(upto.bit_length())]
    return [k for k in sizes if k <= upto]


def warmup(
    square_sizes: list[int] | None = None,
    upto: int | None = None,
    constructions: tuple[str, ...] | None = None,
    batches: tuple[int, ...] = (),
) -> list[int]:
    """AOT-compile the fused pipeline for the given square sizes.

    Servers call this at startup so no block ever pays a compile on the
    critical path (SURVEY §7 hard part 4: recompilation must never sit on
    block production; reference TimeoutPropose is 10s). Pass either an
    explicit list or `upto` for every power of two 1..upto. Returns the
    warmed sizes.

    Only the given `constructions` (default: the active one) are warmed —
    flipping $CELESTIA_RS_CONSTRUCTION after warmup puts the next block's
    compile back on the critical path unless the flip target was listed.

    `batches` additionally warms the batched (vmap'd multi-square)
    programs at those coalesced sizes — a server running with
    $CELESTIA_PIPE_BATCH=B should warm batches=tuple(range(2, B+1)) so
    the dispatcher's first coalesced dispatch never pays a compile.

    Mode is resolved PER SIZE: a server configured with
    $CELESTIA_PIPE_PANEL warms the panel-streamed lowering's programs
    (row/column/roots pieces, incl. the short last panel) for exactly
    the sizes the seam engages at, and the materializing programs for
    the rest — the first giant block never eats the compile.
    """
    if square_sizes is None:
        assert upto is not None, "pass square_sizes or upto"
        square_sizes = warmup_sizes(upto)
    if constructions is None:
        constructions = (active_construction(),)
    import time

    from celestia_app_tpu.kernels.fused import pipeline_mode_for_k
    from celestia_app_tpu.trace import journal

    for construction in constructions:
        for k in square_sizes:
            ods = np.zeros((k, k, SHARE_SIZE), dtype=np.uint8)
            # Warm BOTH entries a server dispatches: the donating program
            # (compute(), the block pipeline's feeder) and the undonated
            # jit_pipeline (repair's re-extend, which re-reads its input
            # and must not donate).  Warming only one would leave the
            # other's first dispatch paying a compile on the block path.
            state = pipeline_cache_state(k, construction, owned=True)
            t0 = time.perf_counter()
            owned = _owned_input_pipeline(k, construction)
            jax.block_until_ready(owned(jnp.asarray(ods)))
            pipe = jit_pipeline(k, construction)
            if pipe is not owned:  # staged mode: both entries are one jit
                jax.block_until_ready(pipe(jnp.asarray(ods)))
            journal.record(
                "warmup", k, mode=pipeline_mode_for_k(k), compile=state,
                construction=construction,
                **_panel_fields(pipeline_mode_for_k(k), k),
                warm_ms=(time.perf_counter() - t0) * 1e3,
            )
            from celestia_app_tpu.trace.device_ledger import note_warmup

            note_warmup(k, construction, pipeline_mode_for_k(k))
            for batch in batches:
                if batch < 2:
                    continue  # batch-1 dispatch rides the unbatched entry
                if pipeline_mode_for_k(k) in ("panel", "sharded_panel"):
                    # Panel squares never coalesce (BlockPipeline forces
                    # batch=1 — a vmapped giant batch would materialize B
                    # full EDSes), so a batched program warmed here could
                    # never dispatch: skip the wasted compile.
                    break
                t0 = time.perf_counter()
                stack = jnp.asarray(
                    np.zeros((batch, k, k, SHARE_SIZE), dtype=np.uint8)
                )
                from celestia_app_tpu.kernels.fused import pipeline_mode

                jax.block_until_ready(
                    _batched_pipeline_for_mode(
                        pipeline_mode(), k, batch, construction, owned=True
                    )(stack)
                )
                journal.record(
                    "warmup", k, mode=pipeline_mode(), compile=state,
                    construction=construction, batch_size=batch,
                    warm_ms=(time.perf_counter() - t0) * 1e3,
                )
    return list(square_sizes)


def extra_warmup_sizes() -> list[int]:
    """$CELESTIA_WARMUP_K: comma/space-separated extra square sizes to
    AOT-warm at server startup, beyond the app's effective cap — the
    giant-square operator knob (a node serving k=1024 panel-streamed
    blocks must not compile on its first block).  Malformed or
    non-power-of-two entries are skipped loudly rather than failing the
    start; cmd/appd.py consumes this at --serve."""
    import os
    import sys

    raw = os.environ.get("CELESTIA_WARMUP_K", "")
    sizes: list[int] = []
    for tok in raw.replace(",", " ").split():
        try:
            k = int(tok)
        except ValueError:
            print(f"ignoring malformed CELESTIA_WARMUP_K entry {tok!r}",
                  file=sys.stderr)
            continue
        if 1 <= k <= MAX_CODEC_SQUARE_SIZE and k & (k - 1) == 0:
            sizes.append(k)
        else:
            print(f"ignoring out-of-range CELESTIA_WARMUP_K entry {k}",
                  file=sys.stderr)
    return sizes


# --- fused-vs-staged parity sentinel ---------------------------------------
#
# $CELESTIA_PARITY_SENTINEL=N re-runs every Nth computed block's DAH through
# the STAGED pipeline off the hot path (a daemon thread) and compares data
# roots, ticking celestia_parity_checks_total{result=match|mismatch|error}.
# A mismatch also writes a `parity_mismatch` trace row.  Nothing here ever
# raises into a serving plane, and the hot path only enqueues handles (the
# staged re-run and both host reads happen on the sentinel thread).

import threading as _sentinel_threading

_PARITY_LOCK = _sentinel_threading.Lock()
_PARITY_COUNT = 0
_PARITY_THREADS: list = []


def parity_sentinel_every() -> int:
    """$CELESTIA_PARITY_SENTINEL: check every Nth block (0 = disabled)."""
    import os

    try:
        return int(os.environ.get("CELESTIA_PARITY_SENTINEL", "0") or "0")
    except ValueError:
        return 0


def _maybe_parity_check(ods_host, k: int, construction: str, droot) -> None:
    """Hot-path side: count the block and, every Nth, hand the (immutable)
    ODS + fused root handles to a background checker."""
    every = parity_sentinel_every()
    if every <= 0:
        return
    from celestia_app_tpu.kernels.fused import pipeline_mode_for_k

    if pipeline_mode_for_k(k) not in ("sharded_panel", "panel", "fused",
                                      "fused_epi"):
        # Staged mode (and its eager host twin) already IS the reference
        # lowering: re-running it against itself would burn a duplicate
        # dispatch to report a meaningless "match".
        return
    global _PARITY_COUNT
    with _PARITY_LOCK:
        _PARITY_COUNT += 1
        if _PARITY_COUNT % every:
            return
        _PARITY_THREADS[:] = [t for t in _PARITY_THREADS if t.is_alive()]
    t = _sentinel_threading.Thread(
        target=_parity_check, args=(ods_host, k, construction, droot,
                                    _parity_provenance()),
        daemon=True, name="parity-sentinel",
    )
    with _PARITY_LOCK:
        _PARITY_THREADS.append(t)
    t.start()


def _parity_provenance() -> dict:
    """trace_id/height of the dispatch that armed this check, captured on
    the HOT-PATH side — the checker thread runs after the context is
    gone, and an unstamped mismatch row is unstitchable (trace_lint
    rule 9, trace/timeline.py)."""
    from celestia_app_tpu.trace.context import current_context

    ctx = current_context()
    return {
        "trace_id": ctx.trace_id if ctx is not None else None,
        "height": ctx.baggage.get("height") if ctx is not None else None,
    }


def _parity_check(ods_host, k: int, construction: str, droot,
                  provenance: dict | None = None) -> None:
    from celestia_app_tpu.trace.metrics import registry
    from celestia_app_tpu.trace.tracer import traced

    provenance = provenance or {"trace_id": None, "height": None}
    checks = registry().counter(
        "celestia_parity_checks_total",
        "fused-vs-staged DAH parity sentinel verdicts",
    )
    try:
        staged = _jit_pipeline(k, construction)(jnp.asarray(np.asarray(ods_host)))
        staged_root = np.asarray(staged[3]).tobytes()
        served_root = np.asarray(droot).tobytes()
        if staged_root == served_root:
            checks.inc(result="match")
            return
        checks.inc(result="mismatch")
        traced().write(
            "parity_mismatch", k=k, construction=construction,
            served=served_root.hex(), staged=staged_root.hex(),
            **provenance,
        )
        # A root divergence between bit-identical-by-contract lowerings
        # is the most forensically urgent trigger there is: capture the
        # full state before any ring buffer moves (never raises).
        from celestia_app_tpu.trace.flight_recorder import note_trigger

        note_trigger(
            "parity_mismatch", k=k, construction=construction,
            served=served_root.hex(), staged=staged_root.hex(),
        )
    except Exception as e:  # chaos-ok: the sentinel must never raise
        checks.inc(result="error")
        traced().write(
            "parity_mismatch", k=k, construction=construction,
            error=f"{type(e).__name__}: {e}"[:200], **provenance,
        )


def drain_parity_checks(timeout_s: float = 30.0) -> None:
    """Wait out in-flight sentinel checks (tests / orderly shutdown)."""
    with _PARITY_LOCK:
        threads = list(_PARITY_THREADS)
    for t in threads:
        t.join(timeout_s)


class ExtendedDataSquare:
    """Host handle to a device-computed EDS with its NMT roots."""

    def __init__(self, eds, row_roots, col_roots, data_root, k: int):
        self._eds = eds
        self._row_roots = row_roots
        self._col_roots = col_roots
        self._data_root = data_root
        self.k = k  # ODS width (original square size)
        # Proof-serving state: per-axis host NMT memo (one tree build per
        # touched row/col per HANDLE, not per request) and, when the serve
        # cache retained this height, the device-resident forest handle
        # (serve/cache.CachedForest) whose precomputed levels replace
        # host hashing entirely.
        self._tree_memo: dict = {}
        self._forest = None  # set by serve/cache.ForestCache.put
        # Retention listener: the continuous pipeline's buffer ring hooks
        # this (parallel/pipeline._BufferRing.pin via attach_forest) so a
        # serve-cache retention PINS the ring slot that fed this square —
        # a recycled donated buffer must never alias a retained EDS.
        self._retain_cb = None

    def attach_forest(self, forest) -> None:
        """Hook the retained device forest onto this handle so every
        proof path (incl. proof/share_proof's host constructors) stops
        re-hashing rows the device already hashed."""
        self._forest = forest
        self._tree_memo.clear()  # forest-backed trees are strictly better
        cb = self._retain_cb
        if cb is not None:
            cb()  # tell the feeding buffer ring this square is retained

    def leaf_namespace(self, row: int, col: int) -> bytes:
        """The namespace the (row, col) EDS leaf carries in its trees:
        the share's own namespace inside Q0, the parity namespace in
        every other quadrant (pkg/wrapper/nmt_wrapper.go:93-114)."""
        if row < self.k and col < self.k:
            return bytes(
                np.asarray(self._eds[row, col, :NAMESPACE_SIZE]).tobytes()
            )
        return PARITY_NAMESPACE_BYTES

    def _axis_tree(self, axis: str, index: int, *, host: bool = False):
        """Memoized per-line NMT for one row ("row") or column ("col").

        Returns an object with the `levels()` surface nmt.proof consumes:
        a forest-backed view (pure indexing) when the serve cache retained
        this square, else a freshly built host NamespacedMerkleTree whose
        leaves follow the full-EDS quadrant namespace rule (Q0 leaves own
        their namespace; EVERY other quadrant is parity — `_row_tree`'s
        old c<k rule was only valid for top rows).  `host=True` forces
        the from-scratch host build even with a forest resident — the
        sampler's bit-exactness fallback must not depend on the machinery
        it is the fallback FOR.
        """
        key = (axis, index, host)
        cached = self._tree_memo.get(key)
        if cached is not None:
            return cached
        if self._forest is not None and not host:
            tree = self._forest.line_tree(axis, index)
        else:
            from celestia_app_tpu.nmt.tree import NamespacedMerkleTree

            line = (
                np.asarray(self._eds[index])
                if axis == "row"
                else np.asarray(self._eds[:, index])
            )
            tree = NamespacedMerkleTree()
            for j in range(2 * self.k):
                r, c = (index, j) if axis == "row" else (j, index)
                ns = (
                    bytes(line[j, :NAMESPACE_SIZE].tobytes())
                    if r < self.k and c < self.k
                    else PARITY_NAMESPACE_BYTES
                )
                tree.push(ns + bytes(line[j].tobytes()))
        self._tree_memo[key] = tree
        return tree

    def row_tree(self, row: int, *, host: bool = False):
        return self._axis_tree("row", row, host=host)

    def col_tree(self, col: int, *, host: bool = False):
        return self._axis_tree("col", col, host=host)

    @property
    def width(self) -> int:
        """EDS width (2k), matching rsmt2d.ExtendedDataSquare.Width()."""
        return 2 * self.k

    @classmethod
    def compute(
        cls, ods: np.ndarray, construction: str | None = None
    ) -> "ExtendedDataSquare":
        import time

        from celestia_app_tpu.kernels.fused import pipeline_mode
        from celestia_app_tpu.trace import journal

        from celestia_app_tpu.chaos.degrade import guarded_dispatch

        k = ods.shape[0]
        if k & (k - 1) or not 1 <= k <= MAX_CODEC_SQUARE_SIZE:
            raise ValueError(f"invalid square size {k}")
        assert ods.shape == (k, k, SHARE_SIZE), ods.shape
        spec_outcome = None
        if speculation_enabled() and _SPECULATOR.pending():
            claimed = _SPECULATOR.claim(np.asarray(ods), construction)
            if claimed is not None:
                # The dispatch already ran at speculate() time; this call
                # pays a content digest and nothing else.  compile="hit"
                # by construction (speculate built the program).
                eds_obj, spec_mode = claimed
                journal.record(
                    "compute", k, mode=spec_mode, compile="hit",
                    speculation="hit",
                )
                _maybe_parity_check(
                    np.asarray(ods), k,
                    construction or active_construction(),
                    eds_obj._data_root,
                )
                return eds_obj
            # A pending entry that did not match IS the round-change
            # outcome: the square was re-proposed with different bytes
            # and the wasted dispatch is discarded, never served.
            spec_outcome = "discard"
        sentinel_input = None  # a buffer still valid AFTER the dispatch
        if isinstance(ods, jax.Array):
            # jnp.asarray is a no-copy pass-through for a device array, so
            # donating here would invalidate the CALLER'S buffer.  Their
            # array, their lifetime: take the non-donating pipeline.
            if ods.dtype != jnp.uint8:  # the host path coerces; so must this
                ods = jnp.asarray(ods, dtype=jnp.uint8)
            state = pipeline_cache_state(k, construction)
            t0 = time.perf_counter()
            mode, (eds, rr, cr, droot) = guarded_dispatch(
                lambda m: _pipeline_for_mode(m, k, construction), ods, k=k
            )
            journal.record(
                "compute", k, mode=mode, compile=state,
                dispatch_ms=(time.perf_counter() - t0) * 1e3,
                **_panel_fields(mode, k),
                **({"speculation": spec_outcome} if spec_outcome else {}),
            )
            sentinel_input = ods  # undonated: still live and immutable
        else:
            # The upload below is this call's own buffer, never read again
            # — the donating pipeline may reuse it as extension scratch.
            # A retry after a REAL mid-dispatch failure re-uploads from
            # the host copy, so donation never poisons the retry.
            state = pipeline_cache_state(k, construction, owned=True)
            t0 = time.perf_counter()
            from celestia_app_tpu.kernels.fused import pipeline_mode_for_k

            if pipeline_mode_for_k(k) in ("panel", "sharded_panel"):
                # Panel mode streams panels out of the HOST copy one at a
                # time (the sharded runner additionally lays each step
                # out row-sharded across the mesh) — a whole-square
                # upload here would stage the giant ODS device-resident
                # next to the half-EDS accumulator, breaking the
                # documented residency bound.  A mid-call ladder fall
                # still works: the materializing jits accept the host
                # array and upload at dispatch.
                x = np.ascontiguousarray(ods, dtype=np.uint8)
            else:
                x = jnp.asarray(ods, dtype=jnp.uint8)
            t1 = time.perf_counter()
            mode, (eds, rr, cr, droot) = guarded_dispatch(
                lambda m: _pipeline_for_mode(m, k, construction, owned=True),
                x,
                refresh=lambda: jnp.asarray(ods, dtype=jnp.uint8),
                k=k,
            )
            journal.record(
                "compute", k, mode=mode, compile=state,
                upload_ms=(t1 - t0) * 1e3,
                dispatch_ms=(time.perf_counter() - t1) * 1e3,
                **_panel_fields(mode, k),
                **({"speculation": spec_outcome} if spec_outcome else {}),
            )
            sentinel_input = ods  # the host copy (x may be donated away)
        _maybe_parity_check(
            sentinel_input, k, construction or active_construction(), droot
        )
        return cls(eds, rr, cr, droot, k)

    # --- rsmt2d-surface accessors (host copies) ---------------------------
    def squared(self) -> np.ndarray:
        return np.asarray(self._eds)

    def row(self, i: int) -> np.ndarray:
        return np.asarray(self._eds[i])

    def col(self, j: int) -> np.ndarray:
        return np.asarray(self._eds[:, j])

    def flattened_ods(self) -> list[bytes]:
        q0 = np.asarray(self._eds[: self.k, : self.k])
        return [q0[i, j].tobytes() for i in range(self.k) for j in range(self.k)]

    def ods_namespaces(self) -> np.ndarray:
        """(k*k, NAMESPACE_SIZE) uint8 of the ODS share namespaces, row
        major — the namespace-range scan input (proof.ods_namespace_range);
        memoized so repeated namespace queries pay one device read."""
        cached = getattr(self, "_ods_ns", None)
        if cached is None:
            cached = self._ods_ns = np.asarray(
                self._eds[: self.k, : self.k, :NAMESPACE_SIZE]
            ).reshape(self.k * self.k, NAMESPACE_SIZE)
        return cached

    @staticmethod
    def _roots_list(roots) -> list[bytes]:
        """Roots as a list of bytes, WITHOUT a numpy S-dtype round trip:
        `np.asarray([...bytes...])` infers a fixed-width 'S' dtype whose
        scalars STRIP trailing 0x00 bytes, so any root ending in a zero
        byte (1 in 256) came back one byte short on handles constructed
        from Python lists — the swarm harness's per-leg handles served
        proofs that could never verify on exactly those lines."""
        if isinstance(roots, (list, tuple)):
            return [bytes(r) for r in roots]
        rr = np.asarray(roots)
        return [rr[i].tobytes() for i in range(rr.shape[0])]

    def row_roots(self) -> list[bytes]:
        return self._roots_list(self._row_roots)

    def col_roots(self) -> list[bytes]:
        return self._roots_list(self._col_roots)

    def data_root(self) -> bytes:
        if isinstance(self._data_root, (bytes, bytearray)):
            return bytes(self._data_root)  # no S-dtype trailing-NUL strip
        return np.asarray(self._data_root).tobytes()


def extend_shares(
    shares: list[bytes], construction: str | None = None
) -> ExtendedDataSquare:
    """Reference pkg/da/data_availability_header.go:65 ExtendShares parity.

    shares: row-major flattened ODS; length must be a square of a power of
    two within bounds.  `construction` pins the RS generator for callers
    that must hold one across several calls (a consensus loop mid-block);
    default resolves the active construction per call.

    $CELESTIA_SQUARE_BACKEND=bridge routes the extension through the C ABI
    worker (bridge/, the reference's wrapper/nmt_wrapper.go:73-86 seam for
    a host-language consensus daemon); any bridge fault falls back to the
    in-process device pipeline — the node must keep committing, and both
    paths are bit-identical, so the fallback never forks consensus.
    """
    n = len(shares)
    k = int(round(n ** 0.5))
    if k * k != n:
        raise ValueError(f"share count {n} is not a perfect square")
    if k & (k - 1) or k > MAX_CODEC_SQUARE_SIZE:
        raise ValueError(f"invalid square size {k}")
    for i, s in enumerate(shares):
        if len(s) != SHARE_SIZE:
            raise ValueError(f"share {i} has length {len(s)}, want {SHARE_SIZE}")
    ods = np.frombuffer(b"".join(shares), dtype=np.uint8).reshape(k, k, SHARE_SIZE)
    if square_backend() == "bridge":
        result = _try_bridge_extend(ods)
        if result is not None:
            return result
    return ExtendedDataSquare.compute(ods, construction)


# --- bridge backend (C ABI worker) -----------------------------------------

import threading as _threading

_BRIDGE_CLIENT = None
_BRIDGE_LOCK = _threading.Lock()  # created at import: first-use is racy


def square_backend() -> str:
    """The active square-extension backend: "device" (in-process jit, the
    default) or "bridge" ($CELESTIA_SQUARE_BACKEND)."""
    import os

    return os.environ.get("CELESTIA_SQUARE_BACKEND", "device")


def _bridge_lib_path() -> str:
    import os

    default = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "bridge", "build", "libcelestia_square_bridge.so",
    )
    return os.environ.get("CELESTIA_BRIDGE_LIB", default)


def _bridge_client():
    """Process-wide BridgeClient, created on first use (spawns the
    persistent worker). Raises on init failure — the caller falls back."""
    global _BRIDGE_CLIENT

    with _BRIDGE_LOCK:
        if _BRIDGE_CLIENT is None:
            from celestia_app_tpu.bridge.client import BridgeClient

            _BRIDGE_CLIENT = BridgeClient(_bridge_lib_path())
        return _BRIDGE_CLIENT


def _reset_bridge() -> None:
    """Drop the (possibly dead) client so a later block can retry init."""
    global _BRIDGE_CLIENT
    client, _BRIDGE_CLIENT = _BRIDGE_CLIENT, None
    if client is not None:
        try:
            client.shutdown()
        except Exception:  # chaos-ok: tearing down an already-dead worker
            pass


def _try_bridge_extend(ods: np.ndarray) -> ExtendedDataSquare | None:
    """One bridge round-trip; None on any fault (caller falls back).

    The fallback contract: a killed/hung worker must cost one failed call,
    not the block — the client is reset so the NEXT block retries a fresh
    worker while this one rides the device path.
    """
    import sys

    k = ods.shape[0]
    try:
        eds, rr, cr, droot = _bridge_client().extend_and_dah(ods)
        return ExtendedDataSquare(
            eds, rr, cr, np.frombuffer(droot, dtype=np.uint8), k
        )
    except Exception as e:  # chaos-ok: any bridge fault -> device path
        print(f"square bridge fault ({e}); falling back to device pipeline",
              file=sys.stderr)
        _reset_bridge()
        return None

"""Multi-chip EDS construction: shard_map over a 1D device mesh.

TPU-native mapping of the reference's per-axis parallelism (SURVEY §2.4):

  P2  row/column axis parallelism  -> the ODS is sharded row-wise across the
      mesh; each device RS-extends and NMT-hashes only its row block.
  P4  transpose between phases     -> one `all_to_all` over ICI re-shards the
      row-extended top half column-wise for the column encode.  This is the
      ring-attention / context-parallel analog for this workload
      (reference: implicit transpose inside rsmt2d, goroutines per axis;
      pkg/da/data_availability_header.go:74).

Row trees never move shares back: each device's column block is a
CONTIGUOUS, ALIGNED power-of-two slice of every row, so its leaf digests
reduce locally to ONE subtree node per row; a single `all_gather` of those
90-byte nodes (2k x 90 per device — vs 2k x 2k/n x 512 of shares) feeds the
top log2(n) levels, computed replicated.  Shares cross the interconnect
exactly once, in the column-phase reshard; everything after ships only
roots.  `make_sharded_dah_pipeline` drops the EDS output entirely for
DAH-only callers, so no share ever re-crosses the ICI (the second share
`all_to_all` in `make_sharded_pipeline` exists purely to hand the caller a
row-sharded EDS).

Per-device column-root blocks (2k/n x 90 bytes) stay sharded out of the
shard_map; XLA inserts the tiny all_gather for the final DAH merkle
(pkg/da/data_availability_header.go:92-108) wherever it is cheapest.

All arithmetic is integer (uint8/int32 matmuls + SHA-256), so the sharded
pipeline is bit-identical to the single-chip path on every device count -
the determinism contract P1 of SURVEY §2.4.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from celestia_app_tpu.parallel._compat import shard_map

from celestia_app_tpu.constants import (
    NAMESPACE_SIZE,
    PARITY_NAMESPACE_BYTES,
    SHARE_SIZE,
)
from celestia_app_tpu.gf.rs import active_construction
from celestia_app_tpu.kernels.merkle import merkle_root_pow2
from celestia_app_tpu.kernels.nmt import (
    leaf_digests,
    reduce_to_width,
    tree_roots_from_digests,
)


def _parity_ns() -> jnp.ndarray:
    return jnp.frombuffer(PARITY_NAMESPACE_BYTES, dtype=jnp.uint8)


def _local_extend_and_roots(k: int, n: int, axis: str, _encode):
    """The shared per-device body: row-sharded ODS block in ->
    (full_cols, row_roots, col_roots_local).

    full_cols is this device's column block of the finished EDS
    ((2k/n, 2k, S), column-major); row_roots (2k, 90) are REPLICATED —
    finished from a 90-byte subtree all_gather, never a share reshard;
    col_roots_local (2k/n, 90) stay sharded.
    """

    def local_step(ods_local: jnp.ndarray):
        # ods_local: (k/n, k, S) — this device's row block of the ODS.
        parity = _parity_ns()
        i = lax.axis_index(axis)

        # Row phase: extend local rows. (k/n, k, S) -> (k/n, 2k, S)
        q1 = _encode(ods_local)
        top_local = jnp.concatenate([ods_local, q1], axis=1)
        # Materialize before the collective: XLA otherwise forwards the two
        # concat operands into a tuple all-to-all with mismatched layouts
        # (rejected by the HLO verifier on the CPU backend).
        top_local = lax.optimization_barrier(top_local)

        # P4: re-shard column-wise. Device j ends up with all k top rows of
        # its 2k/n-column block.  The ONLY collective that moves shares.
        cols_blk = lax.all_to_all(
            top_local, axis, split_axis=1, concat_axis=0, tiled=True
        )  # (k, 2k/n, S)
        cols_local = cols_blk.transpose(1, 0, 2)  # (2k/n, k, S)

        # Column phase: extend every local column of the top half, yielding
        # Q2 and Q3 at once (row/col encodes commute).
        bottom_cols = _encode(cols_local)  # (2k/n, k, S)
        full_cols = jnp.concatenate([cols_local, bottom_cols], axis=1)
        # full_cols: (2k/n, 2k, S) — column-sharded full EDS.

        # Column NMTs on the column-sharded layout (tree per local column,
        # leaves are the 2k rows). Parity namespace everywhere outside Q0
        # (pkg/wrapper/nmt_wrapper.go:93-114).
        local_cols = 2 * k // n
        gcol = i * local_cols + jnp.arange(local_cols)
        grow = jnp.arange(2 * k)
        col_q0 = (gcol[:, None] < k) & (grow[None, :] < k)
        col_ns = jnp.where(
            col_q0[..., None], full_cols[..., :NAMESPACE_SIZE], parity
        )
        # The leaf digest at grid position (row, col) is identical for the
        # row tree and the col tree, so hash each leaf exactly once.  Leaf
        # hashing is 9 SHA-256 blocks/leaf vs 3 for inner nodes; hashing on
        # the column-sharded layout halves the dominant cost per device.
        lmins, _, lhash = leaf_digests(col_ns, full_cols)
        col_roots_local = tree_roots_from_digests(lmins, lmins, lhash)

        # Row trees WITHOUT re-sharding shares: this device's 2k/n columns
        # are a contiguous, aligned power-of-two slice of every row tree's
        # leaves, so they reduce locally to one subtree node per row.  Only
        # those 90-byte nodes cross the ICI; the top log2(n) levels run
        # replicated on every device.
        rmins_l = lmins.transpose(1, 0, 2)  # (2k, 2k/n, 29): T=rows
        rhash_l = lhash.transpose(1, 0, 2)
        smin, smax, shash = reduce_to_width(rmins_l, rmins_l, rhash_l, 1)
        sub = jnp.concatenate(
            [smin[:, 0], smax[:, 0], shash[:, 0]], axis=1
        )  # (2k, 90) — this device's per-row subtree node
        gathered = lax.all_gather(sub, axis)  # (n, 2k, 90), replicated
        g = gathered.transpose(1, 0, 2)  # (2k, n, 90): L=device blocks
        gm = g[..., :NAMESPACE_SIZE]
        gx = g[..., NAMESPACE_SIZE : 2 * NAMESPACE_SIZE]
        gh = g[..., 2 * NAMESPACE_SIZE :]
        tm, tx, th = reduce_to_width(gm, gx, gh, 1)
        row_roots = jnp.concatenate(
            [tm[:, 0], tx[:, 0], th[:, 0]], axis=1
        )  # (2k, 90), replicated

        return full_cols, row_roots, col_roots_local

    return local_step


def make_sharded_pipeline(
    k: int, mesh: Mesh, axis: str = "data", construction: str | None = None
):
    """Build the jitted multi-device pipeline for square size k.

    Returns f(ods) -> (eds, row_roots, col_roots, data_root) where ods is
    (k, k, SHARE_SIZE) uint8 sharded P(axis, None, None); eds comes back
    row-sharded, roots and data root replicated.

    Requires n | k (each device owns k/n ODS rows and 2k/n EDS rows/cols).
    """
    n = mesh.shape[axis]
    if k % n:
        raise ValueError(f"device count {n} must divide square size {k}")
    from celestia_app_tpu.kernels.rs import encode_fn
    from celestia_app_tpu.trace.journal import note_jit_build

    note_jit_build("sharded_pipeline")
    _encode = encode_fn(k, construction)
    body = _local_extend_and_roots(k, n, axis, _encode)

    def local_step(ods_local: jnp.ndarray):
        full_cols, row_roots, col_roots_local = body(ods_local)
        # Hand the caller a ROW-sharded EDS: one more share all_to_all,
        # existing purely for the output layout (roots are already done).
        full_cols = lax.optimization_barrier(full_cols)
        rows_blk = lax.all_to_all(
            full_cols.transpose(1, 0, 2), axis, split_axis=0, concat_axis=1,
            tiled=True,
        )  # (2k/n, 2k, S) — this device's EDS row block.
        return rows_blk, row_roots, col_roots_local

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=P(axis, None, None),
        out_specs=(P(axis, None, None), P(), P(axis, None)),
    )

    def pipeline(ods: jnp.ndarray):
        eds, row_roots, col_roots = sharded(ods)
        droot = merkle_root_pow2(jnp.concatenate([row_roots, col_roots], axis=0))
        return eds, row_roots, col_roots, droot

    in_sh = NamedSharding(mesh, P(axis, None, None))
    rep = NamedSharding(mesh, P())
    from celestia_app_tpu.trace.device_ledger import track

    return track(
        jax.jit(
            pipeline, in_shardings=in_sh, out_shardings=(in_sh, rep, rep, rep)
        ),
        "sharded_pipeline",
        k=k, construction=construction, mode="sharded", shards=n,
    )


def make_sharded_dah_pipeline(
    k: int, mesh: Mesh, axis: str = "data", construction: str | None = None
):
    """DAH-only multi-device pipeline: f(ods) -> (row_roots, col_roots,
    data_root), all replicated — no EDS output.

    Shares cross the ICI exactly once (the column-phase all_to_all);
    everything gathered afterwards is 90-byte roots.  This is the MULTICHIP
    bench row's lowering and the right entry for a DAH-only caller (block
    production where shares are gossiped from the builder, light-client
    header service); when the square itself is needed, use
    make_sharded_pipeline.  Bit-identical roots to the single-chip path.
    """
    n = mesh.shape[axis]
    if k % n:
        raise ValueError(f"device count {n} must divide square size {k}")
    from celestia_app_tpu.kernels.rs import encode_fn
    from celestia_app_tpu.trace.journal import note_jit_build

    note_jit_build("sharded_dah_pipeline")
    _encode = encode_fn(k, construction)
    body = _local_extend_and_roots(k, n, axis, _encode)

    def local_step(ods_local: jnp.ndarray):
        _full_cols, row_roots, col_roots_local = body(ods_local)
        return row_roots, col_roots_local

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=P(axis, None, None),
        out_specs=(P(), P(axis, None)),
    )

    def pipeline(ods: jnp.ndarray):
        row_roots, col_roots = sharded(ods)
        droot = merkle_root_pow2(jnp.concatenate([row_roots, col_roots], axis=0))
        return row_roots, col_roots, droot

    in_sh = NamedSharding(mesh, P(axis, None, None))
    rep = NamedSharding(mesh, P())
    from celestia_app_tpu.trace.device_ledger import track

    return track(
        jax.jit(
            pipeline, in_shardings=in_sh, out_shardings=(rep, rep, rep)
        ),
        "sharded_dah_pipeline",
        k=k, construction=construction, mode="sharded", shards=n,
    )


@lru_cache(maxsize=None)
def default_mesh(n: int | None = None, axis: str = "data") -> Mesh:
    """1D mesh over the first n local devices (all of them by default)."""
    devs = jax.devices()
    n = len(devs) if n is None else n
    return Mesh(np.array(devs[:n]), (axis,))


def sharded_extend_and_dah(ods, mesh: Mesh, axis: str = "data"):
    """Host convenience: place a numpy ODS on the mesh and run the pipeline.

    Journals one block_journal row (source="sharded"): upload is the mesh
    placement, dispatch the async shard_map enqueue — no sync added."""
    import time

    from celestia_app_tpu.gf.rs import active_construction as _active
    from celestia_app_tpu.trace import journal

    k = ods.shape[0]
    state = "hit" if (k, mesh, axis, _active()) in _SHARDED_BUILT else "miss"
    fn = cached_pipeline(k, mesh, axis)
    sh = NamedSharding(mesh, P(axis, None, None))
    t0 = time.perf_counter()
    ods_dev = jax.device_put(jnp.asarray(ods, dtype=jnp.uint8), sh)
    t1 = time.perf_counter()
    out = fn(ods_dev)
    journal.record(
        "sharded", k, mode="sharded", compile=state,
        devices=mesh.shape[axis],
        upload_ms=(t1 - t0) * 1e3,
        dispatch_ms=(time.perf_counter() - t1) * 1e3,
    )
    return out


_SHARDED_BUILT: set[tuple] = set()


@lru_cache(maxsize=None)
def _cached_pipeline(k: int, mesh: Mesh, axis: str, construction: str):
    _SHARDED_BUILT.add((k, mesh, axis, construction))
    return make_sharded_pipeline(k, mesh, axis, construction)


def cached_pipeline(
    k: int, mesh: Mesh, axis: str = "data", construction: str | None = None
):
    """Cached sharded pipeline keyed on (k, mesh, axis, RS construction)."""
    return _cached_pipeline(k, mesh, axis, construction or active_construction())

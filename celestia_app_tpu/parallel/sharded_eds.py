"""Multi-chip EDS construction: shard_map over a 1D device mesh.

TPU-native mapping of the reference's per-axis parallelism (SURVEY §2.4):

  P2  row/column axis parallelism  -> the ODS is sharded row-wise across the
      mesh; each device RS-extends and NMT-hashes only its row block.
  P4  transpose between phases     -> one `all_to_all` over ICI re-shards the
      row-extended top half column-wise for the column encode, and a second
      one brings the finished EDS back to row sharding for the row trees.
      This is the ring-attention / context-parallel analog for this workload
      (reference: implicit transpose inside rsmt2d, goroutines per axis;
      pkg/da/data_availability_header.go:74).

Root gathering is left to the outer jit: per-device root blocks (2k/n x 90
bytes) are tiny, and XLA inserts the all_gather for the final DAH merkle
(pkg/da/data_availability_header.go:92-108) wherever it is cheapest.

All arithmetic is integer (uint8/int32 matmuls + SHA-256), so the sharded
pipeline is bit-identical to the single-chip path on every device count -
the determinism contract P1 of SURVEY §2.4.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from celestia_app_tpu.constants import (
    NAMESPACE_SIZE,
    PARITY_NAMESPACE_BYTES,
    SHARE_SIZE,
)
from celestia_app_tpu.gf.rs import active_construction
from celestia_app_tpu.kernels.merkle import merkle_root_pow2
from celestia_app_tpu.kernels.nmt import leaf_digests, tree_roots_from_digests


def _parity_ns() -> jnp.ndarray:
    return jnp.frombuffer(PARITY_NAMESPACE_BYTES, dtype=jnp.uint8)


def make_sharded_pipeline(
    k: int, mesh: Mesh, axis: str = "data", construction: str | None = None
):
    """Build the jitted multi-device pipeline for square size k.

    Returns f(ods) -> (eds, row_roots, col_roots, data_root) where ods is
    (k, k, SHARE_SIZE) uint8 sharded P(axis, None, None); eds comes back
    row-sharded, roots and data root replicated.

    Requires n | k (each device owns k/n ODS rows and 2k/n EDS rows/cols).
    """
    n = mesh.shape[axis]
    if k % n:
        raise ValueError(f"device count {n} must divide square size {k}")
    from celestia_app_tpu.kernels.rs import encode_fn

    _encode = encode_fn(k, construction)

    def local_step(ods_local: jnp.ndarray):
        # ods_local: (k/n, k, S) — this device's row block of the ODS.
        parity = _parity_ns()
        i = lax.axis_index(axis)

        # Row phase: extend local rows. (k/n, k, S) -> (k/n, 2k, S)
        q1 = _encode(ods_local)
        top_local = jnp.concatenate([ods_local, q1], axis=1)
        # Materialize before the collective: XLA otherwise forwards the two
        # concat operands into a tuple all-to-all with mismatched layouts
        # (rejected by the HLO verifier on the CPU backend).
        top_local = lax.optimization_barrier(top_local)

        # P4: re-shard column-wise. Device j ends up with all k top rows of
        # its 2k/n-column block.
        cols_blk = lax.all_to_all(
            top_local, axis, split_axis=1, concat_axis=0, tiled=True
        )  # (k, 2k/n, S)
        cols_local = cols_blk.transpose(1, 0, 2)  # (2k/n, k, S)

        # Column phase: extend every local column of the top half, yielding
        # Q2 and Q3 at once (row/col encodes commute).
        bottom_cols = _encode(cols_local)  # (2k/n, k, S)
        full_cols = jnp.concatenate([cols_local, bottom_cols], axis=1)
        # full_cols: (2k/n, 2k, S) — column-sharded full EDS.

        # Column NMTs on the column-sharded layout (tree per local column,
        # leaves are the 2k rows). Parity namespace everywhere outside Q0
        # (pkg/wrapper/nmt_wrapper.go:93-114).
        local_cols = 2 * k // n
        gcol = i * local_cols + jnp.arange(local_cols)
        grow = jnp.arange(2 * k)
        col_q0 = (gcol[:, None] < k) & (grow[None, :] < k)
        col_ns = jnp.where(
            col_q0[..., None], full_cols[..., :NAMESPACE_SIZE], parity
        )
        # The leaf digest at grid position (row, col) is identical for the
        # row tree and the col tree, so hash each leaf exactly once (here,
        # column-sharded) and ship the 61-byte (ns, digest) pairs — not the
        # 512-byte shares — through the resharding all_to_all for the row
        # reduction. Leaf hashing is 9 SHA-256 blocks/leaf vs 3 for inner
        # nodes; this halves the dominant hash cost per device.
        lmins, _, lhash = leaf_digests(col_ns, full_cols)
        col_roots_local = tree_roots_from_digests(lmins, lmins, lhash)

        # P4 again: back to row sharding for the row trees and the output.
        # Shares and leaf digests ride one fused all_to_all: concatenate the
        # 61-byte (ns, digest) packs onto the 512-byte shares so the reshard
        # is a single ICI collective instead of two.
        leaf_pack = jnp.concatenate([full_cols, lmins, lhash], axis=2)
        row_pack = lax.all_to_all(
            leaf_pack.transpose(1, 0, 2), axis, split_axis=0, concat_axis=1,
            tiled=True,
        )  # (2k/n, 2k, S+61) — this device's EDS row block + leaf digests.
        rows_blk = row_pack[..., :SHARE_SIZE]
        rmins = row_pack[..., SHARE_SIZE : SHARE_SIZE + NAMESPACE_SIZE]
        rhash = row_pack[..., SHARE_SIZE + NAMESPACE_SIZE :]
        row_roots_local = tree_roots_from_digests(rmins, rmins, rhash)

        return rows_blk, row_roots_local, col_roots_local

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=P(axis, None, None),
        out_specs=(P(axis, None, None), P(axis, None), P(axis, None)),
        check_vma=False,
    )

    def pipeline(ods: jnp.ndarray):
        eds, row_roots, col_roots = sharded(ods)
        droot = merkle_root_pow2(jnp.concatenate([row_roots, col_roots], axis=0))
        return eds, row_roots, col_roots, droot

    in_sh = NamedSharding(mesh, P(axis, None, None))
    rep = NamedSharding(mesh, P())
    return jax.jit(
        pipeline, in_shardings=in_sh, out_shardings=(in_sh, rep, rep, rep)
    )


@lru_cache(maxsize=None)
def default_mesh(n: int | None = None, axis: str = "data") -> Mesh:
    """1D mesh over the first n local devices (all of them by default)."""
    devs = jax.devices()
    n = len(devs) if n is None else n
    return Mesh(np.array(devs[:n]), (axis,))


def sharded_extend_and_dah(ods, mesh: Mesh, axis: str = "data"):
    """Host convenience: place a numpy ODS on the mesh and run the pipeline."""
    k = ods.shape[0]
    fn = cached_pipeline(k, mesh, axis)
    sh = NamedSharding(mesh, P(axis, None, None))
    ods_dev = jax.device_put(jnp.asarray(ods, dtype=jnp.uint8), sh)
    return fn(ods_dev)


@lru_cache(maxsize=None)
def _cached_pipeline(k: int, mesh: Mesh, axis: str, construction: str):
    return make_sharded_pipeline(k, mesh, axis, construction)


def cached_pipeline(
    k: int, mesh: Mesh, axis: str = "data", construction: str | None = None
):
    """Cached sharded pipeline keyed on (k, mesh, axis, RS construction)."""
    return _cached_pipeline(k, mesh, axis, construction or active_construction())

"""Multi-chip erasure repair: decode sweeps sharded over a device mesh.

Completes the §2.4 parallelism story for the repair path (VERDICT r3 —
"repair at speed and at size ... add a sharded variant"): the single-chip
repair (da/repair.py) already runs each same-pattern group as ONE
bit-matmul; here the group's LINES are split across the mesh so each
device decodes 1/n of them, and the final re-extension + NMT verification
runs on the sharded EDS pipeline (parallel/sharded_eds.py).

Sharding shape: the damaged square is small relative to HBM (537 MB at
k=512) and erasure decode must read arbitrary surviving positions, so the
square is REPLICATED and the compute is data-parallel over lines — the
same replicate-the-operand/shard-the-batch tradeoff as the row-sharded
extend's generator matrix.  All arithmetic is integer, so the sharded
repair is bit-identical to the single-chip path on any device count
(determinism contract P1).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from celestia_app_tpu.parallel._compat import shard_map

from celestia_app_tpu.constants import SHARE_SIZE
from celestia_app_tpu.da.dah import DataAvailabilityHeader
from celestia_app_tpu.da.eds import ExtendedDataSquare
from celestia_app_tpu.da.repair import (
    IrrecoverableSquare,
    RootMismatch,
    _put_private,
    _recover_bits_device,
)
from celestia_app_tpu.gf import codec_for_width
from celestia_app_tpu.gf.rs import active_construction
from celestia_app_tpu.kernels.rs import encode_axis
from celestia_app_tpu.parallel.sharded_eds import cached_pipeline


@lru_cache(maxsize=None)
def _sharded_sweep(
    k: int, axis_dim: int, mesh: Mesh, axis: str, construction: str
):
    """One decode of up to 2k same-pattern lines along `axis_dim`,
    line-sharded: each device decodes (2k)/n lines against the replicated
    square and the group's recover matrix.

    Returns f(data, present, line_idx, known_idx, R_bits) -> data' with
    the group's lines decoded (survivors authoritative), exactly like
    da/repair._jit_sweep but with the line batch split across the mesh.
    """
    codec = codec_for_width(k, construction)
    m = codec.field.m

    def local(data, present, line_idx_local, known_idx, R_bits):
        # data/present replicated; line_idx_local: this device's (2k)/n
        # group lines, padded with the out-of-range sentinel 2k (gathers
        # clamp; the outer scatter drops padded writes via mode="drop").
        clamped = jnp.clip(line_idx_local, 0, 2 * k - 1)
        if axis_dim == 0:
            rows = data[clamped]  # (L/n, 2k, S)
            known = jnp.take(rows, known_idx, axis=1)
            full = encode_axis(known, R_bits, m, contract_axis=1)
            pm = present[clamped][..., None]
            return jnp.where(pm, rows, full)  # (L/n, 2k, S)
        cols = data[:, clamped]  # (2k, L/n, S)
        known = jnp.take(data, known_idx, axis=0)[:, clamped]
        full = encode_axis(known, R_bits, m, contract_axis=0)
        pm = present[:, clamped][..., None]
        mixed = jnp.where(pm, cols, full)  # (2k, L/n, S)
        return mixed.transpose(1, 0, 2)  # line-major for the out spec

    sharded = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(), P(axis), P(), P()),
        out_specs=P(axis, None, None),
    )

    def sweep(data, present, line_idx, known_idx, R_bits):
        mixed = sharded(data, present, line_idx, known_idx, R_bits)
        if axis_dim == 0:
            return data.at[line_idx].set(mixed, mode="drop")
        return data.at[:, line_idx].set(mixed.transpose(1, 0, 2), mode="drop")

    rep = NamedSharding(mesh, P())
    from celestia_app_tpu.trace.device_ledger import track

    return track(
        jax.jit(
            sweep,
            in_shardings=(rep, rep, NamedSharding(mesh, P(axis)), rep, rep),
            out_shardings=rep,
        ),
        "sharded_repair_sweep",
        k=k, construction=construction, mode="sharded",
        shards=mesh.shape[axis],
    )


def sharded_repair(
    shares: np.ndarray,
    present: np.ndarray,
    mesh: Mesh,
    dah: DataAvailabilityHeader | None = None,
    axis: str = "data",
) -> ExtendedDataSquare:
    """Reconstruct the full EDS with decode sweeps sharded over `mesh`.

    Same contract as da/repair.repair: shares (2k, 2k, SHARE_SIZE) with
    arbitrary bytes at missing positions, present the availability mask;
    survivors stay authoritative and the result must reproduce them (and
    `dah`, if given).  Requires n | 2k.
    """
    shares = np.asarray(shares, dtype=np.uint8)
    present_host = np.array(present, dtype=bool, copy=True)
    n_axis = shares.shape[0]
    if shares.shape != (n_axis, n_axis, SHARE_SIZE) or n_axis % 2:
        raise ValueError(f"bad EDS shape {shares.shape}")
    k = n_axis // 2
    n_dev = mesh.shape[axis]
    if (2 * k) % n_dev:
        raise ValueError(f"device count {n_dev} must divide EDS width {2 * k}")

    # Everything lives ON THE MESH from the start (replicated): mixing
    # single-device-committed arrays with mesh-sharded jit outputs in the
    # final comparison is exactly the cross-sharding footgun.  Uploads go
    # through private copies — present_host is mutated in place below
    # while dispatches are in flight (see da/repair._put_private).
    construction = active_construction()
    rep = NamedSharding(mesh, P())
    damaged = jax.device_put(jnp.asarray(shares), rep)
    present_orig = _put_private(present_host, rep)
    data = damaged

    while not present_host.all():
        progressed = False
        for axis_dim in (0, 1):
            pm = present_host if axis_dim == 0 else present_host.T
            incomplete = ~pm.all(axis=1)
            solvable = incomplete & (pm.sum(axis=1) >= k)
            if not solvable.any():
                continue
            patterns: dict[bytes, list[int]] = {}
            for i in np.nonzero(solvable)[0]:
                patterns.setdefault(pm[i].tobytes(), []).append(int(i))
            present_dev = _put_private(present_host, rep)
            for pat, lines in patterns.items():
                R_bits, known_idx = _recover_bits_device(k, pat, construction)
                padded = lines + [2 * k] * (2 * k - len(lines))
                line_idx = jnp.asarray(padded, dtype=jnp.int32)
                data = _sharded_sweep(k, axis_dim, mesh, axis, construction)(
                    data, present_dev, line_idx, known_idx, R_bits
                )
                if axis_dim == 0:
                    present_host[lines, :] = True
                else:
                    present_host[:, lines] = True
                progressed = True
        if not progressed:
            raise IrrecoverableSquare(
                f"stuck with {int((~present_host).sum())} missing shares"
            )

    # Verification on the SHARDED pipeline: re-extend the recovered ODS
    # across the mesh and check survivors + DAH, with the construction
    # captured at entry (a mid-repair env flip must not split decode/verify).
    pipe = cached_pipeline(k, mesh, axis, construction)
    ods = jax.device_put(
        data[:k, :k], NamedSharding(mesh, P(axis, None, None))
    )
    eds, rr, cr, droot = pipe(ods)
    consistent = jnp.all((eds == damaged) | ~present_orig[..., None])
    if not bool(consistent):
        raise RootMismatch("recovered shares are not a consistent codeword")
    out = ExtendedDataSquare(eds, rr, cr, droot, k)
    if dah is not None:
        got = DataAvailabilityHeader.from_eds(out)
        if not got.equals(dah):
            raise RootMismatch("repaired square does not match the DAH")
    return out

"""jax version compatibility for the multi-chip code.

`shard_map` graduated from jax.experimental to the top-level namespace
(and its replication-check kwarg was renamed check_rep -> check_vma)
across the jax versions this package meets; resolve both here so the
sharded pipelines import one symbol with one signature.
"""

from __future__ import annotations

try:  # jax >= 0.5
    from jax import shard_map as _shard_map
except ImportError:  # this image's 0.4.x: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(f, *, mesh, in_specs, out_specs):
    """shard_map with the replication check disabled, any jax version.

    The check is disabled because the pipelines emit replicated outputs
    produced via all_gather inside the body, which the static checker
    cannot always prove replicated (it is — every device computes the
    same reduction of the same gathered bytes).
    """
    try:
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except TypeError:  # older kwarg name
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )

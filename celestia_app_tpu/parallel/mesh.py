"""Shared mesh / committed-sharding helpers for the sharded serve plane.

The write side (parallel/sharded_eds.py) built its own mesh + shard_map
plumbing inline; the read side needs the same two primitives, so they
live here for both:

  * a cached 1D device mesh over the first N local devices, on a
    dedicated axis name per consumer (the serve plane uses "serve" so a
    serve mesh never collides with the write pipeline's "data" axis);
  * the SNIPPETS pjit contract, applied to row-partitioned flat arrays:
    the producer commits `out_shardings` and every consumer commits the
    MATCHING `in_shardings`, so an array laid out once at admission is
    never resharded between retention and gather — resharding between
    two jitted programs is exactly the hidden cost the contract exists
    to forbid.

The unit of sharding here is a flat (R, W) byte matrix (an NMT forest:
R = every node of every tree, W = 90 digest bytes) partitioned row-wise:
shard i owns the contiguous row block [i*rps, (i+1)*rps) where
rps = padded_rows(R, n) // n.  `shard_of_row` is the pure host-side
routing function; `sharded_gather_fn` is the one program a whole
micro-batch's gathers dispatch as.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

SERVE_AXIS = "serve"
#: The extend plane's mesh axis (kernels/panel_sharded.py): the sharded
#: extend+DAH pipeline partitions row panels over it, and the retained
#: EDS keeps that layout all the way into the serve gather — a separate
#: name from "serve" so the share mesh and the forest mesh can coexist
#: (and differ in width) in one process.
EXTEND_AXIS = "extend"


@lru_cache(maxsize=None)
def device_mesh(n: int, axis: str = SERVE_AXIS):
    """1D mesh over the first n local devices on a named axis.

    Cached so every (n, axis) pair is ONE Mesh object — meshes key the
    jit caches below (and sharded_eds's), so identity matters.
    """
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if n < 1 or n > len(devs):
        raise ValueError(
            f"mesh wants {n} devices, {len(devs)} available"
        )
    return Mesh(np.array(devs[:n]), (axis,))


def row_sharding(mesh, axis: str = SERVE_AXIS):
    """NamedSharding partitioning axis 0 across the mesh — the ONE
    committed layout both the producer (forest build out_shardings) and
    the consumer (gather in_shardings) name, so the array never moves
    between them."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(axis, None))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def padded_rows(rows: int, shards: int) -> int:
    """Smallest multiple of `shards` >= rows (row-wise partition needs
    equal blocks; the pad rows are gathered only as ignored fill)."""
    return ((rows + shards - 1) // shards) * shards


def shard_of_row(flat_row: int, rows_per_shard: int) -> int:
    """Owning shard of one flat row — the pure host-side routing
    function (contiguous equal blocks, so one integer divide)."""
    return flat_row // rows_per_shard


def bucket_pow2(n: int) -> int:
    """Next power of two >= n (>=1): per-shard gather slots are bucketed
    so the jit cache stays O(log max-batch), the da/repair discipline."""
    return 1 << max(0, (max(1, n) - 1).bit_length())


@lru_cache(maxsize=None)
def sharded_gather_fn(mesh, axis: str, rows_per_shard: int, width: int,
                      batch: int):
    """The batched sharded gather: ONE program per dispatch.

    f(flat (shards*rows_per_shard, width) row-sharded,
      idx  (shards, batch) int32 row-sharded, LOCAL row offsets)
        -> (shards, batch, width) row-sharded

    Each device takes only its own rows (indices are pre-routed
    host-side by shard_of_row), so no shard ever touches another's
    block and no collective moves forest bytes.  in_shardings are
    COMMITTED to the admission layout (row_sharding): a resident forest
    is never resharded by the gather — the SNIPPETS pjit contract.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from celestia_app_tpu.parallel._compat import shard_map
    from celestia_app_tpu.trace.journal import note_jit_build

    def local(flat_local, idx_local):
        # flat_local: (rows_per_shard, width); idx_local: (1, batch)
        return jnp.take(flat_local, idx_local[0], axis=0)[None]

    body = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis, None)),
        out_specs=P(axis, None, None),
    )
    fsh = row_sharding(mesh, axis)
    note_jit_build("serve_shard_gather")
    from celestia_app_tpu.trace.device_ledger import track

    return track(
        jax.jit(
            body,
            in_shardings=(fsh, fsh),
            out_shardings=row_sharding(mesh, axis),
        ),
        "serve_shard_gather",
        mode="sharded", batch=batch, shards=mesh.shape[axis],
    )


def row_sharding3(mesh, axis: str = SERVE_AXIS):
    """NamedSharding partitioning axis 0 of a RANK-3 array across the
    mesh — the committed layout of the sharded extend plane's share
    buffers ((rows, cols, SHARE_SIZE); the rank-2 row_sharding is the
    forests').  One producer commits it (the sharded panel pipeline's
    output programs), every consumer names it back (the serve plane's
    share gather), so the EDS never moves between extend, retention,
    and gather."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(axis, None, None))


def xor_allreduce(x, axis: str, n: int):
    """Bitwise-XOR all-reduce over a mesh axis: recursive doubling via
    lax.ppermute (log2 n exchanges, each the full working set).

    lax.psum adds integers — and a sum of packed GF(2) BYTES is not
    their XOR — so the mod-2 collective the sharded column phase needs
    is built from pairwise exchanges: at distance d every device XORs
    its partial with device (i ^ d)'s, and after log2(n) doublings every
    device holds the XOR of all n partials.  Exactness is the panel
    pipeline's own argument (mod-2 of a sum == XOR of per-part mod-2
    partials), applied across devices instead of across panels.
    Requires n to be a power of two (i ^ d must stay inside the mesh).
    """
    from jax import lax

    if n & (n - 1):
        raise ValueError(f"xor_allreduce needs a power-of-two axis, got {n}")
    d = 1
    while d < n:
        perm = [(i, i ^ d) for i in range(n)]
        x = x ^ lax.ppermute(x, axis, perm)
        d *= 2
    return x


@lru_cache(maxsize=None)
def sharded_share_gather_fn(mesh, axis: str, rows_local: int, n_cols: int,
                            width: int, batch: int):
    """The sharded EDS share gather: ONE program per dispatch.

    f(eds (shards*rows_local, n_cols, width) row-sharded,
      idx (shards, batch) int32 row-sharded, LOCAL FLAT share offsets)
        -> (shards, batch, width) row-sharded

    The share at (r, c) lives at flat offset r*n_cols + c of the
    row-major square; contiguous row blocks flatten to contiguous flat
    blocks, so shard-of-share is the same one-divide routing the forest
    gather uses (route_to_shards with rows_per_shard = rows_local *
    n_cols).  in_shardings name the extend pipeline's committed layout
    (row_sharding3): a retained EDS is never resharded by the serve
    plane's share reads — the PR 13 contract extended from the 90-byte
    forests to the shares themselves.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from celestia_app_tpu.parallel._compat import shard_map
    from celestia_app_tpu.trace.journal import note_jit_build

    def local(eds_local, idx_local):
        flat = eds_local.reshape(rows_local * n_cols, width)
        return jnp.take(flat, idx_local[0], axis=0)[None]

    body = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis, None, None), P(axis, None)),
        out_specs=P(axis, None, None),
    )
    note_jit_build("serve_share_gather")
    from celestia_app_tpu.trace.device_ledger import track

    return track(
        jax.jit(
            body,
            in_shardings=(row_sharding3(mesh, axis), row_sharding(mesh, axis)),
            out_shardings=row_sharding3(mesh, axis),
        ),
        "serve_share_gather",
        mode="sharded", batch=batch, shards=mesh.shape[axis],
    )


def route_to_shards(flat_indices, shards: int, rows_per_shard: int):
    """Host-side routing of one micro-batch's flat gather rows —
    vectorized: this runs once per sharded dispatch on the serve hot
    path, so it is numpy arithmetic end to end, no per-index Python.

    Returns (local_idx (shards, bucket) int32, (shard, slot) index
    arrays locating each original row in the gathered output, counts
    per shard (the bounded per-shard metric)).  Pad slots point at
    local row 0 — valid rows gathered as ignored fill.
    """
    idx = np.asarray(flat_indices, dtype=np.int64)
    shard = idx // rows_per_shard
    counts = np.bincount(shard, minlength=shards) if idx.size else (
        np.zeros(shards, dtype=np.int64)
    )
    bucket = bucket_pow2(int(counts.max()) if idx.size else 1)
    # Slot of each row within its shard, in encounter order: positions
    # in the stable shard-sorted order, minus each shard's block start.
    order = np.argsort(shard, kind="stable")
    starts = np.cumsum(counts) - counts
    slot = np.empty(idx.size, dtype=np.int64)
    slot[order] = np.arange(idx.size) - np.repeat(starts, counts)
    local = np.zeros((shards, bucket), dtype=np.int32)
    local[shard, slot] = idx - shard * rows_per_shard
    return local, (shard, slot), counts

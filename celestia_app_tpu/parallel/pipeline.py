"""Pipelined block streaming (SURVEY §2.4 P5, BASELINE config 5).

The reference processes blocks serially per height; the mainnet-replay
benchmark config instead streams consecutive blocks through the device.
JAX dispatch is asynchronous, so overlap falls out of NOT synchronizing:
`submit` enqueues transfer + the fused extend/NMT/DAH program and returns
immediately; the host builds the next square while the device crunches.
`BlockPipeline` bounds the number of in-flight blocks (double buffering by
default) so HBM holds at most `depth` extended squares.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from celestia_app_tpu.da.eds import ExtendedDataSquare, jit_pipeline
from celestia_app_tpu.trace import traced


@dataclass
class _InFlight:
    tag: object
    outputs: tuple  # (eds, row_roots, col_roots, droot) device arrays
    k: int


class BlockPipeline:
    """Bounded-depth asynchronous square pipeline."""

    def __init__(self, k: int, depth: int = 2):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self.k = k
        self.depth = depth
        self._pipe = jit_pipeline(k)
        self._queue: deque[_InFlight] = deque()

    def submit(self, ods: np.ndarray, tag: object = None) -> None:
        """Enqueue one block; blocks the host only when `depth` squares are
        already in flight (back-pressure)."""
        while len(self._queue) >= self.depth:
            self._drain_one()
        out = self._pipe(jnp.asarray(ods, dtype=jnp.uint8))
        self._queue.append(_InFlight(tag, out, self.k))

    def _drain_one(self) -> tuple[object, ExtendedDataSquare]:
        inflight = self._queue.popleft()
        eds, rr, cr, droot = inflight.outputs
        jax.block_until_ready(droot)
        result = ExtendedDataSquare(eds, rr, cr, droot, inflight.k)
        traced().write("block_pipeline", k=inflight.k, tag=str(inflight.tag))
        return inflight.tag, result

    def drain(self):
        """Yield (tag, ExtendedDataSquare) for every remaining block, in order."""
        while self._queue:
            yield self._drain_one()


def stream_blocks(ods_iter, k: int, depth: int = 2):
    """Stream squares through the device with `depth`-deep overlap.

    Yields (tag, ExtendedDataSquare) in submission order; with depth=2 the
    device computes block i+1 while the caller consumes block i (the
    v5e-4 double-buffering shape of BASELINE config 5).
    """
    pipe = BlockPipeline(k, depth)
    for tag, ods in ods_iter:
        while len(pipe._queue) >= pipe.depth:
            yield pipe._drain_one()
        pipe.submit(ods, tag)
    yield from pipe.drain()

"""Pipelined block streaming (SURVEY §2.4 P5, BASELINE config 5).

The reference processes blocks serially per height; the mainnet-replay
benchmark config instead streams consecutive blocks through the device.
Three overlaps compose here:

  * device-side: JAX dispatch is asynchronous, so the fused
    extend/NMT/DAH program for block i+1 queues behind block i without
    host involvement;
  * host-side: the host->device share transfer is driven by a dedicated
    uploader thread, so block i+1's ODS streams in WHILE block i computes.
    This is the part async dispatch alone cannot give: `device_put` of a
    fresh buffer blocks the calling thread for the full transfer (the
    dominant cost when the device sits behind a network tunnel —
    measured ~0.25s vs ~0.08s compute at k=128), so without the uploader
    the pipeline degrades to transfer+compute serial time;
  * upload/dispatch split: transfer and program dispatch run on SEPARATE
    threads (double-buffered hand-off through a bounded queue), so the
    uploader starts block i+1's transfer the moment its slot frees instead
    of first waiting out block i's dispatch call — on a tunnel-backed
    device a dispatch round-trip is milliseconds of dead link time per
    block that the split reclaims.

Every drained block writes one `block_journal` row (trace/journal.py):
upload/dispatch/drain ms plus the two queue stalls (uploader blocked on
the depth-bounded hand-off, dispatcher starved of staged uploads), all
host perf_counter deltas around calls the pipeline already makes — the
only device sync remains the drain's existing block_until_ready.

`BlockPipeline` bounds in-flight blocks (double buffering by default) so
HBM holds at most `depth` extended squares.  When the fused lowering is
active (kernels/fused.pipeline_mode), each uploaded ODS buffer is DONATED
to its dispatch — the pipeline owns the upload, nothing re-reads it, and
XLA may reuse it as extension scratch, which is what keeps depth>1
affordable at k=512 (one 134 MB scratch saved per in-flight block).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field

import jax
import numpy as np

from celestia_app_tpu.da.eds import (
    ExtendedDataSquare,
    _owned_input_pipeline,
    pipeline_cache_state,
)
from celestia_app_tpu.gf.rs import active_construction
from celestia_app_tpu.trace import journal

_SENTINEL = object()


def _queue_depth_gauge():
    from celestia_app_tpu.trace.metrics import registry

    return registry().gauge(
        "celestia_pipeline_queue_depth",
        "blocks resident per block-pipeline hand-off queue",
    )


@dataclass
class _InFlight:
    tag: object
    outputs: tuple  # (eds, row_roots, col_roots, droot) device arrays
    k: int
    meta: dict = field(default_factory=dict)  # stage timings for the journal


class BlockPipeline:
    """Bounded-depth asynchronous square pipeline with a transfer uploader
    and a separate dispatcher (double-buffered upload/compute overlap)."""

    def __init__(self, k: int, depth: int = 2):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self.k = k
        self.depth = depth
        # A pipeline is bound to the RS construction active at creation:
        # every block it streams uses this one generator, even if
        # $CELESTIA_RS_CONSTRUCTION flips while blocks are in flight.
        self.construction = active_construction()
        # Journal context: pipeline mode + whether this (k, construction)
        # pays a jit build, both pinned before the wrapper is built.  The
        # first journaled block carries the init-time compile state; every
        # later row is by definition a hit.
        from celestia_app_tpu.kernels.fused import pipeline_mode

        self._mode = pipeline_mode()
        self._compile_state = pipeline_cache_state(
            k, self.construction, owned=True
        )
        # The pipeline owns each uploaded buffer and uses it exactly once,
        # so it rides the owned-input entry: the donating fused program by
        # default, the staged jit when the seam says staged.
        self._pipe = _owned_input_pipeline(k, self.construction)
        # submit -> _tasks -> [uploader: device_put] -> _staged
        #        -> [dispatcher: program dispatch] -> _done
        # _tasks/_done bounded by depth: at most `depth` squares in flight
        # on the device and `depth` host buffers waiting to transfer.
        # _staged is a SINGLE-slot hand-off — dispatch is a cheap async
        # enqueue, so one transferred-but-undispatched ODS is all the
        # overlap needs, and the device high-water mark stays at the
        # documented `depth` squares instead of depth + staged uploads.
        self._tasks: queue.Queue = queue.Queue(maxsize=depth)
        self._staged: queue.Queue = queue.Queue(maxsize=1)
        self._done: queue.Queue = queue.Queue(maxsize=depth)
        self._error: BaseException | None = None
        self._stopping = False
        self._closed = False
        self._finished = False  # a _done sentinel has been consumed
        self._uploader = threading.Thread(target=self._upload, daemon=True)
        self._dispatcher = threading.Thread(target=self._dispatch, daemon=True)
        self._uploader.start()
        self._dispatcher.start()

    def _upload(self) -> None:
        failed = False
        while True:
            item = self._tasks.get()
            if item is _SENTINEL:
                self._staged.put(_SENTINEL)
                return
            if failed or self._stopping:
                continue  # keep consuming so no producer blocks forever
            ods, tag = item
            try:
                t0 = time.perf_counter()
                x = jax.device_put(np.ascontiguousarray(ods))
                t1 = time.perf_counter()
            except BaseException as e:  # surfaced on the next drain
                self._error = e
                self._staged.put(_SENTINEL)
                failed = True
                continue
            # Stage timings ride the hand-off in `meta`; the put-stall
            # (uploader blocked because `depth` squares are already in
            # flight downstream) is written the instant put() returns.
            # The consolidated journal row is built at drain time, a full
            # dispatch later, so the read always sees the value in
            # practice — and the row falls back to 0.0, never a missing
            # field, if this thread were descheduled that whole time.
            meta = {"upload_ms": (t1 - t0) * 1e3}
            self._staged.put((x, tag, meta))
            meta["upload_stall_ms"] = (time.perf_counter() - t1) * 1e3

    def _dispatch(self) -> None:
        failed = False
        while True:
            t0 = time.perf_counter()
            item = self._staged.get()
            starve_ms = (time.perf_counter() - t0) * 1e3
            if item is _SENTINEL:
                self._done.put(_SENTINEL)
                return
            if failed or self._stopping:
                continue
            x, tag, meta = item
            try:
                t1 = time.perf_counter()
                out = self._pipe(x)  # async enqueue; no sync added here
                meta["dispatch_ms"] = (time.perf_counter() - t1) * 1e3
                meta["dispatch_starve_ms"] = starve_ms
            except BaseException as e:
                self._error = e
                self._done.put(_SENTINEL)
                failed = True
                continue
            self._done.put(_InFlight(tag, out, self.k, meta))

    def _materialize(self, inflight: _InFlight) -> tuple[object, ExtendedDataSquare]:
        eds, rr, cr, droot = inflight.outputs
        t0 = time.perf_counter()
        jax.block_until_ready(droot)  # the pipeline's one existing sync
        meta = inflight.meta
        journal.record(
            "stream", inflight.k, mode=self._mode,
            compile=self._compile_state, tag=str(inflight.tag),
            depth=self.depth,
            upload_ms=meta.get("upload_ms", 0.0),
            upload_stall_ms=meta.get("upload_stall_ms", 0.0),
            dispatch_ms=meta.get("dispatch_ms", 0.0),
            dispatch_starve_ms=meta.get("dispatch_starve_ms", 0.0),
            drain_ms=(time.perf_counter() - t0) * 1e3,
        )
        self._compile_state = "hit"  # paid (or confirmed) on the first row
        gauge = _queue_depth_gauge()
        for name, q in (("tasks", self._tasks), ("staged", self._staged),
                        ("done", self._done)):
            gauge.set(q.qsize(), queue=name)
        return inflight.tag, ExtendedDataSquare(eds, rr, cr, droot, inflight.k)

    def submit(self, ods: np.ndarray, tag: object = None) -> None:
        """Enqueue one block; blocks the host only when `depth` squares are
        already in flight (back-pressure)."""
        if self._closed:
            raise RuntimeError("pipeline already closed")
        if self._error is not None:
            raise RuntimeError("pipeline feeder failed") from self._error
        self._tasks.put((ods, tag))

    def _drain_one(self) -> tuple[object, ExtendedDataSquare]:
        inflight = self._done.get()
        if inflight is _SENTINEL:
            self._finished = True
            if self._error is not None:
                raise RuntimeError("pipeline feeder failed") from self._error
            raise RuntimeError("pipeline is closed")
        return self._materialize(inflight)

    def drain(self):
        """Close the intake and yield (tag, ExtendedDataSquare) for every
        remaining block, in order."""
        self._closed = True
        self._tasks.put(_SENTINEL)  # both stages always consume: cannot block
        while True:
            inflight = self._done.get()
            if inflight is _SENTINEL:
                self._finished = True
                if self._error is not None:
                    raise RuntimeError("pipeline feeder failed") from self._error
                return
            yield self._materialize(inflight)

    def close(self) -> None:
        """Abandon the pipeline: stop both stages and drop pending results
        (early-exit path — device buffers held by _done are released).

        Keyed on _finished, NOT _closed: abandoning a drain() mid-stream
        leaves _closed set with results still queued, and an early return
        there would strand the dispatcher blocked on a full _done holding
        `depth` extended squares for the process lifetime."""
        if self._finished:
            return
        self._stopping = True  # stages discard anything still queued
        if not self._closed:
            self._closed = True
            self._tasks.put(_SENTINEL)
        # Unblock the stages if their output queues are full, and drop
        # held outputs.
        while True:
            item = self._done.get()
            if item is _SENTINEL:
                break
        self._finished = True
        self._uploader.join(timeout=5)
        self._dispatcher.join(timeout=5)


def stream_blocks(ods_iter, k: int, depth: int = 2):
    """Stream squares through the device with `depth`-deep overlap.

    Yields (tag, ExtendedDataSquare) in submission order; with depth=2 the
    uploader transfers block i+1 while the device computes block i and the
    caller consumes block i-1 (the v5e-4 double-buffering shape of
    BASELINE config 5).  Abandoning the generator early stops the stages
    and releases in-flight device buffers."""
    pipe = BlockPipeline(k, depth)
    finished = False
    try:
        submitted = drained = 0
        for tag, ods in ods_iter:
            # Keep the intake primed without over-filling HBM: drain once
            # we have more than `depth` submissions outstanding.
            while submitted - drained > depth:
                yield pipe._drain_one()
                drained += 1
            pipe.submit(ods, tag)
            submitted += 1
        for item in pipe.drain():
            yield item
        finished = True
    finally:
        if not finished:
            pipe.close()

"""Pipelined block streaming (SURVEY §2.4 P5, BASELINE config 5).

The reference processes blocks serially per height; the mainnet-replay
benchmark config instead streams consecutive blocks through the device.
Three overlaps compose here:

  * device-side: JAX dispatch is asynchronous, so the fused
    extend/NMT/DAH program for block i+1 queues behind block i without
    host involvement;
  * host-side: the host->device share transfer is driven by a dedicated
    uploader thread, so block i+1's ODS streams in WHILE block i computes.
    This is the part async dispatch alone cannot give: `device_put` of a
    fresh buffer blocks the calling thread for the full transfer (the
    dominant cost when the device sits behind a network tunnel —
    measured ~0.25s vs ~0.08s compute at k=128), so without the uploader
    the pipeline degrades to transfer+compute serial time;
  * upload/dispatch split: transfer and program dispatch run on SEPARATE
    threads (double-buffered hand-off through a bounded queue), so the
    uploader starts block i+1's transfer the moment its slot frees instead
    of first waiting out block i's dispatch call — on a tunnel-backed
    device a dispatch round-trip is milliseconds of dead link time per
    block that the split reclaims.

Every drained block writes one `block_journal` row (trace/journal.py):
upload/dispatch/drain ms plus the two queue stalls (uploader blocked on
the depth-bounded hand-off, dispatcher starved of staged uploads), all
host perf_counter deltas around calls the pipeline already makes — the
only device sync remains the drain's existing block_until_ready.

`BlockPipeline` bounds in-flight blocks (double buffering by default) so
HBM holds at most `depth` extended squares.  When the fused lowering is
active (kernels/fused.pipeline_mode), each uploaded ODS buffer is DONATED
to its dispatch — the pipeline owns the upload, nothing re-reads it, and
XLA may reuse it as extension scratch, which is what keeps depth>1
affordable at k=512 (one 134 MB scratch saved per in-flight block).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field

import jax
import numpy as np

from celestia_app_tpu.da.eds import (
    ExtendedDataSquare,
    _pipeline_for_mode,
    pipeline_cache_state,
)
from celestia_app_tpu.gf.rs import active_construction
from celestia_app_tpu.trace import journal

_SENTINEL = object()

#: Transient-upload retry budget (chaos upload_fail / a flaky transfer
#: link): attempts per block before the pipeline declares the feeder dead.
_UPLOAD_RETRIES = 2
#: Poll interval for the deadline-aware queue waits: every bounded put/get
#: wakes this often to check worker liveness, so a dead stage is reported
#: instead of wedging the caller forever.
_POLL_S = 0.1
#: close()'s inactivity window before a still-alive worker is declared
#: wedged: long enough for a cold large-k jit compile to finish (the
#: slow-but-healthy case), short enough that an abandoned process isn't
#: parked behind a dead device forever.
_CLOSE_STALL_S = 60.0


def _queue_depth_gauge():
    from celestia_app_tpu.trace.metrics import registry

    return registry().gauge(
        "celestia_pipeline_queue_depth",
        "blocks resident per block-pipeline hand-off queue",
    )


def _close_leak_counter():
    from celestia_app_tpu.trace.metrics import registry

    return registry().counter(
        "celestia_pipeline_close_leaked_total",
        "pipeline worker threads still alive after close()'s join timeout",
    )


@dataclass
class _InFlight:
    tag: object
    outputs: tuple  # (eds, row_roots, col_roots, droot) device arrays
    k: int
    meta: dict = field(default_factory=dict)  # stage timings for the journal


class BlockPipeline:
    """Bounded-depth asynchronous square pipeline with a transfer uploader
    and a separate dispatcher (double-buffered upload/compute overlap)."""

    def __init__(self, k: int, depth: int = 2):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self.k = k
        self.depth = depth
        # A pipeline is bound to the RS construction active at creation:
        # every block it streams uses this one generator, even if
        # $CELESTIA_RS_CONSTRUCTION flips while blocks are in flight.
        self.construction = active_construction()
        # Journal context: pipeline mode + whether this (k, construction)
        # pays a jit build, both pinned before the wrapper is built.  The
        # first journaled block carries the init-time compile state; every
        # later row is by definition a hit.
        from celestia_app_tpu.kernels.fused import pipeline_mode

        self._mode = pipeline_mode()
        self._compile_state = pipeline_cache_state(
            k, self.construction, owned=True
        )
        # The pipeline owns each uploaded buffer and uses it exactly once,
        # so it rides the owned-input entry: the donating fused program by
        # default, the staged jit when the seam says staged.  Resolved per
        # MODE so the dispatcher can follow the degradation ladder
        # mid-stream (chaos/degrade.guarded_dispatch re-resolves after a
        # breaker trip).
        self._pipe_mode = self._mode
        self._pipe = _pipeline_for_mode(
            self._mode, k, self.construction, owned=True
        )
        # submit -> _tasks -> [uploader: device_put] -> _staged
        #        -> [dispatcher: program dispatch] -> _done
        # _tasks/_done bounded by depth: at most `depth` squares in flight
        # on the device and `depth` host buffers waiting to transfer.
        # _staged is a SINGLE-slot hand-off — dispatch is a cheap async
        # enqueue, so one transferred-but-undispatched ODS is all the
        # overlap needs, and the device high-water mark stays at the
        # documented `depth` squares instead of depth + staged uploads.
        self._tasks: queue.Queue = queue.Queue(maxsize=depth)
        self._staged: queue.Queue = queue.Queue(maxsize=1)
        self._done: queue.Queue = queue.Queue(maxsize=depth)
        self._error: BaseException | None = None
        self._stopping = False
        self._closed = False
        self._finished = False  # a _done sentinel has been consumed
        self._uploader = threading.Thread(target=self._upload, daemon=True)
        self._dispatcher = threading.Thread(target=self._dispatch, daemon=True)
        self._uploader.start()
        self._dispatcher.start()

    def _upload(self) -> None:
        """Uploader thread body.  The inner loop handles per-block faults
        (store the error, forward the sentinel); the outer wrap catches
        anything that escapes the loop itself, so a worker can die wedged
        but never die SILENT — submit()/drain() raise the stored
        exception instead of hanging behind a thread that no longer
        exists."""
        try:
            self._upload_loop()
        except BaseException as e:  # chaos-ok: worker death must be loud
            if self._error is None:
                self._error = e
            self._force_sentinel(self._staged)
            self._note_death("uploader", e)

    def _upload_loop(self) -> None:
        from celestia_app_tpu import chaos
        from celestia_app_tpu.chaos.degrade import recoveries

        failed = False
        while True:
            item = self._tasks.get()
            if item is _SENTINEL:
                self._staged.put(_SENTINEL)
                return
            if failed or self._stopping:
                continue  # keep consuming so no producer blocks forever
            ods, tag = item
            try:
                t0 = time.perf_counter()
                for attempt in range(_UPLOAD_RETRIES + 1):
                    try:
                        chaos.device_upload()  # injected stall/failure
                        x = jax.device_put(np.ascontiguousarray(ods))
                        break
                    except Exception:  # chaos-ok: bounded upload retry
                        if attempt == _UPLOAD_RETRIES:
                            raise
                        time.sleep(0.002 * (2 ** attempt))
                if attempt:
                    recoveries().inc(seam="device.upload", outcome="retried")
                t1 = time.perf_counter()
            except BaseException as e:  # chaos-ok: stored, surfaced on the next drain
                self._error = e
                self._staged.put(_SENTINEL)
                self._note_death("uploader", e)
                failed = True
                continue
            # Stage timings ride the hand-off in `meta`; the put-stall
            # (uploader blocked because `depth` squares are already in
            # flight downstream) is written the instant put() returns.
            # The consolidated journal row is built at drain time, a full
            # dispatch later, so the read always sees the value in
            # practice — and the row falls back to 0.0, never a missing
            # field, if this thread were descheduled that whole time.
            # The host buffer rides along so a failed DONATED dispatch can
            # re-upload (guarded_dispatch's refresh) — one extra reference
            # per staged block, dropped the moment the dispatch lands.
            meta = {"upload_ms": (t1 - t0) * 1e3}
            self._staged.put((x, tag, meta, ods))
            meta["upload_stall_ms"] = (time.perf_counter() - t1) * 1e3

    def _dispatch(self) -> None:
        try:
            self._dispatch_loop()
        except BaseException as e:  # chaos-ok: worker death must be loud
            if self._error is None:
                self._error = e
            self._force_sentinel(self._done)
            self._note_death("dispatcher", e)

    def _note_death(self, stage: str, err: BaseException) -> None:
        """Black-box a pipeline-fatal stage failure: the journal rows
        around the death are the forensic record and the ring buffer is
        still warm.  ALWAYS called after the death sentinel is delivered
        — capture serializes table tails and probes /healthz, and a
        consumer blocked on the queue must not wait behind forensics.
        note_trigger rate-limits and never raises."""
        from celestia_app_tpu.trace.flight_recorder import note_trigger

        note_trigger(
            "worker_death", stage=stage, k=self.k, depth=self.depth,
            mode=self._mode, error=f"{type(err).__name__}: {err}"[:300],
        )

    @staticmethod
    def _force_sentinel(q: queue.Queue) -> None:
        """Deliver a death sentinel even against a full queue, by evicting
        one staged item per lap.  Dropping in-flight work on a DYING
        pipeline is correct — results past the failure are void — whereas
        a dropped sentinel would starve the downstream consumer into the
        silent wedge this propagation machinery exists to kill.  (This
        thread is the queue's only producer, so the evict-then-put race
        only ever runs against consumers, and converges.)"""
        while True:
            try:
                q.put(_SENTINEL, timeout=0.5)
                return
            except queue.Full:
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass

    def _resolve_pipe(self, mode: str):
        """The owned-input pipeline for `mode`, swapping lowerings when
        the degradation ladder moved it mid-stream (journal rows from then
        on carry the mode blocks actually ran)."""
        if mode != self._pipe_mode:
            self._pipe = _pipeline_for_mode(
                mode, self.k, self.construction, owned=True
            )
            self._pipe_mode = self._mode = mode
        return self._pipe

    def _dispatch_loop(self) -> None:
        from celestia_app_tpu.chaos.degrade import guarded_dispatch

        failed = False
        while True:
            t0 = time.perf_counter()
            item = self._staged.get()
            starve_ms = (time.perf_counter() - t0) * 1e3
            if item is _SENTINEL:
                self._done.put(_SENTINEL)
                return
            if failed or self._stopping:
                continue
            x, tag, meta, ods_host = item
            try:
                t1 = time.perf_counter()
                # Async enqueue with retry + ladder fallback; no sync here.
                _, out = guarded_dispatch(
                    self._resolve_pipe, x,
                    refresh=lambda: jax.device_put(
                        np.ascontiguousarray(ods_host)
                    ),
                )
                meta["dispatch_ms"] = (time.perf_counter() - t1) * 1e3
                meta["dispatch_starve_ms"] = starve_ms
            except BaseException as e:  # chaos-ok: stored, surfaced on the next drain
                self._error = e
                self._done.put(_SENTINEL)
                self._note_death("dispatcher", e)
                failed = True
                continue
            self._done.put(_InFlight(tag, out, self.k, meta))

    def _materialize(self, inflight: _InFlight) -> tuple[object, ExtendedDataSquare]:
        eds, rr, cr, droot = inflight.outputs
        t0 = time.perf_counter()
        try:
            jax.block_until_ready(droot)  # the pipeline's one existing sync
        except Exception:  # chaos-ok: deferred fault -> breaker, then surface
            # Async dispatch defers real execution faults to THIS sync,
            # past guarded_dispatch's reach: this block is lost (the
            # caller sees the error), but the breaker still learns, so a
            # persistent fault steps the ladder for the blocks after it.
            from celestia_app_tpu.chaos.degrade import note_async_device_failure

            note_async_device_failure(self._mode)
            raise
        meta = inflight.meta
        journal.record(
            "stream", inflight.k, mode=self._mode,
            compile=self._compile_state, tag=str(inflight.tag),
            depth=self.depth,
            upload_ms=meta.get("upload_ms", 0.0),
            upload_stall_ms=meta.get("upload_stall_ms", 0.0),
            dispatch_ms=meta.get("dispatch_ms", 0.0),
            dispatch_starve_ms=meta.get("dispatch_starve_ms", 0.0),
            drain_ms=(time.perf_counter() - t0) * 1e3,
        )
        self._compile_state = "hit"  # paid (or confirmed) on the first row
        gauge = _queue_depth_gauge()
        for name, q in (("tasks", self._tasks), ("staged", self._staged),
                        ("done", self._done)):
            gauge.set(q.qsize(), queue=name)
        return inflight.tag, ExtendedDataSquare(eds, rr, cr, droot, inflight.k)

    def _raise_worker_death(self, stage: str) -> None:
        err = self._error
        msg = f"pipeline {stage} thread died"
        if err is not None:
            raise RuntimeError(msg) from err
        raise RuntimeError(msg)

    def submit(self, ods: np.ndarray, tag: object = None,
               timeout_s: float | None = None) -> None:
        """Enqueue one block; blocks the host only when `depth` squares are
        already in flight (back-pressure).

        Deadline-aware: the bounded put wakes periodically to check the
        workers, so a dead uploader raises the stored exception here
        instead of wedging the caller behind a queue nobody drains; with
        `timeout_s` set, sustained back-pressure past the deadline raises
        TimeoutError (the caller's load-shedding hook)."""
        if self._closed:
            raise RuntimeError("pipeline already closed")
        if self._error is not None:
            raise RuntimeError("pipeline feeder failed") from self._error
        deadline = (
            time.monotonic() + timeout_s if timeout_s is not None else None
        )
        while True:
            try:
                self._tasks.put((ods, tag), timeout=_POLL_S)
                return
            except queue.Full:
                if self._error is not None:
                    raise RuntimeError(
                        "pipeline feeder failed"
                    ) from self._error
                if not self._uploader.is_alive():
                    self._raise_worker_death("uploader")
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"pipeline back-pressure: no intake slot within "
                        f"{timeout_s}s (depth={self.depth})"
                    ) from None

    def _get_done(self):
        """One _done item, with the wedge check: a dispatcher that died
        without managing to forward a sentinel leaves the queue silent
        forever — detect it and raise the stored error instead."""
        while True:
            try:
                return self._done.get(timeout=_POLL_S)
            except queue.Empty:
                if not self._dispatcher.is_alive() and self._done.empty():
                    # Leave _finished unset: the caller's close() still
                    # owes the uploader an unblock + leak report.
                    self._raise_worker_death("dispatcher")

    def _drain_one(self) -> tuple[object, ExtendedDataSquare]:
        inflight = self._get_done()
        if inflight is _SENTINEL:
            self._finished = True
            if self._error is not None:
                raise RuntimeError("pipeline feeder failed") from self._error
            raise RuntimeError("pipeline is closed")
        return self._materialize(inflight)

    def drain(self):
        """Close the intake and yield (tag, ExtendedDataSquare) for every
        remaining block, in order.  Blocks computed before a mid-stream
        failure still come out; the stored exception raises at the
        failure point (the sentinel) rather than hanging."""
        self._closed = True
        # A LIVE pipeline always consumes the intake (even post-failure
        # the uploader drains and discards), so the sentinel lands; with
        # EITHER worker dead it may never free — a dead uploader reads
        # nothing, and a dead dispatcher leaves the uploader wedged on the
        # _staged hand-off — so skip the intake rather than blocking on a
        # queue nobody will drain (the death wrappers already force-fed
        # the downstream sentinel that _get_done below will surface).
        while True:
            try:
                self._tasks.put(_SENTINEL, timeout=_POLL_S)
                break
            except queue.Full:
                if (not self._uploader.is_alive()
                        or not self._dispatcher.is_alive()):
                    break
        while True:
            inflight = self._get_done()
            if inflight is _SENTINEL:
                self._finished = True
                if self._error is not None:
                    raise RuntimeError("pipeline feeder failed") from self._error
                return
            yield self._materialize(inflight)

    def close(self) -> None:
        """Abandon the pipeline: stop both stages and drop pending results
        (early-exit path — device buffers held by _done are released).

        Keyed on _finished, NOT _closed: abandoning a drain() mid-stream
        leaves _closed set with results still queued, and an early return
        there would strand the dispatcher blocked on a full _done holding
        `depth` extended squares for the process lifetime.

        Worker death is REPORTED, never swallowed: a stage that outlives
        its join timeout (a genuine wedge — the error-propagation paths
        above cover everything else) logs and ticks
        `celestia_pipeline_close_leaked_total{stage}`."""
        if self._finished:
            return
        self._stopping = True  # stages discard anything still queued
        sentinel_needed = not self._closed
        self._closed = True
        # Unblock the stages if their output queues are full, and drop
        # held outputs.  Bounded waits everywhere: the intake sentinel is
        # offered NON-blocking inside the drain loop — with every queue
        # full and _done undrained, a blocking put here would deadlock
        # against the very back-pressure chain this method exists to
        # unwind — and a dispatcher that died without a sentinel (or
        # wedged outright) must not wedge close() itself.  The deadline
        # measures INACTIVITY (re-armed on every drained item), not total
        # wall clock: an abandoned stream whose first dispatch is mid-
        # jit-compile is slow-but-healthy, not a leak to report.
        deadline = time.monotonic() + _CLOSE_STALL_S
        while time.monotonic() < deadline:
            if sentinel_needed:
                try:
                    self._tasks.put_nowait(_SENTINEL)
                    sentinel_needed = False
                except queue.Full:
                    pass  # a drain below frees the chain; retry next lap
            try:
                item = self._done.get(timeout=_POLL_S)
            except queue.Empty:
                if not sentinel_needed and not self._dispatcher.is_alive():
                    break
                continue
            if item is _SENTINEL:
                break
            deadline = time.monotonic() + _CLOSE_STALL_S  # progress: re-arm
        self._finished = True
        self._uploader.join(timeout=5)
        self._dispatcher.join(timeout=5)
        for stage, thread in (("uploader", self._uploader),
                              ("dispatcher", self._dispatcher)):
            if thread.is_alive():
                import sys

                print(f"BlockPipeline.close: {stage} thread leaked past "
                      f"join timeout (k={self.k})", file=sys.stderr)
                _close_leak_counter().inc(stage=stage)


def stream_blocks(ods_iter, k: int, depth: int = 2):
    """Stream squares through the device with `depth`-deep overlap.

    Yields (tag, ExtendedDataSquare) in submission order; with depth=2 the
    uploader transfers block i+1 while the device computes block i and the
    caller consumes block i-1 (the v5e-4 double-buffering shape of
    BASELINE config 5).  Abandoning the generator early stops the stages
    and releases in-flight device buffers."""
    pipe = BlockPipeline(k, depth)
    finished = False
    try:
        submitted = drained = 0
        for tag, ods in ods_iter:
            # Keep the intake primed without over-filling HBM: drain once
            # we have more than `depth` submissions outstanding.
            while submitted - drained > depth:
                yield pipe._drain_one()
                drained += 1
            pipe.submit(ods, tag)
            submitted += 1
        for item in pipe.drain():
            yield item
        finished = True
    finally:
        if not finished:
            pipe.close()

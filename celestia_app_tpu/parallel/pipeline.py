"""Pipelined block streaming (SURVEY §2.4 P5, BASELINE config 5).

The reference processes blocks serially per height; the mainnet-replay
benchmark config instead streams consecutive blocks through the device.
Two overlaps compose here:

  * device-side: JAX dispatch is asynchronous, so the fused
    extend/NMT/DAH program for block i+1 queues behind block i without
    host involvement;
  * host-side: the host->device share transfer is driven by a dedicated
    feeder thread, so block i+1's ODS streams in WHILE block i computes.
    This is the part async dispatch alone cannot give: `device_put` of a
    fresh buffer blocks the calling thread for the full transfer (the
    dominant cost when the device sits behind a network tunnel —
    measured ~0.25s vs ~0.08s compute at k=128), so without the feeder
    the pipeline degrades to transfer+compute serial time.

`BlockPipeline` bounds in-flight blocks (double buffering by default) so
HBM holds at most `depth` extended squares.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass

import jax
import numpy as np

from celestia_app_tpu.da.eds import ExtendedDataSquare, jit_pipeline
from celestia_app_tpu.gf.rs import active_construction
from celestia_app_tpu.trace import traced

_SENTINEL = object()


@dataclass
class _InFlight:
    tag: object
    outputs: tuple  # (eds, row_roots, col_roots, droot) device arrays
    k: int


class BlockPipeline:
    """Bounded-depth asynchronous square pipeline with a transfer feeder."""

    def __init__(self, k: int, depth: int = 2):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self.k = k
        self.depth = depth
        # A pipeline is bound to the RS construction active at creation:
        # every block it streams uses this one generator, even if
        # $CELESTIA_RS_CONSTRUCTION flips while blocks are in flight.
        self.construction = active_construction()
        self._pipe = jit_pipeline(k, self.construction)
        # submit -> _tasks -> [feeder thread: transfer + dispatch] -> _done
        # Both queues bounded by depth: at most `depth` squares in flight
        # on the device and `depth` ODS buffers waiting to transfer.
        self._tasks: queue.Queue = queue.Queue(maxsize=depth)
        self._done: queue.Queue = queue.Queue(maxsize=depth)
        self._error: BaseException | None = None
        self._stopping = False
        self._closed = False
        self._feeder = threading.Thread(target=self._feed, daemon=True)
        self._feeder.start()

    def _feed(self) -> None:
        failed = False
        while True:
            item = self._tasks.get()
            if item is _SENTINEL:
                self._done.put(_SENTINEL)
                return
            if failed or self._stopping:
                continue  # keep consuming so no producer blocks forever
            ods, tag = item
            try:
                x = jax.device_put(np.ascontiguousarray(ods))
                out = self._pipe(x)
            except BaseException as e:  # surfaced on the next drain
                self._error = e
                self._done.put(_SENTINEL)
                failed = True
                continue
            self._done.put(_InFlight(tag, out, self.k))

    def _materialize(self, inflight: _InFlight) -> tuple[object, ExtendedDataSquare]:
        eds, rr, cr, droot = inflight.outputs
        jax.block_until_ready(droot)
        traced().write("block_pipeline", k=inflight.k, tag=str(inflight.tag))
        return inflight.tag, ExtendedDataSquare(eds, rr, cr, droot, inflight.k)

    def submit(self, ods: np.ndarray, tag: object = None) -> None:
        """Enqueue one block; blocks the host only when `depth` squares are
        already in flight (back-pressure)."""
        if self._closed:
            raise RuntimeError("pipeline already closed")
        if self._error is not None:
            raise RuntimeError("pipeline feeder failed") from self._error
        self._tasks.put((ods, tag))

    def _drain_one(self) -> tuple[object, ExtendedDataSquare]:
        inflight = self._done.get()
        if inflight is _SENTINEL:
            if self._error is not None:
                raise RuntimeError("pipeline feeder failed") from self._error
            raise RuntimeError("pipeline is closed")
        return self._materialize(inflight)

    def drain(self):
        """Close the intake and yield (tag, ExtendedDataSquare) for every
        remaining block, in order."""
        self._closed = True
        self._tasks.put(_SENTINEL)  # feeder always consumes: cannot block
        while True:
            inflight = self._done.get()
            if inflight is _SENTINEL:
                if self._error is not None:
                    raise RuntimeError("pipeline feeder failed") from self._error
                return
            yield self._materialize(inflight)

    def close(self) -> None:
        """Abandon the pipeline: stop the feeder and drop pending results
        (early-exit path — device buffers held by _done are released)."""
        if self._closed:
            return
        self._closed = True
        self._stopping = True  # feeder discards anything still queued
        self._tasks.put(_SENTINEL)
        # Unblock the feeder if _done is full, and drop held outputs.
        while True:
            item = self._done.get()
            if item is _SENTINEL:
                break
        self._feeder.join(timeout=5)


def stream_blocks(ods_iter, k: int, depth: int = 2):
    """Stream squares through the device with `depth`-deep overlap.

    Yields (tag, ExtendedDataSquare) in submission order; with depth=2 the
    feeder transfers block i+1 while the device computes block i and the
    caller consumes block i-1 (the v5e-4 double-buffering shape of
    BASELINE config 5).  Abandoning the generator early stops the feeder
    and releases in-flight device buffers."""
    pipe = BlockPipeline(k, depth)
    finished = False
    try:
        submitted = drained = 0
        for tag, ods in ods_iter:
            # Keep the intake primed without over-filling HBM: drain once
            # we have more than `depth` submissions outstanding.
            while submitted - drained > depth:
                yield pipe._drain_one()
                drained += 1
            pipe.submit(ods, tag)
            submitted += 1
        for item in pipe.drain():
            yield item
        finished = True
    finally:
        if not finished:
            pipe.close()

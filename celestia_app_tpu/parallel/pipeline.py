"""Pipelined block streaming (SURVEY §2.4 P5, BASELINE config 5).

The reference processes blocks serially per height; the mainnet-replay
benchmark config instead streams consecutive blocks through the device.
Three overlaps compose here:

  * device-side: JAX dispatch is asynchronous, so the fused
    extend/NMT/DAH program for block i+1 queues behind block i without
    host involvement;
  * host-side: the host->device share transfer is driven by a dedicated
    uploader thread, so block i+1's ODS streams in WHILE block i computes.
    This is the part async dispatch alone cannot give: `device_put` of a
    fresh buffer blocks the calling thread for the full transfer (the
    dominant cost when the device sits behind a network tunnel —
    measured ~0.25s vs ~0.08s compute at k=128), so without the uploader
    the pipeline degrades to transfer+compute serial time;
  * upload/dispatch split: transfer and program dispatch run on SEPARATE
    threads (double-buffered hand-off through a bounded queue), so the
    uploader starts block i+1's transfer the moment its slot frees instead
    of first waiting out block i's dispatch call — on a tunnel-backed
    device a dispatch round-trip is milliseconds of dead link time per
    block that the split reclaims.

Cross-height continuous batching (this file's third era) adds three legs:

  * persistent donated buffers: a small ring of staging buffers
    (`_BufferRing`, depth+1 slots) is allocated ONCE and recycled across
    blocks — the uploader copies height h+1's shares into a free slot
    while height h is still dispatching, instead of allocating a fresh
    contiguous buffer per height.  A slot is recycled only after its
    batch's drain sync confirms the device consumed it, and a slot whose
    square the serve plane RETAINED (serve/cache.ForestCache — donation
    may alias the upload into the retained EDS) is pinned: the next
    acquire swaps in a fresh backing buffer instead of overwriting bytes
    a proof plane may still be serving.
  * vmap'd multi-square dispatch: with `$CELESTIA_PIPE_BATCH` > 1 (or
    `auto`, driven by the square journal's occupancy signal) the uploader
    coalesces queued same-k squares into one (B, k, k, S) staging slot
    and the dispatcher runs ONE vmapped fused program
    (da/eds._batched_pipeline_for_mode) instead of paying B dispatch
    latencies.  A batched-dispatch fault degrades to per-square dispatch
    through the normal guarded ladder (batched -> unbatched fused ->
    staged -> host), ticking celestia_recoveries_total{outcome=unbatched}.
  * speculative extend lives in da/eds.SpeculativeExtender
    ($CELESTIA_PIPE_SPECULATE): the consensus loop can start extending
    the NEXT proposal while the current height is still voting, and
    compute() claims the in-flight result on a content match (discarding
    on round change — every lowering is bit-identical, so speculation is
    a pure latency trade).

Every drained block writes one `block_journal` row (trace/journal.py):
upload/dispatch/drain ms plus the two queue stalls (uploader blocked on
the depth-bounded hand-off, dispatcher starved of staged uploads) and the
dispatch's `batch_size`, all host perf_counter deltas around calls the
pipeline already makes — the only device sync remains the drain's
existing block_until_ready.

`BlockPipeline` bounds in-flight blocks (double buffering by default) so
HBM holds at most `depth` extended batches.  When the fused lowering is
active (kernels/fused.pipeline_mode), each uploaded ODS buffer is DONATED
to its dispatch — the pipeline owns the upload, nothing re-reads it, and
XLA may reuse it as extension scratch, which is what keeps depth>1
affordable at k=512 (one 134 MB scratch saved per in-flight block).
"""

from __future__ import annotations

import os
import queue
import threading
import time
import weakref
from dataclasses import dataclass, field

import jax
import numpy as np

from celestia_app_tpu.constants import SHARE_SIZE
from celestia_app_tpu.da.eds import (
    ExtendedDataSquare,
    _batched_pipeline_for_mode,
    _pipeline_for_mode,
    pipeline_cache_state,
)
from celestia_app_tpu.gf.rs import active_construction
from celestia_app_tpu.trace import journal

_SENTINEL = object()

#: Transient-upload retry budget (chaos upload_fail / a flaky transfer
#: link): attempts per block before the pipeline declares the feeder dead.
_UPLOAD_RETRIES = 2
#: Poll interval for the deadline-aware queue waits: every bounded put/get
#: wakes this often to check worker liveness, so a dead stage is reported
#: instead of wedging the caller forever.
_POLL_S = 0.1
#: close()'s inactivity window before a still-alive worker is declared
#: wedged: long enough for a cold large-k jit compile to finish (the
#: slow-but-healthy case), short enough that an abandoned process isn't
#: parked behind a dead device forever.
_CLOSE_STALL_S = 60.0
#: The coalescing ceiling $CELESTIA_PIPE_BATCH=auto resolves to when the
#: square journal's occupancy signal says traffic is producing small,
#: under-filled squares (the regime where dispatch latency dominates).
_AUTO_BATCH = 4
#: Occupancy below which `auto` batching engages: a square less than half
#: full at the current k means the proposer is cutting small squares.
_AUTO_OCCUPANCY = 0.5


def env_batch() -> int:
    """$CELESTIA_PIPE_BATCH: how many queued same-k squares one dispatch
    may coalesce.  ""/unset/"0"/"1" = off (every square its own
    dispatch); an integer N > 1 = coalesce up to N; "auto" = consult the
    square journal's occupancy signal — when the last exported square ran
    under 50% occupancy (0.0, an empty square, very much included),
    traffic is producing many small squares and the dispatcher batches up
    to 4, otherwise it stays unbatched."""
    val = os.environ.get("CELESTIA_PIPE_BATCH", "").strip().lower()
    if val in ("", "0", "1", "off"):
        return 1
    if val == "auto":
        from celestia_app_tpu.trace.square_journal import last_square

        last = last_square()
        if last is None:
            return 1  # no traffic signal yet: stay unbatched
        occupancy = last.get("occupancy")
        if occupancy is not None and occupancy < _AUTO_OCCUPANCY:
            return _AUTO_BATCH
        return 1
    try:
        return max(1, int(val))
    except ValueError:
        return 1


def env_batch_cap() -> int:
    """The CEILING $CELESTIA_PIPE_BATCH may ever resolve to — what a
    server's warmup must compile for.  Unlike env_batch() this ignores
    the instantaneous occupancy signal: "auto" at startup sees no
    traffic and env_batch() says 1, but the moment small squares arrive
    it will say _AUTO_BATCH, and THAT first coalesced dispatch must not
    pay a compile on the block path."""
    val = os.environ.get("CELESTIA_PIPE_BATCH", "").strip().lower()
    if val == "auto":
        return _AUTO_BATCH
    return env_batch()


def _queue_depth_gauge():
    from celestia_app_tpu.trace.metrics import registry

    return registry().gauge(
        "celestia_pipeline_queue_depth",
        "blocks resident per block-pipeline hand-off queue",
    )


def _close_leak_counter():
    from celestia_app_tpu.trace.metrics import registry

    return registry().counter(
        "celestia_pipeline_close_leaked_total",
        "pipeline worker threads still alive after close()'s join timeout",
    )


def _ring_occupancy_gauge():
    from celestia_app_tpu.trace.metrics import registry

    return registry().gauge(
        "celestia_pipeline_ring_occupancy",
        "buffer-ring slots by state (free / in_use / pinned-for-swap)",
    )


def _batch_size_histogram():
    from celestia_app_tpu.trace.metrics import registry

    return registry().histogram(
        "celestia_pipeline_batch_size",
        "same-k squares coalesced into one pipeline dispatch",
        buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0),
    )


def _rings_owned_bytes() -> int:
    """Staging bytes held by every live buffer ring's CURRENT backing
    arrays.  A pinned slot swapped out for a fresh buffer stops being
    counted here — its old bytes live exactly as long as the retained
    square, whose owner (serve_forest_cache) already reports them."""
    return sum(
        int(h.nbytes) for ring in list(_ALL_RINGS) for h in ring._hosts
    )


_ALL_RINGS: "weakref.WeakSet[_BufferRing]" = weakref.WeakSet()

from celestia_app_tpu.trace.device_ledger import (  # noqa: E402
    register_owner as _register_ring_owner,
)

_register_ring_owner("pipeline_buffer_ring", _rings_owned_bytes)


class _BufferRing:
    """Persistent staging buffers recycled across blocks.

    `slots` host arrays of shape (batch, k, k, SHARE_SIZE), allocated once
    at pipeline construction: the uploader copies each height's shares
    into a free slot (a memcpy into memory the allocator already owns —
    no per-height allocation, and on pinned-memory backends the transfer
    engine reads straight out of it) and `device_put`s the filled rows.

    Recycling contract:

      * a slot frees only when its batch's DRAIN confirmed the device
        consumed the upload (`release` after the batch's last
        block_until_ready) — `device_put` may be zero-copy on CPU, so
        overwriting a slot whose program hasn't executed yet would
        corrupt an in-flight square;
      * a slot whose square was RETAINED by the serve plane
        (ForestCache.put -> eds.attach_forest -> `pin`) is never
        overwritten while pinned: the next `acquire` of a pinned slot
        swaps in a FRESH backing array (write-after-retain is a fresh
        slot) and the old buffer lives exactly as long as the retained
        square does.

    Why the pin is belt-and-braces rather than load-bearing today: the
    retained EDS holds program OUTPUTS, and XLA only aliases an input
    buffer into an output via donation — which it refuses for buffers it
    does not own.  A zero-copy `device_put` (CPU) leaves the buffer
    externally owned, so donation is "not usable" there (the filtered
    warning), and a copying `device_put` (TPU) means the device buffer
    is jax-owned HBM that never references these staging bytes.  Either
    way no current backend can make a retained EDS alias a ring slot.
    The pin exists for a future unified-memory backend where that
    reasoning breaks — and because retention (at commit) can land after
    the drain already released the slot, `pin` takes the slot GENERATION
    its square was staged under: a pin that arrives after the slot was
    re-acquired is counted on `late_pins` (the fence fired after the
    window on a hypothetical aliasing backend — observable, not silent)
    and still pins forward.
    """

    def __init__(self, k: int, slots: int, batch: int):
        self.k = k
        self.batch = batch
        self._cond = threading.Condition()
        self._hosts = [
            np.zeros((batch, k, k, SHARE_SIZE), dtype=np.uint8)
            for _ in range(slots)
        ]
        self._free: list[int] = list(range(slots))
        self._pinned: set[int] = set()
        self._gen = [0] * slots  # bumped per acquire: late-pin detection
        self.swaps = 0  # pinned slots replaced with a fresh buffer
        self.late_pins = 0  # pins that arrived after the slot was reused
        _ALL_RINGS.add(self)

    def acquire(self, timeout_s: float) -> int | None:
        """A free slot id (its buffer safe to overwrite), or None on
        timeout so the caller can re-check liveness.  A pinned slot is
        swapped for a fresh buffer here — the retained square keeps the
        old bytes for its own lifetime."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while not self._free:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cond.wait(remaining)
            sid = self._free.pop()
            if sid in self._pinned:
                self._hosts[sid] = np.zeros_like(self._hosts[sid])
                self._pinned.discard(sid)
                self.swaps += 1
            self._gen[sid] += 1
            return sid

    def generation(self, sid: int) -> int:
        with self._cond:
            return self._gen[sid]

    def host(self, sid: int) -> np.ndarray:
        return self._hosts[sid]

    def release(self, sid: int) -> None:
        with self._cond:
            self._free.append(sid)
            self._cond.notify()

    def pin(self, sid: int, gen: int | None = None) -> None:
        """Mark a slot's current buffer as retained downstream: it will
        be swapped, not overwritten, on its next acquire.  `gen` is the
        generation the retained square was staged under (see the class
        docstring): a pin landing after the slot was already re-acquired
        is counted on `late_pins` — on every current backend that is
        harmless (outputs never alias staging bytes), and counting it
        keeps the fence's coverage observable instead of silently
        assumed."""
        with self._cond:
            if gen is not None and self._gen[sid] != gen:
                self.late_pins += 1
            self._pinned.add(sid)

    def states(self) -> dict[str, int]:
        with self._cond:
            free = len(self._free)
            pinned = len(self._pinned)
        return {
            "free": free,
            "in_use": len(self._hosts) - free,
            "pinned": pinned,
        }


@dataclass
class _InFlight:
    tag: object
    outputs: tuple  # (eds, row_roots, col_roots, droot) device arrays
    k: int
    meta: dict = field(default_factory=dict)  # stage timings for the journal
    mode: str | None = None  # the lowering THIS square actually ran
    slot: tuple | None = None  # (ring, sid, refcount-list, generation)

    def release_slot(self) -> None:
        if self.slot is None:
            return
        ring, sid, ref, _gen = self.slot
        ref[0] -= 1
        if ref[0] == 0:
            ring.release(sid)
        self.slot = None


class BlockPipeline:
    """Bounded-depth asynchronous square pipeline with a transfer uploader
    and a separate dispatcher (double-buffered upload/compute overlap),
    optionally coalescing queued same-k squares into one vmapped dispatch
    (`batch` / $CELESTIA_PIPE_BATCH)."""

    def __init__(self, k: int, depth: int = 2, batch: int | None = None):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self.k = k
        self.depth = depth
        self.batch = max(1, batch if batch is not None else env_batch())
        # Panel streaming ($CELESTIA_PIPE_PANEL, kernels/panel.py): when
        # the seam engages at this k, the staging slot is consumed
        # PANEL-granularly — the uploader skips the whole-ODS device_put
        # and the dispatcher's panel runner uploads one row panel at a
        # time out of the persistent host slot, so the device never
        # stages a giant square whole next to the pipeline's working
        # set.  Panel squares are giant by definition and never coalesce
        # (the vmapped batched program would materialize B full EDSes),
        # so batching is forced off.  The multi-chip sharded rung
        # ($CELESTIA_EXTEND_SHARDS, kernels/panel_sharded.py) rides the
        # same staging: it engages only where the panel seam does, and
        # its runner consumes the host slot one mesh-wide panel step at
        # a time.
        from celestia_app_tpu.kernels.panel import panel_rows

        self._panel = panel_rows(k)
        if self._panel:
            self.batch = 1
        # A pipeline is bound to the RS construction active at creation:
        # every block it streams uses this one generator, even if
        # $CELESTIA_RS_CONSTRUCTION flips while blocks are in flight.
        self.construction = active_construction()
        # Journal context: pipeline mode + whether this (k, construction)
        # pays a jit build, both pinned before the wrapper is built.  The
        # first journaled block carries the init-time compile state; every
        # later row is by definition a hit.
        from celestia_app_tpu.kernels.fused import pipeline_mode_for_k

        self._mode = pipeline_mode_for_k(k)
        self._compile_state = pipeline_cache_state(
            k, self.construction, owned=True
        )
        # The pipeline owns each uploaded buffer and uses it exactly once,
        # so it rides the owned-input entry: the donating fused program by
        # default, the staged jit when the seam says staged.  Resolved per
        # MODE so the dispatcher can follow the degradation ladder
        # mid-stream (chaos/degrade.guarded_dispatch re-resolves after a
        # breaker trip).
        self._pipe_mode = self._mode
        self._pipe = _pipeline_for_mode(
            self._mode, k, self.construction, owned=True
        )
        # One persistent staging buffer per in-flight batch plus one being
        # filled: the uploader writes height h+1 into a free slot while
        # height h is still dispatching, and nothing allocates per block.
        self._ring = _BufferRing(k, slots=depth + 1, batch=self.batch)
        # submit -> _tasks -> [uploader: stage + device_put] -> _staged
        #        -> [dispatcher: program dispatch] -> _done
        # _tasks/_done bounded by depth: at most `depth` batches in flight
        # on the device and `depth` host squares waiting to transfer.
        # _staged is a SINGLE-slot hand-off — dispatch is a cheap async
        # enqueue, so one transferred-but-undispatched batch is all the
        # overlap needs, and the device high-water mark stays at the
        # documented `depth` batches instead of depth + staged uploads.
        self._tasks: queue.Queue = queue.Queue(maxsize=max(depth, self.batch))
        self._staged: queue.Queue = queue.Queue(maxsize=1)
        self._done: queue.Queue = queue.Queue(maxsize=depth * self.batch)
        self._error: BaseException | None = None
        self._stopping = False
        self._closed = False
        self._finished = False  # a _done sentinel has been consumed
        self._uploader = threading.Thread(target=self._upload, daemon=True)
        self._dispatcher = threading.Thread(target=self._dispatch, daemon=True)
        self._uploader.start()
        self._dispatcher.start()

    def _upload(self) -> None:
        """Uploader thread body.  The inner loop handles per-block faults
        (store the error, forward the sentinel); the outer wrap catches
        anything that escapes the loop itself, so a worker can die wedged
        but never die SILENT — submit()/drain() raise the stored
        exception instead of hanging behind a thread that no longer
        exists."""
        try:
            self._upload_loop()
        except BaseException as e:  # chaos-ok: worker death must be loud
            if self._error is None:
                self._error = e
            self._force_sentinel(self._staged)
            self._note_death("uploader", e)

    def _coalesce(self, first) -> tuple[list, bool]:
        """Greedy non-blocking batch fill: `first` plus up to batch-1 more
        queued tasks — the moment the intake runs dry the batch closes
        (the occupancy signal: coalescing trades nothing for latency, it
        only merges dispatches that were ALREADY queued behind each
        other).  Returns (items, sentinel_seen)."""
        items = [first]
        sentinel_seen = False
        while len(items) < self.batch:
            try:
                nxt = self._tasks.get_nowait()
            except queue.Empty:
                break
            if nxt is _SENTINEL:
                sentinel_seen = True
                break
            items.append(nxt)
        return items, sentinel_seen

    def _upload_loop(self) -> None:
        from celestia_app_tpu import chaos
        from celestia_app_tpu.chaos.degrade import recoveries

        failed = False
        while True:
            item = self._tasks.get()
            if item is _SENTINEL:
                self._staged.put(_SENTINEL)
                return
            if failed or self._stopping:
                continue  # keep consuming so no producer blocks forever
            items, sentinel_seen = self._coalesce(item)
            try:
                t0 = time.perf_counter()
                # A free persistent slot (recycled from a drained batch);
                # bounded waits so a stopping/dying pipeline never parks
                # this thread on a ring nobody will drain.  A close() in
                # progress just discards the batch (dropping queued work
                # is close()'s contract, not a death); a DEAD dispatcher
                # is a real failure to propagate.
                sid = None
                while True:
                    sid = self._ring.acquire(_POLL_S)
                    if sid is not None or self._stopping:
                        break
                    if not self._dispatcher.is_alive():
                        raise RuntimeError(
                            "dispatcher died; no staging slot will free"
                        )
                if sid is None:  # stopping: discard, keep consuming
                    if sentinel_seen:
                        self._staged.put(_SENTINEL)
                        return
                    continue
                host = self._ring.host(sid)
                for i, (ods, _tag, _t_enq) in enumerate(items):
                    np.copyto(host[i], ods)
                for attempt in range(_UPLOAD_RETRIES + 1):
                    try:
                        chaos.device_upload()  # injected stall/failure
                        if self._panel:
                            # Panel-granular staging: hand the host slot
                            # through whole — the dispatcher's panel
                            # runner uploads one row panel at a time out
                            # of it, so device staging residency is one
                            # panel, never the giant square.
                            x = host[0]
                        else:
                            x = jax.device_put(
                                host[0] if len(items) == 1
                                else host[: len(items)]
                            )
                        break
                    except Exception:  # chaos-ok: bounded upload retry
                        if attempt == _UPLOAD_RETRIES:
                            raise
                        time.sleep(0.002 * (2 ** attempt))
                if attempt:
                    recoveries().inc(seam="device.upload", outcome="retried")
                t1 = time.perf_counter()
            except BaseException as e:  # chaos-ok: stored, surfaced on the next drain
                self._error = e
                self._staged.put(_SENTINEL)
                self._note_death("uploader", e)
                failed = True
                continue
            # Stage timings ride the hand-off in `meta`; the put-stall
            # (uploader blocked because `depth` batches are already in
            # flight downstream) is written the instant put() returns.
            # The consolidated journal row is built at drain time, a full
            # dispatch later, so the read always sees the value in
            # practice — and the row falls back to 0.0, never a missing
            # field, if this thread were descheduled that whole time.
            # The slot id rides along so a failed DONATED dispatch can
            # re-upload from the persistent staging bytes
            # (guarded_dispatch's refresh) and the drain can recycle it.
            meta = {
                "upload_ms": (t1 - t0) * 1e3,
                # Head-of-line intake wait: how long the batch's OLDEST
                # block sat in _tasks before the uploader picked it up
                # (back-pressure/occupancy queue time, a gap — not work).
                "intake_wait_ms": max(
                    0.0,
                    (t0 - min(t_enq for _ods, _tag, t_enq in items)) * 1e3,
                ),
            }
            tags = [tag for _ods, tag, _t_enq in items]
            self._staged.put((x, tags, meta, sid))
            meta["upload_stall_ms"] = (time.perf_counter() - t1) * 1e3
            if sentinel_seen:
                self._staged.put(_SENTINEL)
                return

    def _dispatch(self) -> None:
        try:
            self._dispatch_loop()
        except BaseException as e:  # chaos-ok: worker death must be loud
            if self._error is None:
                self._error = e
            self._force_sentinel(self._done)
            self._note_death("dispatcher", e)

    def _note_death(self, stage: str, err: BaseException) -> None:
        """Black-box a pipeline-fatal stage failure: the journal rows
        around the death are the forensic record and the ring buffer is
        still warm.  ALWAYS called after the death sentinel is delivered
        — capture serializes table tails and probes /healthz, and a
        consumer blocked on the queue must not wait behind forensics.
        note_trigger rate-limits and never raises."""
        from celestia_app_tpu.trace.flight_recorder import note_trigger

        note_trigger(
            "worker_death", stage=stage, k=self.k, depth=self.depth,
            mode=self._mode, error=f"{type(err).__name__}: {err}"[:300],
        )

    @staticmethod
    def _force_sentinel(q: queue.Queue) -> None:
        """Deliver a death sentinel even against a full queue, by evicting
        one staged item per lap.  Dropping in-flight work on a DYING
        pipeline is correct — results past the failure are void — whereas
        a dropped sentinel would starve the downstream consumer into the
        silent wedge this propagation machinery exists to kill.  (This
        thread is the queue's only producer, so the evict-then-put race
        only ever runs against consumers, and converges.)"""
        while True:
            try:
                q.put(_SENTINEL, timeout=0.5)
                return
            except queue.Full:
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass

    def _resolve_pipe(self, mode: str):
        """The owned-input pipeline for `mode`, swapping lowerings when
        the degradation ladder moved it mid-stream (journal rows from then
        on carry the mode blocks actually ran)."""
        if mode != self._pipe_mode:
            self._pipe = _pipeline_for_mode(
                mode, self.k, self.construction, owned=True
            )
            self._pipe_mode = self._mode = mode
        return self._pipe

    def _dispatch_batched(self, x, sid: int, n: int) -> list[tuple[str, tuple]]:
        """One vmapped dispatch for n coalesced squares; any batched fault
        falls down to n unbatched dispatches through the normal guarded
        ladder (batched -> unbatched fused -> staged -> host), so a fault
        in the batching machinery costs latency, never a block.  Returns
        [(mode, (eds, rr, cr, droot)), ...] per square, in order."""
        from celestia_app_tpu import chaos
        from celestia_app_tpu.chaos.degrade import guarded_dispatch, recoveries
        from celestia_app_tpu.kernels.fused import pipeline_mode

        mode = pipeline_mode()
        try:
            chaos.device_dispatch(mode)
            out = _batched_pipeline_for_mode(
                mode, self.k, n, self.construction, owned=True
            )(x)
            ran = "fused" if mode == "fused_epi" else mode
            return [
                (ran, (out[0][b], out[1][b], out[2][b], out[3][b]))
                for b in range(n)
            ]
        except Exception:  # chaos-ok: batched fault -> unbatched rung
            recoveries().inc(seam="device.dispatch", outcome="unbatched")
            host = self._ring.host(sid)
            results = []
            for b in range(n):
                # The donated batch may be consumed; re-upload each square
                # from the persistent staging bytes and ride the ladder.
                xb = jax.device_put(host[b])
                results.append(
                    guarded_dispatch(
                        self._resolve_pipe, xb,
                        refresh=lambda b=b: jax.device_put(
                            np.ascontiguousarray(host[b])
                        ),
                        k=self.k,
                    )
                )
            return results

    def _dispatch_loop(self) -> None:
        from celestia_app_tpu.chaos.degrade import guarded_dispatch

        failed = False
        while True:
            t0 = time.perf_counter()
            item = self._staged.get()
            starve_ms = (time.perf_counter() - t0) * 1e3
            if item is _SENTINEL:
                self._done.put(_SENTINEL)
                return
            if failed or self._stopping:
                self._ring.release(item[3])  # keep the ring whole
                continue
            x, tags, meta, sid = item
            n = len(tags)
            try:
                t1 = time.perf_counter()
                # Async enqueue with retry + ladder fallback; no sync here.
                if n == 1:
                    host = self._ring.host(sid)
                    mode, out = guarded_dispatch(
                        self._resolve_pipe, x,
                        refresh=lambda: jax.device_put(
                            np.ascontiguousarray(host[0])
                        ),
                        k=self.k,
                    )
                    per_square = [(mode, out)]
                    # One owner for the panel/sharded journal extras —
                    # da/eds._panel_fields — so this row can never
                    # disagree with compute()'s for the same dispatch.
                    from celestia_app_tpu.da.eds import _panel_fields

                    meta.update(_panel_fields(mode, self.k))
                else:
                    per_square = self._dispatch_batched(x, sid, n)
                meta["dispatch_ms"] = (time.perf_counter() - t1) * 1e3
                meta["dispatch_starve_ms"] = starve_ms
                meta["batch_size"] = n
                _batch_size_histogram().observe(float(n), k=str(self.k))
            except BaseException as e:  # chaos-ok: stored, surfaced on the next drain
                self._error = e
                self._ring.release(sid)
                self._done.put(_SENTINEL)
                self._note_death("dispatcher", e)
                failed = True
                continue
            ref = [n]  # the slot recycles when the whole batch drained
            gen = self._ring.generation(sid)  # still held: stable here
            for tag, (mode, out) in zip(tags, per_square):
                self._done.put(_InFlight(
                    tag, out, self.k, meta, mode=mode,
                    slot=(self._ring, sid, ref, gen),
                ))

    def _materialize(self, inflight: _InFlight) -> tuple[object, ExtendedDataSquare]:
        eds, rr, cr, droot = inflight.outputs
        t0 = time.perf_counter()
        try:
            jax.block_until_ready(droot)  # the pipeline's one existing sync
        except Exception:  # chaos-ok: deferred fault -> breaker, then surface
            # Async dispatch defers real execution faults to THIS sync,
            # past guarded_dispatch's reach: this block is lost (the
            # caller sees the error), but the breaker still learns, so a
            # persistent fault steps the ladder for the blocks after it.
            from celestia_app_tpu.chaos.degrade import note_async_device_failure
            from celestia_app_tpu.kernels.fused import env_base_mode_for_k

            inflight.release_slot()
            note_async_device_failure(self._mode,
                                      base=env_base_mode_for_k(self.k))
            raise
        meta = inflight.meta
        journal.record(
            "stream", inflight.k, mode=inflight.mode or self._mode,
            compile=self._compile_state, tag=str(inflight.tag),
            depth=self.depth,
            batch_size=meta.get("batch_size", 1),
            **({"panels": meta["panels"]} if "panels" in meta else {}),
            **({"shards": meta["shards"]} if "shards" in meta else {}),
            intake_wait_ms=meta.get("intake_wait_ms", 0.0),
            upload_ms=meta.get("upload_ms", 0.0),
            upload_stall_ms=meta.get("upload_stall_ms", 0.0),
            dispatch_ms=meta.get("dispatch_ms", 0.0),
            dispatch_starve_ms=meta.get("dispatch_starve_ms", 0.0),
            drain_ms=(time.perf_counter() - t0) * 1e3,
        )
        self._compile_state = "hit"  # paid (or confirmed) on the first row
        result = ExtendedDataSquare(eds, rr, cr, droot, inflight.k)
        if inflight.slot is not None:
            # Serve-plane retention (ForestCache.put -> attach_forest)
            # pins the feeding slot: its buffer is swapped, not recycled.
            # The staged-under generation rides along so a pin landing
            # after the slot's next acquire is detected (ring.late_pins).
            ring, sid, _ref, gen = inflight.slot
            result._retain_cb = lambda: ring.pin(sid, gen)
        inflight.release_slot()
        gauge = _queue_depth_gauge()
        for name, q in (("tasks", self._tasks), ("staged", self._staged),
                        ("done", self._done)):
            gauge.set(q.qsize(), queue=name)
        ring_gauge = _ring_occupancy_gauge()
        for state, count in self._ring.states().items():
            ring_gauge.set(count, state=state)
        return inflight.tag, result

    def _raise_worker_death(self, stage: str) -> None:
        err = self._error
        msg = f"pipeline {stage} thread died"
        if err is not None:
            raise RuntimeError(msg) from err
        raise RuntimeError(msg)

    def submit(self, ods: np.ndarray, tag: object = None,
               timeout_s: float | None = None) -> None:
        """Enqueue one block; blocks the host only when `depth` batches are
        already in flight (back-pressure).

        Deadline-aware: the bounded put wakes periodically to check the
        workers, so a dead uploader raises the stored exception here
        instead of wedging the caller behind a queue nobody drains; with
        `timeout_s` set, sustained back-pressure past the deadline raises
        TimeoutError (the caller's load-shedding hook)."""
        if self._closed:
            raise RuntimeError("pipeline already closed")
        if self._error is not None:
            raise RuntimeError("pipeline feeder failed") from self._error
        deadline = (
            time.monotonic() + timeout_s if timeout_s is not None else None
        )
        while True:
            try:
                # The enqueue stamp rides the task so the uploader can
                # report the head-of-line intake wait (time queued before
                # any stage touched the block) — the timeline's first gap.
                self._tasks.put((ods, tag, time.perf_counter()),
                                timeout=_POLL_S)
                return
            except queue.Full:
                if self._error is not None:
                    raise RuntimeError(
                        "pipeline feeder failed"
                    ) from self._error
                if not self._uploader.is_alive():
                    self._raise_worker_death("uploader")
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"pipeline back-pressure: no intake slot within "
                        f"{timeout_s}s (depth={self.depth})"
                    ) from None

    def _get_done(self):
        """One _done item, with the wedge check: a dispatcher that died
        without managing to forward a sentinel leaves the queue silent
        forever — detect it and raise the stored error instead."""
        while True:
            try:
                return self._done.get(timeout=_POLL_S)
            except queue.Empty:
                if not self._dispatcher.is_alive() and self._done.empty():
                    # Leave _finished unset: the caller's close() still
                    # owes the uploader an unblock + leak report.
                    self._raise_worker_death("dispatcher")

    def _drain_one(self) -> tuple[object, ExtendedDataSquare]:
        inflight = self._get_done()
        if inflight is _SENTINEL:
            self._finished = True
            if self._error is not None:
                raise RuntimeError("pipeline feeder failed") from self._error
            raise RuntimeError("pipeline is closed")
        return self._materialize(inflight)

    def drain(self):
        """Close the intake and yield (tag, ExtendedDataSquare) for every
        remaining block, in order.  Blocks computed before a mid-stream
        failure still come out; the stored exception raises at the
        failure point (the sentinel) rather than hanging."""
        self._closed = True
        # A LIVE pipeline always consumes the intake (even post-failure
        # the uploader drains and discards), so the sentinel lands; with
        # EITHER worker dead it may never free — a dead uploader reads
        # nothing, and a dead dispatcher leaves the uploader wedged on the
        # _staged hand-off — so skip the intake rather than blocking on a
        # queue nobody will drain (the death wrappers already force-fed
        # the downstream sentinel that _get_done below will surface).
        while True:
            try:
                self._tasks.put(_SENTINEL, timeout=_POLL_S)
                break
            except queue.Full:
                if (not self._uploader.is_alive()
                        or not self._dispatcher.is_alive()):
                    break
        while True:
            inflight = self._get_done()
            if inflight is _SENTINEL:
                self._finished = True
                if self._error is not None:
                    raise RuntimeError("pipeline feeder failed") from self._error
                return
            yield self._materialize(inflight)

    def close(self) -> None:
        """Abandon the pipeline: stop both stages and drop pending results
        (early-exit path — device buffers held by _done are released).

        Keyed on _finished, NOT _closed: abandoning a drain() mid-stream
        leaves _closed set with results still queued, and an early return
        there would strand the dispatcher blocked on a full _done holding
        `depth` extended batches for the process lifetime.

        Worker death is REPORTED, never swallowed: a stage that outlives
        its join timeout (a genuine wedge — the error-propagation paths
        above cover everything else) logs and ticks
        `celestia_pipeline_close_leaked_total{stage}`."""
        if self._finished:
            return
        self._stopping = True  # stages discard anything still queued
        sentinel_needed = not self._closed
        self._closed = True
        # Unblock the stages if their output queues are full, and drop
        # held outputs.  Bounded waits everywhere: the intake sentinel is
        # offered NON-blocking inside the drain loop — with every queue
        # full and _done undrained, a blocking put here would deadlock
        # against the very back-pressure chain this method exists to
        # unwind — and a dispatcher that died without a sentinel (or
        # wedged outright) must not wedge close() itself.  The deadline
        # measures INACTIVITY (re-armed on every drained item), not total
        # wall clock: an abandoned stream whose first dispatch is mid-
        # jit-compile is slow-but-healthy, not a leak to report.
        deadline = time.monotonic() + _CLOSE_STALL_S
        while time.monotonic() < deadline:
            if sentinel_needed:
                try:
                    self._tasks.put_nowait(_SENTINEL)
                    sentinel_needed = False
                except queue.Full:
                    pass  # a drain below frees the chain; retry next lap
            try:
                item = self._done.get(timeout=_POLL_S)
            except queue.Empty:
                if not sentinel_needed and not self._dispatcher.is_alive():
                    break
                continue
            if item is _SENTINEL:
                break
            if isinstance(item, _InFlight):
                item.release_slot()  # keep the ring whole for the workers
            deadline = time.monotonic() + _CLOSE_STALL_S  # progress: re-arm
        self._finished = True
        self._uploader.join(timeout=5)
        self._dispatcher.join(timeout=5)
        for stage, thread in (("uploader", self._uploader),
                              ("dispatcher", self._dispatcher)):
            if thread.is_alive():
                import sys

                print(f"BlockPipeline.close: {stage} thread leaked past "
                      f"join timeout (k={self.k})", file=sys.stderr)
                _close_leak_counter().inc(stage=stage)


def stream_blocks(ods_iter, k: int, depth: int = 2, batch: int | None = None):
    """Stream squares through the device with `depth`-deep overlap.

    Yields (tag, ExtendedDataSquare) in submission order; with depth=2 the
    uploader transfers block i+1 while the device computes block i and the
    caller consumes block i-1 (the v5e-4 double-buffering shape of
    BASELINE config 5).  `batch` (default $CELESTIA_PIPE_BATCH) lets the
    dispatcher coalesce queued same-k squares into one vmapped dispatch.
    Abandoning the generator early stops the stages and releases in-flight
    device buffers."""
    pipe = BlockPipeline(k, depth, batch=batch)
    finished = False
    try:
        submitted = drained = 0
        window = max(depth, pipe.batch)
        for tag, ods in ods_iter:
            # Keep the intake primed without over-filling HBM: drain once
            # we have more than a window of submissions outstanding (the
            # window widens with the batch so coalescing has squares to
            # merge).
            while submitted - drained > window:
                yield pipe._drain_one()
                drained += 1
            pipe.submit(ods, tag)
            submitted += 1
        for item in pipe.drain():
            yield item
        finished = True
    finally:
        if not finished:
            pipe.close()

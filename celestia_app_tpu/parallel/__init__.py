"""Multi-chip parallelism: sharded EDS construction over a device mesh."""

from celestia_app_tpu.parallel.sharded_eds import (
    default_mesh,
    make_sharded_dah_pipeline,
    make_sharded_pipeline,
    sharded_extend_and_dah,
)

__all__ = [
    "default_mesh",
    "make_sharded_dah_pipeline",
    "make_sharded_pipeline",
    "sharded_extend_and_dah",
]

"""Multi-chip parallelism: sharded EDS construction over a device mesh,
plus the shared mesh / committed-sharding helpers (parallel/mesh.py) the
sharded serve plane builds on."""

from celestia_app_tpu.parallel.mesh import (
    device_mesh,
    row_sharding,
    sharded_gather_fn,
)
from celestia_app_tpu.parallel.sharded_eds import (
    default_mesh,
    make_sharded_dah_pipeline,
    make_sharded_pipeline,
    sharded_extend_and_dah,
)

__all__ = [
    "default_mesh",
    "device_mesh",
    "make_sharded_dah_pipeline",
    "make_sharded_pipeline",
    "row_sharding",
    "sharded_extend_and_dah",
    "sharded_gather_fn",
]

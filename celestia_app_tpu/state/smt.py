"""Merkleized state: a path-compressed binary trie over sha256(key).

Replaces the round-1 flat whole-state digest with the commitment structure
the reference gets from IAVL (app/app.go:435 — the committed multistore's
root becomes the app hash, pinned by app/test/consistent_apphash_test.go:47):

  * app hash = root of a deterministic merkle trie over all (key, value)
    pairs — shape is a function of the key set only (PATRICIA: one branch
    node per pairwise first-bit-difference), so insertion order never
    matters;
  * updates are persistent (structure-sharing): a commit re-hashes only
    O(delta * log n) nodes, never the whole state;
  * any key has a compact existence / non-existence proof against the app
    hash (the state-proof surface IAVL gives Cosmos light clients).

Domain-separated hashing (all SHA-256):
  leaf    H(0x00 || keyhash || sha256(value))
  branch  H(0x01 || bit_be16 || left || right)
  empty   H(0x02)
A branch node records the first bit position where its two subtrees'
keyhashes differ; bits are MSB-first over the 256-bit keyhash.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

_LEAF, _BRANCH = b"\x00", b"\x01"
EMPTY_ROOT = hashlib.sha256(b"\x02").digest()


def _h(*parts: bytes) -> bytes:
    h = hashlib.sha256()
    for p in parts:
        h.update(p)
    return h.digest()


def key_hash(key: bytes) -> bytes:
    return hashlib.sha256(key).digest()


def _bit(kh: bytes, i: int) -> int:
    return (kh[i >> 3] >> (7 - (i & 7))) & 1


def _first_diff(a: bytes, b: bytes) -> int:
    """First differing bit position of two 32-byte hashes (== 256 if equal)."""
    for byte in range(32):
        x = a[byte] ^ b[byte]
        if x:
            return (byte << 3) + (7 - x.bit_length() + 1)
    return 256


class Leaf:
    __slots__ = ("kh", "vh", "h")

    def __init__(self, kh: bytes, vh: bytes):
        self.kh = kh
        self.vh = vh
        self.h = _h(_LEAF, kh, vh)


class Branch:
    __slots__ = ("bit", "rep", "left", "right", "h")

    def __init__(self, bit: int, left, right):
        self.bit = bit
        self.rep = left.rep if isinstance(left, Branch) else left.kh
        self.left = left
        self.right = right
        self.h = _h(_BRANCH, bit.to_bytes(2, "big"), left.h, right.h)


def root_hash(node) -> bytes:
    return EMPTY_ROOT if node is None else node.h


def _rep(node) -> bytes:
    return node.rep if isinstance(node, Branch) else node.kh


def insert(node, kh: bytes, vh: bytes):
    """Persistent insert/update; returns the new root node."""
    if node is None:
        return Leaf(kh, vh)
    if isinstance(node, Leaf):
        d = _first_diff(kh, node.kh)
        if d == 256:
            return Leaf(kh, vh)  # update in place (new node)
        new = Leaf(kh, vh)
        return Branch(d, new, node) if _bit(kh, d) == 0 else Branch(d, node, new)
    d0 = _first_diff(kh, node.rep)
    if d0 < node.bit:
        # Diverges above this subtree's common prefix: split here.
        new = Leaf(kh, vh)
        return Branch(d0, new, node) if _bit(kh, d0) == 0 else Branch(d0, node, new)
    if _bit(kh, node.bit) == 0:
        return Branch(node.bit, insert(node.left, kh, vh), node.right)
    return Branch(node.bit, node.left, insert(node.right, kh, vh))


def delete(node, kh: bytes):
    """Persistent delete; returns the new root (None if emptied)."""
    if node is None:
        return None
    if isinstance(node, Leaf):
        return None if node.kh == kh else node
    if _bit(kh, node.bit) == 0:
        left = delete(node.left, kh)
        if left is None:
            return node.right
        if left is node.left:
            return node
        return Branch(node.bit, left, node.right)
    right = delete(node.right, kh)
    if right is None:
        return node.left
    if right is node.right:
        return node
    return Branch(node.bit, node.left, right)


@dataclass
class StateProof:
    """Merkle proof for `key` against an app hash.

    `value` is the proven value for existence, None for non-existence. The
    path is root-to-leaf: (branch bit, sibling hash) per traversed branch —
    the verifier re-derives directions from sha256(key), so directions are
    not part of the proof. For non-existence, `leaf_kh`/`leaf_vh` identify
    the leaf found at the key's unique lookup position (or None for an
    empty tree): lookup is deterministic, so a committed path ending in a
    different leaf proves absence.
    """

    key: bytes
    value: bytes | None
    path: list[tuple[int, bytes]]
    leaf_kh: bytes | None = None
    leaf_vh: bytes | None = None


def prove(node, key: bytes, value: bytes | None) -> StateProof:
    """Build the proof for `key` (pass its current value or None if absent)."""
    kh = key_hash(key)
    path: list[tuple[int, bytes]] = []
    cur = node
    while isinstance(cur, Branch):
        if _bit(kh, cur.bit) == 0:
            path.append((cur.bit, cur.right.h))
            cur = cur.left
        else:
            path.append((cur.bit, cur.left.h))
            cur = cur.right
    if cur is None:
        assert value is None and not path
        return StateProof(key, None, [])
    if cur.kh == kh:
        assert value is not None, "key exists; pass its value"
        return StateProof(key, value, path)
    assert value is None, "key absent; found a different leaf"
    return StateProof(key, None, path, leaf_kh=cur.kh, leaf_vh=cur.vh)


def verify(proof: StateProof, app_hash: bytes) -> bool:
    """Check the proof against a committed app hash.

    Malformed proofs (out-of-range bits, wrong-length hashes, missing
    fields) return False — a peer-supplied proof must never crash the
    verifier.
    """
    kh = key_hash(proof.key)
    if proof.value is not None:
        leaf = Leaf(kh, _h(proof.value))
    elif proof.leaf_kh is None:
        return not proof.path and app_hash == EMPTY_ROOT
    else:
        if proof.leaf_kh == kh:
            return False  # a leaf with the key's own hash cannot prove absence
        if not (
            isinstance(proof.leaf_kh, bytes) and len(proof.leaf_kh) == 32
            and isinstance(proof.leaf_vh, bytes) and len(proof.leaf_vh) == 32
        ):
            return False
        leaf = Leaf(proof.leaf_kh, proof.leaf_vh)
    h = leaf.h
    prev_bit = 256
    for bit, sibling in reversed(proof.path):
        if not (
            isinstance(bit, int) and 0 <= bit < prev_bit
            and isinstance(sibling, bytes) and len(sibling) == 32
        ):
            return False  # path bits strictly increase root-to-leaf, in [0,256)
        prev_bit = bit
        if _bit(kh, bit) == 0:
            h = _h(_BRANCH, bit.to_bytes(2, "big"), h, sibling)
        else:
            h = _h(_BRANCH, bit.to_bytes(2, "big"), sibling, h)
    return h == app_hash


def value_hash(value: bytes) -> bytes:
    return _h(value)


def proof_marshal(proof: StateProof) -> bytes:
    """Wire form for proofs that cross chains (IBC relay msgs):
    {key=1, has_value=2, value=3, path=4{bit=1, sibling=2},
    leaf_kh=5, leaf_vh=6}."""
    from celestia_app_tpu.encoding.proto import (
        encode_bytes_field,
        encode_varint_field,
    )

    out = encode_bytes_field(1, proof.key)
    out += encode_varint_field(2, int(proof.value is not None))
    if proof.value is not None:
        out += encode_bytes_field(3, proof.value)
    for bit, sibling in proof.path:
        out += encode_bytes_field(
            4, encode_varint_field(1, bit) + encode_bytes_field(2, sibling)
        )
    if proof.leaf_kh is not None:
        out += encode_bytes_field(5, proof.leaf_kh)
        out += encode_bytes_field(6, proof.leaf_vh or b"")
    return out


def proof_unmarshal(raw: bytes) -> StateProof:
    from celestia_app_tpu.encoding.proto import (
        WIRE_LEN,
        WIRE_VARINT,
        decode_fields,
    )

    key, value, has_value = b"", b"", False
    path: list[tuple[int, bytes]] = []
    leaf_kh = leaf_vh = None
    for n, wt, v in decode_fields(raw):
        if n == 1 and wt == WIRE_LEN:
            key = v
        elif n == 2 and wt == WIRE_VARINT:
            has_value = bool(v)
        elif n == 3 and wt == WIRE_LEN:
            value = v
        elif n == 4 and wt == WIRE_LEN:
            bit, sib = 0, b""
            for pn, pwt, pv in decode_fields(v):
                if pn == 1 and pwt == WIRE_VARINT:
                    bit = pv
                elif pn == 2 and pwt == WIRE_LEN:
                    sib = pv
            path.append((bit, sib))
        elif n == 5 and wt == WIRE_LEN:
            leaf_kh = v
        elif n == 6 and wt == WIRE_LEN:
            leaf_vh = v
    return StateProof(
        key, value if has_value else None, path, leaf_kh, leaf_vh
    )

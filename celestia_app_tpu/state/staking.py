"""Staking-lite: the validator-set state the app's own modules consume.

The reference delegates staking to cosmos-sdk x/staking; the in-repo modules
only read it (x/signal tallies power, x/blobstream snapshots valsets).  This
keeper stores validators (operator address, consensus pubkey, power) with
deterministic iteration — enough surface for those consumers and for the
test harness's deterministic validator sets (test/util/test_app.go:214).
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    encode_bytes_field,
    encode_varint_field,
)
from celestia_app_tpu.state.store import KVStore

_VAL_PREFIX = b"staking/val/"


@dataclass(frozen=True)
class Validator:
    address: str  # operator address (bech32)
    pubkey: bytes
    power: int

    def marshal(self) -> bytes:
        return (
            encode_bytes_field(1, self.address.encode())
            + encode_bytes_field(2, self.pubkey)
            + encode_varint_field(3, self.power)
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Validator":
        addr, pk, power = "", b"", 0
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                addr = val.decode()
            elif num == 2 and wt == WIRE_LEN:
                pk = val
            elif num == 3 and wt == WIRE_VARINT:
                power = val
        return cls(addr, pk, power)


class StakingKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    def set_validator(self, v: Validator) -> None:
        self.store.set(_VAL_PREFIX + v.address.encode(), v.marshal())

    def remove_validator(self, address: str) -> None:
        self.store.delete(_VAL_PREFIX + address.encode())

    def get_validator(self, address: str) -> Validator | None:
        raw = self.store.get(_VAL_PREFIX + address.encode())
        return Validator.unmarshal(raw) if raw else None

    def has_validator(self, address: str) -> bool:
        return self.get_validator(address) is not None

    def get_power(self, address: str) -> int:
        v = self.get_validator(address)
        return v.power if v else 0

    def validators(self) -> list[Validator]:
        return [Validator.unmarshal(v) for _, v in self.store.iterate(_VAL_PREFIX)]

    def total_power(self) -> int:
        return sum(v.power for v in self.validators())

"""Staking: validators + delegations (the x/staking surface the app uses).

The reference delegates staking to cosmos-sdk x/staking; the in-repo
modules read it (x/signal tallies power, x/blobstream snapshots valsets)
and txsim's stake sequence writes it (MsgDelegate/MsgUndelegate/
MsgBeginRedelegate, test/txsim/stake.go).  This keeper stores validators
(operator address, consensus pubkey, power) plus token-backed delegations:

  * power = tokens // POWER_REDUCTION (sdk DefaultPowerReduction: 1 TIA);
  * delegate escrows utia in the bonded pool and raises the validator's
    tokens/power; undelegate starts a 3-week unbonding
    (appconsts.DefaultUnbondingTime, initial_consts.go:28) released by the
    end blocker; redelegation moves bonded tokens instantly;
  * genesis validators carry notional tokens (power x reduction) with no
    escrowed backing — only delegated amounts move real funds (the
    reference funds genesis self-bond out of band too).

Rewards flow through x/distribution (modules/distribution), which treats a
genesis validator's notional power as an implicit operator self-bond;
jailing and slashing (modules/slashing) operate through the jail flag and
`slash` below.
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    encode_bytes_field,
    encode_varint_field,
)
from celestia_app_tpu.state.store import KVStore

_VAL_PREFIX = b"staking/val/"
_TOKENS_PREFIX = b"staking/tokens/"
_DEL_PREFIX = b"staking/del/"
_UBD_PREFIX = b"staking/ubd/"
_JAIL_PREFIX = b"staking/jailed/"

POWER_REDUCTION = 1_000_000  # sdk DefaultPowerReduction: 1 TIA of stake = 1 power
UNBONDING_TIME_NS = 3 * 7 * 24 * 3600 * 10**9  # DefaultUnbondingTime, 3 weeks
BONDED_POOL = "bonded_tokens_pool"
NOT_BONDED_POOL = "not_bonded_tokens_pool"


class StakingError(ValueError):
    pass


@dataclass(frozen=True)
class Validator:
    address: str  # operator address (bech32)
    pubkey: bytes
    power: int

    def marshal(self) -> bytes:
        return (
            encode_bytes_field(1, self.address.encode())
            + encode_bytes_field(2, self.pubkey)
            + encode_varint_field(3, self.power)
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Validator":
        addr, pk, power = "", b"", 0
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                addr = val.decode()
            elif num == 2 and wt == WIRE_LEN:
                pk = val
            elif num == 3 and wt == WIRE_VARINT:
                power = val
        return cls(addr, pk, power)


class StakingKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    def set_validator(self, v: Validator) -> None:
        """Authoritative power registration (genesis / test harnesses).

        Refuses to reset a validator that holds delegations: overwriting
        its tokens record would desync the bonded-pool escrow from the
        delegation records (power changes for delegated validators go
        through delegate/undelegate/redelegate)."""
        if self._has_delegations(v.address):
            raise StakingError(
                f"validator {v.address} holds delegations; power cannot be "
                "set directly"
            )
        # One consensus key, one bonded entry — also on the genesis/test
        # path: vote sign bytes exclude the validator address, so two
        # records sharing a pubkey would let one signer count its power
        # twice toward +2/3 (same rule as create_validator).
        if v.pubkey:
            for other in self.validators():
                if other.pubkey == v.pubkey and other.address != v.address:
                    raise StakingError(
                        f"consensus pubkey already used by validator "
                        f"{other.address}"
                    )
        self.store.set(_VAL_PREFIX + v.address.encode(), v.marshal())
        # Keep tokens consistent with directly-set power.
        self.store.set(
            _TOKENS_PREFIX + v.address.encode(),
            (v.power * POWER_REDUCTION).to_bytes(16, "big"),
        )

    def _has_delegations(self, validator: str) -> bool:
        prefix = _DEL_PREFIX + validator.encode() + b"/"
        for _ in self.store.iterate(prefix):
            return True
        return False

    def remove_validator(self, address: str) -> None:
        self.store.delete(_VAL_PREFIX + address.encode())

    def get_validator(self, address: str) -> Validator | None:
        raw = self.store.get(_VAL_PREFIX + address.encode())
        return Validator.unmarshal(raw) if raw else None

    def has_validator(self, address: str) -> bool:
        return self.get_validator(address) is not None

    def get_power(self, address: str) -> int:
        v = self.get_validator(address)
        return v.power if v else 0

    def validators(self) -> list[Validator]:
        return [Validator.unmarshal(v) for _, v in self.store.iterate(_VAL_PREFIX)]

    def total_power(self) -> int:
        return sum(v.power for v in self.validators())

    # --- jail (x/slashing's handle on the validator set) ---------------------
    def is_jailed(self, address: str) -> bool:
        return self.store.get(_JAIL_PREFIX + address.encode()) is not None

    def jail(self, address: str) -> None:
        """Remove the validator from the bonded set (sdk jailValidator)."""
        if not self.has_validator(address):
            raise StakingError(f"no validator {address}")
        self.store.set(_JAIL_PREFIX + address.encode(), b"\x01")

    def unjail(self, address: str) -> None:
        self.store.delete(_JAIL_PREFIX + address.encode())

    def bonded_validators(self) -> list[Validator]:
        """The active (non-jailed) set: what consensus power, signal
        tallies, and blobstream valsets are built from."""
        return [v for v in self.validators() if not self.is_jailed(v.address)]

    def bonded_power(self) -> int:
        return sum(v.power for v in self.bonded_validators())

    def slash(self, bank, dist, validator: str, fraction_raw: int) -> int:
        """Burn `fraction` of the validator's tokens (sdk Slash semantics:
        bonded tokens burn from the bonded pool; every delegation — and the
        genesis notional self-bond — shrinks pro-rata).  `fraction_raw` is a
        Dec raw (1e18 = 100%).  `dist` settles rewards first so pending
        rewards are computed against pre-slash stake.  Returns burned."""
        precision = 10**18
        if not 0 <= fraction_raw <= precision:
            raise StakingError(f"slash fraction {fraction_raw} outside [0, 1e18]")
        tokens = self.tokens(validator)
        burn_total = tokens * fraction_raw // precision
        if burn_total == 0:
            return 0
        dist.settle_all(self, validator)
        prefix = _DEL_PREFIX + validator.encode() + b"/"
        burned_backed = 0
        for key, val in list(self.store.iterate(prefix)):
            stake = int.from_bytes(val, "big")
            cut = stake * fraction_raw // precision
            if cut:
                self.store.set(key, (stake - cut).to_bytes(16, "big"))
                burned_backed += cut
        notional = dist.notional(validator)
        notional_cut = notional * fraction_raw // precision
        if notional_cut:
            dist.set_notional(validator, notional - notional_cut)
        # Truncation dust stays staked: reduce tokens by what the stake
        # records actually lost, keeping tokens == notional + Σdelegations.
        # Only delegation cuts have bank escrow behind them; the genesis
        # notional self-bond is power-book-only (state/staking.py header).
        self._set_tokens(validator, tokens - burned_backed - notional_cut)
        if burned_backed:
            bank.burn(BONDED_POOL, burned_backed)
        # Unbonding entries for this validator are slashed too, or an
        # undelegation racing the evidence would dodge the burn and shift
        # the whole loss onto the delegators who stayed (the sdk slashes
        # unbonding delegations for the same reason; entries carry
        # creation heights, but this deliberately cuts ALL of the
        # validator's entries — a strict superset of the sdk's
        # created-after-infraction rule, since slash() is not told the
        # infraction height).
        burned_unbonding = 0
        for key, val in list(self.store.iterate(_UBD_PREFIX)):
            if self._ubd_parse(key)[2] != validator:
                continue
            amount = int.from_bytes(val, "big")
            cut = amount * fraction_raw // precision
            if cut:
                self.store.set(key, (amount - cut).to_bytes(16, "big"))
                burned_unbonding += cut
        if burned_unbonding:
            bank.burn(NOT_BONDED_POOL, burned_unbonding)
        return burned_backed + notional_cut + burned_unbonding

    # --- delegations ---------------------------------------------------------
    def tokens(self, validator: str) -> int:
        raw = self.store.get(_TOKENS_PREFIX + validator.encode())
        return int.from_bytes(raw, "big") if raw else 0

    def _set_tokens(self, validator: str, amount: int) -> None:
        self.store.set(_TOKENS_PREFIX + validator.encode(), amount.to_bytes(16, "big"))
        v = self.get_validator(validator)
        self.store.set(
            _VAL_PREFIX + validator.encode(),
            Validator(v.address, v.pubkey, amount // POWER_REDUCTION).marshal(),
        )

    def delegation(self, delegator: str, validator: str) -> int:
        raw = self.store.get(
            _DEL_PREFIX + validator.encode() + b"/" + delegator.encode()
        )
        return int.from_bytes(raw, "big") if raw else 0

    def _set_delegation(self, delegator: str, validator: str, amount: int) -> None:
        key = _DEL_PREFIX + validator.encode() + b"/" + delegator.encode()
        if amount:
            self.store.set(key, amount.to_bytes(16, "big"))
        else:
            self.store.delete(key)

    def delegate(self, bank, delegator: str, validator: str, amount: int) -> None:
        """MsgDelegate: escrow into the bonded pool, raise tokens/power."""
        if amount <= 0:
            raise StakingError("delegation must be positive")
        if not self.has_validator(validator):
            raise StakingError(f"no validator {validator}")
        try:
            bank.send(delegator, BONDED_POOL, amount)
        except ValueError as e:
            raise StakingError(str(e)) from e
        self._set_delegation(
            delegator, validator, self.delegation(delegator, validator) + amount
        )
        self._set_tokens(validator, self.tokens(validator) + amount)

    def undelegate(
        self, bank, delegator: str, validator: str, amount: int, time_ns: int,
        height: int = 0,
    ) -> int:
        """MsgUndelegate: tokens leave the bonded pool now, the delegator
        gets them back at completion (3-week unbonding).  Returns the
        completion time.

        `height` is the entry's creation height (sdk UnbondingDelegationEntry
        .CreationHeight) — the handle MsgCancelUnbondingDelegation names an
        entry by.  Undelegations in one block aggregate into one entry
        (same completion time, same height), as in the sdk."""
        held = self.delegation(delegator, validator)
        if amount <= 0 or amount > held:
            raise StakingError(
                f"invalid undelegation {amount} (delegated: {held})"
            )
        self._set_delegation(delegator, validator, held - amount)
        self._set_tokens(validator, self.tokens(validator) - amount)
        bank.send(BONDED_POOL, NOT_BONDED_POOL, amount)
        completion_ns = time_ns + UNBONDING_TIME_NS
        key = self._ubd_key(completion_ns, delegator, validator, height)
        prev = self.store.get(key)
        total = (int.from_bytes(prev, "big") if prev else 0) + amount
        self.store.set(key, total.to_bytes(16, "big"))
        return completion_ns

    @staticmethod
    def _ubd_key(
        completion_ns: int, delegator: str, validator: str, height: int
    ) -> bytes:
        """Unbonding entry key: completion-ordered, then addressed by
        (delegator, validator, creation height).  The height rides as
        ASCII decimal so every segment stays b"/"-split-safe."""
        return (
            _UBD_PREFIX + completion_ns.to_bytes(12, "big") + b"/"
            + delegator.encode() + b"/" + validator.encode() + b"/"
            + str(height).encode()
        )

    @staticmethod
    def _ubd_parse(key: bytes) -> tuple[int, str, str, int]:
        """(completion_ns, delegator, validator, creation_height) of an
        unbonding entry key."""
        completion_ns = int.from_bytes(
            key[len(_UBD_PREFIX): len(_UBD_PREFIX) + 12], "big"
        )
        parts = key[len(_UBD_PREFIX) + 13:].split(b"/")
        return (
            completion_ns, parts[0].decode(), parts[1].decode(),
            int(parts[2]),
        )

    def cancel_unbonding(
        self, bank, delegator: str, validator: str, amount: int,
        creation_height: int, time_ns: int,
    ) -> None:
        """MsgCancelUnbondingDelegation (sdk v0.46 x/staking): re-bond
        `amount` from the unbonding entry created at `creation_height`
        back to the SAME validator — the entry shrinks (or disappears)
        and the tokens return to the bonded pool immediately.

        sdk guards reproduced: a jailed validator refuses re-bonds
        (ErrValidatorJailed — a tombstoned double-signer must not regain
        power this way), and an entry whose completion time has passed is
        no longer cancellable even though the end blocker releases it
        later in the same block (messages run before end block)."""
        if amount <= 0:
            raise StakingError("cancel amount must be positive")
        if not self.has_validator(validator):
            raise StakingError(f"no validator {validator}")
        if self.is_jailed(validator):
            raise StakingError(f"validator {validator} is jailed")
        entry_key = None
        entry_amount = 0
        for key, val in self.store.iterate(_UBD_PREFIX):
            completion_ns, d, v, h = self._ubd_parse(key)
            if (d, v, h) == (delegator, validator, creation_height):
                if completion_ns <= time_ns:
                    raise StakingError(
                        "unbonding delegation is no longer pending "
                        f"(completed at {completion_ns})"
                    )
                entry_key = key
                entry_amount = int.from_bytes(val, "big")
                break
        if entry_key is None:
            raise StakingError(
                f"no unbonding entry for {delegator}/{validator} at "
                f"height {creation_height}"
            )
        if amount > entry_amount:
            raise StakingError(
                f"cancel amount {amount} exceeds unbonding entry "
                f"{entry_amount}"
            )
        if amount == entry_amount:
            self.store.delete(entry_key)
        else:
            self.store.set(entry_key, (entry_amount - amount).to_bytes(16, "big"))
        bank.send(NOT_BONDED_POOL, BONDED_POOL, amount)
        self._set_delegation(
            delegator, validator, self.delegation(delegator, validator) + amount
        )
        self._set_tokens(validator, self.tokens(validator) + amount)

    def begin_redelegate(
        self, delegator: str, src: str, dst: str, amount: int
    ) -> None:
        """MsgBeginRedelegate: bonded tokens move validators instantly
        (they never leave the bonded pool, as in the sdk)."""
        if src == dst:
            raise StakingError("cannot redelegate to the same validator")
        held = self.delegation(delegator, src)
        if amount <= 0 or amount > held:
            raise StakingError(f"invalid redelegation {amount} (delegated: {held})")
        if not self.has_validator(dst):
            raise StakingError(f"no validator {dst}")
        self._set_delegation(delegator, src, held - amount)
        self._set_tokens(src, self.tokens(src) - amount)
        self._set_delegation(delegator, dst, self.delegation(delegator, dst) + amount)
        self._set_tokens(dst, self.tokens(dst) + amount)

    def min_self_delegation(self, validator: str) -> int:
        raw = self.store.get(b"staking/minself/" + validator.encode())
        return int(raw.decode()) if raw else 0

    def _set_min_self_delegation(self, validator: str, amount: int) -> None:
        self.store.set(
            b"staking/minself/" + validator.encode(), str(amount).encode()
        )

    def create_validator(
        self, bank, dist, operator: str, pubkey: bytes,
        delegator: str, self_stake: int, commission_rate_raw: int = 0,
        min_self_delegation: int = 0,
    ) -> None:
        """MsgCreateValidator: a NEW validator joins with an escrowed
        self-delegation (unlike genesis validators' notional power).  The
        bonded set — consensus votes, signal tallies, blobstream valsets,
        reward allocation — picks it up from the next block."""
        if self.has_validator(operator):
            raise StakingError(f"validator {operator} already exists")
        if self_stake <= 0:
            raise StakingError("self delegation must be positive")
        if not pubkey:
            raise StakingError("validator needs a consensus pubkey")
        # One consensus key, one validator (sdk ErrValidatorPubKeyExists):
        # a shared key would let one signer double-count its power toward
        # the +2/3 quorum under two bonded-set entries.
        for v in self.validators():
            if v.pubkey == pubkey:
                raise StakingError(
                    f"consensus pubkey already used by validator {v.address}"
                )
        self.set_validator(Validator(operator, pubkey, 0))
        if commission_rate_raw:
            from celestia_app_tpu.state.dec import Dec

            dist.set_commission_rate(operator, Dec(commission_rate_raw))
        if min_self_delegation:
            self._set_min_self_delegation(operator, min_self_delegation)
        self.delegate(bank, delegator, operator, self_stake)

    def complete_unbondings(self, bank, time_ns: int) -> list[tuple[str, int]]:
        """End blocker: release matured unbonding entries.  Returns the
        (delegator, amount) payouts."""
        released = []
        for key, val in self.store.iterate(_UBD_PREFIX):
            completion_ns, delegator, _, _ = self._ubd_parse(key)
            if completion_ns > time_ns:
                continue
            amount = int.from_bytes(val, "big")
            bank.send(NOT_BONDED_POOL, delegator, amount)
            self.store.delete(key)
            released.append((delegator, amount))
        return released

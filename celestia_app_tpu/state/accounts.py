"""Auth + bank state keepers over the KV store.

The minimal stateful substrate the reference app needs from cosmos-sdk
auth/bank for its tx flow: account numbers/sequences/pubkeys for signature
checks (ante), balances for fees and sends, module accounts for fee
collection and minting.
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu.constants import BOND_DENOM
from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    encode_bytes_field,
    encode_varint_field,
)
from celestia_app_tpu.state.store import KVStore

FEE_COLLECTOR = "fee_collector"
MINT_MODULE = "mint"

_ACC_PREFIX = b"auth/acc/"
_BAL_PREFIX = b"bank/bal/"
_SUPPLY_KEY = b"bank/supply/"
_GLOBAL_ACC_NUM = b"auth/global_account_number"


@dataclass
class Account:
    address: str
    pubkey: bytes  # 33-byte compressed secp256k1, b"" until first known
    account_number: int
    sequence: int

    def marshal(self) -> bytes:
        return (
            encode_bytes_field(1, self.address.encode())
            + encode_bytes_field(2, self.pubkey)
            + encode_varint_field(3, self.account_number)
            + encode_varint_field(4, self.sequence)
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Account":
        addr, pk, num, seq = "", b"", 0, 0
        for fnum, wt, val in decode_fields(raw):
            if fnum == 1 and wt == WIRE_LEN:
                addr = val.decode()
            elif fnum == 2 and wt == WIRE_LEN:
                pk = val
            elif fnum == 3 and wt == WIRE_VARINT:
                num = val
            elif fnum == 4 and wt == WIRE_VARINT:
                seq = val
        return cls(addr, pk, num, seq)


class AuthKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    def get_account(self, address: str) -> Account | None:
        raw = self.store.get(_ACC_PREFIX + address.encode())
        return Account.unmarshal(raw) if raw is not None else None

    def set_account(self, acc: Account) -> None:
        self.store.set(_ACC_PREFIX + acc.address.encode(), acc.marshal())

    def create_account(self, address: str, pubkey: bytes = b"") -> Account:
        n = int.from_bytes(self.store.get(_GLOBAL_ACC_NUM) or b"\x00", "big")
        self.store.set(_GLOBAL_ACC_NUM, (n + 1).to_bytes(8, "big"))
        acc = Account(address, pubkey, n, 0)
        self.set_account(acc)
        return acc

    def get_or_create(self, address: str) -> Account:
        return self.get_account(address) or self.create_account(address)


class BankKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    def _key(self, address: str, denom: str) -> bytes:
        return _BAL_PREFIX + address.encode() + b"/" + denom.encode()

    def balance(self, address: str, denom: str = BOND_DENOM) -> int:
        raw = self.store.get(self._key(address, denom))
        return int.from_bytes(raw, "big") if raw else 0

    def _set_balance(self, address: str, denom: str, amount: int) -> None:
        if amount < 0:
            raise ValueError("negative balance")
        self.store.set(self._key(address, denom), amount.to_bytes(16, "big"))

    def send(self, sender: str, recipient: str, amount: int, denom: str = BOND_DENOM) -> None:
        bal = self.balance(sender, denom)
        if bal < amount:
            raise ValueError(
                f"insufficient funds: {sender} has {bal}{denom}, needs {amount}"
            )
        self._set_balance(sender, denom, bal - amount)
        self._set_balance(recipient, denom, self.balance(recipient, denom) + amount)

    def mint(self, recipient: str, amount: int, denom: str = BOND_DENOM) -> None:
        self._set_balance(recipient, denom, self.balance(recipient, denom) + amount)
        self._set_supply(denom, self.supply(denom) + amount)

    def burn(self, holder: str, amount: int, denom: str = BOND_DENOM) -> None:
        bal = self.balance(holder, denom)
        if bal < amount:
            raise ValueError("burn exceeds balance")
        self._set_balance(holder, denom, bal - amount)
        self._set_supply(denom, self.supply(denom) - amount)

    def supply(self, denom: str = BOND_DENOM) -> int:
        raw = self.store.get(_SUPPLY_KEY + denom.encode())
        return int.from_bytes(raw, "big") if raw else 0

    def _set_supply(self, denom: str, amount: int) -> None:
        self.store.set(_SUPPLY_KEY + denom.encode(), amount.to_bytes(16, "big"))

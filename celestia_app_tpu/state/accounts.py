"""Auth + bank state keepers over the KV store.

The minimal stateful substrate the reference app needs from cosmos-sdk
auth/bank for its tx flow: account numbers/sequences/pubkeys for signature
checks (ante), balances for fees and sends, module accounts for fee
collection and minting.

Vesting accounts (the reference wires x/auth/vesting, app/modules.go:105)
are base accounts with a lock schedule: `Account.locked(time_ns)` is the
still-vesting amount, and `send_spendable` refuses transfers that would dip
into it.  As in the sdk, locked tokens CAN be delegated (staking escrows
bypass the spendable check) — the lock follows the account, not the coins,
so undelegated tokens return under the same schedule.
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu.constants import BOND_DENOM
from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    encode_bytes_field,
    encode_varint_field,
)
from celestia_app_tpu.state.store import KVStore

FEE_COLLECTOR = "fee_collector"
MINT_MODULE = "mint"

_ACC_PREFIX = b"auth/acc/"
_BAL_PREFIX = b"bank/bal/"
_SUPPLY_KEY = b"bank/supply/"
_GLOBAL_ACC_NUM = b"auth/global_account_number"


VESTING_NONE = 0
VESTING_CONTINUOUS = 1  # linear release between start and end
VESTING_DELAYED = 2  # everything releases at end
VESTING_PERIODIC = 3  # stepwise release per (length, amount) period
VESTING_PERMANENT = 4  # never releases (sdk PermanentLockedAccount)


@dataclass
class Account:
    address: str
    pubkey: bytes  # 33-byte compressed secp256k1, b"" until first known
    account_number: int
    sequence: int
    # Vesting schedule (x/auth/vesting Continuous/DelayedVestingAccount);
    # all-zero for base accounts, and all-zero accounts marshal exactly as
    # before these fields existed (no state-layout break).
    vesting_type: int = VESTING_NONE
    original_vesting: int = 0
    vesting_start_ns: int = 0
    vesting_end_ns: int = 0
    # Locked tokens currently delegated (sdk DelegatedVesting): they are
    # out of the balance, so the lock must not double-count them or
    # later-received liquid funds would freeze.
    delegated_vesting: int = 0
    # Periodic schedule (sdk PeriodicVestingAccount.VestingPeriods):
    # (length_ns, amount) steps releasing cumulatively from start.
    vesting_periods: tuple[tuple[int, int], ...] = ()

    def marshal(self) -> bytes:
        out = (
            encode_bytes_field(1, self.address.encode())
            + encode_bytes_field(2, self.pubkey)
            + encode_varint_field(3, self.account_number)
            + encode_varint_field(4, self.sequence)
        )
        if self.vesting_type:
            out += (
                encode_varint_field(5, self.vesting_type)
                + encode_varint_field(6, self.original_vesting)
                + encode_varint_field(7, self.vesting_start_ns)
                + encode_varint_field(8, self.vesting_end_ns)
                + encode_varint_field(9, self.delegated_vesting)
            )
            for length_ns, amount in self.vesting_periods:
                out += encode_bytes_field(
                    10,
                    encode_varint_field(1, length_ns)
                    + encode_varint_field(2, amount),
                )
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Account":
        addr, pk = "", b""
        ints = {}
        periods: list[tuple[int, int]] = []
        for fnum, wt, val in decode_fields(raw):
            if fnum == 1 and wt == WIRE_LEN:
                addr = val.decode()
            elif fnum == 2 and wt == WIRE_LEN:
                pk = val
            elif fnum == 10 and wt == WIRE_LEN:
                p = {n: v for n, w, v in decode_fields(val) if w == WIRE_VARINT}
                periods.append((p.get(1, 0), p.get(2, 0)))
            elif wt == WIRE_VARINT:
                ints[fnum] = val
        return cls(
            addr, pk, ints.get(3, 0), ints.get(4, 0),
            ints.get(5, 0), ints.get(6, 0), ints.get(7, 0), ints.get(8, 0),
            ints.get(9, 0), tuple(periods),
        )

    def _schedule_locked(self, time_ns: int) -> int:
        if self.vesting_type == VESTING_NONE or self.original_vesting == 0:
            return 0
        if self.vesting_type == VESTING_PERMANENT:
            # sdk PermanentLockedAccount: never vests.
            return self.original_vesting
        if self.vesting_type == VESTING_PERIODIC:
            # Stepwise: each period's amount releases when its cumulative
            # length elapses past start (sdk periodic_vesting_account.go).
            if time_ns <= self.vesting_start_ns:
                return self.original_vesting
            vested = 0
            t = self.vesting_start_ns
            for length_ns, amount in self.vesting_periods:
                t += length_ns
                if time_ns < t:
                    break
                vested += amount
            return max(0, self.original_vesting - vested)
        if time_ns >= self.vesting_end_ns:
            return 0
        if self.vesting_type == VESTING_DELAYED:
            return self.original_vesting
        # Continuous: vested grows linearly from start to end (truncating,
        # as sdk's coin arithmetic does); nothing vests before start.
        if time_ns <= self.vesting_start_ns:
            return self.original_vesting
        elapsed = time_ns - self.vesting_start_ns
        duration = self.vesting_end_ns - self.vesting_start_ns
        vested = self.original_vesting * elapsed // duration
        return self.original_vesting - vested

    def locked(self, time_ns: int) -> int:
        """Still-vesting tokens encumbering the BALANCE at `time_ns`
        (sdk LockedCoins = schedule minus DelegatedVesting: locked tokens
        sitting in the staking escrow are no longer in the balance)."""
        return max(0, self._schedule_locked(time_ns) - self.delegated_vesting)

    def track_delegation(self, amount: int, time_ns: int) -> None:
        """Called when this account delegates (sdk TrackDelegation):
        delegations consume locked tokens first."""
        still_locked = self.locked(time_ns)
        self.delegated_vesting += min(amount, still_locked)

    def track_undelegation(self, amount: int) -> None:
        """Called when this account undelegates (sdk TrackUndelegation)."""
        self.delegated_vesting -= min(self.delegated_vesting, amount)


class AuthKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    def get_account(self, address: str) -> Account | None:
        raw = self.store.get(_ACC_PREFIX + address.encode())
        return Account.unmarshal(raw) if raw is not None else None

    def set_account(self, acc: Account) -> None:
        self.store.set(_ACC_PREFIX + acc.address.encode(), acc.marshal())

    def create_account(self, address: str, pubkey: bytes = b"") -> Account:
        n = int.from_bytes(self.store.get(_GLOBAL_ACC_NUM) or b"\x00", "big")
        self.store.set(_GLOBAL_ACC_NUM, (n + 1).to_bytes(8, "big"))
        acc = Account(address, pubkey, n, 0)
        self.set_account(acc)
        return acc

    def get_or_create(self, address: str) -> Account:
        return self.get_account(address) or self.create_account(address)


class BankKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    def _key(self, address: str, denom: str) -> bytes:
        return _BAL_PREFIX + address.encode() + b"/" + denom.encode()

    def balance(self, address: str, denom: str = BOND_DENOM) -> int:
        raw = self.store.get(self._key(address, denom))
        return int.from_bytes(raw, "big") if raw else 0

    def balances_of(self, address: str) -> dict[str, int]:
        """denom -> amount for one address (the bank AllBalances query):
        an address-scoped prefix walk, not the global supply walk."""
        prefix = _BAL_PREFIX + address.encode() + b"/"
        return {
            key[len(prefix):].decode(): int.from_bytes(val, "big")
            for key, val in self.store.iterate(prefix)
        }

    def _set_balance(self, address: str, denom: str, amount: int) -> None:
        if amount < 0:
            raise ValueError("negative balance")
        self.store.set(self._key(address, denom), amount.to_bytes(16, "big"))

    def send(self, sender: str, recipient: str, amount: int, denom: str = BOND_DENOM) -> None:
        bal = self.balance(sender, denom)
        if bal < amount:
            raise ValueError(
                f"insufficient funds: {sender} has {bal}{denom}, needs {amount}"
            )
        self._set_balance(sender, denom, bal - amount)
        self._set_balance(recipient, denom, self.balance(recipient, denom) + amount)

    def mint(self, recipient: str, amount: int, denom: str = BOND_DENOM) -> None:
        self._set_balance(recipient, denom, self.balance(recipient, denom) + amount)
        self._set_supply(denom, self.supply(denom) + amount)

    def burn(self, holder: str, amount: int, denom: str = BOND_DENOM) -> None:
        bal = self.balance(holder, denom)
        if bal < amount:
            raise ValueError("burn exceeds balance")
        self._set_balance(holder, denom, bal - amount)
        self._set_supply(denom, self.supply(denom) - amount)

    def supply(self, denom: str = BOND_DENOM) -> int:
        raw = self.store.get(_SUPPLY_KEY + denom.encode())
        return int.from_bytes(raw, "big") if raw else 0

    def _set_supply(self, denom: str, amount: int) -> None:
        self.store.set(_SUPPLY_KEY + denom.encode(), amount.to_bytes(16, "big"))

    def balances(self) -> dict[tuple[str, str], int]:
        """(address, denom) -> amount over all accounts — the x/crisis
        supply invariant walks this.

        Split at the FIRST '/': bech32 addresses cannot contain one, but
        IBC voucher denoms do ("port/channel/denom") — an rsplit parsed
        "addr/transfer/channel-0/uatom" as address "addr/transfer/
        channel-0" holding "uatom", corrupting the supply walk."""
        out = {}
        for key, val in self.store.iterate(_BAL_PREFIX):
            addr, denom = key[len(_BAL_PREFIX):].split(b"/", 1)
            out[(addr.decode(), denom.decode())] = int.from_bytes(val, "big")
        return out


def assert_spendable(
    auth: AuthKeeper, bank: BankKeeper, sender: str, amount: int, time_ns: int
) -> None:
    """Raise unless `sender` can part with `amount` without dipping into
    still-vesting tokens (sdk LockedCoins).  Module accounts have no
    Account record and no lock."""
    acc = auth.get_account(sender)
    locked = acc.locked(time_ns) if acc is not None else 0
    if locked:
        bal = bank.balance(sender)
        if bal - amount < locked:
            raise ValueError(
                f"insufficient spendable funds: {sender} has {bal}utia with "
                f"{locked}utia still vesting, cannot send {amount}"
            )


def send_spendable(
    auth: AuthKeeper, bank: BankKeeper, sender: str, recipient: str,
    amount: int, time_ns: int,
) -> None:
    """A transfer that respects the sender's vesting lock: spendable =
    balance - locked (sdk bank SendCoins via LockedCoins)."""
    assert_spendable(auth, bank, sender, amount, time_ns)
    bank.send(sender, recipient, amount)

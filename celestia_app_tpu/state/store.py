"""Deterministic versioned KV store (the multistore analog).

Replaces the reference's IAVL-backed CommitMultiStore (app/app.go:435,
LoadHeight :592) with the simplest structure that preserves the contracts
the app actually relies on:

  * deterministic app hash over committed state (consensus determinism,
    pinned by the reference's TestConsistentAppHash,
    app/test/consistent_apphash_test.go:47);
  * branch/write-back semantics (CacheContext) for proposal handling and
    per-tx atomicity;
  * per-height committed versions for restart/rollback/export
    (checkpoint/resume, SURVEY §5).

Not a merkle store: state proofs against the app hash are out of scope for
the DA-focused framework (the reference's light clients prove against the
*data* root, which is fully supported in proof/).
"""

from __future__ import annotations

import hashlib


class KVStore:
    """A mutable string->bytes map with branch/commit semantics."""

    def __init__(self, data: dict[bytes, bytes] | None = None):
        self._data: dict[bytes, bytes] = dict(data) if data else {}

    def get(self, key: bytes) -> bytes | None:
        return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        if not isinstance(value, bytes):
            raise TypeError("store values must be bytes")
        self._data[key] = value

    def delete(self, key: bytes) -> None:
        self._data.pop(key, None)

    def has(self, key: bytes) -> bool:
        return key in self._data

    def iterate(self, prefix: bytes) -> list[tuple[bytes, bytes]]:
        """Deterministic (sorted) iteration over a key prefix."""
        return sorted(
            (k, v) for k, v in self._data.items() if k.startswith(prefix)
        )

    def branch(self) -> "KVStore":
        """An isolated copy; apply back with `write_back`."""
        return KVStore(self._data)

    def write_back(self, branch: "KVStore") -> None:
        self._data = dict(branch._data)

    def snapshot(self) -> dict[bytes, bytes]:
        return dict(self._data)

    def hash(self) -> bytes:
        """Deterministic digest of the full contents."""
        h = hashlib.sha256()
        for k, v in sorted(self._data.items()):
            h.update(len(k).to_bytes(4, "big"))
            h.update(k)
            h.update(len(v).to_bytes(4, "big"))
            h.update(v)
        return h.digest()


class CommitStore:
    """Height-versioned commits of a KVStore (restart / rollback / export)."""

    def __init__(self):
        self.working = KVStore()
        self._committed: dict[int, dict[bytes, bytes]] = {}
        self.last_height = 0
        self.last_app_hash = b"\x00" * 32

    def commit(self, height: int) -> bytes:
        self._committed[height] = self.working.snapshot()
        self.last_height = height
        self.last_app_hash = self.working.hash()
        return self.last_app_hash

    def load_height(self, height: int) -> None:
        if height == 0:
            self.working = KVStore()
        else:
            if height not in self._committed:
                raise KeyError(f"no committed state at height {height}")
            self.working = KVStore(self._committed[height])
        self.last_height = height
        self.last_app_hash = self.working.hash() if height else b"\x00" * 32

    def rollback(self) -> int:
        """Drop the latest committed height (server rollback command)."""
        if self.last_height == 0:
            raise ValueError("nothing to roll back")
        self._committed.pop(self.last_height, None)
        self.load_height(self.last_height - 1) if self.last_height > 1 else self.load_height(0)
        return self.last_height

    def prune(self, keep_recent: int) -> None:
        cutoff = self.last_height - keep_recent
        for h in [h for h in self._committed if h < cutoff]:
            del self._committed[h]

    def export(self, height: int | None = None) -> dict[bytes, bytes]:
        if height is None:
            height = self.last_height
        return dict(self._committed[height])

    # --- disk persistence (restart/resume, reference LoadHeight app/app.go:592)
    def save(self, path: str, keep_recent: int = 2) -> None:
        """Write the most recent committed heights to disk.

        Two heights are kept so one `rollback` still works after a restart
        (the sdk server's rollback command rolls back exactly one height).
        """
        import json
        import os
        import tempfile

        heights = sorted(self._committed)[-keep_recent:]
        state = {
            "height": self.last_height,
            "versions": [
                {
                    "height": h,
                    "kv": {k.hex(): v.hex() for k, v in self._committed[h].items()},
                }
                for h in heights
            ],
        }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".")
        with os.fdopen(fd, "w") as f:
            json.dump(state, f)
        os.replace(tmp, path)  # atomic: a crash never corrupts the snapshot

    @classmethod
    def load(cls, path: str) -> "CommitStore":
        import json

        with open(path) as f:
            state = json.load(f)
        cs = cls()
        for version in state["versions"]:
            cs._committed[version["height"]] = {
                bytes.fromhex(k): bytes.fromhex(v) for k, v in version["kv"].items()
            }
        cs.load_height(state["height"])
        return cs

"""Deterministic versioned KV store (the multistore analog).

Replaces the reference's IAVL-backed CommitMultiStore (app/app.go:435,
LoadHeight :592) with a dict-backed store whose commitment is a merkleized
trie (state/smt.py), preserving the contracts the app relies on:

  * deterministic app hash over committed state (consensus determinism,
    pinned by the reference's TestConsistentAppHash,
    app/test/consistent_apphash_test.go:47) — here the root of a
    path-compressed merkle trie, maintained incrementally: a commit
    re-hashes O(delta * log n) nodes, never the whole state;
  * key existence / non-existence proofs against the committed app hash
    (`CommitStore.proof`, verified by `state.smt.verify`);
  * branch/write-back semantics (CacheContext) for proposal handling and
    per-tx atomicity — branches are copy-on-write overlays, so taking one
    per tx costs O(writes in the tx), not O(state);
  * per-height committed versions for restart/rollback/export
    (checkpoint/resume, SURVEY §5). The per-height snapshot is one shallow
    dict copy per *block* (off the per-tx path).
"""

from __future__ import annotations

from celestia_app_tpu.state import smt

_TOMBSTONE = None  # overlay marker for deletes


class KVStore:
    """A string->bytes map with copy-on-write branches and a merkle root.

    A root store owns the data dict and an incrementally-maintained merkle
    trie; `branch()` returns an overlay recording only its own writes.
    """

    def __init__(self, data: dict[bytes, bytes] | None = None, parent: "KVStore | None" = None):
        self._parent = parent
        if parent is None:
            self._data: dict[bytes, bytes] = dict(data) if data else {}
            self._trie = None
            self._dirty: set[bytes] = set(self._data)
            self._root_cache: bytes | None = None
        else:
            assert data is None
            self._writes: dict[bytes, bytes | None] = {}

    # --- reads ------------------------------------------------------------
    def get(self, key: bytes) -> bytes | None:
        node = self
        while node._parent is not None:
            if key in node._writes:
                return node._writes[key]
            node = node._parent
        return node._data.get(key)

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def iterate(self, prefix: bytes) -> list[tuple[bytes, bytes]]:
        """Deterministic (sorted) iteration over a key prefix."""
        merged: dict[bytes, bytes | None] = {}
        chain = []
        node = self
        while node._parent is not None:
            chain.append(node)
            node = node._parent
        for k, v in node._data.items():
            if k.startswith(prefix):
                merged[k] = v
        for overlay in reversed(chain):  # oldest overlay first, self last
            for k, v in overlay._writes.items():
                if k.startswith(prefix):
                    merged[k] = v
        return sorted((k, v) for k, v in merged.items() if v is not _TOMBSTONE)

    # --- writes -----------------------------------------------------------
    def set(self, key: bytes, value: bytes) -> None:
        if not isinstance(value, bytes):
            raise TypeError("store values must be bytes")
        if self._parent is not None:
            self._writes[key] = value
        else:
            self._data[key] = value
            self._dirty.add(key)
            self._root_cache = None

    def delete(self, key: bytes) -> None:
        if self._parent is not None:
            self._writes[key] = _TOMBSTONE
        else:
            self._data.pop(key, None)
            self._dirty.add(key)
            self._root_cache = None

    # --- branching --------------------------------------------------------
    def branch(self) -> "KVStore":
        """A copy-on-write overlay; apply back with `write_back`."""
        return KVStore(parent=self)

    def write_back(self, branch: "KVStore") -> None:
        """Apply an overlay's writes to this store (its direct parent)."""
        assert branch._parent is self, "write_back target must be the branch's parent"
        for k, v in branch._writes.items():
            if v is _TOMBSTONE:
                self.delete(k)
            else:
                self.set(k, v)
        branch._writes = {}

    def snapshot(self) -> dict[bytes, bytes]:
        if self._parent is None:
            return dict(self._data)
        snap = self._parent.snapshot()
        for k, v in self._writes.items():
            if v is _TOMBSTONE:
                snap.pop(k, None)
            else:
                snap[k] = v
        return snap

    # --- commitment -------------------------------------------------------
    def hash(self) -> bytes:
        """Merkle root of the contents (incremental on a root store)."""
        if self._parent is not None:
            return KVStore(self.snapshot()).hash()
        if self._root_cache is None:
            for k in self._dirty:
                v = self._data.get(k)
                kh = smt.key_hash(k)
                if v is None:
                    self._trie = smt.delete(self._trie, kh)
                else:
                    self._trie = smt.insert(self._trie, kh, smt.value_hash(v))
            self._dirty.clear()
            self._root_cache = smt.root_hash(self._trie)
        return self._root_cache

    def proof(self, key: bytes) -> smt.StateProof:
        """Existence/non-existence proof against this store's `hash()`."""
        if self._parent is not None:
            raise ValueError("proofs are served by root stores only")
        self.hash()  # flush dirty keys into the trie
        return smt.prove(self._trie, key, self._data.get(key))


class CommitStore:
    """Height-versioned commits of a KVStore (restart / rollback / export)."""

    def __init__(self):
        self.working = KVStore()
        self._committed: dict[int, dict[bytes, bytes]] = {}
        # height -> app hash, recorded at commit (historical queries);
        # height -> read-only KVStore view, memoized lazily — rebuilding
        # the SMT from a snapshot is O(state), so one view serves all of a
        # height's proofs.
        self._app_hashes: dict[int, bytes] = {}
        self._views: dict[int, KVStore] = {}
        self.last_height = 0
        self.last_app_hash = b"\x00" * 32

    def commit(self, height: int) -> bytes:
        self._committed[height] = self.working.snapshot()
        self.last_height = height
        self.last_app_hash = self.working.hash()
        self._app_hashes[height] = self.last_app_hash
        return self.last_app_hash

    def proof(self, key: bytes) -> smt.StateProof:
        """State proof for `key` against `last_app_hash` (call post-commit)."""
        return self.working.proof(key)

    def _view(self, height: int) -> KVStore:
        """Memoized read-only store over a committed snapshot."""
        view = self._views.get(height)
        if view is None:
            if height not in self._committed:
                raise KeyError(f"no committed state at height {height}")
            view = KVStore(self._committed[height])
            self._views[height] = view
            for h in sorted(self._views)[:-8]:  # bound the cache
                del self._views[h]
        return view

    def app_hash_at(self, height: int) -> bytes:
        """The app hash of a past committed height (recomputed from the
        snapshot for stores restored from disk)."""
        got = self._app_hashes.get(height)
        if got is None:
            got = self._app_hashes[height] = self._view(height).hash()
        return got

    def proof_at(self, key: bytes, height: int) -> smt.StateProof:
        """State proof for `key` against the app hash of a PAST committed
        height (IBC relayers prove at the height a light-client consensus
        state pins, which trails the chain tip)."""
        return self._view(height).proof(key)

    def load_height(self, height: int) -> None:
        if height == 0:
            self.working = KVStore()
        else:
            if height not in self._committed:
                raise KeyError(f"no committed state at height {height}")
            self.working = KVStore(self._committed[height])
        self.last_height = height
        self.last_app_hash = self.working.hash() if height else b"\x00" * 32

    def rollback(self) -> int:
        """Drop the latest committed height (server rollback command)."""
        if self.last_height == 0:
            raise ValueError("nothing to roll back")
        self._committed.pop(self.last_height, None)
        self._app_hashes.pop(self.last_height, None)
        self._views.pop(self.last_height, None)
        self.load_height(self.last_height - 1) if self.last_height > 1 else self.load_height(0)
        return self.last_height

    def prune(self, keep_recent: int) -> None:
        cutoff = self.last_height - keep_recent
        for h in [h for h in self._committed if h < cutoff]:
            del self._committed[h]
            self._app_hashes.pop(h, None)
            self._views.pop(h, None)

    def export(self, height: int | None = None) -> dict[bytes, bytes]:
        if height is None:
            height = self.last_height
        return dict(self._committed[height])

    # --- disk persistence (restart/resume, reference LoadHeight app/app.go:592)
    def save(self, path: str, keep_recent: int = 2) -> None:
        """Write the most recent committed heights to disk.

        Two heights are kept so one `rollback` still works after a restart
        (the sdk server's rollback command rolls back exactly one height).
        """
        import json
        import os
        import tempfile

        heights = sorted(self._committed)[-keep_recent:]
        state = {
            "height": self.last_height,
            "versions": [
                {
                    "height": h,
                    "kv": {k.hex(): v.hex() for k, v in self._committed[h].items()},
                }
                for h in heights
            ],
        }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".")
        with os.fdopen(fd, "w") as f:
            json.dump(state, f)
        os.replace(tmp, path)  # atomic: a crash never corrupts the snapshot

    @classmethod
    def load(cls, path: str) -> "CommitStore":
        import json

        with open(path) as f:
            state = json.load(f)
        cs = cls()
        for version in state["versions"]:
            cs._committed[version["height"]] = {
                bytes.fromhex(k): bytes.fromhex(v) for k, v in version["kv"].items()
            }
        cs.load_height(state["height"])
        return cs

"""18-decimal fixed-point arithmetic (sdk.Dec parity).

The mint schedule and fee checks are consensus-critical; the reference
computes them with cosmos-sdk's Dec — integers scaled by 1e18 with
round-half-to-even at each multiplication (x/mint/types/minter.go,
app/ante/fee_checker.go).  Python floats would drift; this mirrors the Dec
semantics the schedule depends on.
"""

from __future__ import annotations

from dataclasses import dataclass

PRECISION = 10**18


def _round_half_even(numerator: int, denominator: int) -> int:
    q, r = divmod(numerator, denominator)
    double = 2 * r
    if double > denominator or (double == denominator and q % 2):
        q += 1
    return q


@dataclass(frozen=True)
class Dec:
    """A fixed-point decimal: value = raw / 1e18."""

    raw: int

    @classmethod
    def from_int(cls, n: int) -> "Dec":
        return cls(n * PRECISION)

    @classmethod
    def from_str(cls, s: str) -> "Dec":
        if "." in s:
            whole, frac = s.split(".")
            frac = (frac + "0" * 18)[:18]
        else:
            whole, frac = s, "0" * 18
        sign = -1 if whole.startswith("-") else 1
        whole = whole.lstrip("-")
        return cls(sign * (int(whole or "0") * PRECISION + int(frac)))

    @classmethod
    def from_fraction(cls, num: int, den: int) -> "Dec":
        return cls(_round_half_even(num * PRECISION, den))

    def mul(self, other: "Dec") -> "Dec":
        return Dec(_round_half_even(self.raw * other.raw, PRECISION))

    def quo(self, other: "Dec") -> "Dec":
        return Dec(_round_half_even(self.raw * PRECISION, other.raw))

    def add(self, other: "Dec") -> "Dec":
        return Dec(self.raw + other.raw)

    def sub(self, other: "Dec") -> "Dec":
        return Dec(self.raw - other.raw)

    def power(self, n: int) -> "Dec":
        """Repeated truncating multiplication (sdk.Dec.Power semantics)."""
        result = Dec.from_int(1)
        base = self
        e = n
        while e:
            if e & 1:
                result = result.mul(base)
            base = base.mul(base)
            e >>= 1
        return result

    def mul_int(self, n: int) -> "Dec":
        return Dec(self.raw * n)

    def truncate_int(self) -> int:
        """Truncate toward zero to an integer."""
        if self.raw >= 0:
            return self.raw // PRECISION
        return -((-self.raw) // PRECISION)

    def ceil_int(self) -> int:
        return -((-self.raw) // PRECISION)

    def __lt__(self, other: "Dec") -> bool:
        return self.raw < other.raw

    def __le__(self, other: "Dec") -> bool:
        return self.raw <= other.raw

    def __str__(self) -> str:
        sign = "-" if self.raw < 0 else ""
        a = abs(self.raw)
        return f"{sign}{a // PRECISION}.{a % PRECISION:018d}"

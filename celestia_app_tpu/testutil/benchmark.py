"""Throughput benchmark harness (reference test/e2e/benchmark).

The reference's headline e2e criterion: sustain blocks carrying >= 90% of
MaxBlockBytes over the run (test/e2e/benchmark/throughput.go:110-128,
benchmark.go:172-189).  This harness drives the in-process node with
saturating PFB load and evaluates the same criterion; block sizes, fill
ratios, and wall times land in the trace tables for inspection.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from celestia_app_tpu.constants import CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
from celestia_app_tpu.modules.blob.types import estimate_gas
from celestia_app_tpu.shares.namespace import Namespace
from celestia_app_tpu.shares.sparse import Blob
from celestia_app_tpu.trace import traced
from celestia_app_tpu.user import Signer
from celestia_app_tpu.state.accounts import AuthKeeper


@dataclass
class ThroughputResult:
    blocks: int
    fills: list[float]  # per-block bytes / MaxBlockBytes
    mean_fill: float
    mean_block_bytes: float
    mean_block_seconds: float

    @property
    def blocks_per_second(self) -> float:
        return 1.0 / self.mean_block_seconds if self.mean_block_seconds else 0.0

    def passing_blocks(self, min_ratio: float = 0.9) -> int:
        return sum(f >= min_ratio for f in self.fills)

    def sustained(self, min_ratio: float = 0.9) -> bool:
        """throughput.go:124 pass criterion: EVERY block in the run carries
        >= min_ratio of MaxBlockBytes (reference default 90%)."""
        return self.blocks > 0 and self.passing_blocks(min_ratio) == self.blocks


def max_block_bytes(gov_max_square_size: int) -> int:
    """DefaultMaxBytes shape: square capacity x usable share bytes
    (pkg/appconsts/initial_consts.go:10-14)."""
    return (
        gov_max_square_size
        * gov_max_square_size
        * CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
    )


def run_throughput(
    node,
    blocks: int = 5,
    blob_size: int = 50_000,
    target_fill: float = 0.9,
    seed: int = 7,
    oversubmit: int = 2,
) -> ThroughputResult:
    """Saturate every block with PFBs, produce, and score fill ratios.

    Submits `oversubmit` blobs beyond the theoretical capacity each block so
    the square builder fills to its real (alignment-padded) limit — the
    e2e saturator's behavior (txsim at full tilt); overflow txs are dropped
    by the builder, not rejected.
    """
    rng = np.random.default_rng(seed)
    app = node.app
    signer = Signer(node.chain_id)
    auth = AuthKeeper(app.cms.working)
    for k in node.keys:
        acc = auth.get_account(k.public_key().address())
        signer.add_account(k, acc.account_number, acc.sequence)
    addr = signer.addresses()[0]

    cap_bytes = max_block_bytes(app.gov_max_square_size)
    per_block = max(1, -(-cap_bytes // blob_size) + oversubmit)
    # Pay fees at a realistic gas price, not 1 utia/gas: a saturating
    # gov-256 run is ~65 multi-million-gas PFBs per block, and fee=gas
    # drains a funded test account inside one block (observed as fills
    # collapsing to ~0.24 at k=256 while the builder sat half empty).
    min_price = float(str(app.node_min_gas_price))
    price = max(min_price * 10, 0.00001)

    fills: list[float] = []
    sizes: list[int] = []
    times: list[float] = []
    for _ in range(blocks):
        txs = []
        for _ in range(per_block):
            ns = Namespace.v0(rng.integers(1, 256, 10, dtype=np.uint8).tobytes())
            blob = Blob(ns, rng.integers(0, 256, blob_size, dtype=np.uint8).tobytes())
            gas = estimate_gas([blob_size])
            fee = max(1, int(gas * price) + 1)
            txs.append(signer.create_pay_for_blobs(addr, [blob], gas, fee))
            signer.increment_sequence(addr)
        t0 = time.perf_counter()
        data = app.prepare_proposal(txs)
        assert app.process_proposal(data)
        app.finalize_block(app.last_block_time_ns + 10**9, list(data.txs))
        app.commit()
        dt = time.perf_counter() - t0
        block_bytes = sum(len(t) for t in data.txs)
        fill = block_bytes / cap_bytes
        fills.append(fill)
        sizes.append(block_bytes)
        times.append(dt)
        traced().write(
            "throughput", height=app.height, block_bytes=block_bytes,
            fill=fill, seconds=dt,
        )
        # Re-sync sequences: txs dropped by the square cap would desync.
        acc = AuthKeeper(app.cms.working).get_account(addr)
        signer.set_sequence(addr, acc.sequence)

    return ThroughputResult(
        blocks=blocks,
        fills=fills,
        mean_fill=sum(fills) / len(fills),
        mean_block_bytes=sum(sizes) / len(sizes),
        mean_block_seconds=sum(times) / len(times),
    )

"""Two connected in-process chains + a relayer (the ibctesting analog).

Mirrors the reference's IBC test setup shape (test/tokenfilter/setup.go,
test/pfm/simapp.go drive ibctesting paths): two apps with an OPEN channel
pair, a funded relayer account on each side, and helpers that move packets
and acks across as signed MsgRecvPacket / MsgAcknowledgement / MsgTimeout
txs through real blocks.
"""

from __future__ import annotations

from celestia_app_tpu.crypto.keys import PrivateKey
from celestia_app_tpu.modules.ibc import Channel, ChannelKeeper, Packet
from celestia_app_tpu.state.accounts import AuthKeeper
from celestia_app_tpu.testutil.testnode import (
    TestNode,
    deterministic_genesis,
    funded_keys,
)
from celestia_app_tpu.tx.messages import (
    Coin,
    MsgAcknowledgement,
    MsgRecvPacket,
    MsgTimeout,
    MsgTransfer,
)
from celestia_app_tpu.tx.sign import Fee, build_and_sign

TRANSFER_PORT = "transfer"


class ChainEnd:
    def __init__(
        self, name: str, app_version: int, channel_id: str, token_filter: bool = True
    ):
        from celestia_app_tpu.app import App
        from celestia_app_tpu.state.dec import Dec

        self.keys = [
            PrivateKey.from_seed(f"{name}-user-{i}".encode()) for i in range(3)
        ]
        self.relayer = PrivateKey.from_seed(f"{name}-relayer".encode())
        app = App(
            node_min_gas_price=Dec.from_str("0.000001"),
            ibc_token_filter=token_filter,
        )
        app.init_chain(
            deterministic_genesis(
                self.keys + [self.relayer],
                chain_id=f"{name}-chain",
                app_version=app_version,
            )
        )
        self.node = TestNode(keys=self.keys + [self.relayer], app=app)
        self.channel_id = channel_id

    def submit(self, key: PrivateKey, msg, gas: int = 400_000):
        addr = key.public_key().address()
        acct = AuthKeeper(self.node.app.cms.working).get_account(addr)
        raw = build_and_sign(
            [msg], key, self.node.chain_id, acct.account_number, acct.sequence,
            Fee((Coin("utia", 20_000),), gas),
        )
        res = self.node.broadcast(raw)
        if res.code != 0:
            return res, []
        _, results = self.node.produce_block()
        return results[-1], results

    def balance(self, address: str, denom: str = "utia") -> int:
        from celestia_app_tpu.state.accounts import BankKeeper

        return BankKeeper(self.node.app.cms.working).balance(address, denom=denom)


class ConnectedChains:
    """celestia (chain_a, tokenfilter ON) <-> counterparty simapp (chain_b,
    no filter — the reference's test/pfm/simapp.go role), over
    transfer/channel-0 on both ends."""

    def __init__(self, app_version: int = 2, b_token_filter: bool = False):
        self.a = ChainEnd("alpha", app_version, "channel-0")
        self.b = ChainEnd("beta", app_version, "channel-0", token_filter=b_token_filter)
        for end, other in ((self.a, self.b), (self.b, self.a)):
            ChannelKeeper(end.node.app.cms.working).create_channel(
                Channel(
                    TRANSFER_PORT, end.channel_id, TRANSFER_PORT, other.channel_id
                )
            )

    @staticmethod
    def _sent_packet(results) -> Packet | None:
        for r in results:
            for e in r.events:
                if e[0] == "ibc.send_packet":
                    return Packet.unmarshal(bytes.fromhex(e[1]))
        return None

    @staticmethod
    def _written_ack(results) -> bytes | None:
        for r in results:
            for e in r.events:
                if e[0] == "ibc.write_acknowledgement":
                    return bytes.fromhex(e[2])
        return None

    def transfer(
        self, src: ChainEnd, dst: ChainEnd, key: PrivateKey, receiver: str,
        denom: str, amount: int, timeout_height: int = 0,
        timeout_timestamp_ns: int = 0, memo: str = "",
    ):
        """Send a transfer on src; returns (packet, tx result)."""
        msg = MsgTransfer(
            TRANSFER_PORT, src.channel_id, Coin(denom, amount),
            key.public_key().address(), receiver,
            timeout_revision_height=timeout_height,
            timeout_timestamp_ns=timeout_timestamp_ns, memo=memo,
        )
        result, results = src.submit(key, msg)
        return self._sent_packet(results), result

    def relay(self, packet: Packet, src: ChainEnd, dst: ChainEnd) -> bytes:
        """recv on dst, ack back on src; returns the acknowledgement."""
        relayer = dst.relayer
        result, results = dst.submit(
            relayer,
            MsgRecvPacket(packet.marshal(), relayer.public_key().address()),
        )
        assert result.code == 0, result.log
        ack = self._written_ack(results)
        assert ack is not None, "recv wrote no acknowledgement"
        result, _ = src.submit(
            src.relayer,
            MsgAcknowledgement(
                packet.marshal(), src.relayer.public_key().address(), ack
            ),
        )
        assert result.code == 0, result.log
        return ack

    def timeout(self, packet: Packet, src: ChainEnd, proof_height: int):
        return src.submit(
            src.relayer,
            MsgTimeout(
                packet.marshal(), src.relayer.public_key().address(),
                proof_height=proof_height,
            ),
        )

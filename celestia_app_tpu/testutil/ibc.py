"""Two connected in-process chains + a relayer (the ibctesting analog).

Mirrors the reference's IBC test setup shape (test/tokenfilter/setup.go,
test/pfm/simapp.go drive ibctesting paths): two apps with an OPEN channel
pair, a funded relayer account on each side, and helpers that move packets
and acks across as signed MsgRecvPacket / MsgAcknowledgement / MsgTimeout
txs through real blocks.
"""

from __future__ import annotations

from celestia_app_tpu.crypto.keys import PrivateKey
from celestia_app_tpu.modules.ibc import Channel, ChannelKeeper, Packet
from celestia_app_tpu.state.accounts import AuthKeeper
from celestia_app_tpu.testutil.testnode import (
    TestNode,
    deterministic_genesis,
    funded_keys,
)
from celestia_app_tpu.tx.messages import (
    Coin,
    MsgAcknowledgement,
    MsgRecvPacket,
    MsgTimeout,
    MsgTransfer,
)
from celestia_app_tpu.tx.sign import Fee, build_and_sign

TRANSFER_PORT = "transfer"


class ChainEnd:
    def __init__(
        self, name: str, app_version: int, channel_id: str, token_filter: bool = True
    ):
        from celestia_app_tpu.app import App
        from celestia_app_tpu.state.dec import Dec

        self.keys = [
            PrivateKey.from_seed(f"{name}-user-{i}".encode()) for i in range(3)
        ]
        self.relayer = PrivateKey.from_seed(f"{name}-relayer".encode())
        # The consensus keys behind deterministic_genesis's validator set —
        # what signs the Commits light clients verify.
        self.val_keys = [
            PrivateKey.from_seed(f"validator-{i}".encode()) for i in range(3)
        ]
        app = App(
            node_min_gas_price=Dec.from_str("0.000001"),
            ibc_token_filter=token_filter,
        )
        app.init_chain(
            deterministic_genesis(
                self.keys + [self.relayer],
                chain_id=f"{name}-chain",
                app_version=app_version,
            )
        )
        self.node = TestNode(keys=self.keys + [self.relayer], app=app)
        self.channel_id = channel_id

    @property
    def chain_id(self) -> str:
        return self.node.chain_id

    @property
    def height(self) -> int:
        return self.node.app.height

    @property
    def store(self):
        return self.node.app.cms.working

    def produce(self):
        return self.node.produce_block()

    def app_hash_at(self, height: int) -> bytes:
        # The commit store records every height's hash — no parallel
        # bookkeeping, so blocks produced through ANY path count.
        return self.node.app.cms.app_hash_at(height)

    def submit(self, key: PrivateKey, msg, gas: int = 400_000):
        addr = key.public_key().address()
        acct = AuthKeeper(self.node.app.cms.working).get_account(addr)
        raw = build_and_sign(
            [msg], key, self.node.chain_id, acct.account_number, acct.sequence,
            Fee((Coin("utia", 20_000),), gas),
        )
        res = self.node.broadcast(raw)
        if res.code != 0:
            return res, []
        _, results = self.produce()
        return results[-1], results

    # --- the light-client surface (what a relayer reads off this chain) ----
    def validator_map(self):
        from celestia_app_tpu.crypto.keys import PublicKey
        from celestia_app_tpu.state.staking import StakingKeeper

        return {
            v.address: (PublicKey(v.pubkey), v.power)
            for v in StakingKeeper(self.node.app.cms.working).bonded_validators()
            if v.pubkey
        }

    def commit_for(self, height: int, keys: list | None = None):
        """A real +2/3 Commit for `height`, signed by the genesis
        validators' consensus keys (what the serving plane's voting round
        produces; TestNode has no vote plane, so the harness signs).
        `keys` overrides the signer set — rotation tests model a chain
        whose validators changed by signing later commits with new keys."""
        from celestia_app_tpu.consensus import PRECOMMIT, Commit, Vote, block_id

        data_root = self.node.blocks[height - 1].hash
        prev_hash = self.app_hash_at(height - 1)
        time_ns = self.node.block_times[height]
        bid = block_id(data_root, prev_hash, time_ns)
        votes = tuple(
            Vote.sign(k, self.chain_id, height, PRECOMMIT, bid)
            for k in (keys if keys is not None else self.val_keys)
        )
        return Commit(height, bid, votes, data_root, prev_hash, time_ns=time_ns)

    def proof_at(self, key: bytes, height: int):
        return self.node.app.cms.proof_at(key, height)

    def balance(self, address: str, denom: str = "utia") -> int:
        from celestia_app_tpu.state.accounts import BankKeeper

        return BankKeeper(self.node.app.cms.working).balance(address, denom=denom)


class ConnectedChains:
    """celestia (chain_a, tokenfilter ON) <-> counterparty simapp (chain_b,
    no filter — the reference's test/pfm/simapp.go role), over
    transfer/channel-0 on both ends."""

    def __init__(self, app_version: int = 2, b_token_filter: bool = False):
        self.a = ChainEnd("alpha", app_version, "channel-0")
        self.b = ChainEnd("beta", app_version, "channel-0", token_filter=b_token_filter)
        for end, other in ((self.a, self.b), (self.b, self.a)):
            ChannelKeeper(end.node.app.cms.working).create_channel(
                Channel(
                    TRANSFER_PORT, end.channel_id, TRANSFER_PORT, other.channel_id
                )
            )

    @staticmethod
    def _sent_packet(results) -> Packet | None:
        for r in results:
            for e in r.events:
                if e[0] == "ibc.send_packet":
                    return Packet.unmarshal(bytes.fromhex(e[1]))
        return None

    @staticmethod
    def _written_ack(results) -> bytes | None:
        for r in results:
            for e in r.events:
                if e[0] == "ibc.write_acknowledgement":
                    return bytes.fromhex(e[2])
        return None

    def transfer(
        self, src: ChainEnd, dst: ChainEnd, key: PrivateKey, receiver: str,
        denom: str, amount: int, timeout_height: int = 0,
        timeout_timestamp_ns: int = 0, memo: str = "",
    ):
        """Send a transfer on src; returns (packet, tx result)."""
        msg = MsgTransfer(
            TRANSFER_PORT, src.channel_id, Coin(denom, amount),
            key.public_key().address(), receiver,
            timeout_revision_height=timeout_height,
            timeout_timestamp_ns=timeout_timestamp_ns, memo=memo,
        )
        result, results = src.submit(key, msg)
        return self._sent_packet(results), result

    def relay(self, packet: Packet, src: ChainEnd, dst: ChainEnd) -> bytes:
        """recv on dst, ack back on src; returns the acknowledgement."""
        relayer = dst.relayer
        result, results = dst.submit(
            relayer,
            MsgRecvPacket(packet.marshal(), relayer.public_key().address()),
        )
        assert result.code == 0, result.log
        ack = self._written_ack(results)
        assert ack is not None, "recv wrote no acknowledgement"
        result, _ = src.submit(
            src.relayer,
            MsgAcknowledgement(
                packet.marshal(), src.relayer.public_key().address(), ack
            ),
        )
        assert result.code == 0, result.log
        return ack

    def timeout(self, packet: Packet, src: ChainEnd, proof_height: int):
        return src.submit(
            src.relayer,
            MsgTimeout(
                packet.marshal(), src.relayer.public_key().address(),
                proof_height=proof_height,
            ),
        )


class VerifiedChains:
    """Two chains joined the REAL way: light clients of each other's
    consensus, the 03-connection + 04-channel handshakes proof-verified
    step by step, and packet relay that ships SMT state proofs with every
    MsgRecvPacket / MsgAcknowledgement / MsgTimeout (the full ibc-go path
    the IBC-lite harness above shortcuts)."""

    def __init__(self, app_version: int = 2, b_token_filter: bool = False):
        from celestia_app_tpu.modules.ibc.client import ClientKeeper

        self.a = ChainEnd("alpha", app_version, "", token_filter=True)
        self.b = ChainEnd(
            "beta", app_version, "", token_filter=b_token_filter
        )
        # A block of history so clients have something to verify.
        self.a.produce()
        self.b.produce()
        self.client_on_a = ClientKeeper(self.a.store).create_client(
            self.b.chain_id, self.b.validator_map()
        )
        self.client_on_b = ClientKeeper(self.b.store).create_client(
            self.a.chain_id, self.a.validator_map()
        )

    def _client_of(self, holder: ChainEnd) -> str:
        return self.client_on_a if holder is self.a else self.client_on_b

    def sync(self, src: ChainEnd, dst: ChainEnd) -> int:
        """Land src's pending state in a commit and update dst's client of
        src with it.  Returns the height dst can now verify proofs at:
        the commit at H+1 pins src's app hash at H."""
        from celestia_app_tpu.modules.ibc.client import ClientKeeper

        src.produce()  # capture pending writes at height H
        src.produce()  # H+1: its commit attests H's app hash
        ClientKeeper(dst.store).update_client(
            self._client_of(dst), src.commit_for(src.height)
        )
        return src.height - 1

    def handshake(self, version: str = "ics20-1") -> tuple[str, str]:
        """The full 8-step dance; returns (channel_id on a, on b)."""
        from celestia_app_tpu.modules.ibc.handshake import (
            ChannelHandshake,
            ConnectionKeeper,
            channel_key,
            connection_key,
        )

        a, b = self.a, self.b
        conn_a = ConnectionKeeper(a.store).open_init(
            self.client_on_a, self.client_on_b
        )
        h = self.sync(a, b)
        conn_b = ConnectionKeeper(b.store).open_try(
            self.client_on_b, conn_a, self.client_on_a,
            a.proof_at(connection_key(conn_a), h), h,
        )
        h = self.sync(b, a)
        ConnectionKeeper(a.store).open_ack(
            conn_a, conn_b, b.proof_at(connection_key(conn_b), h), h
        )
        h = self.sync(a, b)
        ConnectionKeeper(b.store).open_confirm(
            conn_b, a.proof_at(connection_key(conn_a), h), h
        )

        chan_a = ChannelHandshake(a.store).open_init(
            conn_a, TRANSFER_PORT, TRANSFER_PORT, version
        )
        h = self.sync(a, b)
        chan_b = ChannelHandshake(b.store).open_try(
            conn_b, TRANSFER_PORT, TRANSFER_PORT, chan_a,
            a.proof_at(channel_key(TRANSFER_PORT, chan_a), h), h, version,
        )
        h = self.sync(b, a)
        ChannelHandshake(a.store).open_ack(
            TRANSFER_PORT, chan_a, chan_b,
            b.proof_at(channel_key(TRANSFER_PORT, chan_b), h), h,
        )
        h = self.sync(a, b)
        ChannelHandshake(b.store).open_confirm(
            TRANSFER_PORT, chan_b,
            a.proof_at(channel_key(TRANSFER_PORT, chan_a), h), h,
        )
        self.a.channel_id = chan_a
        self.b.channel_id = chan_b
        return chan_a, chan_b

    # --- proof-carrying relay ------------------------------------------------
    def relay_recv(self, packet: Packet, src: ChainEnd, dst: ChainEnd):
        """recv on dst with a verified commitment proof from src."""
        from celestia_app_tpu.modules.ibc.core import _chan_key
        from celestia_app_tpu.state import smt

        h = self.sync(src, dst)
        key = _chan_key(
            b"commit", packet.source_port, packet.source_channel,
            packet.sequence,
        )
        proof = smt.proof_marshal(src.proof_at(key, h))
        relayer = dst.relayer
        return dst.submit(
            relayer,
            MsgRecvPacket(
                packet.marshal(), relayer.public_key().address(),
                proof_height=h, proof=proof,
            ),
        )

    def relay_ack(self, packet: Packet, ack: bytes, src: ChainEnd, dst: ChainEnd):
        """ack back on src with a verified ack proof from dst."""
        from celestia_app_tpu.modules.ibc.core import _chan_key
        from celestia_app_tpu.state import smt

        h = self.sync(dst, src)
        key = _chan_key(
            b"ack", packet.destination_port, packet.destination_channel,
            packet.sequence,
        )
        proof = smt.proof_marshal(dst.proof_at(key, h))
        return src.submit(
            src.relayer,
            MsgAcknowledgement(
                packet.marshal(), src.relayer.public_key().address(), ack,
                proof_height=h, proof=proof,
            ),
        )

    def relay_timeout(self, packet: Packet, src: ChainEnd, dst: ChainEnd):
        """timeout on src with a verified NON-receipt proof from dst."""
        from celestia_app_tpu.modules.ibc.core import _chan_key
        from celestia_app_tpu.state import smt

        h = self.sync(dst, src)
        key = _chan_key(
            b"receipt", packet.destination_port, packet.destination_channel,
            packet.sequence,
        )
        proof = smt.proof_marshal(dst.proof_at(key, h))
        return src.submit(
            src.relayer,
            MsgTimeout(
                packet.marshal(), src.relayer.public_key().address(),
                proof_height=h, proof=proof,
            ),
        )


# VerifiedChains sends transfers exactly like the IBC-lite harness.
VerifiedChains._sent_packet = staticmethod(ConnectedChains._sent_packet)
VerifiedChains._written_ack = staticmethod(ConnectedChains._written_ack)
VerifiedChains.transfer = ConnectedChains.transfer

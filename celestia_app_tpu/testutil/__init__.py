from celestia_app_tpu.testutil.testnode import (
    TestNode,
    deterministic_genesis,
    funded_keys,
)

__all__ = ["TestNode", "deterministic_genesis", "funded_keys"]

"""Malicious app variants: fault injection for negative testing.

Parity with the reference's malicious-node harness (test/util/malicious:
app.go BehaviorConfig, out_of_order_builder.go:63-90, tree.go BlindTree):
a proposer that builds invalid squares — shares out of namespace order, or
an outright wrong data root — so tests can prove an honest validator
rejects them.  This is also the wrong-kernel-output fault model for the TPU
pipeline (SURVEY §4.5).
"""

from __future__ import annotations

from celestia_app_tpu.app import App, BlockData
from celestia_app_tpu.da import DataAvailabilityHeader, extend_shares
from celestia_app_tpu.shares.share import Share
from celestia_app_tpu.square import builder as square

OUT_OF_ORDER = "out_of_order"
WRONG_ROOT = "wrong_root"


class MaliciousApp(App):
    """An App whose PrepareProposal misbehaves from `start_height` on."""

    def __init__(self, behavior: str = OUT_OF_ORDER, start_height: int = 1, **kwargs):
        super().__init__(**kwargs)
        if behavior not in (OUT_OF_ORDER, WRONG_ROOT):
            raise ValueError(f"unknown behavior {behavior}")
        self.behavior = behavior
        self.start_height = start_height

    def prepare_proposal(self, raw_txs: list[bytes]) -> BlockData:
        if self.height + 1 < self.start_height:
            return super().prepare_proposal(raw_txs)
        filtered = self._filter_txs(raw_txs)
        sq, kept = square.build(filtered, self.max_effective_square_size())
        if self.behavior == WRONG_ROOT:
            return BlockData(tuple(kept), sq.size, b"\xde\xad" * 16)

        # OUT_OF_ORDER: swap two distinct-namespace blob shares, then commit
        # honestly to the tampered square (the reference's OutOfOrderExport
        # swaps blobs across namespaces and hashes with a BlindTree that
        # skips namespace-order validation).
        shares = [bytearray(s.raw) for s in sq.shares]
        placements = sq.placements
        if len(placements) >= 2 and placements[0].start != placements[1].start:
            a, b = placements[0].start, placements[1].start
            shares[a], shares[b] = shares[b], shares[a]
        raw_shares = [bytes(s) for s in shares]
        try:
            eds = extend_shares(raw_shares)
            dah = DataAvailabilityHeader.from_eds(eds)
            root = dah.hash()
        except ValueError:
            root = b"\xbe\xef" * 16
        return BlockData(tuple(kept), sq.size, root)

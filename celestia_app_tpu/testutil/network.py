"""Multi-validator in-process network.

The reference has no in-process multi-validator harness — multi-node
testing goes straight to knuu/k8s (SURVEY §4.8).  Here the replicated state
machine (SURVEY §2.4 P1) is exercised directly: N real Apps share one
genesis; each round a rotating proposer runs PrepareProposal, every
validator runs ProcessProposal + finalize + commit, and the harness asserts
data roots and app hashes agree byte-for-byte — the determinism contract the
TPU kernels must uphold.
"""

from __future__ import annotations

from celestia_app_tpu.app import App, BlockData, Genesis
from celestia_app_tpu.mempool import PriorityMempool
from celestia_app_tpu.state.dec import Dec
from celestia_app_tpu.testutil.testnode import BLOCK_INTERVAL_NS, deterministic_genesis, funded_keys


class ConsensusFailure(AssertionError):
    pass


class Network:
    """N validators running the identical state machine in one process."""

    __test__ = False

    def __init__(self, n_validators: int = 3, genesis: Genesis | None = None, keys=None):
        self.keys = keys if keys is not None else funded_keys(4)
        self.genesis = genesis or deterministic_genesis(
            self.keys, n_validators=n_validators
        )
        self.nodes: list[App] = []
        for _ in range(n_validators):
            app = App(node_min_gas_price=Dec.from_str("0.000001"))
            app.init_chain(self.genesis)
            self.nodes.append(app)
        self.mempool = PriorityMempool()
        self.blocks: list[BlockData] = []

    @property
    def chain_id(self) -> str:
        return self.genesis.chain_id

    @property
    def app(self) -> App:
        """Primary node view (the TxClient/testnode surface)."""
        return self.nodes[0]

    def query_account(self, address: str):
        """Auth query against the primary node (TxClient surface)."""
        from celestia_app_tpu.state.accounts import AuthKeeper

        return AuthKeeper(self.nodes[0].cms.working).get_account(address)

    def broadcast(self, raw_tx: bytes):
        """CheckTx against the primary node (gossip: one mempool)."""
        res = self.nodes[0].check_tx(raw_tx)
        if res.code == 0:
            priority = next((e[1] for e in res.events if e[0] == "priority"), 0)
            self.mempool.insert(raw_tx, priority, self.nodes[0].height)
        return res

    def produce_block(self):
        """One consensus round: rotate proposer, validate everywhere,
        commit everywhere, compare roots + app hashes."""
        height = self.nodes[0].height + 1
        proposer = self.nodes[(height - 1) % len(self.nodes)]
        data = proposer.prepare_proposal(self.mempool.reap())

        for i, node in enumerate(self.nodes):
            if not node.process_proposal(data):
                raise ConsensusFailure(f"validator {i} rejected proposal at height {height}")

        time_ns = self.nodes[0].last_block_time_ns + BLOCK_INTERVAL_NS
        app_hashes = set()
        results = None
        for node in self.nodes:
            res = node.finalize_block(time_ns, list(data.txs))
            app_hashes.add(node.commit())
            if results is None:
                results = res
        if len(app_hashes) != 1:
            raise ConsensusFailure(
                f"app hash divergence at height {height}: {[h.hex()[:16] for h in app_hashes]}"
            )
        self.mempool.update(height, list(data.txs))
        self.blocks.append(data)
        return data, results

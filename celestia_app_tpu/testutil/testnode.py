"""In-process single-node chain harness.

The workhorse harness tier of the reference test strategy (SURVEY §4:
test/util/testnode NewNetwork) without a consensus engine: TestNode drives
the real App through the full block lifecycle — CheckTx admission,
PrepareProposal, ProcessProposal self-validation, Finalize, Commit — exactly
as the proposer's node would, with deterministic keys and genesis
(test/util/test_app.go:63 SetupTestAppWithGenesisValSet analog).
"""

from __future__ import annotations

from celestia_app_tpu.app import App, BlockData, Genesis, GenesisAccount, TxResult
from celestia_app_tpu.crypto import PrivateKey
from celestia_app_tpu.state.dec import Dec
from celestia_app_tpu.state.staking import Validator

GENESIS_TIME_NS = 1_700_000_000 * 10**9
BLOCK_INTERVAL_NS = 15 * 10**9  # GoalBlockTime
DEFAULT_BALANCE = 10**12  # 1M TIA in utia


def funded_keys(n: int) -> list[PrivateKey]:
    return [PrivateKey.from_seed(f"account-{i}".encode()) for i in range(n)]


def deterministic_genesis(
    keys: list[PrivateKey],
    chain_id: str = "tpu-test-chain",
    app_version: int = 2,
    n_validators: int = 3,
    gov_max_square_size: int = 64,
    data_commitment_window: int = 0,
) -> Genesis:
    accounts = tuple(
        GenesisAccount(k.public_key().address(), DEFAULT_BALANCE, k.public_key().bytes)
        for k in keys
    )
    validators = tuple(
        Validator(
            PrivateKey.from_seed(f"validator-{i}".encode()).public_key().address(),
            PrivateKey.from_seed(f"validator-{i}".encode()).public_key().bytes,
            power=100,
        )
        for i in range(n_validators)
    )
    return Genesis(
        chain_id=chain_id,
        genesis_time_ns=GENESIS_TIME_NS,
        accounts=accounts,
        validators=validators,
        app_version=app_version,
        gov_max_square_size=gov_max_square_size,
        data_commitment_window=data_commitment_window,
    )


class TestNode:
    """A single-process chain: mempool + proposer + validator in one."""

    __test__ = False  # not a pytest class

    def __init__(
        self,
        genesis: Genesis | None = None,
        keys: list[PrivateKey] | None = None,
        app: App | None = None,
    ):
        from celestia_app_tpu.mempool import PriorityMempool

        if app is not None:
            # Wrap an existing (e.g. disk-loaded) app: serving a restarted
            # chain (cmd/appd start --serve).
            self.keys = keys or []
            self.app = app
        else:
            self.keys = keys if keys is not None else funded_keys(4)
            self.app = App(node_min_gas_price=Dec.from_str("0.000001"))
            self.app.init_chain(genesis or deterministic_genesis(self.keys))
        import threading

        self.mempool = PriorityMempool()
        self.blocks: list[BlockData] = []
        self.block_times: dict[int, int] = {}  # height -> block time
        # Wall clock of the last commit (the /healthz block-age input).
        self.last_commit_walltime: float | None = None
        # tx hash -> (height, code, log): the RPC `tx` query's index.
        self.tx_index: dict[bytes, tuple[int, int, str]] = {}
        # Event bus: commit-time notification for tx/block subscribers —
        # the in-process analog of Tendermint's websocket /subscribe
        # (tm.event='Tx'): long-poll waiters block here instead of polling
        # the index.
        self.commit_event = threading.Condition()

    @property
    def chain_id(self) -> str:
        return self.app.chain_id

    def broadcast(self, raw_tx: bytes, ctx=None) -> TxResult:
        """CheckTx + mempool admission under a request trace: `ctx` (or
        the thread's current context, or a fresh local root) follows the
        tx into the mempool entry, so the block that later reaps it — and
        everything below, down to the DAH dispatch — shares its trace_id.
        """
        from celestia_app_tpu.trace.context import (
            current_context,
            trace_span,
            use_context,
        )

        if ctx is None:
            ctx = current_context()
        if ctx is None:
            from celestia_app_tpu.trace.context import new_context

            ctx = new_context(layer="rpc", source="local")
        # A blob tx's submitting namespace rides the trace baggage from
        # here on: every descendant span (mempool wait, square build,
        # dispatch, commit) and its e2e observation carries the tenant.
        from celestia_app_tpu.trace.square_journal import tx_namespace_label

        ns_lbl = tx_namespace_label(raw_tx)
        if ns_lbl is not None and ctx.baggage.get("namespace") != ns_lbl:
            ctx = ctx.child(namespace=ns_lbl)
        # CheckTx still serializes on the app's check state (a node lock
        # when the subclass has one), but the mempool admission below runs
        # under the pool's OWN per-shard locks (mempool.py) — concurrent
        # BroadcastTx admission no longer holds the node lock end-to-end.
        from contextlib import nullcontext

        check_lock = getattr(self, "lock", None) or nullcontext()
        with use_context(ctx), trace_span(
            "tx_submit", layer="rpc", e2e="submit", tx_bytes=len(raw_tx),
        ) as sp:
            with check_lock:
                res = self.app.check_tx(raw_tx)
                height = self.app.height
            sp["result"] = str(res.code)
            if res.code == 0:
                priority = next(
                    (e[1] for e in res.events if e[0] == "priority"), 0
                )
                # May raise qos.QosThrottled ($CELESTIA_QOS): the planes
                # render it 429 / RESOURCE_EXHAUSTED byte-identically.
                self.mempool.insert(
                    raw_tx, priority, height, ctx=current_context(),
                    ns=ns_lbl or "tx",  # already parsed above; don't re-parse
                )
        return res

    def _block_trace_context(self, reaped: list[bytes], height: int):
        """The block's TraceContext: adopt the FIRST reaped tx's
        submission trace (reap order is deterministic, so every proposer
        picks the same one) so a single trace_id runs from BroadcastTx to
        the DAH root; an empty block roots a fresh trace."""
        from celestia_app_tpu.trace.context import new_context

        for raw in reaped:
            ctx = self.mempool.ctx_for(raw)
            if ctx is not None:
                return ctx.child(height=height)
        return new_context(layer="block", height=height)

    def produce_block(
        self,
        time_ns: int | None = None,
        last_commit_signers: set[str] | None = None,
        evidence: tuple = (),
    ) -> tuple[BlockData, list[TxResult]]:
        """One full consensus round against the app itself.

        `time_ns` defaults to deterministic logical time (last + 15s, the
        GoalBlockTime) for reproducible tests; serving daemons pass wall
        clock so on-chain time tracks reality (x/mint provisions depend on
        it).  `last_commit_signers`/`evidence` feed x/slashing liveness and
        x/evidence (ABCI LastCommitInfo / ByzantineValidators).
        """
        from celestia_app_tpu.trace.context import trace_span, use_context

        if time_ns is None:
            time_ns = self.app.last_block_time_ns + BLOCK_INTERVAL_NS
        reaped = self.mempool.reap(self.block_max_bytes())
        block_ctx = self._block_trace_context(reaped, self.app.height + 1)
        with use_context(block_ctx):
            with trace_span(
                "block_propose", layer="consensus", e2e="propose",
                height=self.app.height + 1, n_txs=len(reaped),
            ):
                data = self.app.prepare_proposal(reaped)
                if not self.app.process_proposal(data):
                    raise AssertionError("node rejected its own proposal")
            with trace_span(
                "block_commit", layer="consensus", e2e="commit",
                height=self.app.height + 1,
            ):
                results = self._commit_block_data(
                    data, time_ns,
                    last_commit_signers=last_commit_signers, evidence=evidence,
                )
        return data, results

    def block_max_bytes(self) -> int:
        """The on-chain Block.MaxBytes cap the mempool reaps under (the
        reference's celestia-core reap budget) — skip-semantics in the
        mempool, so one oversized high-priority tx cannot blank blocks."""
        from celestia_app_tpu.modules.consensus_params import ConsensusParamsKeeper

        return ConsensusParamsKeeper(self.app.cms.working).block_max_bytes()

    def _commit_block_data(
        self,
        data: BlockData,
        time_ns: int,
        last_commit_signers: set[str] | None = None,
        evidence: tuple = (),
    ) -> list[TxResult]:
        """Execute + commit an already-validated block and do the node
        bookkeeping — the single copy of the commit sequence shared by the
        local produce path and the serving plane's replication paths."""
        results = self.app.finalize_block(
            time_ns, list(data.txs),
            last_commit_signers=last_commit_signers, evidence=evidence,
        )
        self.app.commit()
        self.mempool.update(self.app.height, list(data.txs))
        # Mempool RECHECK (CometBFT's recheck=true default): replay the
        # resident txs through CheckTx against the fresh state.  This (a)
        # evicts txs the new state invalidated, and (b) rebuilds the check
        # state's sequence expectations to include resident txs — without
        # it, a client pipelining sequences ahead of commits is rejected
        # with a sequence mismatch the moment a block lands.
        for raw in self.mempool.resident_txs():
            if self.app.check_tx(raw).code != 0:
                self.mempool.remove_tx(raw)
        self.blocks.append(data)
        self.block_times[self.app.height] = time_ns
        self.index_block(self.app.height, list(data.txs), results)
        return results

    # --- query surface shared with the RPC plane ---------------------------
    def index_block(self, height: int, txs: list[bytes], results: list[TxResult]) -> None:
        from celestia_app_tpu.tx import tx_hash

        import time

        for raw, res in zip(txs, results):
            self.tx_index[tx_hash(raw)] = (height, res.code, res.log)
        self.last_commit_walltime = time.time()
        with self.commit_event:
            self.commit_event.notify_all()

    def query_account(self, address: str):
        """(account_number, sequence, pubkey) or None — the auth query."""
        from celestia_app_tpu.state.accounts import AuthKeeper

        return AuthKeeper(self.app.cms.working).get_account(address)

    def tx_status(self, tx_hash: bytes) -> tuple[int, int, str] | None:
        """(height, code, log) for a committed tx, None if unknown."""
        return self.tx_index.get(tx_hash)

    def wait_tx(self, tx_hash: bytes, timeout_s: float = 30.0):
        """Block until `tx_hash` is committed; (height, code, log) or None.

        The subscription path (Tendermint /subscribe tm.event='Tx' analog):
        waiters sleep on the commit event instead of polling tx_status in a
        loop — one wakeup per committed block, zero queries in between.
        """
        import time

        deadline = time.monotonic() + timeout_s
        with self.commit_event:
            while True:
                status = self.tx_index.get(tx_hash)
                if status is not None:
                    return status
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self.commit_event.wait(remaining):
                    return self.tx_index.get(tx_hash)

    def validators(self) -> list[dict]:
        """The validator set, shaped like RemoteNode.validators() so
        clients (txsim) stay node-agnostic across local and wire nodes."""
        from celestia_app_tpu.state.staking import StakingKeeper

        return [
            {"address": v.address, "power": v.power}
            for v in StakingKeeper(self.app.cms.working).validators()
        ]

"""Bech32 address encoding (BIP-173), as used for cosmos-style addresses.

Reference account addresses are bech32("celestia", ripemd160(sha256(pk)))
(cosmos-sdk types; surfaced all over x/blob e.g. MsgPayForBlobs.signer).
"""

from __future__ import annotations

_CHARSET = "qpzry9x8gf2tvdw0s3jn54khce6mua7l"
_GEN = (0x3B6A57B2, 0x26508E6D, 0x1EA119FA, 0x3D4233DD, 0x2A1462B3)


def _polymod(values: list[int]) -> int:
    chk = 1
    for v in values:
        b = chk >> 25
        chk = (chk & 0x1FFFFFF) << 5 ^ v
        for i in range(5):
            chk ^= _GEN[i] if (b >> i) & 1 else 0
    return chk


def _hrp_expand(hrp: str) -> list[int]:
    return [ord(c) >> 5 for c in hrp] + [0] + [ord(c) & 31 for c in hrp]


def _create_checksum(hrp: str, data: list[int]) -> list[int]:
    values = _hrp_expand(hrp) + data
    mod = _polymod(values + [0] * 6) ^ 1
    return [(mod >> 5 * (5 - i)) & 31 for i in range(6)]


def _convertbits(data: bytes | list[int], frombits: int, tobits: int, pad: bool) -> list[int]:
    acc = 0
    bits = 0
    ret: list[int] = []
    maxv = (1 << tobits) - 1
    for value in data:
        if value < 0 or value >> frombits:
            raise ValueError("invalid value for conversion")
        acc = (acc << frombits) | value
        bits += frombits
        while bits >= tobits:
            bits -= tobits
            ret.append((acc >> bits) & maxv)
    if pad:
        if bits:
            ret.append((acc << (tobits - bits)) & maxv)
    elif bits >= frombits or ((acc << (tobits - bits)) & maxv):
        raise ValueError("invalid padding in bech32 data")
    return ret


def encode(hrp: str, payload: bytes) -> str:
    data = _convertbits(payload, 8, 5, True)
    checksum = _create_checksum(hrp, data)
    return hrp + "1" + "".join(_CHARSET[d] for d in data + checksum)


def decode(addr: str) -> tuple[str, bytes]:
    """Returns (hrp, payload); raises ValueError on any malformation."""
    if addr.lower() != addr and addr.upper() != addr:
        raise ValueError("mixed-case bech32")
    addr = addr.lower()
    pos = addr.rfind("1")
    if pos < 1 or pos + 7 > len(addr) or len(addr) > 90:
        raise ValueError("invalid bech32 framing")
    hrp, rest = addr[:pos], addr[pos + 1 :]
    if any(c not in _CHARSET for c in rest):
        raise ValueError("invalid bech32 character")
    data = [_CHARSET.index(c) for c in rest]
    if _polymod(_hrp_expand(hrp) + data) != 1:
        raise ValueError("bad bech32 checksum")
    return hrp, bytes(_convertbits(data[:-6], 5, 8, False))

from celestia_app_tpu.crypto.keys import (
    ACCOUNT_HRP,
    PrivateKey,
    PublicKey,
    validate_address,
)

__all__ = ["ACCOUNT_HRP", "PrivateKey", "PublicKey", "validate_address"]

"""Keccak-256 (the Ethereum/EVM hash): keccak-f[1600] sponge, rate 1088.

The blobstream contract surface hashes EVM-ABI-encoded valsets and data
commitments with Keccak256 (reference x/blobstream/types/valset.go:55,75
via golang.org/x/crypto/sha3 `legacyKeccak256`); round 2 substituted
sha256 with domain separation, which broke EVM byte-parity (VERDICT r2
missing #4).  This is the real permutation, host-side: attestation
digests are a handful of hashes per block — consensus-plane bookkeeping,
not the TPU hot path (the hot path's SHA-256 lives in kernels/sha256.py).

Keccak256 is the ORIGINAL Keccak padding (0x01 multirate), not SHA-3's
0x06 — Ethereum froze on the pre-NIST variant; test vectors in
tests/test_keccak.py pin both this and the NIST SHA3-256 variant (0x06)
against published values.
"""

from __future__ import annotations

_MASK = (1 << 64) - 1

# Rotation offsets r[x][y] (FIPS 202 / Keccak reference, indexed [x][y]).
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]

# Round constants RC[i] for keccak-f[1600]'s 24 rounds.
_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]


def _rotl(v: int, n: int) -> int:
    n %= 64
    return ((v << n) | (v >> (64 - n))) & _MASK


def keccak_f1600(lanes: list[int]) -> list[int]:
    """The permutation over 25 64-bit lanes, index a[x + 5*y]."""
    a = list(lanes)
    for rc in _RC:
        # theta
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20]
             for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] ^= d[x]
        # rho + pi
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl(
                    a[x + 5 * y], _ROT[x][y]
                )
        # chi
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] = b[x + 5 * y] ^ (
                    (~b[(x + 1) % 5 + 5 * y]) & b[(x + 2) % 5 + 5 * y] & _MASK
                )
        # iota
        a[0] ^= rc
    return a


def _sponge(data: bytes, rate: int, pad_byte: int, out_len: int) -> bytes:
    lanes = [0] * 25
    # Absorb: multirate padding pad_byte ... 0x80 (the two can share a byte).
    padded = bytearray(data)
    pad_len = rate - (len(padded) % rate)
    padded += bytes(pad_len)
    padded[len(data)] ^= pad_byte
    padded[-1] ^= 0x80
    for off in range(0, len(padded), rate):
        block = padded[off: off + rate]
        for i in range(rate // 8):
            lanes[i] ^= int.from_bytes(block[8 * i: 8 * i + 8], "little")
        lanes = keccak_f1600(lanes)
    # Squeeze (out_len <= rate for the 256-bit variants).
    out = b"".join(lane.to_bytes(8, "little") for lane in lanes[: rate // 8])
    return out[:out_len]


def keccak256(data: bytes) -> bytes:
    """Ethereum's Keccak-256: rate 1088, legacy 0x01 padding."""
    return _sponge(data, 136, 0x01, 32)


def sha3_256(data: bytes) -> bytes:
    """NIST SHA3-256 (FIPS 202): same permutation, 0x06 padding."""
    return _sponge(data, 136, 0x06, 32)

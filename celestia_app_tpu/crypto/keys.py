"""Account keys: secp256k1 (cosmos account scheme) with compact signatures.

Parity with the reference's account cryptography (cosmos-sdk secp256k1,
spec specs/src/specs/public_key_cryptography.md): 33-byte compressed
pubkeys, 64-byte r||s signatures over sha256(msg) with low-S normalization,
addresses = ripemd160(sha256(pubkey)) in bech32 ("celestia" HRP).
"""

from __future__ import annotations

import hashlib

from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.utils import (
    Prehashed,
    decode_dss_signature,
    encode_dss_signature,
)
from cryptography.hazmat.primitives import hashes, serialization

from celestia_app_tpu.crypto import bech32

ACCOUNT_HRP = "celestia"
_ORDER = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
def _sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


class PrivateKey:
    """A secp256k1 signing key."""

    def __init__(self, key: ec.EllipticCurvePrivateKey):
        self._key = key

    @classmethod
    def generate(cls) -> "PrivateKey":
        return cls(ec.generate_private_key(ec.SECP256K1()))

    @classmethod
    def from_seed(cls, seed: bytes) -> "PrivateKey":
        """Deterministic key from a seed (testing/txsim reproducibility)."""
        d = int.from_bytes(_sha256(b"celestia_app_tpu-key" + seed), "big") % (_ORDER - 1) + 1
        return cls(ec.derive_private_key(d, ec.SECP256K1()))

    def public_key(self) -> "PublicKey":
        return PublicKey.from_cryptography(self._key.public_key())

    def sign(self, msg: bytes) -> bytes:
        """64-byte r||s signature over sha256(msg), low-S normalized.

        Deterministic (RFC 6979) like the reference's cosmos-sdk/btcec
        signer: identical (key, msg) always yields identical bytes —
        identical txs -> identical data roots across runs, a
        consensus-layer equivalence OpenSSL's randomized nonces broke.
        Pinned against the public secp256k1 RFC 6979 vector in
        tests/test_deterministic_signing.py.
        """
        der = self._key.sign(
            _sha256(msg),
            ec.ECDSA(Prehashed(hashes.SHA256()), deterministic_signing=True),
        )
        r, s = decode_dss_signature(der)
        if s > _ORDER // 2:
            s = _ORDER - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")


class PublicKey:
    """A 33-byte compressed secp256k1 public key."""

    def __init__(self, compressed: bytes):
        if len(compressed) != 33:
            raise ValueError(f"compressed pubkey must be 33 bytes, got {len(compressed)}")
        self.bytes = compressed

    @classmethod
    def from_cryptography(cls, pub: ec.EllipticCurvePublicKey) -> "PublicKey":
        return cls(
            pub.public_bytes(
                serialization.Encoding.X962, serialization.PublicFormat.CompressedPoint
            )
        )

    def _to_cryptography(self) -> ec.EllipticCurvePublicKey:
        return ec.EllipticCurvePublicKey.from_encoded_point(ec.SECP256K1(), self.bytes)

    def verify(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != 64:
            return False
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if not 0 < r < _ORDER or not 0 < s <= _ORDER // 2:
            return False
        try:
            self._to_cryptography().verify(
                encode_dss_signature(r, s),
                _sha256(msg),
                ec.ECDSA(Prehashed(hashes.SHA256())),
            )
            return True
        except Exception:
            return False

    def address_bytes(self) -> bytes:
        return hashlib.new("ripemd160", _sha256(self.bytes)).digest()

    def address(self) -> str:
        return bech32.encode(ACCOUNT_HRP, self.address_bytes())


def validate_address(addr: str) -> bytes:
    """Decode a bech32 account address; raises ValueError if invalid."""
    hrp, payload = bech32.decode(addr)
    if hrp != ACCOUNT_HRP:
        raise ValueError(f"wrong address prefix {hrp!r}")
    if len(payload) != 20:
        raise ValueError(f"address payload must be 20 bytes, got {len(payload)}")
    return payload

"""Threshold multisig pubkeys (the sdk's LegacyAminoPubKey surface).

Reference: the default sdk ante chain admits multisig accounts with up to
TxSigLimit = 7 sub-signatures (NewValidateSigCountDecorator +
SigVerificationDecorator in app/ante/ante.go:15-82); celestia-app changes
neither.  Wire shapes follow cosmos protos:

  /cosmos.crypto.multisig.LegacyAminoPubKey { threshold=1, public_keys=2 }
  ModeInfo.Multi { bitarray=1 (CompactBitArray), mode_infos=2 }
  CompactBitArray { extra_bits_stored=1, elems=2 }   (MSB-first bits)
  MultiSignature  { signatures=1 repeated }          (set-bit order)

Documented deviation: the sdk derives the multisig ADDRESS from the legacy
amino encoding of the key set (sha256(amino(pubkey))[:20]); amino is not
reimplemented here, so the address hashes the proto encoding instead —
deterministic and collision-resistant over (threshold, keys), but not
byte-equal to an sdk-derived multisig address.  Every sub-signature signs
the standard SIGN_MODE_DIRECT SignDoc of the outer tx.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from celestia_app_tpu.crypto import bech32
from celestia_app_tpu.crypto.keys import ACCOUNT_HRP, PublicKey
from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    encode_bytes_field,
    encode_varint_field,
)
from celestia_app_tpu.tx.messages import Any

URL_MULTISIG_PUBKEY = "/cosmos.crypto.multisig.LegacyAminoPubKey"
URL_SECP256K1_PUBKEY = "/cosmos.crypto.secp256k1.PubKey"


def _marshal_simple_pubkey(pk: PublicKey) -> bytes:
    return Any(URL_SECP256K1_PUBKEY, encode_bytes_field(1, pk.bytes)).marshal()


@dataclass(frozen=True)
class MultisigPubKey:
    """t-of-n threshold key over secp256k1 sub-keys."""

    threshold: int
    public_keys: tuple[PublicKey, ...]

    def __post_init__(self):
        if not 1 <= self.threshold <= len(self.public_keys):
            raise ValueError(
                f"threshold {self.threshold} out of range for "
                f"{len(self.public_keys)} keys"
            )

    # --- wire --------------------------------------------------------------
    def value_bytes(self) -> bytes:
        out = encode_varint_field(1, self.threshold)
        for pk in self.public_keys:
            out += encode_bytes_field(2, _marshal_simple_pubkey(pk))
        return out

    def to_any(self) -> Any:
        return Any(URL_MULTISIG_PUBKEY, self.value_bytes())

    @classmethod
    def from_value(cls, raw: bytes) -> "MultisigPubKey":
        threshold = 0
        keys: list[PublicKey] = []
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_VARINT:
                threshold = val
            elif num == 2 and wt == WIRE_LEN:
                a = Any.unmarshal(val)
                if a.type_url != URL_SECP256K1_PUBKEY:
                    raise ValueError(f"multisig sub-key type {a.type_url}")
                for n2, w2, v2 in decode_fields(a.value):
                    if n2 == 1 and w2 == WIRE_LEN:
                        keys.append(PublicKey(v2))
        return cls(threshold, tuple(keys))

    # --- identity ----------------------------------------------------------
    def address(self) -> str:
        digest = hashlib.sha256(self.value_bytes()).digest()[:20]
        return bech32.encode(ACCOUNT_HRP, digest)

    # --- verification ------------------------------------------------------
    def verify_multi(
        self, doc: bytes, bits: tuple[bool, ...], signatures: tuple[bytes, ...]
    ) -> bool:
        """True iff >= threshold sub-keys signed `doc`; `bits[i]` marks
        whether key i participated, `signatures` in set-bit order."""
        if len(bits) != len(self.public_keys):
            return False
        set_idx = [i for i, b in enumerate(bits) if b]
        if len(set_idx) != len(signatures) or len(set_idx) < self.threshold:
            return False
        return all(
            self.public_keys[i].verify(doc, sig)
            for i, sig in zip(set_idx, signatures)
        )


# --- CompactBitArray ------------------------------------------------------
def marshal_bitarray(bits: tuple[bool, ...]) -> bytes:
    n = len(bits)
    elems = bytearray((n + 7) // 8)
    for i, b in enumerate(bits):
        if b:
            elems[i // 8] |= 0x80 >> (i % 8)  # MSB-first, sdk CompactBitArray
    return encode_varint_field(1, n % 8) + encode_bytes_field(2, bytes(elems))


def unmarshal_bitarray(raw: bytes) -> tuple[bool, ...]:
    extra = 0
    elems = b""
    for num, wt, val in decode_fields(raw):
        if num == 1 and wt == WIRE_VARINT:
            extra = val
        elif num == 2 and wt == WIRE_LEN:
            elems = val
    n = len(elems) * 8 - ((8 - extra) % 8 if extra else 0)
    return tuple(bool(elems[i // 8] & (0x80 >> (i % 8))) for i in range(n))


# --- MultiSignature -------------------------------------------------------
def marshal_multisignature(signatures: tuple[bytes, ...]) -> bytes:
    out = b""
    for s in signatures:
        out += encode_bytes_field(1, s)
    return out


def unmarshal_multisignature(raw: bytes) -> tuple[bytes, ...]:
    return tuple(
        val for num, wt, val in decode_fields(raw) if num == 1 and wt == WIRE_LEN
    )

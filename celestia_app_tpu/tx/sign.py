"""Transaction envelope + SIGN_MODE_DIRECT signing.

Wire parity with cosmos tx.proto as the reference consumes it through
pkg/user (Signer, pkg/user/signer.go:23-36): TxBody / AuthInfo / SignDoc /
TxRaw with the standard field numbers, secp256k1 pubkeys wrapped in Any.
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu.crypto.keys import PrivateKey, PublicKey
from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    encode_bytes_field,
    encode_varint_field,
)
from celestia_app_tpu.tx.messages import Any, Coin, decode_msg

URL_SECP256K1_PUBKEY = "/cosmos.crypto.secp256k1.PubKey"
SIGN_MODE_DIRECT = 1


@dataclass(frozen=True)
class Fee:
    """cosmos.tx.v1beta1.Fee {amount=1, gas_limit=2, payer=3, granter=4}.
    `granter` routes the fee through an x/feegrant allowance (the
    reference's txsim master account pays sub-account fees this way,
    test/txsim/account.go:238-239)."""

    amount: tuple[Coin, ...]
    gas_limit: int
    payer: str = ""
    granter: str = ""

    def marshal(self) -> bytes:
        out = b""
        for c in self.amount:
            out += encode_bytes_field(1, c.marshal())
        out += encode_varint_field(2, self.gas_limit)
        if self.payer:
            out += encode_bytes_field(3, self.payer.encode())
        if self.granter:
            out += encode_bytes_field(4, self.granter.encode())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Fee":
        coins: list[Coin] = []
        gas = 0
        payer, granter = "", ""
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                coins.append(Coin.unmarshal(val))
            elif num == 2 and wt == WIRE_VARINT:
                gas = val
            elif num == 3 and wt == WIRE_LEN:
                payer = val.decode()
            elif num == 4 and wt == WIRE_LEN:
                granter = val.decode()
        return cls(tuple(coins), gas, payer, granter)


def _marshal_pubkey(pk) -> bytes:
    from celestia_app_tpu.tx.multisig import MultisigPubKey

    if isinstance(pk, MultisigPubKey):
        return pk.to_any().marshal()
    return Any(URL_SECP256K1_PUBKEY, encode_bytes_field(1, pk.bytes)).marshal()


def _unmarshal_pubkey(raw: bytes):
    from celestia_app_tpu.tx.multisig import URL_MULTISIG_PUBKEY, MultisigPubKey

    a = Any.unmarshal(raw)
    if a.type_url == URL_MULTISIG_PUBKEY:
        return MultisigPubKey.from_value(a.value)
    if a.type_url != URL_SECP256K1_PUBKEY:
        raise ValueError(f"unsupported pubkey type {a.type_url}")
    for num, wt, val in decode_fields(a.value):
        if num == 1 and wt == WIRE_LEN:
            return PublicKey(val)
    raise ValueError("pubkey Any missing key bytes")


def _marshal_mode_info_single(mode: int) -> bytes:
    return encode_bytes_field(1, encode_varint_field(1, mode))


def _marshal_mode_info_multi(bits: tuple[bool, ...]) -> bytes:
    from celestia_app_tpu.tx.multisig import marshal_bitarray

    inner = encode_bytes_field(1, marshal_bitarray(bits))
    for b in bits:
        if b:
            inner += encode_bytes_field(2, _marshal_mode_info_single(SIGN_MODE_DIRECT))
    return encode_bytes_field(2, inner)  # ModeInfo.multi = field 2


@dataclass(frozen=True)
class SignerInfo:
    """One tx signer.  `public_key` is a PublicKey or a MultisigPubKey;
    `mode_bits` (multisig only) marks which sub-keys participated."""

    public_key: object
    sequence: int
    mode_bits: tuple[bool, ...] | None = None

    def marshal(self) -> bytes:
        mode = (
            _marshal_mode_info_multi(self.mode_bits)
            if self.mode_bits is not None
            else _marshal_mode_info_single(SIGN_MODE_DIRECT)
        )
        return (
            encode_bytes_field(1, _marshal_pubkey(self.public_key))
            + encode_bytes_field(2, mode)
            + encode_varint_field(3, self.sequence)
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "SignerInfo":
        from celestia_app_tpu.tx.multisig import unmarshal_bitarray

        pk = None
        seq = 0
        mode_bits = None
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                pk = _unmarshal_pubkey(val)
            elif num == 2 and wt == WIRE_LEN:
                for n2, w2, v2 in decode_fields(val):
                    if n2 == 2 and w2 == WIRE_LEN:  # ModeInfo.multi
                        for n3, w3, v3 in decode_fields(v2):
                            if n3 == 1 and w3 == WIRE_LEN:
                                mode_bits = unmarshal_bitarray(v3)
            elif num == 3 and wt == WIRE_VARINT:
                seq = val
        if pk is None:
            raise ValueError("signer info missing public key")
        return cls(pk, seq, mode_bits)


@dataclass(frozen=True)
class TxBody:
    """cosmos tx.proto TxBody: messages=1, memo=2, timeout_height=3,
    extension_options=1023, non_critical_extension_options=2047."""

    messages: tuple[Any, ...]
    memo: str = ""
    timeout_height: int = 0
    extension_options: tuple[Any, ...] = ()
    non_critical_extension_options: tuple[Any, ...] = ()

    def marshal(self) -> bytes:
        out = b""
        for m in self.messages:
            out += encode_bytes_field(1, m.marshal())
        if self.memo:
            out += encode_bytes_field(2, self.memo.encode())
        if self.timeout_height:
            out += encode_varint_field(3, self.timeout_height)
        for e in self.extension_options:
            out += encode_bytes_field(1023, e.marshal())
        for e in self.non_critical_extension_options:
            out += encode_bytes_field(2047, e.marshal())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "TxBody":
        msgs: list[Any] = []
        memo = ""
        timeout_height = 0
        ext: list[Any] = []
        non_critical: list[Any] = []
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                msgs.append(Any.unmarshal(val))
            elif num == 2 and wt == WIRE_LEN:
                memo = val.decode()
            elif num == 3 and wt == WIRE_VARINT:
                timeout_height = val
            elif num == 1023 and wt == WIRE_LEN:
                ext.append(Any.unmarshal(val))
            elif num == 2047 and wt == WIRE_LEN:
                non_critical.append(Any.unmarshal(val))
        return cls(tuple(msgs), memo, timeout_height, tuple(ext), tuple(non_critical))


@dataclass(frozen=True)
class AuthInfo:
    signer_infos: tuple[SignerInfo, ...]
    fee: Fee

    def marshal(self) -> bytes:
        out = b""
        for s in self.signer_infos:
            out += encode_bytes_field(1, s.marshal())
        out += encode_bytes_field(2, self.fee.marshal())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "AuthInfo":
        infos: list[SignerInfo] = []
        fee = Fee((), 0)
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                infos.append(SignerInfo.unmarshal(val))
            elif num == 2 and wt == WIRE_LEN:
                fee = Fee.unmarshal(val)
        return cls(tuple(infos), fee)


def sign_doc_bytes(
    body_bytes: bytes, auth_info_bytes: bytes, chain_id: str, account_number: int
) -> bytes:
    return (
        encode_bytes_field(1, body_bytes)
        + encode_bytes_field(2, auth_info_bytes)
        + encode_bytes_field(3, chain_id.encode())
        + encode_varint_field(4, account_number)
    )


@dataclass(frozen=True)
class Tx:
    """A decoded transaction (TxRaw contents)."""

    body_bytes: bytes
    auth_info_bytes: bytes
    signatures: tuple[bytes, ...]

    def marshal(self) -> bytes:
        out = encode_bytes_field(1, self.body_bytes) + encode_bytes_field(
            2, self.auth_info_bytes
        )
        for s in self.signatures:
            out += encode_bytes_field(3, s)
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Tx":
        body, auth = b"", b""
        sigs: list[bytes] = []
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                body = val
            elif num == 2 and wt == WIRE_LEN:
                auth = val
            elif num == 3 and wt == WIRE_LEN:
                sigs.append(val)
        if not body or not auth:
            raise ValueError("tx missing body or auth info")
        return cls(body, auth, tuple(sigs))

    @property
    def body(self) -> TxBody:
        return TxBody.unmarshal(self.body_bytes)

    @property
    def auth_info(self) -> AuthInfo:
        return AuthInfo.unmarshal(self.auth_info_bytes)

    def msgs(self) -> list:
        return [decode_msg(m) for m in self.body.messages]

    def verify_signature(self, chain_id: str, account_number: int) -> bool:
        """Verify the (single) signer's SIGN_MODE_DIRECT signature — a
        plain secp256k1 key, or a threshold multisig (every sub-signature
        signs the same SignDoc)."""
        from celestia_app_tpu.tx.multisig import (
            MultisigPubKey,
            unmarshal_multisignature,
        )

        info = self.auth_info
        if len(info.signer_infos) != 1 or len(self.signatures) != 1:
            return False
        signer = info.signer_infos[0]
        doc = sign_doc_bytes(
            self.body_bytes, self.auth_info_bytes, chain_id, account_number
        )
        if isinstance(signer.public_key, MultisigPubKey):
            if signer.mode_bits is None:
                return False
            return signer.public_key.verify_multi(
                doc, signer.mode_bits, unmarshal_multisignature(self.signatures[0])
            )
        return signer.public_key.verify(doc, self.signatures[0])


def build_and_sign(
    msgs: list,
    key: PrivateKey,
    chain_id: str,
    account_number: int,
    sequence: int,
    fee: Fee,
    memo: str = "",
    timeout_height: int = 0,
) -> bytes:
    """Construct and sign a tx; returns the TxRaw bytes."""
    body = TxBody(tuple(m.to_any() for m in msgs), memo, timeout_height)
    auth = AuthInfo((SignerInfo(key.public_key(), sequence),), fee)
    body_bytes = body.marshal()
    auth_bytes = auth.marshal()
    doc = sign_doc_bytes(body_bytes, auth_bytes, chain_id, account_number)
    return Tx(body_bytes, auth_bytes, (key.sign(doc),)).marshal()


def build_and_sign_multisig(
    msgs: list,
    multisig_pk,
    signing_keys: dict[int, PrivateKey],
    chain_id: str,
    account_number: int,
    sequence: int,
    fee: Fee,
    memo: str = "",
    timeout_height: int = 0,
) -> bytes:
    """Construct a t-of-n multisig tx.  `signing_keys` maps sub-key index
    -> PrivateKey for each participant; every participant signs the same
    SIGN_MODE_DIRECT SignDoc and the signatures travel as one
    MultiSignature in set-bit order."""
    from celestia_app_tpu.tx.multisig import marshal_multisignature

    bits = tuple(
        i in signing_keys for i in range(len(multisig_pk.public_keys))
    )
    body = TxBody(tuple(m.to_any() for m in msgs), memo, timeout_height)
    auth = AuthInfo((SignerInfo(multisig_pk, sequence, bits),), fee)
    body_bytes = body.marshal()
    auth_bytes = auth.marshal()
    doc = sign_doc_bytes(body_bytes, auth_bytes, chain_id, account_number)
    sigs = tuple(
        signing_keys[i].sign(doc)
        for i in range(len(multisig_pk.public_keys))
        if i in signing_keys
    )
    return Tx(body_bytes, auth_bytes, (marshal_multisignature(sigs),)).marshal()

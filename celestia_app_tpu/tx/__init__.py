from celestia_app_tpu.tx.envelopes import (
    BLOB_TX_TYPE_ID,
    INDEX_WRAPPER_TYPE_ID,
    BlobTx,
    IndexWrapper,
    marshal_blob,
    unmarshal_blob,
    tx_hash,
    unmarshal_blob_tx,
    unmarshal_index_wrapper,
)

__all__ = [
    "BLOB_TX_TYPE_ID",
    "INDEX_WRAPPER_TYPE_ID",
    "BlobTx",
    "IndexWrapper",
    "marshal_blob",
    "unmarshal_blob",
    "unmarshal_blob_tx",
    "unmarshal_index_wrapper",
]

"""Consensus tx envelopes: BlobTx and IndexWrapper.

Wire layouts follow reference proto/celestia/core/v1/blob/blob.proto and the
IndexWrapper table in specs/src/specs/data_structures.md:

  Blob         { bytes namespace_id = 1; bytes data = 2;
                 uint32 share_version = 3; uint32 namespace_version = 4; }
  BlobTx       { bytes tx = 1; repeated Blob blobs = 2; string type_id = 3; }
  IndexWrapper { bytes tx = 1; repeated uint32 share_indexes = 2;
                 string type_id = 3; }

A BlobTx carries blobs alongside the signed sdk tx through the mempool and
the proposal; an IndexWrapper is what the block proposer writes into the
square's PAY_FOR_BLOB compact shares — the PFB tx plus the share index of
each blob it pays for.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    decode_packed_uint32,
    encode_bytes_field,
    encode_packed_uint32_field,
    encode_uvarint,
    encode_varint_field,
)
from celestia_app_tpu.shares.namespace import Namespace
from celestia_app_tpu.shares.sparse import Blob

BLOB_TX_TYPE_ID = b"BLOB"
INDEX_WRAPPER_TYPE_ID = b"INDX"


def marshal_blob(blob: Blob) -> bytes:
    return (
        encode_bytes_field(1, blob.namespace.id)
        + encode_bytes_field(2, blob.data)
        + encode_varint_field(3, blob.share_version)
        + encode_varint_field(4, blob.namespace.version)
    )


def unmarshal_blob(buf: bytes) -> Blob:
    ns_id = b""
    data = b""
    share_version = 0
    ns_version = 0
    for num, wt, val in decode_fields(buf):
        if num == 1 and wt == WIRE_LEN:
            ns_id = val
        elif num == 2 and wt == WIRE_LEN:
            data = val
        elif num == 3 and wt == WIRE_VARINT:
            share_version = val
        elif num == 4 and wt == WIRE_VARINT:
            ns_version = val
    return Blob(Namespace(ns_version, ns_id), data, share_version)


@dataclass(frozen=True)
class BlobTx:
    """A signed sdk tx (containing a MsgPayForBlobs) plus its blobs."""

    tx: bytes
    blobs: tuple[Blob, ...]

    def marshal(self) -> bytes:
        out = encode_bytes_field(1, self.tx)
        for b in self.blobs:
            out += encode_bytes_field(2, marshal_blob(b))
        out += encode_bytes_field(3, BLOB_TX_TYPE_ID)
        return out


def unmarshal_blob_tx(raw: bytes) -> BlobTx | None:
    """Returns the BlobTx, or None if `raw` is not a BlobTx envelope.

    Mirrors go-square blob.UnmarshalBlobTx as used at app/check_tx.go:19 and
    app/process_proposal.go:59: the type_id field must equal "BLOB".
    """
    try:
        fields = decode_fields(raw)
    except ValueError:
        return None
    tx = b""
    blobs: list[Blob] = []
    type_id = b""
    try:
        for num, wt, val in fields:
            if num == 1 and wt == WIRE_LEN:
                tx = val
            elif num == 2 and wt == WIRE_LEN:
                blobs.append(unmarshal_blob(val))
            elif num == 3 and wt == WIRE_LEN:
                type_id = val
    except ValueError:
        return None
    if type_id != BLOB_TX_TYPE_ID or not blobs:
        return None
    return BlobTx(tx, tuple(blobs))


@dataclass(frozen=True)
class IndexWrapper:
    """A PFB tx wrapped with the first-share index of each of its blobs."""

    tx: bytes
    share_indexes: tuple[int, ...]

    def marshal(self) -> bytes:
        return (
            encode_bytes_field(1, self.tx)
            + encode_packed_uint32_field(2, list(self.share_indexes))
            + encode_bytes_field(3, INDEX_WRAPPER_TYPE_ID)
        )

    def marshal_with_worst_case_indexes(self, upper_bound: int) -> bytes:
        """Envelope bytes with every index at `upper_bound` — the size cap
        used while the final blob positions are still unknown."""
        return IndexWrapper(
            self.tx, tuple(upper_bound for _ in self.share_indexes)
        ).marshal()


def unmarshal_index_wrapper(raw: bytes) -> IndexWrapper | None:
    """Returns the IndexWrapper, or None if `raw` is not one (type_id gate)."""
    try:
        fields = decode_fields(raw)
    except ValueError:
        return None
    tx = b""
    indexes: list[int] = []
    type_id = b""
    for num, wt, val in fields:
        if num == 1 and wt == WIRE_LEN:
            tx = val
        elif num == 2 and wt == WIRE_LEN:
            indexes.extend(decode_packed_uint32(val))
        elif num == 2 and wt == WIRE_VARINT:
            indexes.append(val)
        elif num == 3 and wt == WIRE_LEN:
            type_id = val
    if type_id != INDEX_WRAPPER_TYPE_ID:
        return None
    return IndexWrapper(tx, tuple(indexes))


def uvarint_size(n: int) -> int:
    return len(encode_uvarint(n))


def tx_hash(raw_tx: bytes) -> bytes:
    """Canonical tx hash: sha256 over the full broadcast bytes (BlobTx
    envelope included). The single join key between client confirmation
    polling, the node's tx index, and the RPC tx-status query."""
    return hashlib.sha256(raw_tx).digest()

"""sdk.Msg types and the Any envelope.

Wire parity with the reference protos: MsgPayForBlobs
(proto/celestia/blob/v1/tx.proto), bank MsgSend (cosmos bank.v1beta1), and
google.protobuf.Any {type_url=1, value=2}.  Each message knows its type URL;
the registry maps URLs back to decoders (the InterfaceRegistry analog,
app/encoding/encoding.go:26).
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    decode_packed_uint32,
    encode_bytes_field,
    encode_packed_uint32_field,
    encode_varint_field,
)

URL_MSG_PAY_FOR_BLOBS = "/celestia.blob.v1.MsgPayForBlobs"
URL_MSG_SEND = "/cosmos.bank.v1beta1.MsgSend"
URL_MSG_SIGNAL_VERSION = "/celestia.signal.v1.MsgSignalVersion"
URL_MSG_TRY_UPGRADE = "/celestia.signal.v1.MsgTryUpgrade"


@dataclass(frozen=True)
class Any:
    type_url: str
    value: bytes

    def marshal(self) -> bytes:
        return encode_bytes_field(1, self.type_url.encode()) + encode_bytes_field(
            2, self.value
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Any":
        url, value = "", b""
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                url = val.decode()
            elif num == 2 and wt == WIRE_LEN:
                value = val
        return cls(url, value)


@dataclass(frozen=True)
class Coin:
    denom: str
    amount: int

    def marshal(self) -> bytes:
        # cosmos Coin.amount is a decimal string on the wire.
        return encode_bytes_field(1, self.denom.encode()) + encode_bytes_field(
            2, str(self.amount).encode()
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Coin":
        denom, amount = "", 0
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                denom = val.decode()
            elif num == 2 and wt == WIRE_LEN:
                amount = int(val.decode())
        return cls(denom, amount)


@dataclass(frozen=True)
class MsgPayForBlobs:
    """Pays for blob inclusion (reference x/blob/types/payforblob.go:48)."""

    signer: str
    namespaces: tuple[bytes, ...]  # 29-byte encoded namespaces
    blob_sizes: tuple[int, ...]
    share_commitments: tuple[bytes, ...]
    share_versions: tuple[int, ...]

    TYPE_URL = URL_MSG_PAY_FOR_BLOBS

    def marshal(self) -> bytes:
        out = encode_bytes_field(1, self.signer.encode())
        for ns in self.namespaces:
            out += encode_bytes_field(2, ns)
        out += encode_packed_uint32_field(3, list(self.blob_sizes))
        for c in self.share_commitments:
            out += encode_bytes_field(4, c)
        out += encode_packed_uint32_field(8, list(self.share_versions))
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgPayForBlobs":
        signer = ""
        namespaces: list[bytes] = []
        sizes: list[int] = []
        commitments: list[bytes] = []
        versions: list[int] = []
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                signer = val.decode()
            elif num == 2 and wt == WIRE_LEN:
                namespaces.append(val)
            elif num == 3 and wt == WIRE_LEN:
                sizes.extend(decode_packed_uint32(val))
            elif num == 3 and wt == WIRE_VARINT:
                sizes.append(val)
            elif num == 4 and wt == WIRE_LEN:
                commitments.append(val)
            elif num == 8 and wt == WIRE_LEN:
                versions.extend(decode_packed_uint32(val))
            elif num == 8 and wt == WIRE_VARINT:
                versions.append(val)
        return cls(
            signer, tuple(namespaces), tuple(sizes), tuple(commitments), tuple(versions)
        )

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())


@dataclass(frozen=True)
class MsgSend:
    from_address: str
    to_address: str
    amount: tuple[Coin, ...]

    TYPE_URL = URL_MSG_SEND

    def marshal(self) -> bytes:
        out = encode_bytes_field(1, self.from_address.encode())
        out += encode_bytes_field(2, self.to_address.encode())
        for c in self.amount:
            out += encode_bytes_field(3, c.marshal())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgSend":
        f, t = "", ""
        coins: list[Coin] = []
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                f = val.decode()
            elif num == 2 and wt == WIRE_LEN:
                t = val.decode()
            elif num == 3 and wt == WIRE_LEN:
                coins.append(Coin.unmarshal(val))
        return cls(f, t, tuple(coins))

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())


@dataclass(frozen=True)
class MsgSignalVersion:
    """Validator signals readiness for an app version (x/signal)."""

    validator_address: str
    version: int

    TYPE_URL = URL_MSG_SIGNAL_VERSION

    def marshal(self) -> bytes:
        return encode_bytes_field(1, self.validator_address.encode()) + encode_varint_field(
            2, self.version
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgSignalVersion":
        addr, version = "", 0
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                addr = val.decode()
            elif num == 2 and wt == WIRE_VARINT:
                version = val
        return cls(addr, version)

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())


@dataclass(frozen=True)
class MsgTryUpgrade:
    """Triggers the upgrade tally (x/signal keeper.TryUpgrade)."""

    signer: str

    TYPE_URL = URL_MSG_TRY_UPGRADE

    def marshal(self) -> bytes:
        return encode_bytes_field(1, self.signer.encode())

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgTryUpgrade":
        signer = ""
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                signer = val.decode()
        return cls(signer)

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())


MSG_DECODERS = {
    URL_MSG_PAY_FOR_BLOBS: MsgPayForBlobs.unmarshal,
    URL_MSG_SEND: MsgSend.unmarshal,
    URL_MSG_SIGNAL_VERSION: MsgSignalVersion.unmarshal,
    URL_MSG_TRY_UPGRADE: MsgTryUpgrade.unmarshal,
}


def decode_msg(any_msg: Any):
    dec = MSG_DECODERS.get(any_msg.type_url)
    if dec is None:
        raise ValueError(f"unknown message type {any_msg.type_url}")
    return dec(any_msg.value)

"""sdk.Msg types and the Any envelope.

Wire parity with the reference protos: MsgPayForBlobs
(proto/celestia/blob/v1/tx.proto), bank MsgSend (cosmos bank.v1beta1), and
google.protobuf.Any {type_url=1, value=2}.  Each message knows its type URL;
the registry maps URLs back to decoders (the InterfaceRegistry analog,
app/encoding/encoding.go:26).
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    decode_packed_uint32,
    encode_bytes_field,
    encode_packed_uint32_field,
    encode_varint_field,
)

URL_MSG_PAY_FOR_BLOBS = "/celestia.blob.v1.MsgPayForBlobs"
URL_MSG_SEND = "/cosmos.bank.v1beta1.MsgSend"
URL_MSG_MULTI_SEND = "/cosmos.bank.v1beta1.MsgMultiSend"
URL_MSG_CREATE_VESTING_ACCOUNT = "/cosmos.vesting.v1beta1.MsgCreateVestingAccount"
URL_MSG_CREATE_PERIODIC_VESTING_ACCOUNT = (
    "/cosmos.vesting.v1beta1.MsgCreatePeriodicVestingAccount"
)
URL_MSG_CREATE_PERMANENT_LOCKED_ACCOUNT = (
    "/cosmos.vesting.v1beta1.MsgCreatePermanentLockedAccount"
)
URL_MSG_VERIFY_INVARIANT = "/cosmos.crisis.v1beta1.MsgVerifyInvariant"
URL_MSG_SUBMIT_EVIDENCE = "/cosmos.evidence.v1beta1.MsgSubmitEvidence"
URL_MSG_SIGNAL_VERSION = "/celestia.signal.v1.MsgSignalVersion"
URL_MSG_TRY_UPGRADE = "/celestia.signal.v1.MsgTryUpgrade"
URL_MSG_SUBMIT_PROPOSAL = "/cosmos.gov.v1beta1.MsgSubmitProposal"
URL_MSG_VOTE = "/cosmos.gov.v1beta1.MsgVote"
URL_MSG_VOTE_WEIGHTED = "/cosmos.gov.v1beta1.MsgVoteWeighted"
URL_MSG_DEPOSIT = "/cosmos.gov.v1beta1.MsgDeposit"
URL_PARAM_CHANGE_PROPOSAL = "/cosmos.params.v1beta1.ParameterChangeProposal"
URL_MSG_GOV_V1_SUBMIT_PROPOSAL = "/cosmos.gov.v1.MsgSubmitProposal"
URL_MSG_GOV_V1_EXEC_LEGACY_CONTENT = "/cosmos.gov.v1.MsgExecLegacyContent"
URL_MSG_GOV_V1_VOTE = "/cosmos.gov.v1.MsgVote"
URL_MSG_GOV_V1_VOTE_WEIGHTED = "/cosmos.gov.v1.MsgVoteWeighted"
URL_MSG_GOV_V1_DEPOSIT = "/cosmos.gov.v1.MsgDeposit"
URL_COMMUNITY_POOL_SPEND_PROPOSAL = (
    "/cosmos.distribution.v1beta1.CommunityPoolSpendProposal"
)
URL_MSG_TRANSFER = "/ibc.applications.transfer.v1.MsgTransfer"
URL_MSG_RECV_PACKET = "/ibc.core.channel.v1.MsgRecvPacket"
URL_MSG_ACKNOWLEDGEMENT = "/ibc.core.channel.v1.MsgAcknowledgement"
URL_MSG_TIMEOUT = "/ibc.core.channel.v1.MsgTimeout"
URL_MSG_DELEGATE = "/cosmos.staking.v1beta1.MsgDelegate"
URL_MSG_UNDELEGATE = "/cosmos.staking.v1beta1.MsgUndelegate"
URL_MSG_BEGIN_REDELEGATE = "/cosmos.staking.v1beta1.MsgBeginRedelegate"
URL_MSG_CANCEL_UNBONDING = "/cosmos.staking.v1beta1.MsgCancelUnbondingDelegation"
URL_MSG_WITHDRAW_DELEGATOR_REWARD = (
    "/cosmos.distribution.v1beta1.MsgWithdrawDelegatorReward"
)
URL_MSG_WITHDRAW_VALIDATOR_COMMISSION = (
    "/cosmos.distribution.v1beta1.MsgWithdrawValidatorCommission"
)
URL_MSG_SET_WITHDRAW_ADDRESS = "/cosmos.distribution.v1beta1.MsgSetWithdrawAddress"
URL_MSG_FUND_COMMUNITY_POOL = "/cosmos.distribution.v1beta1.MsgFundCommunityPool"
URL_MSG_UNJAIL = "/cosmos.slashing.v1beta1.MsgUnjail"
URL_MSG_CREATE_VALIDATOR = "/cosmos.staking.v1beta1.MsgCreateValidator"
URL_MSG_EDIT_VALIDATOR = "/cosmos.staking.v1beta1.MsgEditValidator"
URL_SECP256K1_PUBKEY_STR = "/cosmos.crypto.secp256k1.PubKey"
URL_MSG_GRANT_ALLOWANCE = "/cosmos.feegrant.v1beta1.MsgGrantAllowance"
URL_MSG_REVOKE_ALLOWANCE = "/cosmos.feegrant.v1beta1.MsgRevokeAllowance"
URL_BASIC_ALLOWANCE = "/cosmos.feegrant.v1beta1.BasicAllowance"
URL_ALLOWED_MSG_ALLOWANCE = "/cosmos.feegrant.v1beta1.AllowedMsgAllowance"
URL_MSG_AUTHZ_GRANT = "/cosmos.authz.v1beta1.MsgGrant"
URL_MSG_AUTHZ_EXEC = "/cosmos.authz.v1beta1.MsgExec"
URL_MSG_AUTHZ_REVOKE = "/cosmos.authz.v1beta1.MsgRevoke"
URL_GENERIC_AUTHORIZATION = "/cosmos.authz.v1beta1.GenericAuthorization"
URL_SEND_AUTHORIZATION = "/cosmos.bank.v1beta1.SendAuthorization"


def _encode_timestamp(ns: int) -> bytes:
    """google.protobuf.Timestamp {seconds=1, nanos=2}."""
    out = encode_varint_field(1, ns // 10**9)
    if ns % 10**9:
        out += encode_varint_field(2, ns % 10**9)
    return out


def _decode_timestamp(raw: bytes) -> int:
    f = {n: v for n, wt, v in decode_fields(raw) if wt == WIRE_VARINT}
    return f.get(1, 0) * 10**9 + f.get(2, 0)


@dataclass(frozen=True)
class Any:
    type_url: str
    value: bytes

    def marshal(self) -> bytes:
        return encode_bytes_field(1, self.type_url.encode()) + encode_bytes_field(
            2, self.value
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Any":
        url, value = "", b""
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                url = val.decode()
            elif num == 2 and wt == WIRE_LEN:
                value = val
        return cls(url, value)


@dataclass(frozen=True)
class Coin:
    denom: str
    amount: int

    def marshal(self) -> bytes:
        # cosmos Coin.amount is a decimal string on the wire.
        return encode_bytes_field(1, self.denom.encode()) + encode_bytes_field(
            2, str(self.amount).encode()
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Coin":
        denom, amount = "", 0
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                denom = val.decode()
            elif num == 2 and wt == WIRE_LEN:
                amount = int(val.decode())
        return cls(denom, amount)


@dataclass(frozen=True)
class MsgPayForBlobs:
    """Pays for blob inclusion (reference x/blob/types/payforblob.go:48)."""

    signer: str
    namespaces: tuple[bytes, ...]  # 29-byte encoded namespaces
    blob_sizes: tuple[int, ...]
    share_commitments: tuple[bytes, ...]
    share_versions: tuple[int, ...]

    TYPE_URL = URL_MSG_PAY_FOR_BLOBS

    def marshal(self) -> bytes:
        out = encode_bytes_field(1, self.signer.encode())
        for ns in self.namespaces:
            out += encode_bytes_field(2, ns)
        out += encode_packed_uint32_field(3, list(self.blob_sizes))
        for c in self.share_commitments:
            out += encode_bytes_field(4, c)
        out += encode_packed_uint32_field(8, list(self.share_versions))
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgPayForBlobs":
        signer = ""
        namespaces: list[bytes] = []
        sizes: list[int] = []
        commitments: list[bytes] = []
        versions: list[int] = []
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                signer = val.decode()
            elif num == 2 and wt == WIRE_LEN:
                namespaces.append(val)
            elif num == 3 and wt == WIRE_LEN:
                sizes.extend(decode_packed_uint32(val))
            elif num == 3 and wt == WIRE_VARINT:
                sizes.append(val)
            elif num == 4 and wt == WIRE_LEN:
                commitments.append(val)
            elif num == 8 and wt == WIRE_LEN:
                versions.extend(decode_packed_uint32(val))
            elif num == 8 and wt == WIRE_VARINT:
                versions.append(val)
        return cls(
            signer, tuple(namespaces), tuple(sizes), tuple(commitments), tuple(versions)
        )

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    def validate_basic(self) -> None:
        """Stateless checks (x/blob/types/payforblob.go ValidateBasic)."""
        from celestia_app_tpu.modules.blob.types import validate_msg_pay_for_blobs

        validate_msg_pay_for_blobs(self)


@dataclass(frozen=True)
class MsgSend:
    from_address: str
    to_address: str
    amount: tuple[Coin, ...]

    TYPE_URL = URL_MSG_SEND

    def marshal(self) -> bytes:
        out = encode_bytes_field(1, self.from_address.encode())
        out += encode_bytes_field(2, self.to_address.encode())
        for c in self.amount:
            out += encode_bytes_field(3, c.marshal())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgSend":
        f, t = "", ""
        coins: list[Coin] = []
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                f = val.decode()
            elif num == 2 and wt == WIRE_LEN:
                t = val.decode()
            elif num == 3 and wt == WIRE_LEN:
                coins.append(Coin.unmarshal(val))
        return cls(f, t, tuple(coins))

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    def validate_basic(self) -> None:
        """Stateless checks (sdk bank MsgSend.ValidateBasic)."""
        from celestia_app_tpu.crypto.keys import validate_address

        validate_address(self.from_address)
        validate_address(self.to_address)
        if not self.amount:
            raise ValueError("send amount must not be empty")
        for c in self.amount:
            if c.amount <= 0:
                raise ValueError(f"send amount must be positive, got {c.amount}")


@dataclass(frozen=True)
class BankIO:
    """cosmos.bank.v1beta1 Input / Output {address=1, coins=2 repeated}."""

    address: str
    coins: tuple[Coin, ...]

    def marshal(self) -> bytes:
        out = encode_bytes_field(1, self.address.encode())
        for c in self.coins:
            out += encode_bytes_field(2, c.marshal())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "BankIO":
        addr = ""
        coins: list[Coin] = []
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                addr = val.decode()
            elif num == 2 and wt == WIRE_LEN:
                coins.append(Coin.unmarshal(val))
        return cls(addr, tuple(coins))


@dataclass(frozen=True)
class MsgMultiSend:
    """cosmos.bank.v1beta1.MsgMultiSend {inputs=1, outputs=2}.

    Deviation from sdk v0.46, aligned with v0.47+: exactly ONE input.
    Multi-input MultiSends require a signature from every input address,
    and this chain's ante admits one signer per tx (PARITY §ante row 11)
    — accepting unsigned inputs would let one signer move other
    accounts' funds, so the single-input rule is enforced statelessly."""

    inputs: tuple[BankIO, ...]
    outputs: tuple[BankIO, ...]

    TYPE_URL = URL_MSG_MULTI_SEND

    def marshal(self) -> bytes:
        out = b""
        for i in self.inputs:
            out += encode_bytes_field(1, i.marshal())
        for o in self.outputs:
            out += encode_bytes_field(2, o.marshal())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgMultiSend":
        ins: list[BankIO] = []
        outs: list[BankIO] = []
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                ins.append(BankIO.unmarshal(val))
            elif num == 2 and wt == WIRE_LEN:
                outs.append(BankIO.unmarshal(val))
        return cls(tuple(ins), tuple(outs))

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.inputs[0].address if self.inputs else ""

    def validate_basic(self) -> None:
        """sdk bank MsgMultiSend.ValidateBasic + the single-input rule:
        no inputs/outputs -> ErrNoInputs/ErrNoOutputs; per-denom sums
        must match (ErrInputOutputMismatch); coins positive."""
        from celestia_app_tpu.crypto.keys import validate_address

        if not self.inputs:
            raise ValueError("no inputs to send transaction")
        if len(self.inputs) != 1:
            raise ValueError("multiple senders not allowed")
        if not self.outputs:
            raise ValueError("no outputs to send transaction")
        sums: dict[str, int] = {}
        for io, sign in ((self.inputs, 1), (self.outputs, -1)):
            for entry in io:
                validate_address(entry.address)
                if not entry.coins:
                    raise ValueError("empty coins in multi-send entry")
                for c in entry.coins:
                    if c.amount <= 0:
                        raise ValueError(
                            f"send amount must be positive, got {c.amount}"
                        )
                    if c.denom != "utia":
                        # TIA-only chain: the handler moves utia; a
                        # foreign-denom output would be silently dropped.
                        raise ValueError(
                            f"invalid send denom {c.denom!r}, expected utia"
                        )
                    sums[c.denom] = sums.get(c.denom, 0) + sign * c.amount
        if any(v != 0 for v in sums.values()):
            raise ValueError("sum inputs != sum outputs")


@dataclass(frozen=True)
class MsgSubmitEvidence:
    """cosmos.evidence.v1beta1.MsgSubmitEvidence {submitter=1,
    evidence=2 Any}.

    Reference behavior: the evidence keeper is wired WITHOUT a router
    (/root/reference/app/app.go:348-353 — no SetRouter call), so a
    tx-submitted evidence never succeeds; equivocation evidence reaches
    the chain through the consensus plane (ABCI ByzantineValidators),
    never through this tx.  This framework reproduces the outcome — the
    msg decodes, validates, and always fails (with the sdk's registered
    ErrNoEvidenceHandlerExists text; the reference's exact nil-router
    failure shape is unverifiable in-image)."""

    submitter: str
    evidence: Any

    TYPE_URL = URL_MSG_SUBMIT_EVIDENCE

    def marshal(self) -> bytes:
        out = encode_bytes_field(1, self.submitter.encode())
        out += encode_bytes_field(2, self.evidence.marshal())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgSubmitEvidence":
        f = {num: val for num, wt, val in decode_fields(raw) if wt == WIRE_LEN}
        return cls(f.get(1, b"").decode(), Any.unmarshal(f.get(2, b"")))

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.submitter

    def validate_basic(self) -> None:
        from celestia_app_tpu.crypto.keys import validate_address

        validate_address(self.submitter)
        if not self.evidence.type_url:
            raise ValueError("missing evidence")


@dataclass(frozen=True)
class MsgVerifyInvariant:
    """cosmos.crisis.v1beta1.MsgVerifyInvariant {sender=1,
    invariant_module_name=2, invariant_route=3}: run one registered
    invariant on-chain.  A broken invariant HALTS the chain (the sdk
    panics); a passing check just costs the ConstantFee."""

    sender: str
    invariant_module_name: str
    invariant_route: str

    TYPE_URL = URL_MSG_VERIFY_INVARIANT

    def marshal(self) -> bytes:
        out = encode_bytes_field(1, self.sender.encode())
        out += encode_bytes_field(2, self.invariant_module_name.encode())
        out += encode_bytes_field(3, self.invariant_route.encode())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgVerifyInvariant":
        f = {num: val for num, wt, val in decode_fields(raw) if wt == WIRE_LEN}
        return cls(
            f.get(1, b"").decode(), f.get(2, b"").decode(),
            f.get(3, b"").decode(),
        )

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.sender

    def validate_basic(self) -> None:
        from celestia_app_tpu.crypto.keys import validate_address

        validate_address(self.sender)
        if not self.invariant_module_name or not self.invariant_route:
            raise ValueError("invariant module and route must be set")


@dataclass(frozen=True)
class MsgCreateVestingAccount:
    """cosmos.vesting.v1beta1.MsgCreateVestingAccount {from_address=1,
    to_address=2, amount=3 repeated Coin, end_time=4 int64 unix SECONDS,
    delayed=5 bool}: fund a brand-new vesting account.  delayed=false ->
    ContinuousVestingAccount starting at the block time; delayed=true ->
    DelayedVestingAccount (everything releases at end_time)."""

    from_address: str
    to_address: str
    amount: tuple[Coin, ...]
    end_time: int
    delayed: bool = False

    TYPE_URL = URL_MSG_CREATE_VESTING_ACCOUNT

    def marshal(self) -> bytes:
        out = encode_bytes_field(1, self.from_address.encode())
        out += encode_bytes_field(2, self.to_address.encode())
        for c in self.amount:
            out += encode_bytes_field(3, c.marshal())
        if self.end_time:
            # int64: negatives ride as 10-byte two's-complement varints.
            out += encode_varint_field(4, self.end_time & ((1 << 64) - 1))
        if self.delayed:
            out += encode_varint_field(5, 1)
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgCreateVestingAccount":
        f, t = "", ""
        coins: list[Coin] = []
        ints: dict[int, int] = {}
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                f = val.decode()
            elif num == 2 and wt == WIRE_LEN:
                t = val.decode()
            elif num == 3 and wt == WIRE_LEN:
                coins.append(Coin.unmarshal(val))
            elif wt == WIRE_VARINT:
                ints[num] = val
        from celestia_app_tpu.encoding.proto import int64_from_uvarint

        return cls(
            f, t, tuple(coins), int64_from_uvarint(ints.get(4, 0)), bool(ints.get(5, 0))
        )

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.from_address

    def validate_basic(self) -> None:
        """sdk vesting MsgCreateVestingAccount.ValidateBasic: valid
        addresses, positive coins, end_time > 0."""
        from celestia_app_tpu.crypto.keys import validate_address

        validate_address(self.from_address)
        validate_address(self.to_address)
        # TIA-only chain (tokenfilter): the handler vests utia; silently
        # dropping a foreign denom would report code 0 while locking
        # nothing.
        _validate_utia_coins(self.amount, "vesting amount")
        if self.end_time <= 0:
            raise ValueError("invalid end time")


def _validate_utia_coins(coins: tuple[Coin, ...], what: str) -> None:
    if not coins:
        raise ValueError(f"{what} must not be empty")
    for c in coins:
        if c.amount <= 0:
            raise ValueError(f"{what} must be positive, got {c.amount}")
        if c.denom != "utia":
            raise ValueError(f"invalid {what} denom {c.denom!r}, expected utia")


@dataclass(frozen=True)
class VestingPeriod:
    """cosmos.vesting.v1beta1.Period {length=1 int64 SECONDS, amount=2
    repeated Coin}."""

    length: int  # seconds
    amount: tuple[Coin, ...]

    def marshal(self) -> bytes:
        out = b""
        if self.length:
            out += encode_varint_field(1, self.length & ((1 << 64) - 1))
        for c in self.amount:
            out += encode_bytes_field(2, c.marshal())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "VestingPeriod":
        from celestia_app_tpu.encoding.proto import int64_from_uvarint

        length = 0
        coins: list[Coin] = []
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_VARINT:
                length = int64_from_uvarint(val)
            elif num == 2 and wt == WIRE_LEN:
                coins.append(Coin.unmarshal(val))
        return cls(length, tuple(coins))


@dataclass(frozen=True)
class MsgCreatePeriodicVestingAccount:
    """cosmos.vesting.v1beta1.MsgCreatePeriodicVestingAccount
    {from_address=1, to_address=2, start_time=3 int64, vesting_periods=4
    repeated Period}: fund a brand-new account releasing stepwise — each
    period's amount unlocks when its cumulative length elapses past
    start_time."""

    from_address: str
    to_address: str
    start_time: int  # unix seconds
    vesting_periods: tuple[VestingPeriod, ...]

    TYPE_URL = URL_MSG_CREATE_PERIODIC_VESTING_ACCOUNT

    def marshal(self) -> bytes:
        out = encode_bytes_field(1, self.from_address.encode())
        out += encode_bytes_field(2, self.to_address.encode())
        if self.start_time:
            out += encode_varint_field(3, self.start_time & ((1 << 64) - 1))
        for p in self.vesting_periods:
            out += encode_bytes_field(4, p.marshal())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgCreatePeriodicVestingAccount":
        from celestia_app_tpu.encoding.proto import int64_from_uvarint

        f, t, start = "", "", 0
        periods: list[VestingPeriod] = []
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                f = val.decode()
            elif num == 2 and wt == WIRE_LEN:
                t = val.decode()
            elif num == 3 and wt == WIRE_VARINT:
                start = int64_from_uvarint(val)
            elif num == 4 and wt == WIRE_LEN:
                periods.append(VestingPeriod.unmarshal(val))
        return cls(f, t, start, tuple(periods))

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.from_address

    def total(self) -> int:
        return sum(
            c.amount
            for p in self.vesting_periods
            for c in p.amount
            if c.denom == "utia"
        )

    def validate_basic(self) -> None:
        """sdk ValidateBasic: valid addresses, start_time >= 1, at least
        one period, each period length > 0 with valid positive coins."""
        from celestia_app_tpu.crypto.keys import validate_address

        validate_address(self.from_address)
        validate_address(self.to_address)
        if self.start_time < 1:
            # sdk v0.46 rejects a zero/negative anchor — the proto
            # default of 0 would vest everything at epoch.
            raise ValueError(f"invalid start time of {self.start_time}")
        if not self.vesting_periods:
            raise ValueError("vesting periods must not be empty")
        for i, p in enumerate(self.vesting_periods):
            if p.length <= 0:
                raise ValueError(f"invalid period length of {p.length} in period {i}")
            _validate_utia_coins(p.amount, "vesting amount")


@dataclass(frozen=True)
class MsgCreatePermanentLockedAccount:
    """cosmos.vesting.v1beta1.MsgCreatePermanentLockedAccount
    {from_address=1, to_address=2, amount=3 repeated Coin}: fund a
    brand-new account whose tokens never vest (delegatable, never
    spendable)."""

    from_address: str
    to_address: str
    amount: tuple[Coin, ...]

    TYPE_URL = URL_MSG_CREATE_PERMANENT_LOCKED_ACCOUNT

    def marshal(self) -> bytes:
        out = encode_bytes_field(1, self.from_address.encode())
        out += encode_bytes_field(2, self.to_address.encode())
        for c in self.amount:
            out += encode_bytes_field(3, c.marshal())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgCreatePermanentLockedAccount":
        f, t = "", ""
        coins: list[Coin] = []
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                f = val.decode()
            elif num == 2 and wt == WIRE_LEN:
                t = val.decode()
            elif num == 3 and wt == WIRE_LEN:
                coins.append(Coin.unmarshal(val))
        return cls(f, t, tuple(coins))

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.from_address

    def validate_basic(self) -> None:
        from celestia_app_tpu.crypto.keys import validate_address

        validate_address(self.from_address)
        validate_address(self.to_address)
        _validate_utia_coins(self.amount, "locked amount")


@dataclass(frozen=True)
class MsgSignalVersion:
    """Validator signals readiness for an app version (x/signal)."""

    validator_address: str
    version: int

    TYPE_URL = URL_MSG_SIGNAL_VERSION

    def marshal(self) -> bytes:
        return encode_bytes_field(1, self.validator_address.encode()) + encode_varint_field(
            2, self.version
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgSignalVersion":
        addr, version = "", 0
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                addr = val.decode()
            elif num == 2 and wt == WIRE_VARINT:
                version = val
        return cls(addr, version)

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    def validate_basic(self) -> None:
        if not self.validator_address:
            raise ValueError("validator address must not be empty")


@dataclass(frozen=True)
class MsgTryUpgrade:
    """Triggers the upgrade tally (x/signal keeper.TryUpgrade)."""

    signer: str

    TYPE_URL = URL_MSG_TRY_UPGRADE

    def marshal(self) -> bytes:
        return encode_bytes_field(1, self.signer.encode())

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgTryUpgrade":
        signer = ""
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                signer = val.decode()
        return cls(signer)

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    def validate_basic(self) -> None:
        from celestia_app_tpu.crypto.keys import validate_address

        validate_address(self.signer)


@dataclass(frozen=True)
class ProposalParamChange:
    """cosmos.params.v1beta1.ParamChange {subspace=1, key=2, value=3}."""

    subspace: str
    key: str
    value: str

    def marshal(self) -> bytes:
        return (
            encode_bytes_field(1, self.subspace.encode())
            + encode_bytes_field(2, self.key.encode())
            + encode_bytes_field(3, self.value.encode())
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "ProposalParamChange":
        f = {num: val for num, wt, val in decode_fields(raw) if wt == WIRE_LEN}
        return cls(
            f.get(1, b"").decode(), f.get(2, b"").decode(), f.get(3, b"").decode()
        )


def _parse_gov_content(
    content: Any,
) -> tuple[str, str, tuple, str, tuple]:
    """Decode a gov Content Any -> (title, description, changes,
    spend_recipient, spend_amount).  Supported contents:
    ParameterChangeProposal {title=1, description=2, changes=3} and
    CommunityPoolSpendProposal {title=1, description=2, recipient=3,
    amount=4} (the distrclient.ProposalHandler the reference registers,
    default_overrides.go:207).  Shared by the v1beta1 MsgSubmitProposal
    and gov v1's MsgExecLegacyContent."""
    if content.type_url not in (
        URL_PARAM_CHANGE_PROPOSAL, URL_COMMUNITY_POOL_SPEND_PROPOSAL
    ):
        raise ValueError(f"unsupported proposal content {content.type_url}")
    is_spend = content.type_url == URL_COMMUNITY_POOL_SPEND_PROPOSAL
    title, description, spend_recipient = "", "", ""
    changes: list[ProposalParamChange] = []
    spend_amount: list[Coin] = []
    for cn, cwt, cval in decode_fields(content.value):
        if cn == 1 and cwt == WIRE_LEN:
            title = cval.decode()
        elif cn == 2 and cwt == WIRE_LEN:
            description = cval.decode()
        elif cn == 3 and cwt == WIRE_LEN and not is_spend:
            changes.append(ProposalParamChange.unmarshal(cval))
        elif cn == 3 and cwt == WIRE_LEN:
            spend_recipient = cval.decode()
        elif cn == 4 and cwt == WIRE_LEN and is_spend:
            spend_amount.append(Coin.unmarshal(cval))
    return title, description, tuple(changes), spend_recipient, tuple(spend_amount)


@dataclass(frozen=True)
class MsgSubmitProposal:
    """cosmos.gov.v1beta1.MsgSubmitProposal {content=1 (Any),
    initial_deposit=2, proposer=3}.  Supported contents:
    ParameterChangeProposal {title=1, description=2, changes=3} and
    CommunityPoolSpendProposal {title=1, description=2, recipient=3,
    amount=4} (the distrclient.ProposalHandler the reference registers,
    default_overrides.go:207)."""

    title: str
    description: str
    changes: tuple[ProposalParamChange, ...]
    initial_deposit: tuple[Coin, ...]
    proposer: str
    spend_recipient: str = ""
    spend_amount: tuple[Coin, ...] = ()

    TYPE_URL = URL_MSG_SUBMIT_PROPOSAL

    def _content(self) -> Any:
        body = encode_bytes_field(1, self.title.encode()) + encode_bytes_field(
            2, self.description.encode()
        )
        if self.spend_recipient:
            body += encode_bytes_field(3, self.spend_recipient.encode())
            for c in self.spend_amount:
                body += encode_bytes_field(4, c.marshal())
            return Any(URL_COMMUNITY_POOL_SPEND_PROPOSAL, body)
        for c in self.changes:
            body += encode_bytes_field(3, c.marshal())
        return Any(URL_PARAM_CHANGE_PROPOSAL, body)

    def marshal(self) -> bytes:
        out = encode_bytes_field(1, self._content().marshal())
        for c in self.initial_deposit:
            out += encode_bytes_field(2, c.marshal())
        out += encode_bytes_field(3, self.proposer.encode())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgSubmitProposal":
        title, description = "", ""
        changes: tuple[ProposalParamChange, ...] = ()
        deposit: list[Coin] = []
        proposer = ""
        spend_recipient = ""
        spend_amount: tuple[Coin, ...] = ()
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                (
                    title, description, changes, spend_recipient, spend_amount,
                ) = _parse_gov_content(Any.unmarshal(val))
            elif num == 2 and wt == WIRE_LEN:
                deposit.append(Coin.unmarshal(val))
            elif num == 3 and wt == WIRE_LEN:
                proposer = val.decode()
        return cls(
            title, description, changes, tuple(deposit), proposer,
            spend_recipient, spend_amount,
        )

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.proposer

    def validate_basic(self) -> None:
        from celestia_app_tpu.crypto.keys import validate_address

        validate_address(self.proposer)
        for c in self.initial_deposit:
            if c.amount < 0:
                raise ValueError("negative deposit")
        if self.spend_recipient and self.changes:
            # The wire carries exactly one content Any; encoding would
            # silently drop the param changes — reject instead.
            raise ValueError(
                "proposal cannot carry both param changes and a community "
                "pool spend"
            )
        if self.spend_recipient and any(
            c.amount <= 0 for c in self.spend_amount
        ):
            raise ValueError("community pool spend must be positive")


@dataclass(frozen=True)
class MsgVote:
    """cosmos.gov.v1beta1.MsgVote {proposal_id=1, voter=2, option=3}."""

    proposal_id: int
    voter: str
    option: int  # VoteOption numbering (1=yes 2=abstain 3=no 4=veto)

    TYPE_URL = URL_MSG_VOTE

    def marshal(self) -> bytes:
        return (
            encode_varint_field(1, self.proposal_id)
            + encode_bytes_field(2, self.voter.encode())
            + encode_varint_field(3, self.option)
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgVote":
        pid, voter, option = 0, "", 0
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_VARINT:
                pid = val
            elif num == 2 and wt == WIRE_LEN:
                voter = val.decode()
            elif num == 3 and wt == WIRE_VARINT:
                option = val
        return cls(pid, voter, option)

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.voter

    def validate_basic(self) -> None:
        if self.proposal_id <= 0:
            raise ValueError("invalid proposal id")
        if self.option not in (1, 2, 3, 4):
            raise ValueError(f"invalid vote option {self.option}")


def encode_weighted_option(option: int, weight: str) -> bytes:
    """WeightedVoteOption {option=1, weight=2 (Dec string)} — the single
    codec for this shape, shared by the MsgVoteWeighted wire form and the
    gov keeper's vote records."""
    return encode_varint_field(1, option) + encode_bytes_field(
        2, weight.encode()
    )


def decode_weighted_option(raw: bytes) -> tuple[int, str]:
    opt, weight = 0, ""
    for n, wt, v in decode_fields(raw):
        if n == 1 and wt == WIRE_VARINT:
            opt = v
        elif n == 2 and wt == WIRE_LEN:
            weight = v.decode()
    return opt, weight


@dataclass(frozen=True)
class MsgVoteWeighted:
    """cosmos.gov.v1beta1.MsgVoteWeighted {proposal_id=1, voter=2,
    options=3 (repeated WeightedVoteOption {option=1, weight=2})} —
    weight is an 18-decimal Dec string on the wire."""

    proposal_id: int
    voter: str
    options: tuple[tuple[int, str], ...]  # (VoteOption number, Dec string)

    TYPE_URL = URL_MSG_VOTE_WEIGHTED

    def marshal(self) -> bytes:
        out = encode_varint_field(1, self.proposal_id)
        out += encode_bytes_field(2, self.voter.encode())
        for opt, weight in self.options:
            out += encode_bytes_field(3, encode_weighted_option(opt, weight))
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgVoteWeighted":
        pid, voter = 0, ""
        options: list[tuple[int, str]] = []
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_VARINT:
                pid = val
            elif num == 2 and wt == WIRE_LEN:
                voter = val.decode()
            elif num == 3 and wt == WIRE_LEN:
                options.append(decode_weighted_option(val))
        return cls(pid, voter, tuple(options))

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.voter

    def validate_basic(self) -> None:
        """Stateless parity with sdk v1beta1 ValidateBasic: options
        non-empty, each weight in (0, 1], no duplicates, total exactly 1 —
        invalid weighted votes must die at CheckTx, not DeliverTx."""
        from celestia_app_tpu.crypto.keys import validate_address
        from celestia_app_tpu.state.dec import Dec

        validate_address(self.voter)
        if self.proposal_id <= 0:
            raise ValueError("invalid proposal id")
        if not self.options:
            raise ValueError("weighted vote needs at least one option")
        total = Dec(0)
        seen: set[int] = set()
        one = Dec.from_int(1)
        for opt, weight in self.options:
            if opt not in (1, 2, 3, 4):
                raise ValueError(f"invalid vote option {opt}")
            if opt in seen:
                raise ValueError(f"duplicate vote option {opt}")
            seen.add(opt)
            w = Dec.from_str(weight)
            if w <= Dec(0) or one < w:
                raise ValueError(f"vote weight {weight} outside (0, 1]")
            total = total.add(w)
        if total.raw != one.raw:
            raise ValueError(f"vote weights must sum to 1, got {total}")


@dataclass(frozen=True)
class MsgDeposit:
    """cosmos.gov.v1beta1.MsgDeposit {proposal_id=1, depositor=2, amount=3}."""

    proposal_id: int
    depositor: str
    amount: tuple[Coin, ...]

    TYPE_URL = URL_MSG_DEPOSIT

    def marshal(self) -> bytes:
        out = encode_varint_field(1, self.proposal_id)
        out += encode_bytes_field(2, self.depositor.encode())
        for c in self.amount:
            out += encode_bytes_field(3, c.marshal())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgDeposit":
        pid, depositor = 0, ""
        coins: list[Coin] = []
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_VARINT:
                pid = val
            elif num == 2 and wt == WIRE_LEN:
                depositor = val.decode()
            elif num == 3 and wt == WIRE_LEN:
                coins.append(Coin.unmarshal(val))
        return cls(pid, depositor, tuple(coins))

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.depositor

    def validate_basic(self) -> None:
        if self.proposal_id <= 0:
            raise ValueError("invalid proposal id")
        if not self.amount or any(c.amount <= 0 for c in self.amount):
            raise ValueError("deposit must be positive")


# --- gov v1 (cosmos.gov.v1, sdk v0.46) -------------------------------------
#
# The reference chain serves BOTH gov msg servers (the sdk wires v1 and
# v1beta1 side by side); modern clients speak v1, where a proposal carries
# arbitrary messages and legacy Content rides inside MsgExecLegacyContent.
# Field numbers are the v1beta1 ones plus a trailing `metadata` string.


def gov_module_address() -> str:
    """The sdk-canonical gov module account address:
    bech32(hrp, sha256("gov")[:20]) (authtypes.NewModuleAddress) — the
    `authority` v1 clients put on MsgExecLegacyContent."""
    import hashlib

    from celestia_app_tpu.crypto import bech32
    from celestia_app_tpu.crypto.keys import ACCOUNT_HRP

    return bech32.encode(ACCOUNT_HRP, hashlib.sha256(b"gov").digest()[:20])


@dataclass(frozen=True)
class MsgExecLegacyContent:
    """cosmos.gov.v1.MsgExecLegacyContent {content=1 Any, authority=2}:
    the v1 wrapper carrying a v1beta1 Content inside a v1 proposal.  Not
    a tx msg — only the gov module account may execute it, so it appears
    exclusively inside MsgSubmitProposalV1.messages."""

    content: Any
    authority: str

    TYPE_URL = URL_MSG_GOV_V1_EXEC_LEGACY_CONTENT

    def marshal(self) -> bytes:
        return encode_bytes_field(1, self.content.marshal()) + encode_bytes_field(
            2, self.authority.encode()
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgExecLegacyContent":
        f = {num: val for num, wt, val in decode_fields(raw) if wt == WIRE_LEN}
        return cls(Any.unmarshal(f.get(1, b"")), f.get(2, b"").decode())

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())


@dataclass(frozen=True)
class MsgSubmitProposalV1:
    """cosmos.gov.v1.MsgSubmitProposal {messages=1 repeated Any,
    initial_deposit=2 repeated Coin, proposer=3, metadata=4}.

    Deviation (documented): this chain's gov router executes legacy
    Content only, so exactly ONE message is accepted and it must be a
    MsgExecLegacyContent wrapping a supported Content — the same set the
    v1beta1 surface takes.  `metadata` rides the wire but is not
    persisted (tallying never reads it)."""

    messages: tuple[Any, ...]
    initial_deposit: tuple[Coin, ...]
    proposer: str
    metadata: str = ""

    TYPE_URL = URL_MSG_GOV_V1_SUBMIT_PROPOSAL

    def marshal(self) -> bytes:
        out = b""
        for m in self.messages:
            out += encode_bytes_field(1, m.marshal())
        for c in self.initial_deposit:
            out += encode_bytes_field(2, c.marshal())
        out += encode_bytes_field(3, self.proposer.encode())
        if self.metadata:
            out += encode_bytes_field(4, self.metadata.encode())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgSubmitProposalV1":
        msgs: list[Any] = []
        deposit: list[Coin] = []
        proposer, metadata = "", ""
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                msgs.append(Any.unmarshal(val))
            elif num == 2 and wt == WIRE_LEN:
                deposit.append(Coin.unmarshal(val))
            elif num == 3 and wt == WIRE_LEN:
                proposer = val.decode()
            elif num == 4 and wt == WIRE_LEN:
                metadata = val.decode()
        return cls(tuple(msgs), tuple(deposit), proposer, metadata)

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.proposer

    def legacy_content(self) -> MsgExecLegacyContent:
        """The single MsgExecLegacyContent this proposal carries; raises
        on anything else (this chain's gov router executes legacy
        Content only)."""
        if len(self.messages) != 1:
            raise ValueError(
                "gov v1 proposals carry exactly one message on this chain"
            )
        m = self.messages[0]
        if m.type_url != URL_MSG_GOV_V1_EXEC_LEGACY_CONTENT:
            raise ValueError(
                f"proposal message {m.type_url} not supported by the gov "
                "router (only MsgExecLegacyContent)"
            )
        exec_msg = MsgExecLegacyContent.unmarshal(m.value)
        from celestia_app_tpu.modules.gov import GOV_MODULE

        if exec_msg.authority not in (GOV_MODULE, gov_module_address()):
            raise ValueError(
                f"invalid authority {exec_msg.authority!r}: expected the "
                "gov module account"
            )
        return exec_msg

    def validate_basic(self) -> None:
        from celestia_app_tpu.crypto.keys import validate_address

        validate_address(self.proposer)
        for c in self.initial_deposit:
            if c.amount < 0:
                raise ValueError("negative deposit")
        # Statelessly pin the router rule + authority so a bad proposal
        # never escrows a deposit.
        self.legacy_content()


@dataclass(frozen=True)
class MsgVoteV1:
    """cosmos.gov.v1.MsgVote {proposal_id=1, voter=2, option=3,
    metadata=4} — v1beta1 numbering plus metadata."""

    proposal_id: int
    voter: str
    option: int
    metadata: str = ""

    TYPE_URL = URL_MSG_GOV_V1_VOTE

    def marshal(self) -> bytes:
        out = (
            encode_varint_field(1, self.proposal_id)
            + encode_bytes_field(2, self.voter.encode())
            + encode_varint_field(3, self.option)
        )
        if self.metadata:
            out += encode_bytes_field(4, self.metadata.encode())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgVoteV1":
        pid, voter, option, metadata = 0, "", 0, ""
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_VARINT:
                pid = val
            elif num == 2 and wt == WIRE_LEN:
                voter = val.decode()
            elif num == 3 and wt == WIRE_VARINT:
                option = val
            elif num == 4 and wt == WIRE_LEN:
                metadata = val.decode()
        return cls(pid, voter, option, metadata)

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.voter

    def validate_basic(self) -> None:
        if self.proposal_id <= 0:
            raise ValueError("invalid proposal id")
        if self.option not in (1, 2, 3, 4):
            raise ValueError(f"invalid vote option {self.option}")


@dataclass(frozen=True)
class MsgVoteWeightedV1:
    """cosmos.gov.v1.MsgVoteWeighted {proposal_id=1, voter=2, options=3
    repeated WeightedVoteOption, metadata=4}."""

    proposal_id: int
    voter: str
    options: tuple[tuple[int, str], ...]  # (option, Dec-string weight)
    metadata: str = ""

    TYPE_URL = URL_MSG_GOV_V1_VOTE_WEIGHTED

    def marshal(self) -> bytes:
        out = encode_varint_field(1, self.proposal_id)
        out += encode_bytes_field(2, self.voter.encode())
        for opt, weight in self.options:
            out += encode_bytes_field(3, encode_weighted_option(opt, weight))
        if self.metadata:
            out += encode_bytes_field(4, self.metadata.encode())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgVoteWeightedV1":
        pid, voter, metadata = 0, "", ""
        options: list[tuple[int, str]] = []
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_VARINT:
                pid = val
            elif num == 2 and wt == WIRE_LEN:
                voter = val.decode()
            elif num == 3 and wt == WIRE_LEN:
                options.append(decode_weighted_option(val))
            elif num == 4 and wt == WIRE_LEN:
                metadata = val.decode()
        return cls(pid, voter, tuple(options), metadata)

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.voter

    # Same stateless weight rules as the v1beta1 surface (non-empty, each
    # weight in (0, 1], no duplicates, total exactly 1): an invalid
    # weighted vote must die at CheckTx on either url.
    validate_basic = MsgVoteWeighted.validate_basic


@dataclass(frozen=True)
class MsgDepositV1:
    """cosmos.gov.v1.MsgDeposit — same shape as v1beta1 {proposal_id=1,
    depositor=2, amount=3} under the v1 type url."""

    proposal_id: int
    depositor: str
    amount: tuple[Coin, ...]

    TYPE_URL = URL_MSG_GOV_V1_DEPOSIT

    marshal = MsgDeposit.marshal
    to_any = MsgDeposit.to_any
    validate_basic = MsgDeposit.validate_basic

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgDepositV1":
        base = MsgDeposit.unmarshal(raw)
        return cls(base.proposal_id, base.depositor, base.amount)

    @property
    def signer(self) -> str:
        return self.depositor


@dataclass(frozen=True)
class MsgTransfer:
    """ibc.applications.transfer.v1.MsgTransfer {source_port=1,
    source_channel=2, token=3, sender=4, receiver=5, timeout_height=6
    {revision_number=1, revision_height=2}, timeout_timestamp=7, memo=8}."""

    source_port: str
    source_channel: str
    token: Coin
    sender: str
    receiver: str
    timeout_revision_number: int = 0
    timeout_revision_height: int = 0
    timeout_timestamp_ns: int = 0
    memo: str = ""

    TYPE_URL = URL_MSG_TRANSFER

    def marshal(self) -> bytes:
        out = encode_bytes_field(1, self.source_port.encode())
        out += encode_bytes_field(2, self.source_channel.encode())
        out += encode_bytes_field(3, self.token.marshal())
        out += encode_bytes_field(4, self.sender.encode())
        out += encode_bytes_field(5, self.receiver.encode())
        if self.timeout_revision_number or self.timeout_revision_height:
            out += encode_bytes_field(
                6,
                encode_varint_field(1, self.timeout_revision_number)
                + encode_varint_field(2, self.timeout_revision_height),
            )
        if self.timeout_timestamp_ns:
            out += encode_varint_field(7, self.timeout_timestamp_ns)
        if self.memo:
            out += encode_bytes_field(8, self.memo.encode())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgTransfer":
        strs: dict[int, bytes] = {}
        ints: dict[int, int] = {}
        for num, wt, val in decode_fields(raw):
            if wt == WIRE_LEN:
                strs[num] = val
            elif wt == WIRE_VARINT:
                ints[num] = val
        rev_num = rev_h = 0
        if 6 in strs:
            hf = {n: v for n, wt, v in decode_fields(strs[6]) if wt == WIRE_VARINT}
            rev_num, rev_h = hf.get(1, 0), hf.get(2, 0)
        return cls(
            strs.get(1, b"").decode(), strs.get(2, b"").decode(),
            Coin.unmarshal(strs.get(3, b"")), strs.get(4, b"").decode(),
            strs.get(5, b"").decode(), rev_num, rev_h, ints.get(7, 0),
            strs.get(8, b"").decode(),
        )

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.sender

    def validate_basic(self) -> None:
        from celestia_app_tpu.crypto.keys import validate_address

        validate_address(self.sender)
        if not self.receiver:
            raise ValueError("receiver must not be empty")
        if self.token.amount <= 0:
            raise ValueError("transfer amount must be positive")
        if not self.source_channel:
            raise ValueError("source channel must not be empty")


def _relay_msg(url: str, signer_field: int, proof_field: int,
               height_field: int, ack_field: int | None = None):
    """MsgRecvPacket / MsgAcknowledgement / MsgTimeout share one shape:
    a packet, a state proof + proof height, optional ack bytes, and the
    relayer signer.  Field numbers follow ibc.core.channel.v1
    (MsgRecvPacket proof_commitment=2, proof_height=3, signer=4;
    MsgAcknowledgement acknowledgement=2, proof_acked=3, proof_height=4,
    signer=5; MsgTimeout proof_unreceived=2, proof_height=3, signer=5).
    `proof` carries a marshaled SMT StateProof (state/smt.py) verified
    through the channel's light client when the channel is
    connection-backed; empty for direct-OPEN test channels (IBC-lite
    trusted relay)."""

    @dataclass(frozen=True)
    class RelayMsg:
        packet_bytes: bytes
        signer: str
        acknowledgement: bytes = b""
        proof_height: int = 0
        proof: bytes = b""

        TYPE_URL = url
        _SIGNER_FIELD = signer_field
        _ACK_FIELD = ack_field
        _PROOF_FIELD = proof_field
        _HEIGHT_FIELD = height_field

        def marshal(self) -> bytes:
            out = encode_bytes_field(1, self.packet_bytes)
            if self._ACK_FIELD is not None and self.acknowledgement:
                out += encode_bytes_field(self._ACK_FIELD, self.acknowledgement)
            if self.proof:
                out += encode_bytes_field(self._PROOF_FIELD, self.proof)
            if self.proof_height:
                out += encode_bytes_field(
                    self._HEIGHT_FIELD, encode_varint_field(2, self.proof_height)
                )
            out += encode_bytes_field(self._SIGNER_FIELD, self.signer.encode())
            return out

        @classmethod
        def unmarshal(cls, raw: bytes):
            packet, signer, ack, ph, proof = b"", "", b"", 0, b""
            for num, wt, val in decode_fields(raw):
                if num == 1 and wt == WIRE_LEN:
                    packet = val
                elif num == cls._ACK_FIELD and wt == WIRE_LEN:
                    ack = val
                elif num == cls._PROOF_FIELD and wt == WIRE_LEN:
                    proof = val
                elif num == cls._HEIGHT_FIELD and wt == WIRE_LEN:
                    hf = {n: v for n, wt2, v in decode_fields(val) if wt2 == WIRE_VARINT}
                    ph = hf.get(2, 0)
                elif num == cls._SIGNER_FIELD and wt == WIRE_LEN:
                    signer = val.decode()
            return cls(packet, signer, ack, ph, proof)

        def to_any(self) -> Any:
            return Any(self.TYPE_URL, self.marshal())

        def packet(self):
            from celestia_app_tpu.modules.ibc.core import Packet

            return Packet.unmarshal(self.packet_bytes)

        def state_proof(self):
            from celestia_app_tpu.state import smt

            return smt.proof_unmarshal(self.proof) if self.proof else None

        def validate_basic(self) -> None:
            if not self.packet_bytes:
                raise ValueError("relay msg missing packet")

    RelayMsg.__name__ = RelayMsg.__qualname__ = url.rsplit(".", 1)[-1]
    return RelayMsg


MsgRecvPacket = _relay_msg(
    URL_MSG_RECV_PACKET, signer_field=4, proof_field=2, height_field=3
)
MsgAcknowledgement = _relay_msg(
    URL_MSG_ACKNOWLEDGEMENT, signer_field=5, proof_field=3, height_field=4,
    ack_field=2,
)
MsgTimeout = _relay_msg(
    URL_MSG_TIMEOUT, signer_field=5, proof_field=2, height_field=3
)


def _staking_msg(url: str, has_dst: bool = False):
    """MsgDelegate / MsgUndelegate {delegator_address=1,
    validator_address=2, amount=3}; MsgBeginRedelegate {delegator_address=1,
    validator_src_address=2, validator_dst_address=3, amount=4}
    (cosmos.staking.v1beta1 field numbers)."""

    @dataclass(frozen=True)
    class StakingMsg:
        delegator_address: str
        validator_address: str  # the src validator for redelegations
        amount: Coin
        validator_dst_address: str = ""

        TYPE_URL = url
        _HAS_DST = has_dst

        def marshal(self) -> bytes:
            out = encode_bytes_field(1, self.delegator_address.encode())
            out += encode_bytes_field(2, self.validator_address.encode())
            if self._HAS_DST:
                out += encode_bytes_field(3, self.validator_dst_address.encode())
                out += encode_bytes_field(4, self.amount.marshal())
            else:
                out += encode_bytes_field(3, self.amount.marshal())
            return out

        @classmethod
        def unmarshal(cls, raw: bytes):
            f = {num: val for num, wt, val in decode_fields(raw) if wt == WIRE_LEN}
            if cls._HAS_DST:
                return cls(
                    f.get(1, b"").decode(), f.get(2, b"").decode(),
                    Coin.unmarshal(f.get(4, b"")), f.get(3, b"").decode(),
                )
            return cls(
                f.get(1, b"").decode(), f.get(2, b"").decode(),
                Coin.unmarshal(f.get(3, b"")),
            )

        def to_any(self) -> Any:
            return Any(self.TYPE_URL, self.marshal())

        @property
        def signer(self) -> str:
            return self.delegator_address

        def validate_basic(self) -> None:
            from celestia_app_tpu.crypto.keys import validate_address

            validate_address(self.delegator_address)
            if not self.validator_address:
                raise ValueError("validator address must not be empty")
            if self._HAS_DST and not self.validator_dst_address:
                raise ValueError("destination validator must not be empty")
            if self.amount.denom != "utia":
                raise ValueError(
                    f"invalid bond denom {self.amount.denom!r}, expected utia"
                )
            if self.amount.amount <= 0:
                raise ValueError("stake amount must be positive")

    StakingMsg.__name__ = StakingMsg.__qualname__ = url.rsplit(".", 1)[-1]
    return StakingMsg


MsgDelegate = _staking_msg(URL_MSG_DELEGATE)
MsgUndelegate = _staking_msg(URL_MSG_UNDELEGATE)
MsgBeginRedelegate = _staking_msg(URL_MSG_BEGIN_REDELEGATE, has_dst=True)


@dataclass(frozen=True)
class MsgCancelUnbondingDelegation:
    """cosmos.staking.v1beta1.MsgCancelUnbondingDelegation (sdk v0.46)
    {delegator_address=1, validator_address=2, amount=3 Coin,
    creation_height=4 int64}: re-bond tokens from the unbonding entry
    created at `creation_height` back to the same validator."""

    delegator_address: str
    validator_address: str
    amount: Coin
    creation_height: int

    TYPE_URL = URL_MSG_CANCEL_UNBONDING

    def marshal(self) -> bytes:
        out = encode_bytes_field(1, self.delegator_address.encode())
        out += encode_bytes_field(2, self.validator_address.encode())
        out += encode_bytes_field(3, self.amount.marshal())
        if self.creation_height:
            # int64: negatives ride as 10-byte two's-complement varints.
            out += encode_varint_field(4, self.creation_height & ((1 << 64) - 1))
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgCancelUnbondingDelegation":
        from celestia_app_tpu.encoding.proto import int64_from_uvarint

        f = {(num, wt): val for num, wt, val in decode_fields(raw)}
        return cls(
            f.get((1, WIRE_LEN), b"").decode(),
            f.get((2, WIRE_LEN), b"").decode(),
            Coin.unmarshal(f.get((3, WIRE_LEN), b"")),
            int64_from_uvarint(f.get((4, WIRE_VARINT), 0)),
        )

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.delegator_address

    def validate_basic(self) -> None:
        from celestia_app_tpu.crypto.keys import validate_address

        validate_address(self.delegator_address)
        if not self.validator_address:
            raise ValueError("validator address must not be empty")
        if self.amount.denom != "utia":
            raise ValueError(
                f"invalid bond denom {self.amount.denom!r}, expected utia"
            )
        if self.amount.amount <= 0:
            raise ValueError("cancel amount must be positive")
        if self.creation_height < 0:
            raise ValueError("creation height must be non-negative")


@dataclass(frozen=True)
class MsgCreateValidator:
    """cosmos.staking.v1beta1.MsgCreateValidator {description=1
    {moniker=1}, commission=2 {rate=1, max_rate=2, max_change_rate=3 —
    Dec strings}, min_self_delegation=3 (string), delegator_address=4,
    validator_address=5, pubkey=6 (Any), value=7 (Coin)}."""

    moniker: str
    commission_rate: str  # Dec string, e.g. "0.100000000000000000"
    delegator_address: str
    validator_address: str
    pubkey: bytes  # consensus pubkey bytes (secp256k1 compressed here)
    value: Coin
    min_self_delegation: int = 1
    commission_max_rate: str = "1.000000000000000000"
    commission_max_change_rate: str = "0.010000000000000000"

    TYPE_URL = URL_MSG_CREATE_VALIDATOR

    def marshal(self) -> bytes:
        # proto3 canonical form: an empty Description submessage still
        # appears (field presence), but its empty moniker string does not.
        out = encode_bytes_field(
            1,
            encode_bytes_field(1, self.moniker.encode()) if self.moniker else b"",
        )
        out += encode_bytes_field(
            2,
            encode_bytes_field(1, self.commission_rate.encode())
            + encode_bytes_field(2, self.commission_max_rate.encode())
            + encode_bytes_field(3, self.commission_max_change_rate.encode()),
        )
        out += encode_bytes_field(3, str(self.min_self_delegation).encode())
        out += encode_bytes_field(4, self.delegator_address.encode())
        out += encode_bytes_field(5, self.validator_address.encode())
        out += encode_bytes_field(
            6,
            Any(
                URL_SECP256K1_PUBKEY_STR, encode_bytes_field(1, self.pubkey)
            ).marshal(),
        )
        out += encode_bytes_field(7, self.value.marshal())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgCreateValidator":
        f = {n: v for n, wt, v in decode_fields(raw) if wt == WIRE_LEN}
        moniker = ""
        for n, wt, v in decode_fields(f.get(1, b"")):
            if n == 1 and wt == WIRE_LEN:
                moniker = v.decode()
        rates = {}
        for n, wt, v in decode_fields(f.get(2, b"")):
            if wt == WIRE_LEN:
                rates[n] = v.decode()
        pk = b""
        if 6 in f:
            a = Any.unmarshal(f[6])
            if a.type_url != URL_SECP256K1_PUBKEY_STR:
                raise ValueError(
                    f"unsupported consensus pubkey type {a.type_url}"
                )
            for n, wt, v in decode_fields(a.value):
                if n == 1 and wt == WIRE_LEN:
                    pk = v
        return cls(
            moniker, rates.get(1, ""), f.get(4, b"").decode(),
            f.get(5, b"").decode(), pk, Coin.unmarshal(f.get(7, b"")),
            int(f.get(3, b"1").decode() or "1"),
            rates.get(2, "1.000000000000000000"),
            rates.get(3, "0.010000000000000000"),
        )

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.delegator_address

    def validate_basic(self) -> None:
        from celestia_app_tpu.crypto.keys import validate_address
        from celestia_app_tpu.state.dec import Dec

        validate_address(self.delegator_address)
        validate_address(self.validator_address)
        if self.validator_address != self.delegator_address:
            # The sdk derives the operator address from the signer's key;
            # in this framework's single-address model that means they are
            # literally equal — otherwise anyone could squat a validator
            # record under an address they don't control.
            raise ValueError(
                "validator address must be the signer (operator = delegator)"
            )
        if not self.pubkey:
            raise ValueError("validator needs a consensus pubkey")
        if self.value.denom != "utia" or self.value.amount <= 0:
            raise ValueError("self delegation must be positive utia")
        if self.value.amount < self.min_self_delegation:
            raise ValueError("self delegation below min_self_delegation")
        rate = Dec.from_str(self.commission_rate or "0")
        max_rate = Dec.from_str(self.commission_max_rate or "1")
        if rate < Dec(0) or Dec.from_int(1) < rate:
            raise ValueError("commission rate outside [0, 1]")
        if max_rate < rate:
            raise ValueError("commission rate exceeds its own max_rate")


@dataclass(frozen=True)
class MsgEditValidator:
    """cosmos.staking.v1beta1.MsgEditValidator {description=1 {moniker=1},
    validator_address=2, commission_rate=3 (Dec string, empty = keep)}."""

    moniker: str
    validator_address: str
    commission_rate: str = ""

    TYPE_URL = URL_MSG_EDIT_VALIDATOR

    def marshal(self) -> bytes:
        out = encode_bytes_field(
            1,
            encode_bytes_field(1, self.moniker.encode()) if self.moniker else b"",
        )
        out += encode_bytes_field(2, self.validator_address.encode())
        if self.commission_rate:
            out += encode_bytes_field(3, self.commission_rate.encode())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgEditValidator":
        f = {n: v for n, wt, v in decode_fields(raw) if wt == WIRE_LEN}
        moniker = ""
        for n, wt, v in decode_fields(f.get(1, b"")):
            if n == 1 and wt == WIRE_LEN:
                moniker = v.decode()
        return cls(moniker, f.get(2, b"").decode(), f.get(3, b"").decode())

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.validator_address

    def validate_basic(self) -> None:
        if not self.validator_address:
            raise ValueError("validator address must not be empty")
        if self.commission_rate:
            from celestia_app_tpu.state.dec import Dec

            rate = Dec.from_str(self.commission_rate)
            if rate < Dec(0) or Dec.from_int(1) < rate:
                raise ValueError("commission rate outside [0, 1]")


def _two_addr_msg(url: str, name1: str, name2: str | None):
    """Two-string-field distribution messages (cosmos.distribution.v1beta1):
    MsgWithdrawDelegatorReward {delegator_address=1, validator_address=2},
    MsgSetWithdrawAddress {delegator_address=1, withdraw_address=2},
    MsgWithdrawValidatorCommission {validator_address=1}."""

    @dataclass(frozen=True)
    class TwoAddrMsg:
        addr1: str
        addr2: str = ""

        TYPE_URL = url
        _HAS_SECOND = name2 is not None

        def marshal(self) -> bytes:
            out = encode_bytes_field(1, self.addr1.encode())
            if self._HAS_SECOND:
                out += encode_bytes_field(2, self.addr2.encode())
            return out

        @classmethod
        def unmarshal(cls, raw: bytes):
            f = {num: val for num, wt, val in decode_fields(raw) if wt == WIRE_LEN}
            return cls(f.get(1, b"").decode(), f.get(2, b"").decode())

        def to_any(self) -> Any:
            return Any(self.TYPE_URL, self.marshal())

        @property
        def signer(self) -> str:
            return self.addr1

        def validate_basic(self) -> None:
            if not self.addr1:
                raise ValueError(f"{name1} must not be empty")
            if self._HAS_SECOND and not self.addr2:
                raise ValueError(f"{name2} must not be empty")

    TwoAddrMsg.__name__ = TwoAddrMsg.__qualname__ = url.rsplit(".", 1)[-1]
    setattr(TwoAddrMsg, name1.replace(" ", "_"), property(lambda self: self.addr1))
    if name2 is not None:
        setattr(TwoAddrMsg, name2.replace(" ", "_"), property(lambda self: self.addr2))
    return TwoAddrMsg


MsgWithdrawDelegatorReward = _two_addr_msg(
    URL_MSG_WITHDRAW_DELEGATOR_REWARD, "delegator address", "validator address"
)
MsgSetWithdrawAddress = _two_addr_msg(
    URL_MSG_SET_WITHDRAW_ADDRESS, "delegator address", "withdraw address"
)
MsgWithdrawValidatorCommission = _two_addr_msg(
    URL_MSG_WITHDRAW_VALIDATOR_COMMISSION, "validator address", None
)
# cosmos.slashing.v1beta1.MsgUnjail {validator_addr=1} — same one-string
# shape as a commission withdrawal, different URL and field name.
MsgUnjail = _two_addr_msg(URL_MSG_UNJAIL, "validator address", None)


@dataclass(frozen=True)
class MsgFundCommunityPool:
    """cosmos.distribution.v1beta1.MsgFundCommunityPool
    {amount=1 (repeated Coin), depositor=2}."""

    amount: tuple[Coin, ...]
    depositor: str

    TYPE_URL = URL_MSG_FUND_COMMUNITY_POOL

    def marshal(self) -> bytes:
        out = b""
        for c in self.amount:
            out += encode_bytes_field(1, c.marshal())
        out += encode_bytes_field(2, self.depositor.encode())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgFundCommunityPool":
        coins: list[Coin] = []
        depositor = ""
        for num, wt, val in decode_fields(raw):
            if num == 1 and wt == WIRE_LEN:
                coins.append(Coin.unmarshal(val))
            elif num == 2 and wt == WIRE_LEN:
                depositor = val.decode()
        return cls(tuple(coins), depositor)

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.depositor

    def validate_basic(self) -> None:
        from celestia_app_tpu.crypto.keys import validate_address

        validate_address(self.depositor)
        if not self.amount or any(c.amount <= 0 for c in self.amount):
            raise ValueError("community pool deposit must be positive")


@dataclass(frozen=True)
class MsgGrantAllowance:
    """cosmos.feegrant.v1beta1.MsgGrantAllowance {granter=1, grantee=2,
    allowance=3 (Any)}.  Wire allowances: BasicAllowance {spend_limit=1
    repeated Coin, expiration=2 Timestamp} optionally wrapped in
    AllowedMsgAllowance {allowance=1 Any, allowed_messages=2}."""

    granter: str
    grantee: str
    spend_limit: int = 0  # 0 = unlimited
    expiration_ns: int = 0  # 0 = never
    allowed_msgs: tuple[str, ...] = ()

    TYPE_URL = URL_MSG_GRANT_ALLOWANCE

    def _allowance(self) -> Any:
        basic = b""
        if self.spend_limit:
            basic += encode_bytes_field(1, Coin("utia", self.spend_limit).marshal())
        if self.expiration_ns:
            basic += encode_bytes_field(2, _encode_timestamp(self.expiration_ns))
        inner = Any(URL_BASIC_ALLOWANCE, basic)
        if not self.allowed_msgs:
            return inner
        body = encode_bytes_field(1, inner.marshal())
        for url in self.allowed_msgs:
            body += encode_bytes_field(2, url.encode())
        return Any(URL_ALLOWED_MSG_ALLOWANCE, body)

    def marshal(self) -> bytes:
        return (
            encode_bytes_field(1, self.granter.encode())
            + encode_bytes_field(2, self.grantee.encode())
            + encode_bytes_field(3, self._allowance().marshal())
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgGrantAllowance":
        f = {n: v for n, wt, v in decode_fields(raw) if wt == WIRE_LEN}
        granter = f.get(1, b"").decode()
        grantee = f.get(2, b"").decode()
        spend, exp = 0, 0
        allowed: list[str] = []
        a = Any.unmarshal(f.get(3, b""))
        if a.type_url == URL_ALLOWED_MSG_ALLOWANCE:
            inner_raw = b""
            for n, wt, v in decode_fields(a.value):
                if n == 1 and wt == WIRE_LEN:
                    inner_raw = v
                elif n == 2 and wt == WIRE_LEN:
                    allowed.append(v.decode())
            a = Any.unmarshal(inner_raw)
        if a.type_url != URL_BASIC_ALLOWANCE:
            raise ValueError(f"unsupported allowance {a.type_url}")
        for n, wt, v in decode_fields(a.value):
            if n == 1 and wt == WIRE_LEN:
                c = Coin.unmarshal(v)
                if c.denom != "utia":
                    # Dropping a foreign-denom limit would decode a capped
                    # allowance as UNLIMITED (0) — reject instead.
                    raise ValueError(
                        f"unsupported fee allowance denom {c.denom!r}"
                    )
                spend += c.amount
            elif n == 2 and wt == WIRE_LEN:
                exp = _decode_timestamp(v)
        return cls(granter, grantee, spend, exp, tuple(allowed))

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.granter

    def validate_basic(self) -> None:
        from celestia_app_tpu.crypto.keys import validate_address

        validate_address(self.granter)
        validate_address(self.grantee)
        if self.granter == self.grantee:
            raise ValueError("cannot self-grant a fee allowance")


@dataclass(frozen=True)
class MsgRevokeAllowance:
    """cosmos.feegrant.v1beta1.MsgRevokeAllowance {granter=1, grantee=2}."""

    granter: str
    grantee: str

    TYPE_URL = URL_MSG_REVOKE_ALLOWANCE

    def marshal(self) -> bytes:
        return encode_bytes_field(1, self.granter.encode()) + encode_bytes_field(
            2, self.grantee.encode()
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgRevokeAllowance":
        f = {n: v for n, wt, v in decode_fields(raw) if wt == WIRE_LEN}
        return cls(f.get(1, b"").decode(), f.get(2, b"").decode())

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.granter

    def validate_basic(self) -> None:
        from celestia_app_tpu.crypto.keys import validate_address

        validate_address(self.granter)
        validate_address(self.grantee)


@dataclass(frozen=True)
class MsgAuthzGrant:
    """cosmos.authz.v1beta1.MsgGrant {granter=1, grantee=2, grant=3
    {authorization=1 (Any), expiration=2 Timestamp}}.  Authorizations:
    GenericAuthorization {msg=1} or SendAuthorization {spend_limit=1}."""

    granter: str
    grantee: str
    msg_type_url: str
    spend_limit: int = 0  # >0 encodes a SendAuthorization
    expiration_ns: int = 0

    TYPE_URL = URL_MSG_AUTHZ_GRANT

    def _authorization(self) -> Any:
        if self.spend_limit:
            return Any(
                URL_SEND_AUTHORIZATION,
                encode_bytes_field(1, Coin("utia", self.spend_limit).marshal()),
            )
        return Any(
            URL_GENERIC_AUTHORIZATION,
            encode_bytes_field(1, self.msg_type_url.encode()),
        )

    def marshal(self) -> bytes:
        grant = encode_bytes_field(1, self._authorization().marshal())
        if self.expiration_ns:
            grant += encode_bytes_field(2, _encode_timestamp(self.expiration_ns))
        return (
            encode_bytes_field(1, self.granter.encode())
            + encode_bytes_field(2, self.grantee.encode())
            + encode_bytes_field(3, grant)
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgAuthzGrant":
        f = {n: v for n, wt, v in decode_fields(raw) if wt == WIRE_LEN}
        granter = f.get(1, b"").decode()
        grantee = f.get(2, b"").decode()
        url, spend, exp = "", 0, 0
        for n, wt, v in decode_fields(f.get(3, b"")):
            if n == 1 and wt == WIRE_LEN:
                auth = Any.unmarshal(v)
                if auth.type_url == URL_GENERIC_AUTHORIZATION:
                    for an, awt, av in decode_fields(auth.value):
                        if an == 1 and awt == WIRE_LEN:
                            url = av.decode()
                elif auth.type_url == URL_SEND_AUTHORIZATION:
                    url = URL_MSG_SEND
                    for an, awt, av in decode_fields(auth.value):
                        if an == 1 and awt == WIRE_LEN:
                            c = Coin.unmarshal(av)
                            if c.denom != "utia":
                                # A foreign-denom limit must not decode to
                                # spend_limit=0 (= unbounded).
                                raise ValueError(
                                    f"unsupported authorization denom {c.denom!r}"
                                )
                            spend += c.amount
                else:
                    raise ValueError(f"unsupported authorization {auth.type_url}")
            elif n == 2 and wt == WIRE_LEN:
                exp = _decode_timestamp(v)
        return cls(granter, grantee, url, spend, exp)

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.granter

    def validate_basic(self) -> None:
        from celestia_app_tpu.crypto.keys import validate_address

        validate_address(self.granter)
        validate_address(self.grantee)
        if self.granter == self.grantee:
            raise ValueError("cannot self-grant")
        if not self.msg_type_url:
            raise ValueError("authorization needs a msg type url")
        if self.spend_limit and self.msg_type_url != URL_MSG_SEND:
            # spend_limit>0 encodes a SendAuthorization, whose wire shape
            # carries no msg-type field and whose sdk Accept() covers
            # MsgSend ONLY — combining it with another msg type (incl.
            # MsgMultiSend) would sign a different authority than this
            # object declares and be wire-lossy.  MultiSend under authz
            # is a GenericAuthorization (unlimited), as in the sdk.
            raise ValueError(
                "spend_limit applies only to a MsgSend authorization"
            )


@dataclass(frozen=True)
class MsgAuthzExec:
    """cosmos.authz.v1beta1.MsgExec {grantee=1, msgs=2 (repeated Any)}."""

    grantee: str
    msgs: tuple[Any, ...]

    TYPE_URL = URL_MSG_AUTHZ_EXEC

    def marshal(self) -> bytes:
        out = encode_bytes_field(1, self.grantee.encode())
        for m in self.msgs:
            out += encode_bytes_field(2, m.marshal())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgAuthzExec":
        grantee = ""
        msgs: list[Any] = []
        for n, wt, v in decode_fields(raw):
            if n == 1 and wt == WIRE_LEN:
                grantee = v.decode()
            elif n == 2 and wt == WIRE_LEN:
                msgs.append(Any.unmarshal(v))
        return cls(grantee, tuple(msgs))

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    def inner_msgs(self) -> list:
        return [decode_msg(m) for m in self.msgs]

    @property
    def signer(self) -> str:
        return self.grantee

    def validate_basic(self) -> None:
        from celestia_app_tpu.crypto.keys import validate_address

        validate_address(self.grantee)
        if not self.msgs:
            raise ValueError("MsgExec needs at least one message")
        for m in self.inner_msgs():
            m.validate_basic()


@dataclass(frozen=True)
class MsgAuthzRevoke:
    """cosmos.authz.v1beta1.MsgRevoke {granter=1, grantee=2, msg_type_url=3}."""

    granter: str
    grantee: str
    msg_type_url: str

    TYPE_URL = URL_MSG_AUTHZ_REVOKE

    def marshal(self) -> bytes:
        return (
            encode_bytes_field(1, self.granter.encode())
            + encode_bytes_field(2, self.grantee.encode())
            + encode_bytes_field(3, self.msg_type_url.encode())
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MsgAuthzRevoke":
        f = {n: v for n, wt, v in decode_fields(raw) if wt == WIRE_LEN}
        return cls(
            f.get(1, b"").decode(), f.get(2, b"").decode(), f.get(3, b"").decode()
        )

    def to_any(self) -> Any:
        return Any(self.TYPE_URL, self.marshal())

    @property
    def signer(self) -> str:
        return self.granter

    def validate_basic(self) -> None:
        from celestia_app_tpu.crypto.keys import validate_address

        validate_address(self.granter)
        if not self.msg_type_url:
            raise ValueError("revoke needs a msg type url")


MSG_DECODERS = {
    URL_MSG_CREATE_VALIDATOR: MsgCreateValidator.unmarshal,
    URL_MSG_EDIT_VALIDATOR: MsgEditValidator.unmarshal,
    URL_MSG_GRANT_ALLOWANCE: MsgGrantAllowance.unmarshal,
    URL_MSG_REVOKE_ALLOWANCE: MsgRevokeAllowance.unmarshal,
    URL_MSG_AUTHZ_GRANT: MsgAuthzGrant.unmarshal,
    URL_MSG_AUTHZ_EXEC: MsgAuthzExec.unmarshal,
    URL_MSG_AUTHZ_REVOKE: MsgAuthzRevoke.unmarshal,
    URL_MSG_UNJAIL: MsgUnjail.unmarshal,
    URL_MSG_WITHDRAW_DELEGATOR_REWARD: MsgWithdrawDelegatorReward.unmarshal,
    URL_MSG_WITHDRAW_VALIDATOR_COMMISSION: MsgWithdrawValidatorCommission.unmarshal,
    URL_MSG_SET_WITHDRAW_ADDRESS: MsgSetWithdrawAddress.unmarshal,
    URL_MSG_FUND_COMMUNITY_POOL: MsgFundCommunityPool.unmarshal,
    URL_MSG_DELEGATE: MsgDelegate.unmarshal,
    URL_MSG_UNDELEGATE: MsgUndelegate.unmarshal,
    URL_MSG_BEGIN_REDELEGATE: MsgBeginRedelegate.unmarshal,
    URL_MSG_CANCEL_UNBONDING: MsgCancelUnbondingDelegation.unmarshal,
    URL_MSG_PAY_FOR_BLOBS: MsgPayForBlobs.unmarshal,
    URL_MSG_SEND: MsgSend.unmarshal,
    URL_MSG_MULTI_SEND: MsgMultiSend.unmarshal,
    URL_MSG_CREATE_VESTING_ACCOUNT: MsgCreateVestingAccount.unmarshal,
    URL_MSG_CREATE_PERIODIC_VESTING_ACCOUNT: (
        MsgCreatePeriodicVestingAccount.unmarshal
    ),
    URL_MSG_CREATE_PERMANENT_LOCKED_ACCOUNT: (
        MsgCreatePermanentLockedAccount.unmarshal
    ),
    URL_MSG_VERIFY_INVARIANT: MsgVerifyInvariant.unmarshal,
    URL_MSG_SUBMIT_EVIDENCE: MsgSubmitEvidence.unmarshal,
    URL_MSG_SIGNAL_VERSION: MsgSignalVersion.unmarshal,
    URL_MSG_TRY_UPGRADE: MsgTryUpgrade.unmarshal,
    URL_MSG_SUBMIT_PROPOSAL: MsgSubmitProposal.unmarshal,
    URL_MSG_VOTE: MsgVote.unmarshal,
    URL_MSG_VOTE_WEIGHTED: MsgVoteWeighted.unmarshal,
    URL_MSG_DEPOSIT: MsgDeposit.unmarshal,
    URL_MSG_GOV_V1_SUBMIT_PROPOSAL: MsgSubmitProposalV1.unmarshal,
    URL_MSG_GOV_V1_VOTE: MsgVoteV1.unmarshal,
    URL_MSG_GOV_V1_VOTE_WEIGHTED: MsgVoteWeightedV1.unmarshal,
    URL_MSG_GOV_V1_DEPOSIT: MsgDepositV1.unmarshal,
    URL_MSG_TRANSFER: MsgTransfer.unmarshal,
    URL_MSG_RECV_PACKET: MsgRecvPacket.unmarshal,
    URL_MSG_ACKNOWLEDGEMENT: MsgAcknowledgement.unmarshal,
    URL_MSG_TIMEOUT: MsgTimeout.unmarshal,
}


def decode_msg(any_msg: Any):
    dec = MSG_DECODERS.get(any_msg.type_url)
    if dec is None:
        raise ValueError(f"unknown message type {any_msg.type_url}")
    return dec(any_msg.value)

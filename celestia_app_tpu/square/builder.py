"""Deterministic square construction: Build (proposer) / Construct (validator).

Behavioral parity with the go-square Builder as driven by the reference app
(square.Build at app/prepare_proposal.go:50, square.Construct at
app/process_proposal.go:122 and app/extend_block.go:16):

  * the square holds, in order: normal txs (compact shares, TRANSACTION
    namespace), PFB txs wrapped as IndexWrappers (compact shares,
    PAY_FOR_BLOB namespace), primary-reserved padding, blobs sorted by
    namespace (stable in PFB order within a namespace) at subtree-aligned
    start indexes, namespace padding between blobs, tail padding to k*k;
  * blob start alignment follows the non-interactive default rules
    (layout.next_share_index), independent of the square size;
  * the square size is the smallest power of two that fits.

The one place this construction is self-referential: blob start indexes are
written into the PFB IndexWrappers, whose byte length changes the compact
share count, which moves the blob starts.  We resolve the fixpoint by
seeding every index at its upper bound and iterating; sizes only shrink, so
the iteration converges and both Build and Construct land on the identical
layout.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from celestia_app_tpu.constants import SUBTREE_ROOT_THRESHOLD
from celestia_app_tpu.shares.compact import (
    compact_shares_needed,
    split_txs,
    tx_sequence_len,
    write_uvarint,
)
from celestia_app_tpu.shares.namespace import (
    PAY_FOR_BLOB_NAMESPACE,
    TRANSACTION_NAMESPACE,
)
from celestia_app_tpu.shares.share import (
    Share,
    reserved_padding_shares,
    tail_padding_shares,
)
from celestia_app_tpu.shares.sparse import SparseShareSplitter
from celestia_app_tpu.square.layout import next_share_index, round_up_power_of_two
from celestia_app_tpu.tx.envelopes import (
    BlobTx,
    IndexWrapper,
    unmarshal_blob_tx,
)


@dataclass(frozen=True)
class BlobPlacement:
    """Where one blob landed in the square."""

    pfb_index: int  # index into the builder's blob-tx list
    blob_index: int  # index within that blob tx
    start: int  # first share index (row-major)
    share_count: int


@dataclass(frozen=True)
class _Layout:
    size: int  # square size k
    tx_share_count: int
    pfb_share_count: int
    txs: tuple[bytes, ...]  # normal txs, block order
    wrapped_pfbs: tuple[bytes, ...]
    placements: tuple[BlobPlacement, ...]
    end: int  # share index one past the last non-tail-padding share


@dataclass(frozen=True)
class NamespaceUsage:
    """One namespace's footprint in a built square."""

    namespace: bytes  # the 29-byte encoded namespace
    blobs: int
    shares: int
    data_bytes: int  # sum of blob payload lengths


@dataclass(frozen=True)
class SquareAccounting:
    """Exact share-count breakdown of one exported square.

    Every share in the k*k square is attributed to exactly one bucket, so
    tx + pfb + blob + reserved + namespace + tail == size*size always —
    the invariant the square journal rows carry and tests pin.
    """

    size: int  # square size k
    tx_shares: int  # compact TRANSACTION-namespace shares
    pfb_shares: int  # compact PAY_FOR_BLOB shares (IndexWrappers)
    blob_shares: int  # sparse shares holding blob payloads
    reserved_padding: int  # compact range -> first blob alignment gap
    namespace_padding: int  # alignment gaps between blobs
    tail_padding: int  # end of content -> k*k
    namespaces: tuple[NamespaceUsage, ...]  # sorted by namespace bytes

    @property
    def total_shares(self) -> int:
        return self.size * self.size

    @property
    def used_shares(self) -> int:
        """Shares carrying data (everything that is not padding)."""
        return self.tx_shares + self.pfb_shares + self.blob_shares

    @property
    def padding_shares(self) -> int:
        return self.reserved_padding + self.namespace_padding + self.tail_padding

    @property
    def occupancy(self) -> float:
        """used / k*k — the square-size efficiency signal."""
        return self.used_shares / self.total_shares


class SquareOverflow(ValueError):
    """The content does not fit in the maximum square size."""


def _compact_share_index(byte_offset: int) -> int:
    """Index of the compact share containing sequence byte `byte_offset`."""
    from celestia_app_tpu.constants import (
        CONTINUATION_COMPACT_SHARE_CONTENT_SIZE as CONT,
        FIRST_COMPACT_SHARE_CONTENT_SIZE as FIRST,
    )

    if byte_offset < FIRST:
        return 0
    return 1 + (byte_offset - FIRST) // CONT


class Square:
    """An immutable k x k square of shares plus its layout metadata."""

    def __init__(
        self,
        shares: list[Share],
        layout: _Layout,
        accounting: SquareAccounting | None = None,
    ):
        self.shares = shares
        self.size = layout.size
        self._layout = layout
        self.accounting = accounting

    def __len__(self) -> int:
        return len(self.shares)

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Square)
            and self.size == other.size
            and [s.raw for s in self.shares] == [s.raw for s in other.shares]
        )

    def share_bytes(self) -> list[bytes]:
        return [s.raw for s in self.shares]

    def is_empty(self) -> bool:
        return self._layout.end == 0

    @property
    def tx_share_range(self) -> tuple[int, int]:
        return (0, self._layout.tx_share_count)

    @property
    def pfb_share_range(self) -> tuple[int, int]:
        lo = self._layout.tx_share_count
        return (lo, lo + self._layout.pfb_share_count)

    @property
    def placements(self) -> tuple[BlobPlacement, ...]:
        return self._layout.placements

    def blob_share_range(self, pfb_index: int, blob_index: int) -> tuple[int, int]:
        for p in self._layout.placements:
            if p.pfb_index == pfb_index and p.blob_index == blob_index:
                return (p.start, p.start + p.share_count)
        raise KeyError(f"no blob ({pfb_index}, {blob_index}) in square")

    def wrapped_pfb_txs(self) -> tuple[bytes, ...]:
        """The IndexWrapper bytes committed in the PAY_FOR_BLOB shares."""
        return self._layout.wrapped_pfbs

    def find_tx_share_range(self, tx_index: int) -> tuple[int, int]:
        """Share span [lo, hi) of block tx `tx_index`.

        Block tx order is normal txs then blob txs (reference go-square
        square.FindTxShareRange via pkg/proof/proof.go:28-42); for a blob tx
        the span covers its IndexWrapper bytes in the PFB compact run.
        """
        n_tx = len(self._layout.txs)
        if tx_index < n_tx:
            units, region_start = list(self._layout.txs), 0
            unit = tx_index
        else:
            unit = tx_index - n_tx
            if unit >= len(self._layout.wrapped_pfbs):
                raise IndexError(f"tx index {tx_index} out of range")
            units = list(self._layout.wrapped_pfbs)
            region_start = self._layout.tx_share_count
        offset = sum(len(write_uvarint(len(u))) + len(u) for u in units[:unit])
        length = len(write_uvarint(len(units[unit]))) + len(units[unit])
        return (
            region_start + _compact_share_index(offset),
            region_start + _compact_share_index(offset + length - 1) + 1,
        )


class Builder:
    """Accumulates txs and blob txs; exports the deterministic square."""

    def __init__(
        self,
        max_square_size: int,
        subtree_root_threshold: int = SUBTREE_ROOT_THRESHOLD,
    ):
        if max_square_size < 1 or max_square_size & (max_square_size - 1):
            raise ValueError(f"max square size must be a power of two: {max_square_size}")
        self.max_square_size = max_square_size
        self.subtree_root_threshold = subtree_root_threshold
        self._txs: list[bytes] = []
        self._blob_txs: list[BlobTx] = []
        self._solves = 0  # layout fixpoint runs (fit checks + exports)

    # --- append (greedy fit checks) ---------------------------------------
    def append_tx(self, tx: bytes) -> bool:
        self._txs.append(tx)
        if self._fits():
            return True
        self._txs.pop()
        return False

    def append_blob_tx(self, btx: BlobTx) -> bool:
        self._blob_txs.append(btx)
        if self._fits():
            return True
        self._blob_txs.pop()
        return False

    def _fits(self) -> bool:
        try:
            self._solve()
            return True
        except SquareOverflow:
            return False

    # --- layout -----------------------------------------------------------
    def _solve(self) -> _Layout:
        self._solves += 1
        tx_shares = compact_shares_needed(tx_sequence_len(self._txs))

        # All blobs in placement order: sorted by namespace, stable in
        # (pfb, blob) order (priority within a namespace is submission order;
        # spec data_square_layout.md "Ordering").
        indexed_blobs = [
            (ti, bi, blob)
            for ti, btx in enumerate(self._blob_txs)
            for bi, blob in enumerate(btx.blobs)
        ]
        order = sorted(
            range(len(indexed_blobs)),
            key=lambda i: indexed_blobs[i][2].namespace.to_bytes(),
        )

        # Fixpoint: seed every share index at its upper bound so wrapper
        # sizes start maximal and only shrink.
        bound = self.max_square_size * self.max_square_size
        starts: dict[tuple[int, int], int] = {
            (ti, bi): bound for ti, bi, _ in indexed_blobs
        }
        for _ in range(32):
            wrapped = tuple(
                IndexWrapper(
                    btx.tx,
                    tuple(starts[(ti, bi)] for bi in range(len(btx.blobs))),
                ).marshal()
                for ti, btx in enumerate(self._blob_txs)
            )
            pfb_shares = compact_shares_needed(tx_sequence_len(list(wrapped)))
            cursor = tx_shares + pfb_shares
            new_starts: dict[tuple[int, int], int] = {}
            placements: list[BlobPlacement] = []
            for oi in order:
                ti, bi, blob = indexed_blobs[oi]
                count = blob.share_count()
                start = next_share_index(cursor, count, self.subtree_root_threshold)
                new_starts[(ti, bi)] = start
                placements.append(BlobPlacement(ti, bi, start, count))
                cursor = start + count
            if new_starts == starts:
                break
            starts = new_starts
        else:  # pragma: no cover - the monotone iteration always converges
            raise RuntimeError("square layout fixpoint did not converge")

        end = cursor
        size = max(1, round_up_power_of_two(math.isqrt(max(end - 1, 0)) + 1))
        if size > self.max_square_size:
            raise SquareOverflow(
                f"content needs square size {size} > max {self.max_square_size}"
            )
        return _Layout(
            size=size,
            tx_share_count=tx_shares,
            pfb_share_count=pfb_shares,
            txs=tuple(self._txs),
            wrapped_pfbs=wrapped,
            placements=tuple(placements),
            end=end,
        )

    def export(self) -> Square:
        layout = self._solve()
        shares: list[Share] = []
        shares += split_txs(self._txs, TRANSACTION_NAMESPACE)
        shares += split_txs(list(layout.wrapped_pfbs), PAY_FOR_BLOB_NAMESPACE)
        assert len(shares) == layout.tx_share_count + layout.pfb_share_count

        if layout.placements:
            first_start = layout.placements[0].start
            shares += reserved_padding_shares(first_start - len(shares))
            sparse = SparseShareSplitter()
            cursor = first_start
            for p in layout.placements:
                if p.start > cursor:
                    sparse.write_namespace_padding(p.start - cursor)
                    cursor = p.start
                blob = self._blob_txs[p.pfb_index].blobs[p.blob_index]
                sparse.write(blob)
                cursor += p.share_count
            shares += sparse.export()

        total = layout.size * layout.size
        shares += tail_padding_shares(total - len(shares))
        return Square(shares, layout, self._accounting(layout))

    def _accounting(self, layout: _Layout) -> SquareAccounting:
        """The padding/occupancy breakdown export() used to throw away:
        re-derived from the solved layout alone (no extra fixpoint runs)."""
        compact_end = layout.tx_share_count + layout.pfb_share_count
        if layout.placements:
            reserved = layout.placements[0].start - compact_end
            ns_pad = 0
            cursor = layout.placements[0].start
            for p in layout.placements:
                ns_pad += p.start - cursor
                cursor = p.start + p.share_count
            blob_shares = sum(p.share_count for p in layout.placements)
        else:
            reserved = ns_pad = blob_shares = 0
        per_ns: dict[bytes, list[int]] = {}  # ns bytes -> [blobs, shares, bytes]
        for p in layout.placements:
            blob = self._blob_txs[p.pfb_index].blobs[p.blob_index]
            agg = per_ns.setdefault(blob.namespace.to_bytes(), [0, 0, 0])
            agg[0] += 1
            agg[1] += p.share_count
            agg[2] += len(blob.data)
        return SquareAccounting(
            size=layout.size,
            tx_shares=layout.tx_share_count,
            pfb_shares=layout.pfb_share_count,
            blob_shares=blob_shares,
            reserved_padding=reserved,
            namespace_padding=ns_pad,
            tail_padding=layout.size * layout.size - layout.end,
            namespaces=tuple(
                NamespaceUsage(ns, b, s, by)
                for ns, (b, s, by) in sorted(per_ns.items())
            ),
        )

    # --- introspection ----------------------------------------------------
    def current_size(self) -> int:
        return self._solve().size

    @property
    def txs(self) -> list[bytes]:
        return list(self._txs)

    @property
    def blob_txs(self) -> list[BlobTx]:
        return list(self._blob_txs)


def _classify(raw_txs: list[bytes]) -> list[tuple[bytes, BlobTx | None]]:
    return [(raw, unmarshal_blob_tx(raw)) for raw in raw_txs]


def _journal_export(sq: Square, sp: dict, phase: str, solves: int) -> None:
    """Shared journal tail of build()/construct(): occupancy onto the
    span, one square_journal row — one copy so the proposer and validator
    rows can never drift."""
    sp["occupancy"] = round(sq.accounting.occupancy, 6)
    from celestia_app_tpu.trace import square_journal

    square_journal.record(sq, phase=phase, layout_solves=solves)


def build(
    raw_txs: list[bytes],
    max_square_size: int,
    subtree_root_threshold: int = SUBTREE_ROOT_THRESHOLD,
) -> tuple[Square, list[bytes]]:
    """Proposer path (reference square.Build, app/prepare_proposal.go:50).

    Greedily packs as many txs as fit — normal txs first, then blob txs —
    dropping the rest.  Returns (square, kept_txs) where kept_txs are the
    original bytes in block order (normal txs, then BlobTxs).
    """
    from celestia_app_tpu.trace.context import trace_span

    with trace_span(
        "square_build", layer="square", e2e="square_build",
        n_candidates=len(raw_txs),
    ) as sp:
        builder = Builder(max_square_size, subtree_root_threshold)
        kept_normal: list[bytes] = []
        kept_blob: list[bytes] = []
        for raw, btx in _classify(raw_txs):
            if btx is None:
                if builder.append_tx(raw):
                    kept_normal.append(raw)
            else:
                if builder.append_blob_tx(btx):
                    kept_blob.append(raw)
        sq = builder.export()
        sp["n_txs"] = len(kept_normal)
        sp["n_blob_txs"] = len(kept_blob)
        sp["n_blobs"] = len(sq.placements)
        sp["dropped"] = len(raw_txs) - len(kept_normal) - len(kept_blob)
        sp["layout_solves"] = builder._solves
        sp["k"] = sq.size
        _journal_export(sq, sp, "build", builder._solves)
    return sq, kept_normal + kept_blob


def construct(
    raw_txs: list[bytes],
    max_square_size: int,
    subtree_root_threshold: int = SUBTREE_ROOT_THRESHOLD,
) -> Square:
    """Validator path (reference square.Construct, app/process_proposal.go:122).

    Every tx must fit; raises SquareOverflow otherwise.
    """
    from celestia_app_tpu.trace.context import trace_span

    with trace_span(
        "square_construct", layer="square", n_candidates=len(raw_txs),
    ) as sp:
        builder = Builder(max_square_size, subtree_root_threshold)
        for raw, btx in _classify(raw_txs):
            ok = builder.append_tx(raw) if btx is None else builder.append_blob_tx(btx)
            if not ok:
                raise SquareOverflow("proposal txs overflow the maximum square size")
        sq = builder.export()
        sp["n_blobs"] = len(sq.placements)
        sp["layout_solves"] = builder._solves
        sp["k"] = sq.size
        _journal_export(sq, sp, "construct", builder._solves)
    return sq

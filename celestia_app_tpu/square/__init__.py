from celestia_app_tpu.square.builder import (
    BlobPlacement,
    Builder,
    NamespaceUsage,
    Square,
    SquareAccounting,
    SquareOverflow,
    build,
    construct,
)
from celestia_app_tpu.square.layout import (
    blob_min_square_size,
    next_share_index,
    round_up_power_of_two,
    subtree_width,
)

__all__ = [
    "BlobPlacement",
    "Builder",
    "NamespaceUsage",
    "Square",
    "SquareAccounting",
    "SquareOverflow",
    "build",
    "construct",
    "blob_min_square_size",
    "next_share_index",
    "round_up_power_of_two",
    "subtree_width",
]

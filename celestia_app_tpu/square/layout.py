"""Blob placement math: the non-interactive default rules.

Behavioral parity with the reference's layout spec
(specs/src/specs/data_square_layout.md "Blob Share Commitment Rules";
go-square non_interactive_defaults semantics, ADR-013): a blob's first share
index must be a multiple of its SubtreeWidth, which is a function of the blob
size and SubtreeRootThreshold only — never of the square size — so share
commitments are square-size independent.
"""

from __future__ import annotations

import math


def round_up_power_of_two(n: int) -> int:
    """Smallest power of two >= n (n >= 1 -> >= 1)."""
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def round_down_power_of_two(n: int) -> int:
    if n < 1:
        raise ValueError("n must be >= 1")
    return 1 << (n.bit_length() - 1)


def blob_min_square_size(share_count: int) -> int:
    """Smallest square size that could fit `share_count` shares."""
    sc = max(share_count, 1)
    return round_up_power_of_two(math.isqrt(sc - 1) + 1)  # ceil(sqrt(sc)), pow2


def subtree_width(share_count: int, subtree_root_threshold: int) -> int:
    """Width (in shares) of the largest subtree root mountain for a blob.

    ceil(share_count / threshold), rounded up to a power of two, capped at
    the blob's minimum square size.
    """
    s = -(-share_count // subtree_root_threshold)
    return min(round_up_power_of_two(s), blob_min_square_size(share_count))


def next_share_index(cursor: int, blob_share_len: int, subtree_root_threshold: int) -> int:
    """First valid start index >= cursor for a blob of blob_share_len shares."""
    width = subtree_width(blob_share_len, subtree_root_threshold)
    return -(-cursor // width) * width


def next_multiple_of_blob_min_square_size(cursor: int, share_count: int) -> int:
    """Alignment used by the v0 commitment scheme's first mountain."""
    w = blob_min_square_size(share_count)
    return -(-cursor // w) * w

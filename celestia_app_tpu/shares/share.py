"""The 512-byte share: the atomic unit of the data square.

Byte layout (specs/src/specs/shares.md "Share Format"):

    namespace (29) | info byte (1) | [sequence len (4) if seq start]
    | [reserved bytes (4) if compact] | data ... zero-padded to 512

Info byte: 7-bit share version (big-endian high bits) | 1-bit sequence-start.
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu.constants import (
    COMPACT_SHARE_RESERVED_BYTES,
    MAX_SHARE_VERSION,
    NAMESPACE_SIZE,
    SEQUENCE_LEN_BYTES,
    SHARE_INFO_BYTES,
    SHARE_SIZE,
    SHARE_VERSION_ZERO,
)
from celestia_app_tpu.shares.namespace import (
    Namespace,
    PRIMARY_RESERVED_PADDING_NAMESPACE,
    TAIL_PADDING_NAMESPACE,
)

SUPPORTED_SHARE_VERSIONS = (SHARE_VERSION_ZERO,)


def make_info_byte(share_version: int, is_sequence_start: bool) -> int:
    if not 0 <= share_version <= MAX_SHARE_VERSION:
        raise ValueError(f"share version out of range: {share_version}")
    return (share_version << 1) | int(bool(is_sequence_start))


def parse_info_byte(b: int) -> tuple[int, bool]:
    """Returns (share_version, is_sequence_start)."""
    return b >> 1, bool(b & 1)


@dataclass(frozen=True)
class Share:
    """An immutable 512-byte share."""

    raw: bytes

    def __post_init__(self) -> None:
        if len(self.raw) != SHARE_SIZE:
            raise ValueError(f"share must be {SHARE_SIZE} bytes, got {len(self.raw)}")

    # --- field accessors --------------------------------------------------
    def namespace(self) -> Namespace:
        return Namespace.from_bytes(self.raw[:NAMESPACE_SIZE])

    def info_byte(self) -> int:
        return self.raw[NAMESPACE_SIZE]

    def share_version(self) -> int:
        return parse_info_byte(self.info_byte())[0]

    def is_sequence_start(self) -> bool:
        return parse_info_byte(self.info_byte())[1]

    def sequence_len(self) -> int:
        """Big-endian uint32 sequence length; only present on sequence starts."""
        if not self.is_sequence_start():
            raise ValueError("sequence length only present in first share of a sequence")
        off = NAMESPACE_SIZE + SHARE_INFO_BYTES
        return int.from_bytes(self.raw[off : off + SEQUENCE_LEN_BYTES], "big")

    def is_compact(self) -> bool:
        ns = self.namespace()
        return ns.is_compact()

    def reserved_bytes(self) -> int:
        """Big-endian uint32 index of the first unit starting in this (compact) share."""
        if not self.is_compact():
            raise ValueError("reserved bytes only present in compact shares")
        off = NAMESPACE_SIZE + SHARE_INFO_BYTES
        if self.is_sequence_start():
            off += SEQUENCE_LEN_BYTES
        return int.from_bytes(self.raw[off : off + COMPACT_SHARE_RESERVED_BYTES], "big")

    def data(self) -> bytes:
        """The raw data region (everything after the prefix fields)."""
        off = NAMESPACE_SIZE + SHARE_INFO_BYTES
        if self.is_sequence_start():
            off += SEQUENCE_LEN_BYTES
        if self.is_compact():
            off += COMPACT_SHARE_RESERVED_BYTES
        return self.raw[off:]

    def is_padding(self) -> bool:
        ns = self.namespace()
        if ns == TAIL_PADDING_NAMESPACE or ns == PRIMARY_RESERVED_PADDING_NAMESPACE:
            return True
        return self.is_sequence_start() and not self.is_compact() and self.sequence_len() == 0

    def validate(self) -> None:
        if self.share_version() not in SUPPORTED_SHARE_VERSIONS:
            raise ValueError(f"unsupported share version {self.share_version()}")


def _build_prefix(
    namespace: Namespace,
    share_version: int,
    is_sequence_start: bool,
    sequence_len: int | None,
) -> bytearray:
    buf = bytearray()
    buf += namespace.to_bytes()
    buf.append(make_info_byte(share_version, is_sequence_start))
    if is_sequence_start:
        if sequence_len is None:
            raise ValueError("sequence start share requires a sequence length")
        buf += int(sequence_len).to_bytes(SEQUENCE_LEN_BYTES, "big")
    return buf


def shares_needed(total_bytes: int, first_content_size: int, cont_content_size: int) -> int:
    """Shares needed for a sequence of total_bytes of content."""
    if total_bytes == 0:
        return 0
    if total_bytes <= first_content_size:
        return 1
    rem = total_bytes - first_content_size
    return 1 + -(-rem // cont_content_size)


def padding_share(namespace: Namespace, share_version: int = SHARE_VERSION_ZERO) -> Share:
    """A padding share: sequence start, sequence length 0, zero data.

    Only sparse (non-compact) namespaces are valid: padding never occurs
    inside the compact tx/PFB runs, and a compact-namespace share without
    reserved bytes would be malformed.
    """
    if namespace.is_compact():
        raise ValueError(f"padding shares cannot use compact namespace {namespace}")
    buf = _build_prefix(namespace, share_version, True, 0)
    buf += bytes(SHARE_SIZE - len(buf))
    return Share(bytes(buf))


def namespace_padding_shares(namespace: Namespace, n: int) -> list[Share]:
    return [padding_share(namespace)] * n


def reserved_padding_shares(n: int) -> list[Share]:
    return [padding_share(PRIMARY_RESERVED_PADDING_NAMESPACE)] * n


def tail_padding_shares(n: int) -> list[Share]:
    return [padding_share(TAIL_PADDING_NAMESPACE)] * n


def shares_to_bytes(shares: list[Share]) -> list[bytes]:
    return [s.raw for s in shares]


def shares_from_bytes(raw: list[bytes]) -> list[Share]:
    return [Share(r) for r in raw]

from celestia_app_tpu.shares.namespace import (  # noqa: F401
    Namespace,
    PARITY_NS_BYTES,
    PARITY_SHARE_NAMESPACE,
    PAY_FOR_BLOB_NAMESPACE,
    PRIMARY_RESERVED_PADDING_NAMESPACE,
    TAIL_PADDING_NAMESPACE,
    TRANSACTION_NAMESPACE,
)
from celestia_app_tpu.shares.share import (  # noqa: F401
    Share,
    make_info_byte,
    padding_share,
    parse_info_byte,
    reserved_padding_shares,
    shares_from_bytes,
    shares_to_bytes,
    tail_padding_shares,
)
from celestia_app_tpu.shares.sparse import (  # noqa: F401
    Blob,
    SparseShareSplitter,
    parse_sparse_shares,
    sparse_shares_needed,
    split_blob,
)
from celestia_app_tpu.shares.compact import (  # noqa: F401
    compact_shares_needed,
    parse_compact_shares,
    split_txs,
    tx_sequence_len,
)

"""Compact (transaction) share splitting and merging.

Transactions in the TRANSACTION_NAMESPACE / PAY_FOR_BLOB_NAMESPACE are
varint-length-prefixed and written continuously across shares.  Every compact
share carries 4 "reserved bytes": the in-share byte index of the start of the
first unit that *starts* in the share, or 0 (specs/src/specs/shares.md
"Transaction Shares").
"""

from __future__ import annotations

from celestia_app_tpu.constants import (
    COMPACT_SHARE_RESERVED_BYTES,
    CONTINUATION_COMPACT_SHARE_CONTENT_SIZE,
    FIRST_COMPACT_SHARE_CONTENT_SIZE,
    NAMESPACE_SIZE,
    SEQUENCE_LEN_BYTES,
    SHARE_INFO_BYTES,
    SHARE_SIZE,
    SHARE_VERSION_ZERO,
)
from celestia_app_tpu.shares.namespace import Namespace
from celestia_app_tpu.shares.share import Share, _build_prefix, shares_needed

_FIRST_DATA_OFFSET = (
    NAMESPACE_SIZE + SHARE_INFO_BYTES + SEQUENCE_LEN_BYTES + COMPACT_SHARE_RESERVED_BYTES
)  # 38
_CONT_DATA_OFFSET = NAMESPACE_SIZE + SHARE_INFO_BYTES + COMPACT_SHARE_RESERVED_BYTES  # 34


def write_uvarint(n: int) -> bytes:
    """Protobuf unsigned varint encoding."""
    if n < 0:
        raise ValueError("uvarint must be non-negative")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def read_uvarint(buf: bytes, pos: int) -> tuple[int, int]:
    """Returns (value, new_pos)."""
    shift = 0
    value = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated uvarint")
        b = buf[pos]
        pos += 1
        value |= (b & 0x7F) << shift
        if not b & 0x80:
            return value, pos
        shift += 7
        if shift > 63:
            raise ValueError("uvarint overflow")


def split_txs(txs: list[bytes], namespace: Namespace) -> list[Share]:
    """Split length-prefixed txs into one compact share sequence."""
    if not txs:
        return []
    # Sequence data = concat(uvarint(len(tx)) || tx); record unit start offsets.
    data = bytearray()
    unit_starts: list[int] = []
    for tx in txs:
        unit_starts.append(len(data))
        data += write_uvarint(len(tx))
        data += tx
    seq_len = len(data)

    # Chunk the sequence data into share content regions.
    chunks: list[bytes] = []
    chunk_ranges: list[tuple[int, int]] = []  # [start, end) in sequence coords
    pos = 0
    size = FIRST_COMPACT_SHARE_CONTENT_SIZE
    while pos < seq_len:
        chunks.append(bytes(data[pos : pos + size]))
        chunk_ranges.append((pos, min(pos + size, seq_len)))
        pos += size
        size = CONTINUATION_COMPACT_SHARE_CONTENT_SIZE

    shares: list[Share] = []
    starts_iter = iter(unit_starts)
    next_start = next(starts_iter, None)
    for i, (chunk, (lo, hi)) in enumerate(zip(chunks, chunk_ranges)):
        first = i == 0
        # Reserved bytes: in-share index of the first unit starting in [lo, hi).
        while next_start is not None and next_start < lo:
            next_start = next(starts_iter, None)
        data_off = _FIRST_DATA_OFFSET if first else _CONT_DATA_OFFSET
        if next_start is not None and lo <= next_start < hi:
            reserved = data_off + (next_start - lo)
        else:
            reserved = 0
        buf = _build_prefix(namespace, SHARE_VERSION_ZERO, first, seq_len if first else None)
        buf += int(reserved).to_bytes(COMPACT_SHARE_RESERVED_BYTES, "big")
        buf += chunk
        buf += bytes(SHARE_SIZE - len(buf))
        shares.append(Share(bytes(buf)))
    return shares


def parse_compact_shares(shares: list[Share]) -> list[bytes]:
    """Inverse of split_txs: recover the tx list from a compact share run."""
    if not shares:
        return []
    first = shares[0]
    if not first.is_sequence_start():
        raise ValueError("first compact share must be a sequence start")
    ns = first.namespace()
    seq_len = first.sequence_len()
    data = bytearray(first.data())
    for i, s in enumerate(shares[1:], start=1):
        if s.is_sequence_start():
            raise ValueError(f"unexpected sequence start in compact share {i}")
        if s.namespace() != ns:
            raise ValueError(f"namespace changed mid-sequence at compact share {i}")
        data += s.data()
    if len(data) < seq_len:
        raise ValueError(
            f"compact share run truncated: sequence length {seq_len}, got {len(data)} bytes"
        )
    buf = bytes(data[:seq_len])
    txs: list[bytes] = []
    pos = 0
    while pos < len(buf):
        ln, pos = read_uvarint(buf, pos)
        if pos + ln > len(buf):
            raise ValueError("truncated tx in compact shares")
        txs.append(buf[pos : pos + ln])
        pos += ln
    return txs


def compact_shares_needed(total_prefixed_bytes: int) -> int:
    """Shares needed for a sequence of total_prefixed_bytes (incl. varints)."""
    return shares_needed(
        total_prefixed_bytes,
        FIRST_COMPACT_SHARE_CONTENT_SIZE,
        CONTINUATION_COMPACT_SHARE_CONTENT_SIZE,
    )


def tx_sequence_len(txs: list[bytes]) -> int:
    return sum(len(write_uvarint(len(t))) + len(t) for t in txs)

"""Sparse (blob) share splitting and merging.

A blob is written to one share sequence: the first share carries the
sequence-start flag and the blob length; continuation shares carry only raw
data; the final share is zero-padded (specs/src/specs/shares.md "Share
Splitting").
"""

from __future__ import annotations

from dataclasses import dataclass, field

from celestia_app_tpu.constants import (
    CONTINUATION_SPARSE_SHARE_CONTENT_SIZE,
    FIRST_SPARSE_SHARE_CONTENT_SIZE,
    SHARE_SIZE,
    SHARE_VERSION_ZERO,
)
from celestia_app_tpu.shares.namespace import Namespace
from celestia_app_tpu.shares.share import (
    Share,
    _build_prefix,
    namespace_padding_shares,
    shares_needed,
)


@dataclass(frozen=True)
class Blob:
    """User data bound to exactly one namespace."""

    namespace: Namespace
    data: bytes
    share_version: int = SHARE_VERSION_ZERO

    def __post_init__(self) -> None:
        if self.share_version != SHARE_VERSION_ZERO:
            raise ValueError(f"unsupported share version {self.share_version}")
        if len(self.data) == 0:
            raise ValueError("blob data must not be empty")

    def share_count(self) -> int:
        return sparse_shares_needed(len(self.data))

    def compare(self, other: "Blob") -> int:
        a, b = self.namespace.to_bytes(), other.namespace.to_bytes()
        return (a > b) - (a < b)


def sparse_shares_needed(blob_len: int) -> int:
    """Number of shares a blob of blob_len bytes occupies."""
    return shares_needed(
        blob_len, FIRST_SPARSE_SHARE_CONTENT_SIZE, CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
    )


def split_blob(blob: Blob) -> list[Share]:
    """Split one blob into its share sequence.

    Vectorized: all continuation shares are one numpy reshape over the
    blob bytes instead of a per-share Python loop — share splitting is
    the square builder's dominant HOST cost at big squares (measured
    ~10s per k=512 block byte-by-byte, which alone would eat most of the
    15 s block budget on a TPU where the device half takes ~0.4s)."""
    import numpy as np

    data = blob.data
    n = len(data)
    first_prefix = bytes(
        _build_prefix(blob.namespace, blob.share_version, True, n)
    )
    first_room = SHARE_SIZE - len(first_prefix)
    if n <= first_room:
        buf = first_prefix + data
        return [Share(buf + bytes(SHARE_SIZE - len(buf)))]

    cont_prefix = bytes(
        _build_prefix(blob.namespace, blob.share_version, False, None)
    )
    cont_room = SHARE_SIZE - len(cont_prefix)
    rest = np.frombuffer(data, dtype=np.uint8)[first_room:]
    n_cont = -(-rest.size // cont_room)
    arr = np.zeros((1 + n_cont, SHARE_SIZE), dtype=np.uint8)
    arr[0, : len(first_prefix)] = np.frombuffer(first_prefix, dtype=np.uint8)
    arr[0, len(first_prefix):] = np.frombuffer(
        data[:first_room], dtype=np.uint8
    )
    arr[1:, : len(cont_prefix)] = np.frombuffer(cont_prefix, dtype=np.uint8)
    pad = (-rest.size) % cont_room
    if pad:
        rest = np.concatenate([rest, np.zeros(pad, dtype=np.uint8)])
    arr[1:, len(cont_prefix):] = rest.reshape(n_cont, cont_room)
    share_bytes = arr.tobytes()
    return [
        Share(share_bytes[i * SHARE_SIZE : (i + 1) * SHARE_SIZE])
        for i in range(1 + n_cont)
    ]


class SparseShareSplitter:
    """Accumulates blobs (and namespace padding) into a share list."""

    def __init__(self) -> None:
        self._shares: list[Share] = []

    def write(self, blob: Blob) -> None:
        self._shares.extend(split_blob(blob))

    def write_namespace_padding(self, n: int) -> None:
        """Pad with the namespace of the last written blob (layout invariant)."""
        if n == 0:
            return
        if not self._shares:
            raise ValueError("cannot write namespace padding before any blob")
        self._shares.extend(namespace_padding_shares(self._shares[-1].namespace(), n))

    def export(self) -> list[Share]:
        return list(self._shares)

    def count(self) -> int:
        return len(self._shares)


def parse_sparse_shares(shares: list[Share]) -> list[Blob]:
    """Merge a sorted run of sparse shares back into blobs (inverse of split)."""
    blobs: list[Blob] = []
    i = 0
    while i < len(shares):
        s = shares[i]
        if not s.is_sequence_start():
            raise ValueError(f"share {i} is not a sequence start")
        seq_len = s.sequence_len()
        if seq_len == 0:  # padding share
            i += 1
            continue
        ns = s.namespace()
        version = s.share_version()
        data = bytearray(s.data())
        i += 1
        while len(data) < seq_len:
            if i >= len(shares):
                raise ValueError("share sequence truncated")
            cont = shares[i]
            if cont.is_sequence_start():
                raise ValueError("unexpected sequence start inside sequence")
            if cont.namespace() != ns:
                raise ValueError("namespace changed mid-sequence")
            data += cont.data()
            i += 1
        blobs.append(Blob(ns, bytes(data[:seq_len]), version))
    return blobs

"""Namespaces: 29-byte (1-byte version + 28-byte id) identifiers.

Behavioral parity with the reference namespace spec
(specs/src/specs/namespace.md; go-square/namespace).
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu.constants import (
    NAMESPACE_ID_SIZE,
    NAMESPACE_SIZE,
    NAMESPACE_VERSION_SIZE,
    PARITY_NAMESPACE_BYTES,
)

NAMESPACE_VERSION_ZERO = 0
NAMESPACE_VERSION_MAX = 255
# Version-0 namespace ids must have 18 leading zero bytes; 10 user bytes remain.
NAMESPACE_VERSION_ZERO_PREFIX_LEN = 18
NAMESPACE_VERSION_ZERO_ID_SIZE = NAMESPACE_ID_SIZE - NAMESPACE_VERSION_ZERO_PREFIX_LEN  # 10


@dataclass(frozen=True, order=False)
class Namespace:
    """An immutable 29-byte namespace (version byte + 28-byte id)."""

    version: int
    id: bytes

    def __post_init__(self) -> None:
        if not 0 <= self.version <= NAMESPACE_VERSION_MAX:
            raise ValueError(f"namespace version out of range: {self.version}")
        if len(self.id) != NAMESPACE_ID_SIZE:
            raise ValueError(
                f"namespace id must be {NAMESPACE_ID_SIZE} bytes, got {len(self.id)}"
            )

    # --- constructors -----------------------------------------------------
    @staticmethod
    def from_bytes(raw: bytes) -> "Namespace":
        if len(raw) != NAMESPACE_SIZE:
            raise ValueError(f"namespace must be {NAMESPACE_SIZE} bytes, got {len(raw)}")
        return Namespace(raw[0], bytes(raw[NAMESPACE_VERSION_SIZE:]))

    @staticmethod
    def v0(sub_id: bytes) -> "Namespace":
        """Build a user-specifiable version-0 namespace from <=10 user bytes."""
        if len(sub_id) > NAMESPACE_VERSION_ZERO_ID_SIZE:
            raise ValueError(
                f"version-0 sub-id too long: {len(sub_id)} > {NAMESPACE_VERSION_ZERO_ID_SIZE}"
            )
        padded = bytes(NAMESPACE_ID_SIZE - len(sub_id)) + sub_id
        return Namespace(NAMESPACE_VERSION_ZERO, padded)

    # --- encoding ---------------------------------------------------------
    def to_bytes(self) -> bytes:
        return bytes([self.version]) + self.id

    def __bytes__(self) -> bytes:  # pragma: no cover - convenience
        return self.to_bytes()

    # --- ordering (lexicographic over the 29 encoded bytes) ---------------
    def __lt__(self, other: "Namespace") -> bool:
        return self.to_bytes() < other.to_bytes()

    def __le__(self, other: "Namespace") -> bool:
        return self.to_bytes() <= other.to_bytes()

    def __gt__(self, other: "Namespace") -> bool:
        return self.to_bytes() > other.to_bytes()

    def __ge__(self, other: "Namespace") -> bool:
        return self.to_bytes() >= other.to_bytes()

    # --- classification ---------------------------------------------------
    def is_reserved(self) -> bool:
        return self.is_primary_reserved() or self.is_secondary_reserved()

    def is_primary_reserved(self) -> bool:
        return self <= MAX_PRIMARY_RESERVED_NAMESPACE

    def is_secondary_reserved(self) -> bool:
        return self >= MIN_SECONDARY_RESERVED_NAMESPACE

    def is_parity(self) -> bool:
        return self == PARITY_SHARE_NAMESPACE

    def is_tail_padding(self) -> bool:
        return self == TAIL_PADDING_NAMESPACE

    def is_pay_for_blob(self) -> bool:
        return self == PAY_FOR_BLOB_NAMESPACE

    def is_compact(self) -> bool:
        """Compact (tx/PFB) namespaces carry reserved bytes in their shares."""
        return self.is_tx() or self.is_pay_for_blob()

    def is_tx(self) -> bool:
        return self == TRANSACTION_NAMESPACE

    def is_supported_user_namespace(self) -> bool:
        """True iff a user may submit blobs under this namespace."""
        return (
            self.version == NAMESPACE_VERSION_ZERO
            and self.id[:NAMESPACE_VERSION_ZERO_PREFIX_LEN]
            == bytes(NAMESPACE_VERSION_ZERO_PREFIX_LEN)
            and not self.is_reserved()
        )

    def validate_for_blob(self) -> None:
        if not self.is_supported_user_namespace():
            raise ValueError(f"invalid user blob namespace: {self.to_bytes().hex()}")

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Namespace(0x{self.to_bytes().hex()})"


def _primary(last_byte: int) -> Namespace:
    return Namespace(0, bytes(NAMESPACE_ID_SIZE - 1) + bytes([last_byte]))


def _secondary(last_byte: int) -> Namespace:
    return Namespace(0xFF, bytes([0xFF] * (NAMESPACE_ID_SIZE - 1)) + bytes([last_byte]))


# Reserved namespaces (specs/src/specs/namespace.md "Reserved Namespaces").
TRANSACTION_NAMESPACE = _primary(0x01)
INTERMEDIATE_STATE_ROOT_NAMESPACE = _primary(0x02)
PAY_FOR_BLOB_NAMESPACE = _primary(0x04)
PRIMARY_RESERVED_PADDING_NAMESPACE = _primary(0xFF)
MAX_PRIMARY_RESERVED_NAMESPACE = _primary(0xFF)
MIN_SECONDARY_RESERVED_NAMESPACE = _secondary(0x00)
TAIL_PADDING_NAMESPACE = _secondary(0xFE)
PARITY_SHARE_NAMESPACE = _secondary(0xFF)

PARITY_NS_BYTES = PARITY_SHARE_NAMESPACE.to_bytes()
if PARITY_NS_BYTES != PARITY_NAMESPACE_BYTES:
    raise AssertionError("PARITY_SHARE_NAMESPACE diverged from constants.PARITY_NAMESPACE_BYTES")

"""blocktime: block interval statistics (reference tools/blocktime).

Computes the interval distribution over a window of block timestamps
(tools/blocktime/main.go:14 pulls them over RPC; here they come from the
node's recorded times or any list of nanosecond timestamps).
"""

from __future__ import annotations


def interval_stats(block_times_ns: list[int]) -> dict:
    if len(block_times_ns) < 2:
        return {"blocks": len(block_times_ns), "intervals": 0}
    intervals = [
        (b - a) / 1e9 for a, b in zip(block_times_ns, block_times_ns[1:])
    ]
    intervals_sorted = sorted(intervals)
    n = len(intervals)
    return {
        "blocks": len(block_times_ns),
        "intervals": n,
        "mean_s": sum(intervals) / n,
        "min_s": intervals_sorted[0],
        "max_s": intervals_sorted[-1],
        "p50_s": intervals_sorted[n // 2],
        "p95_s": intervals_sorted[min(n - 1, int(n * 0.95))],
    }

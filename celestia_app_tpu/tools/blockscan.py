"""blockscan: decode and summarize blocks (reference tools/blockscan).

Walks produced blocks, classifying every tx (normal / BlobTx), decoding
messages, and reporting square stats — the debugging lens the reference
points at a live RPC (tools/blockscan/main.go:19), here pointed at an
in-process node or a list of BlockData.
"""

from __future__ import annotations

from celestia_app_tpu.tx.envelopes import unmarshal_blob_tx
from celestia_app_tpu.tx.sign import Tx


def scan_block(data) -> dict:
    """Summarize one BlockData."""
    txs = []
    n_blobs = 0
    blob_bytes = 0
    for raw in data.txs:
        btx = unmarshal_blob_tx(raw)
        if btx is not None:
            n_blobs += len(btx.blobs)
            blob_bytes += sum(len(b.data) for b in btx.blobs)
            kind = "blob"
            inner = btx.tx
        else:
            kind = "normal"
            inner = raw
        try:
            msgs = [type(m).__name__ for m in Tx.unmarshal(inner).msgs()]
        except ValueError:
            msgs = ["<undecodable>"]
        txs.append({"kind": kind, "msgs": msgs, "bytes": len(raw)})
    return {
        "square_size": data.square_size,
        "data_root": data.hash.hex(),
        "n_txs": len(data.txs),
        "n_blobs": n_blobs,
        "blob_bytes": blob_bytes,
        "txs": txs,
    }


def scan(blocks) -> list[dict]:
    return [scan_block(b) for b in blocks]

"""Leopard-construction systematic RS: the reference-parity codec attempt.

The reference pins `rsmt2d.NewLeoRSCodec` (pkg/appconsts/global_consts.go:92),
the leopard additive-FFT Reed-Solomon code (klauspost/reedsolomon leopard8/
leopard16). Structurally, leopard's systematic encode with k data and k
parity shards is:

  * fix the additive-FFT evaluation grid  omega[i] = XOR of basis[j] over
    the set bits j of i,  where `basis` is a Cantor basis of GF(2^m);
  * the data shards are the values of the unique degree-<k polynomial at
    the HIGH half of the grid (omega[k..2k)) — the IFFT step interpolates
    them there;
  * parity shards are that polynomial's values at the LOW half
    (omega[0..k)) — the FFT step evaluates there.

That mapping (interpolate-high, evaluate-low) makes the code a plain GF
matrix seam: G = V[low] @ inv(V[high]) over the omega grid, which this
module derives exactly (Vandermonde + Gaussian inverse — no butterflies
needed; the FFT is only leopard's *fast algorithm* for the same linear
map). The device kernel consumes G as data, so the construction slots into
kernels/rs.py with zero structural change.

What is pinned vs unverifiable IN THIS IMAGE (no Go toolchain, no leopard
source anywhere on disk — see PARITY.md "Leopard parity" for the full
audit):

  pinned (high confidence):
    * the interpolate-high/evaluate-low systematic layout and the
      omega-grid enumeration by binary index;
    * GF(2^8) polynomial 0x11D (shared by leopard8 and this repo's field);
    * MDS-ness, systematic-ness, and the constant-share degeneracy that
      the reference golden DAH vectors exercise (tests).
  unverifiable in-image (flagged, overridable via module constants):
    * the exact Cantor basis constants leopard hardcodes (we derive a
      canonical basis deterministically instead — recurrence
      b_{j+1}^2 + b_{j+1} = b_j from b_0 = 1, smallest root each step,
      which is *a* Cantor basis but not provably *leopard's*);
    * GF(2^16) polynomial (0x1002D believed, not confirmable here);
    * the bit-order of the index -> basis-element map.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

from celestia_app_tpu.gf.field import GF, _field

# Field polynomials for the leopard construction. ff8's 0x11D is shared
# with this repo's default GF(2^8). ff16's is believed to be 0x1002D
# (x^16+x^5+x^3+x^2+1) — unverifiable in-image; override here if the true
# constant is ever confirmed to differ.
LEOPARD_POLY = {8: 0x11D, 16: 0x1002D}

# Set to a tuple of ints to force the exact basis (e.g. once leopard's
# hardcoded kCantorBasis constants can be confirmed); None derives the
# canonical basis below.
FORCED_CANTOR_BASIS: dict[int, tuple[int, ...] | None] = {8: None, 16: None}


def leopard_field(m: int) -> GF:
    return _field(m, LEOPARD_POLY[m])


def _solve_artin_schreier(f: GF, c: int) -> int:
    """Smallest x with x^2 + x == c, or -1 if none (Tr(c) == 1)."""
    xs = np.arange(f.order, dtype=np.uint32)
    sq = f.mul(xs, xs).astype(np.uint32) ^ xs
    hits = np.where(sq == c)[0]
    return int(hits[0]) if hits.size else -1


@lru_cache(maxsize=None)
def cantor_basis(m: int) -> tuple[int, ...]:
    """A canonical Cantor basis of GF(2^m): b_0 = 1, and b_{j+1} is the
    smallest solution of x^2 + x = b_j. Valid for m a power of two (trace
    conditions hold down the chain); each step has two roots (x, x+1) —
    'smallest' is this module's deterministic tie-break.
    """
    forced = FORCED_CANTOR_BASIS.get(m)
    if forced is not None:
        return forced
    f = leopard_field(m)
    basis = [1]
    for _ in range(m - 1):
        nxt = _solve_artin_schreier(f, basis[-1])
        if nxt < 0:
            raise ValueError(f"Cantor chain broke at {basis[-1]:#x} in GF(2^{m})")
        basis.append(nxt)
    return tuple(basis)


def eval_grid(m: int, n: int) -> np.ndarray:
    """omega[0..n): omega[i] = XOR of basis[j] for each set bit j of i."""
    basis = cantor_basis(m)
    r = max(1, (n - 1).bit_length())
    if r > len(basis):
        raise ValueError(f"grid of {n} points needs {r} basis elements in GF(2^{m})")
    idx = np.arange(n, dtype=np.uint32)
    omega = np.zeros(n, dtype=np.uint32)
    for j in range(r):
        omega ^= np.where((idx >> j) & 1, basis[j], 0).astype(np.uint32)
    return omega


def leopard_points(k: int, field: GF) -> np.ndarray:
    """Evaluation points for RSCodec's share layout under the leopard map.

    RSCodec indexes shares data-first (0..k-1 data, k..2k-1 parity);
    leopard places data on the grid's high half and parity on the low half,
    so share i < k maps to omega[k+i] and parity share p to omega[p].
    """
    omega = eval_grid(field.m, 2 * k)
    return np.concatenate([omega[k:], omega[:k]]).astype(field.dtype)

"""Systematic Reed-Solomon over GF(2^8)/GF(2^16): the rsmt2d codec seam.

Mirrors the capability surface of `rsmt2d.Codec` (reference
pkg/appconsts/global_consts.go:92 selects rsmt2d.NewLeoRSCodec): encode k data
shares to k parity shares, and decode the full codeword from any k of the 2k
shares.  Field selection follows leopard's rule: codewords of <= 256 symbols
use GF(2^8) (square size k <= 128), wider codewords use GF(2^16)
(k in {256, 512}).

Construction (fully specified, deterministic - consensus-critical):
  * evaluation points are the field elements 0, 1, ..., 2k-1;
  * data share i holds the codeword values at point i, parity share p the
    values at point k+p, of the unique degree-<k interpolating polynomial;
  * parity generator  G = V[k:2k] @ inv(V[0:k])  (k x k over GF);
  * GF(2^16) symbols are little-endian byte pairs within a share.

Everything here is host-side numpy: the encode oracle for tests, and the
constant matrices that the JAX kernel (kernels/rs.py) bit-expands onto the
MXU.  MDS: any k x k minor of the 2k x k Vandermonde at distinct points is
invertible, so any k surviving shares determine the codeword.
"""

from __future__ import annotations

import os
from functools import lru_cache

import numpy as np

from celestia_app_tpu.gf.field import GF, _field


def field_for_width(codeword_width: int) -> GF:
    """Field used for a codeword of `codeword_width` total shares (2k)."""
    if codeword_width <= 256:
        return _field(8)
    if codeword_width <= 65536:
        return _field(16)
    raise ValueError(f"codeword too wide: {codeword_width}")


class RSCodec:
    """Systematic RS codec for a fixed number of data shares k.

    `construction` selects the evaluation-point layout (and field poly):
      * "vandermonde" — this repo's fully-specified default: data at points
        0..k-1, parity at k..2k-1, repo field polynomials;
      * "leopard" — the reference-parity attempt (gf/leopard.py): the
        additive-FFT omega grid with data on its high half, leopard field
        polynomials. Same MDS/systematic surface, different parity bytes.
    Both constructions share every code path below — only `points` and
    `field` differ, and the device kernel consumes the resulting generator
    as data.
    """

    def __init__(self, k: int, construction: str = "vandermonde"):
        if k < 1 or k & (k - 1):
            raise ValueError(f"k must be a power of two, got {k}")
        self.k = k
        self.construction = construction
        if construction == "leopard":
            from celestia_app_tpu.gf.leopard import leopard_field, leopard_points

            self.field = leopard_field(8 if 2 * k <= 256 else 16)
            points = leopard_points(k, self.field)
        elif construction == "vandermonde":
            self.field = field_for_width(2 * k)
            points = np.arange(2 * k, dtype=np.uint32).astype(self.field.dtype)
        else:
            raise ValueError(f"unknown RS construction {construction!r}")
        f = self.field
        V = f.vandermonde(points, k)  # (2k, k)
        self._v_all = V
        self.generator = f.matmul(V[k:], f.inv_matrix(V[:k]))  # (k, k)

    # --- symbol <-> byte packing -----------------------------------------
    def to_symbols(self, shares: np.ndarray) -> np.ndarray:
        """(n, share_size) uint8 -> (n, share_size/bytes_per_symbol) field dtype."""
        shares = np.asarray(shares, dtype=np.uint8)
        if self.field.m == 8:
            return shares
        assert shares.shape[-1] % 2 == 0
        return shares.view("<u2")

    def from_symbols(self, symbols: np.ndarray) -> np.ndarray:
        if self.field.m == 8:
            return np.asarray(symbols, dtype=np.uint8)
        return np.asarray(symbols, dtype="<u2").view(np.uint8)

    # --- codec surface (rsmt2d.Codec parity) ------------------------------
    def encode(self, data_shares: np.ndarray) -> np.ndarray:
        """(k, share_size) uint8 data -> (k, share_size) uint8 parity."""
        data = np.asarray(data_shares, dtype=np.uint8)
        assert data.shape[0] == self.k, data.shape
        sym = self.to_symbols(data)
        parity = self.field.matmul(self.generator, sym)
        return self.from_symbols(parity)

    def extend(self, data_shares: np.ndarray) -> np.ndarray:
        """(k, s) -> (2k, s): data followed by parity (systematic layout)."""
        data = np.asarray(data_shares, dtype=np.uint8)
        return np.concatenate([data, self.encode(data)], axis=0)

    def recover_matrix(self, known_positions: np.ndarray) -> np.ndarray:
        """(2k, k) GF matrix R with full_codeword = R @ codeword[known[:k]].

        `known_positions` must list >= k distinct positions in [0, 2k); the
        first k are used.  This is the erasure-decode as a constant matmul -
        the same shape the TPU repair kernel consumes.
        """
        pos = np.asarray(known_positions, dtype=np.int64)[: self.k]
        if len(pos) < self.k:
            raise ValueError(f"need >= {self.k} shares to decode, got {len(pos)}")
        f = self.field
        V_known = self._v_all[pos]  # (k, k)
        return f.matmul(self._v_all, f.inv_matrix(V_known))  # (2k, k)

    def decode(self, shares: np.ndarray, present: np.ndarray) -> np.ndarray:
        """Reconstruct all 2k shares.

        shares: (2k, share_size) uint8 with arbitrary content at missing rows;
        present: (2k,) bool mask of available shares.
        Mirrors rsmt2d.ExtendedDataSquare.Repair's per-axis decode.
        """
        shares = np.asarray(shares, dtype=np.uint8)
        present = np.asarray(present, dtype=bool)
        known = np.where(present)[0]
        R = self.recover_matrix(known)
        sym = self.to_symbols(shares[known[: self.k]])
        return self.from_symbols(self.field.matmul(R, sym))

    # --- device lowering --------------------------------------------------
    def generator_bits(self) -> np.ndarray:
        """Bit-expanded generator: (k*m, k*m) uint8 in {0,1} for the MXU."""
        return self.field.expand_bit_matrix(self.generator)

    def extend_bits(self) -> np.ndarray:
        """Bit-expanded [I; G]: (2k*m, k*m) - one matmul yields the full
        extended column, handy for the fused column phase."""
        full = np.concatenate(
            [np.eye(self.k, dtype=self.field.dtype), self.generator], axis=0
        )
        return self.field.expand_bit_matrix(full)


@lru_cache(maxsize=None)
def _codec_cached(k: int, construction: str) -> RSCodec:
    return RSCodec(k, construction)


def active_construction() -> str:
    """The process-wide RS construction selected by $CELESTIA_RS_CONSTRUCTION.

    Every cached device program that bakes a generator in (da/eds.py,
    da/repair.py, kernels/rs.py, parallel/sharded_*.py) keys its cache on
    this value, so flipping the env var mid-process selects a different
    cache entry instead of silently serving stale compiles (the round-3
    nondeterministic RootMismatch hazard)."""
    return os.environ.get("CELESTIA_RS_CONSTRUCTION", "vandermonde")


def codec_for_width(k: int, construction: str | None = None) -> RSCodec:
    """Cached codec for square size k (codewords are 2k wide).

    `construction` defaults to $CELESTIA_RS_CONSTRUCTION (or "vandermonde").
    """
    if construction is None:
        construction = active_construction()
    return _codec_cached(k, construction)

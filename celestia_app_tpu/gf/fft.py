"""Additive (LCH) FFT over GF(2^m): leopard's O(n log n) evaluation algorithm.

The reference pins `rsmt2d.NewLeoRSCodec` (pkg/appconsts/global_consts.go:92),
whose encode is the Lin-Chung-Han additive FFT ("Novel Polynomial Basis and
Its Application to Reed-Solomon Erasure Codes", FFT butterflies over the
subspace polynomial basis) as implemented by klauspost/reedsolomon's leopard
ports.  This module is the host reference for that algorithm, parameterized
by the subspace basis so BOTH of this repo's RS constructions ride it:

  * leopard construction — basis = gf/leopard.cantor_basis; data shares sit
    on the grid's high coset (shift b_K), parity on the low (shift 0);
  * vandermonde construction — basis = (1, 2, 4, ..): the evaluation points
    0..2k-1 ARE that basis's subspace enumeration (omega_i == i), data on
    the low half (shift 0), parity on the high coset (shift k).

Correctness contract (pinned by tests/test_fft.py): for every k and both
constructions, `encode_fft` reproduces RSCodec.encode — the generator
matmul G = V_parity @ inv(V_data) — bit for bit.  The FFT is the same
linear map computed in O(n log n) butterflies instead of O(n^2) dot
products; kernels/fft.py lowers the butterfly stages to batched bit-matmul
groups for the MXU.

Machinery (FNT-paper notation):

  W_j(x)  = prod_{v in span(b_0..b_{j-1})} (x + v)     subspace vanishing
            polynomial — GF(2)-linearized, so W_j(x+y) = W_j(x) + W_j(y);
  What_j  = W_j / W_j(b_j)                              normalized;
  stage-j butterfly between a[i] and a[i+2^j] with twiddle
  w = What_j(omega_block + shift):
      FFT   (coeffs -> values, stages j = r-1 .. 0):
          a[i]     ^= w * a[i+d];   a[i+d] ^= a[i]
      IFFT  (values -> coeffs, stages j = 0 .. r-1):
          a[i+d]   ^= a[i];         a[i]   ^= w * a[i+d]
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

from celestia_app_tpu.gf.field import GF


@lru_cache(maxsize=None)
def _subspace_table(field: GF, basis: tuple[int, ...]) -> np.ndarray:
    """T[j][i] = W_j(basis[i]) for j <= i < r (zero for i < j).

    Recurrence: W_0(x) = x and W_{j+1}(x) = W_j(x) * (W_j(x) + W_j(b_j)),
    since W_{j+1}(x) = W_j(x) * W_j(x + b_j) and W_j is linearized.
    """
    r = len(basis)
    T = np.zeros((r + 1, r), dtype=np.uint32)
    T[0, :] = np.asarray(basis, dtype=np.uint32)
    for j in range(r):
        pivot = T[j, j]
        for i in range(j + 1, r):
            T[j + 1, i] = int(field.mul(T[j, i], T[j, i] ^ pivot))
    return T


def _w_eval(field: GF, basis: tuple[int, ...], j: int, x: int) -> int:
    """W_j(x) for an arbitrary field element x: product over the 2^j
    subspace elements (used only for coset shifts; grid points go through
    the linear table)."""
    out = 1
    for v_idx in range(1 << j):
        v = 0
        for b in range(j):
            if (v_idx >> b) & 1:
                v ^= basis[b]
        out = int(field.mul(out, x ^ v))
    return out


def stage_twiddles(
    field: GF, basis: tuple[int, ...], r: int, j: int, shift: int
) -> np.ndarray:
    """What_j at every stage-j block base point (+ coset shift).

    Returns (n / 2^{j+1},) GF elements: entry t is
    What_j(omega_{t * 2^{j+1}} + shift), the constant twiddle of block t.
    """
    T = _subspace_table(field, tuple(basis))
    norm_inv = int(field.inv(T[j, j]))
    w_shift = _w_eval(field, tuple(basis), j, shift) if shift else 0
    n_blocks = 1 << (r - j - 1)
    out = np.zeros(n_blocks, dtype=np.uint32)
    for t in range(n_blocks):
        w = w_shift
        for b in range(j + 1, r):  # block base has bits only at j+1..r-1
            if (t >> (b - j - 1)) & 1:
                w ^= int(T[j, b])
        out[t] = int(field.mul(w, norm_inv))
    return out.astype(field.dtype)


def fft(field: GF, basis, a: np.ndarray, shift: int = 0) -> np.ndarray:
    """Evaluate novel-basis coefficients a[0..n) at span(basis[:r]) + shift.

    a: (n, ...) GF symbols, n = 2^r a power of two; returns same shape.
    """
    a = np.array(a, dtype=np.uint32, copy=True)
    n = a.shape[0]
    r = n.bit_length() - 1
    assert 1 << r == n, f"transform size {n} not a power of two"
    basis = tuple(basis)
    for j in range(r - 1, -1, -1):
        d = 1 << j
        tw = stage_twiddles(field, basis, r, j, shift)
        for t in range(n >> (j + 1)):
            base = t << (j + 1)
            u = a[base : base + d]
            v = a[base + d : base + 2 * d]
            w = int(tw[t])
            if w:
                u ^= field.mul(w, v).astype(np.uint32)
            v ^= u
    return a.astype(field.dtype)


def ifft(field: GF, basis, a: np.ndarray, shift: int = 0) -> np.ndarray:
    """Inverse of `fft`: values at span(basis[:r]) + shift -> coefficients."""
    a = np.array(a, dtype=np.uint32, copy=True)
    n = a.shape[0]
    r = n.bit_length() - 1
    assert 1 << r == n, f"transform size {n} not a power of two"
    basis = tuple(basis)
    for j in range(r):
        d = 1 << j
        tw = stage_twiddles(field, basis, r, j, shift)
        for t in range(n >> (j + 1)):
            base = t << (j + 1)
            u = a[base : base + d]
            v = a[base + d : base + 2 * d]
            v ^= u
            w = int(tw[t])
            if w:
                u ^= field.mul(w, v).astype(np.uint32)
    return a.astype(field.dtype)


def encode_params(codec) -> tuple[GF, tuple[int, ...], int, int]:
    """(field, k-point basis, data coset shift, parity coset shift) for an
    RSCodec — the FFT-encode description of its construction."""
    k = codec.k
    K = k.bit_length() - 1
    if codec.construction == "leopard":
        from celestia_app_tpu.gf.leopard import cantor_basis

        basis = cantor_basis(codec.field.m)
        data_shift = basis[K] if k > 1 else basis[0]
        return codec.field, tuple(basis[:K]), data_shift, 0
    if codec.construction == "vandermonde":
        basis = tuple(1 << i for i in range(max(K, 1)))
        return codec.field, basis[:K], 0, k
    raise ValueError(f"no FFT description for construction {codec.construction!r}")


def encode_fft(codec, data_symbols: np.ndarray) -> np.ndarray:
    """Systematic encode via IFFT(data coset) -> FFT(parity coset).

    data_symbols: (k, ...) GF symbols; returns (k, ...) parity symbols,
    identical to codec.field.matmul(codec.generator, data_symbols).
    """
    field, basis, data_shift, parity_shift = encode_params(codec)
    coeffs = ifft(field, basis, data_symbols, data_shift)
    return fft(field, basis, coeffs, parity_shift)

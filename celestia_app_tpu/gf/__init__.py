"""Finite-field arithmetic and Reed-Solomon codecs for the data square.

Replaces the reference's `rsmt2d` + klauspost/reedsolomon leopard codec
(selected at reference pkg/appconsts/global_consts.go:92) with a TPU-first
design: the systematic RS encode is a constant generator matrix over
GF(2^8) (codewords <= 256 symbols wide, i.e. square size k <= 128) or
GF(2^16) (k in {256, 512}), applied as a *binary* bit-matmul on the MXU.

Layout of this package:
  field.py  - GF(2^m) table arithmetic + linear algebra (numpy, host side)
  rs.py     - systematic RS codec: generator matrices, encode/decode oracle
"""

from celestia_app_tpu.gf.field import GF, GF8, GF16
from celestia_app_tpu.gf.rs import RSCodec, codec_for_width

__all__ = ["GF", "GF8", "GF16", "RSCodec", "codec_for_width"]

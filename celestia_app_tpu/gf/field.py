"""GF(2^m) arithmetic over numpy arrays (host-side reference + matrix setup).

The TPU kernels never execute table lookups: every GF operation that reaches
the device is first lowered here to a constant binary matrix (multiplication
by a field constant is GF(2)-linear on the bit vector), so the device work is
a plain 0/1 matmul.  This module provides:

  * exp/log table arithmetic for GF(2^8) (poly 0x11D) and GF(2^16)
    (poly 0x1100B) - used to build generator matrices and as a CPU oracle;
  * vectorized GF matrix multiply / Gaussian inverse (for erasure decode);
  * `mul_bit_matrix`: the m x m GF(2) matrix of "multiply by constant c",
    the building block of the device-side bit-expanded generator.

Parity notes vs the reference stack: rsmt2d's default codec is leopard
(FFT RS); its parity bytes are one fixed linear code among many MDS codes.
We use the classic systematic evaluation-point construction (data = values at
points 0..k-1, parity = values at points k..2k-1 of the unique interpolating
polynomial), which is MDS by the Vandermonde argument and fully determined by
this spec - the determinism contract (SURVEY P1) is what consensus needs.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

_PRIM_POLY = {8: 0x11D, 16: 0x1100B}


def _clmul_mod(a: int, b: int, m: int, poly: int) -> int:
    """Carry-less multiply mod poly — table-free bootstrap multiply."""
    prod = 0
    while b:
        if b & 1:
            prod ^= a
        a <<= 1
        b >>= 1
    for bit in range(2 * m - 2, m - 1, -1):
        if prod >> bit & 1:
            prod ^= poly << (bit - m)
    return prod


def _pow_mod(a: int, e: int, m: int, poly: int) -> int:
    out = 1
    while e:
        if e & 1:
            out = _clmul_mod(out, a, m, poly)
        a = _clmul_mod(a, a, m, poly)
        e >>= 1
    return out


def _prime_factors(n: int) -> list[int]:
    out, d = [], 2
    while d * d <= n:
        if n % d == 0:
            out.append(d)
            while n % d == 0:
                n //= d
        d += 1
    if n > 1:
        out.append(n)
    return out


def _poly_gcd(a: int, b: int) -> int:
    """GCD of GF(2)[x] polynomials (bitmask representation)."""
    while b:
        while a.bit_length() >= b.bit_length() and a:
            a ^= b << (a.bit_length() - b.bit_length())
        a, b = b, a
    return a


def _is_irreducible(poly: int, m: int) -> bool:
    """Degree-m poly irreducible over GF(2): x^(2^m) == x mod poly AND
    gcd(x^(2^(m/p)) + x, poly) == 1 for every prime p | m (the Frobenius
    condition alone also accepts squarefree products of smaller factors)."""
    t = 2
    for _ in range(m):
        t = _clmul_mod(t, t, m, poly)
    if t != 2:
        return False
    for p in _prime_factors(m):
        t = 2
        for _ in range(m // p):
            t = _clmul_mod(t, t, m, poly)
        if _poly_gcd(t ^ 2, poly) != 1:
            return False
    return True


class GF:
    """GF(2^m) with exp/log tables, m in {8, 16}. Elements are numpy uints.

    `poly` defaults to this repo's codec polynomials; pass another
    irreducible polynomial (e.g. leopard ff16's) to get that field. The
    exp/log tables are built on the smallest generator element, so
    non-primitive polynomials whose `x` is not a generator still work.
    """

    def __init__(self, m: int, poly: int | None = None):
        if m not in (8, 16):
            raise ValueError(f"unsupported field GF(2^{m})")
        self.m = m
        self.order = 1 << m
        self.poly = poly if poly is not None else _PRIM_POLY[m]
        self.dtype = np.uint8 if m == 8 else np.uint16
        if not _is_irreducible(self.poly, m):
            raise ValueError(f"0x{self.poly:x} is not irreducible over GF(2)")
        # Smallest generator: order test against the prime factors of 2^m-1.
        n1 = self.order - 1
        factors = _prime_factors(n1)
        for g in range(2, self.order):
            if all(_pow_mod(g, n1 // p, m, self.poly) != 1 for p in factors):
                break
        else:  # unreachable for a field: its unit group is cyclic
            raise ValueError(f"no generator in GF(2^{m})/0x{self.poly:x}")
        # exp table of length 2*(order-1) so exp[log a + log b] needs no mod.
        exp = np.zeros(2 * (self.order - 1), dtype=np.uint32)
        log = np.zeros(self.order, dtype=np.uint32)
        x = 1
        for i in range(n1):
            exp[i] = x
            log[x] = i
            x = _clmul_mod(x, g, m, self.poly)
        exp[n1:] = exp[:n1]
        self.exp = exp
        self.log = log
        # The tables live for the process (lru-cached _field below) —
        # report them to the memory-ownership ledger so the /device
        # residual stays attributable even at GF(2^16) (768 KB each).
        from celestia_app_tpu.trace.device_ledger import note_owned_bytes

        note_owned_bytes(
            "gf_tables", (m, self.poly), int(exp.nbytes) + int(log.nbytes)
        )

    # --- scalar/array ops -------------------------------------------------
    def mul(self, a, b):
        """Elementwise GF multiply (broadcasting)."""
        a = np.asarray(a, dtype=np.uint32)
        b = np.asarray(b, dtype=np.uint32)
        # log sums stay below 2*(order-1): the doubled exp table needs no mod
        out = self.exp[self.log[a] + self.log[b]]
        out = np.where((a == 0) | (b == 0), 0, out)
        return out.astype(self.dtype)

    def inv(self, a):
        a = np.asarray(a, dtype=np.uint32)
        if np.any(a == 0):
            raise ZeroDivisionError("GF inverse of 0")
        return self.exp[(self.order - 1 - self.log[a]) % (self.order - 1)].astype(self.dtype)

    def pow(self, a: int, e: int):
        if a == 0:
            return self.dtype(0 if e else 1)
        return self.dtype(self.exp[(int(self.log[a]) * e) % (self.order - 1)])

    def matmul(self, A: np.ndarray, B: np.ndarray) -> np.ndarray:
        """GF matrix multiply: (n,k) x (k,p) -> (n,p).

        Vectorized over the contraction via table lookups + XOR-reduce.
        """
        A = np.asarray(A, dtype=np.uint32)
        B = np.asarray(B, dtype=np.uint32)
        n, k = A.shape
        k2, p = B.shape
        assert k == k2, (A.shape, B.shape)
        out = np.zeros((n, p), dtype=np.uint32)
        logB = self.log[B]  # (k, p)
        for i in range(k):  # XOR-accumulate one rank-1 GF outer product at a time
            col = A[:, i]  # (n,)
            prod = self.exp[self.log[col][:, None] + logB[i][None, :]]
            prod = np.where((col[:, None] == 0) | (B[i][None, :] == 0), 0, prod)
            out ^= prod
        return out.astype(self.dtype)

    def inv_matrix(self, A: np.ndarray) -> np.ndarray:
        """Gaussian elimination inverse over GF(2^m)."""
        A = np.array(A, dtype=np.uint32)
        n = A.shape[0]
        assert A.shape == (n, n)
        aug = np.concatenate([A, np.eye(n, dtype=np.uint32)], axis=1)
        for col in range(n):
            piv = col + int(np.argmax(aug[col:, col] != 0))
            if aug[piv, col] == 0:
                raise np.linalg.LinAlgError("singular GF matrix")
            if piv != col:
                aug[[col, piv]] = aug[[piv, col]]
            aug[col] = self.mul(aug[col], self.inv(aug[col, col])).astype(np.uint32)
            mask = aug[:, col] != 0
            mask[col] = False
            rows = np.where(mask)[0]
            if rows.size:
                factors = aug[rows, col]
                aug[rows] ^= self.mul(factors[:, None], aug[col][None, :]).astype(np.uint32)
        return aug[:, n:].astype(self.dtype)

    def vandermonde(self, points: np.ndarray, k: int) -> np.ndarray:
        """V[i, j] = points[i]^j, shape (len(points), k)."""
        points = np.asarray(points, dtype=np.uint32)
        V = np.ones((len(points), k), dtype=np.uint32)
        for j in range(1, k):
            V[:, j] = self.mul(V[:, j - 1], points)
        return V.astype(self.dtype)

    # --- bit-expansion (device lowering) ---------------------------------
    def mul_bit_matrix(self, c: int) -> np.ndarray:
        """The m x m GF(2) matrix M_c with bits(c*x) = M_c @ bits(x) mod 2.

        Bit b of a symbol is (x >> b) & 1; column b of M_c is bits(c * 2^b).
        """
        m = self.m
        M = np.zeros((m, m), dtype=np.uint8)
        for b in range(m):
            prod = int(self.mul(c, 1 << b))
            for r in range(m):
                M[r, b] = (prod >> r) & 1
        return M

    def expand_bit_matrix(self, A: np.ndarray) -> np.ndarray:
        """Bit-expand a GF matrix (n,k) -> binary matrix (n*m, k*m).

        (G_bits @ data_bits) mod 2 == bits(G gfmatmul data): the whole GF
        matmul becomes one 0/1 matmul, which is what lands on the MXU.
        """
        A = np.asarray(A, dtype=np.uint32)
        n, k = A.shape
        m = self.m
        out = np.zeros((n * m, k * m), dtype=np.uint8)
        # cache per distinct constant - generator matrices repeat values a lot
        cache: dict[int, np.ndarray] = {}
        for i in range(n):
            for j in range(k):
                c = int(A[i, j])
                if c == 0:
                    continue
                M = cache.get(c)
                if M is None:
                    M = cache[c] = self.mul_bit_matrix(c)
                out[i * m : (i + 1) * m, j * m : (j + 1) * m] = M
        return out


@lru_cache(maxsize=None)
def _field(m: int, poly: int | None = None) -> GF:
    return GF(m, poly)


GF8 = _field(8)
GF16 = _field(16)

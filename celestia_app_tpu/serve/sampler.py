"""ProofSampler: queued DAS sample requests, answered a whole batch per
dispatch.

The read-side twin of the fused->staged seam: two lowerings of "prove
share (row, col) against the committed DAH root", pinned byte-identical:

  batched (default)  the index plan for every queued request is computed
                     host-side (range_proof_node_coords — pure int math),
                     then the whole batch's proof nodes and shares come
                     off the cached forest in ONE gather per array
                     (serve/cache.CachedForest.gather), and RowProof
                     audit paths are indexed out of the memoized
                     data-root tree levels.  Zero hashing per request.
  host (fallback)    rebuild the touched row's NMT from the retained
                     shares (eds.row_tree(host=True)) and re-derive the
                     audit path recursively (merkle.proof) — no forest,
                     no gather, no batch machinery.  Slower, independent,
                     bit-identical.

$CELESTIA_SERVE_MODE pins the lowering ("batched" / "host"); the chaos
seam `proof.serve` ($CELESTIA_CHAOS proof_fail / proof_slow_ms) injects
failures into the batched dispatch, which the sampler absorbs by
answering the SAME batch on the host path — ticking
celestia_recoveries_total{seam="proof.serve"} — so an injected fault
costs latency, never a wrong or missing proof.

Queueing: concurrent `share_proof` callers park on a shared queue; the
first arrival becomes the batch leader, waits $CELESTIA_SERVE_BATCH_MS
(default 0: drain whatever queued), and answers everyone in one
dispatch.  Latency lands on celestia_proof_latency_seconds{phase}:
queue_wait and total per sample, gather and assemble per batch.

Adversary detection (chaos/adversary.py — the ISSUE-10 attack model):

  * a sample landing on a share the WITHHOLDING PROPOSER hid raises
    ShareWithheld — the failed sample IS the light client's detection
    signal (celestia_da_detections_total{kind="withheld"} + the
    `withholding_detected` flight trigger);
  * when an adversary TAMPERS with the served square (malform_shares /
    wrong_root), every assembled proof passes a VERIFICATION GATE
    against the committed data root before leaving the sampler: a proof
    that does not verify raises BadProofDetected
    (kind="bad_proof" + the `root_mismatch` flight trigger) — a
    malformed share or forged root is detected, never served as a valid
    proof.  $CELESTIA_SERVE_VERIFY=1 arms the gate unconditionally
    (paranoid mode); with no adversary configured the gate costs one
    attr read per batch.
"""

from __future__ import annotations

import os
import threading
import time
from functools import lru_cache

from celestia_app_tpu.proof.share_proof import RowProof, ShareProof
from celestia_app_tpu.constants import NAMESPACE_SIZE, PARITY_NAMESPACE_BYTES
from celestia_app_tpu.nmt.proof import (
    NmtRangeProof,
    prove_range_from_levels,
    range_proof_node_coords,
)


class ShareWithheld(LookupError):
    """The sampled share is being withheld from the serve path (a
    data-withholding attack detected by this very sample)."""

    def __init__(self, height: int, row: int, col: int):
        super().__init__(
            f"share ({row},{col}) at height {height} is withheld "
            "(data-availability attack detected)"
        )
        self.height = height
        self.row = row
        self.col = col


class BadProofDetected(ValueError):
    """An assembled proof failed verification against the committed data
    root — a malformed square or wrong-root attack, detected at the
    sampler before any client saw a "valid" proof."""


def serve_mode() -> str:
    """$CELESTIA_SERVE_MODE: "batched" (default) or "host"."""
    return (
        "host"
        if os.environ.get("CELESTIA_SERVE_MODE", "") == "host"
        else "batched"
    )


def batch_window_s() -> float:
    """$CELESTIA_SERVE_BATCH_MS: how long the batch leader waits for more
    requests to coalesce before dispatching (0 = drain what queued)."""
    try:
        return max(
            float(os.environ.get("CELESTIA_SERVE_BATCH_MS", "0") or 0), 0.0
        ) / 1e3
    except ValueError:
        return 0.0


@lru_cache(maxsize=4096)
def _sample_coords(total: int, col: int) -> tuple[tuple[int, int], ...]:
    """(level, index) plan for a single-leaf range [col, col+1) — shared
    by every request sampling that column of a same-k square."""
    return tuple(range_proof_node_coords(total, col, col + 1))


def _latency():
    from celestia_app_tpu.trace.metrics import DEVICE_SECONDS_BUCKETS, registry

    return registry().histogram(
        "celestia_proof_latency_seconds",
        "DAS proof serving latency by phase (queue_wait/gather/assemble "
        "per the sampler; total is per served sample, labeled with the "
        "served share's capped namespace)",
        buckets=DEVICE_SECONDS_BUCKETS,
    )


def _shard_label(p: "_Pending") -> str:
    """Bounded `shard` label of one sample: the serve shard owning the
    sampled coordinate's leaf node (serve/shard.py routing math) — "0"
    on the single-device plane (one getattr, no layout math)."""
    leaf_shard = getattr(p.entry, "leaf_shard", None)
    if leaf_shard is None:
        return "0"
    return str(leaf_shard(p.row, p.col, p.axis))


def _proof_namespace_label(proof) -> str:
    """Capped per-tenant label of one served proof — the PR 4 accounting
    plane's cardinality contract applied to the read path (parity shares
    and failed samples fold into the reserved `other` bucket)."""
    from celestia_app_tpu.trace.square_journal import (
        OTHER_LABEL,
        capped_namespace_label,
        namespace_label,
    )

    ns = getattr(proof, "namespace", None)
    if not isinstance(ns, bytes) or ns == PARITY_NAMESPACE_BYTES:
        return OTHER_LABEL
    return capped_namespace_label(namespace_label(ns))


class _Pending:
    __slots__ = ("entry", "row", "col", "axis", "event", "proof", "error",
                 "t_submit")

    def __init__(self, entry, row: int, col: int, axis: str):
        self.entry = entry
        self.row = row
        self.col = col
        self.axis = axis
        self.event = threading.Event()
        self.proof: ShareProof | None = None
        self.error: Exception | None = None
        self.t_submit = time.perf_counter()


def _check_withheld(entry, coords) -> None:
    """The withholding intercept: raise ShareWithheld on the FIRST
    sampled coordinate the adversary hides — ticking the detection
    counter and black-boxing through the rate-limited
    `withholding_detected` trigger.  No adversary configured = one
    injector read, nothing else."""
    from celestia_app_tpu import chaos

    adv = chaos.active_adversary()
    if adv is None or adv.withhold_frac <= 0:
        return
    if getattr(entry, "healed", False):
        # A healed height serves from this node's own recovered,
        # root-verified store — the withholding proposer no longer sits
        # between the node and these bytes (serve/heal.py).
        return
    height = getattr(entry, "height", 0)
    n = 2 * entry.k
    for row, col in coords:
        if adv.withholds(height, n, row, col):
            from celestia_app_tpu.chaos.adversary import detections
            from celestia_app_tpu.serve import heal
            from celestia_app_tpu.trace.flight_recorder import note_trigger

            adv.count_injection("adversary.withhold", "withhold_frac")
            detections().inc(kind="withheld")
            note_trigger(
                "withholding_detected",
                height=height, row=int(row), col=int(col),
                withhold_frac=adv.withhold_frac,
            )
            # The detect -> act wire: a registered HealingEngine turns
            # this very detection into a repair + re-admit; the failed
            # sample itself still answers the terminal 410.
            heal.note_detection("withheld", height, entry=entry)
            raise ShareWithheld(height, int(row), int(col))


def _qos_gate_sample(entry, row: int, col: int) -> None:
    """The read-path per-tenant proof-rate gate (qos.py), resolved from
    the sampled coordinate's OWN namespace bytes pre-gather.  One cached
    env compare when enforcement is off; parity quadrants never carry a
    tenant."""
    from celestia_app_tpu import qos

    enf = qos.enforcer()
    if enf is None or row >= entry.k or col >= entry.k:
        return
    # One memoized device read per HANDLE (ods_namespaces), then a pure
    # host index per request: refusing an over-limit tenant must cost
    # less than the gather it sheds, or throttling is no protection.
    ns = bytes(entry.eds.ods_namespaces()[row * entry.k + col].tobytes())
    if ns == PARITY_NAMESPACE_BYTES:
        return
    from celestia_app_tpu.trace.square_journal import (
        capped_namespace_label,
        namespace_label,
    )

    enf.admit_proof(capped_namespace_label(namespace_label(ns)))


def _verify_gate_armed(entry) -> bool:
    """Proof verification before serving: armed when an adversary is
    tampering with served state, or unconditionally via
    $CELESTIA_SERVE_VERIFY=1."""
    if os.environ.get("CELESTIA_SERVE_VERIFY", "") == "1":
        return True
    from celestia_app_tpu import chaos

    adv = chaos.active_adversary()
    return adv is not None and adv.tampers()


class ProofSampler:
    """Batching sampler over ForestCache entries (serve/cache.py)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._queue: list[_Pending] = []
        self._leader_active = False

    # --- the queued entry point --------------------------------------------
    def share_proof(self, entry, row: int, col: int, axis: str = "row",
                    timeout_s: float = 30.0) -> ShareProof:
        """One sample through the batch queue: enqueue, and either lead
        the next batch dispatch or park until a leader answers."""
        # Per-sample withholding check BEFORE enqueue: one caller's
        # withheld coordinate must fail that caller, never its
        # batch-mates (a real server refuses one share, not the batch).
        _check_withheld(entry, [(row, col)])
        # Read-path QoS ($CELESTIA_QOS <tenant>.proof_rate) BEFORE the
        # gather: the tenant is the sampled share's own namespace (one
        # 29-byte read off the entry — the PR 10 label, resolved early),
        # so an over-limit spammer is refused at share-read cost instead
        # of after a full proof build it would make everyone else queue
        # behind.  Parity-quadrant coordinates carry no tenant and are
        # never throttled (uniform DAS sampling is protocol traffic).
        _qos_gate_sample(entry, row, col)
        p = _Pending(entry, row, col, axis)
        with self._lock:
            self._queue.append(p)
            lead = not self._leader_active
            if lead:
                self._leader_active = True
        if lead:
            window = batch_window_s()
            if window:
                time.sleep(window)
            with self._lock:
                batch, self._queue = self._queue, []
                self._leader_active = False
            self._serve_batch(batch)
        elif not p.event.wait(timeout_s):
            raise TimeoutError(
                f"proof sample ({row},{col}) not served within {timeout_s}s"
            )
        if p.error is not None:
            raise p.error
        assert p.proof is not None
        return p.proof

    def _serve_batch(self, batch: list[_Pending]) -> None:
        lat = _latency()
        t0 = time.perf_counter()
        for p in batch:
            lat.observe(t0 - p.t_submit, phase="queue_wait")
        by_entry: dict[tuple, list[_Pending]] = {}
        for p in batch:
            by_entry.setdefault((id(p.entry), p.axis), []).append(p)
        from celestia_app_tpu.trace.tracer import traced

        # One row per (entry, axis) group, each stamped with the group's
        # height — a batched dispatch serving three heights writes three
        # rows, so the height timeline (trace/timeline.py) never has to
        # guess which heights a batch touched.  `heights` still carries
        # the batch-wide group count on every row (the coalescing fact).
        tracer = traced()
        for group in by_entry.values():
            entry = group[0].entry
            tracer.write(
                "proof_serve", batch=len(group), heights=len(by_entry),
                height=getattr(entry, "height", None),
                mode=serve_mode(),
                shards=getattr(entry, "shards", 0),
                # The extend plane's share partition
                # (kernels/panel_sharded): independent of the forest mesh
                # above, so the row carries both — a
                # sharded-forest/unsharded-share plane and its inverse
                # are distinguishable from one trace table.
                share_shards=getattr(entry, "share_shards", 0),
            )
        for group in by_entry.values():
            entry = group[0].entry
            coords = [(p.row, p.col) for p in group]
            try:
                proofs = self.sample_batch(entry, coords, axis=group[0].axis)
                for p, proof in zip(group, proofs):
                    p.proof = proof
            except Exception as e:  # noqa: BLE001 — parked callers must wake
                for p in group:
                    p.error = e
            finally:
                for p in group:
                    # Per-sample total carries the served share's capped
                    # namespace — the read path's per-tenant latency view
                    # (batch-level gather/assemble stay unlabeled: one
                    # dispatch serves many tenants).
                    lat.observe(
                        time.perf_counter() - p.t_submit, phase="total",
                        namespace=_proof_namespace_label(p.proof),
                        shard=_shard_label(p),
                    )
                    p.event.set()

    # --- the two lowerings --------------------------------------------------
    def sample_batch(self, entry, coords, axis: str = "row") -> list[ShareProof]:
        """Answer [(row, col), ...] against one cached height on one
        sampling axis; routes the $CELESTIA_SERVE_MODE seam and absorbs
        injected/real batched-path faults by re-answering on the host
        path (bit-identical)."""
        from celestia_app_tpu import chaos
        from celestia_app_tpu.chaos.degrade import recoveries

        n = 2 * entry.k
        if axis not in ("row", "col"):
            raise ValueError(f"axis must be 'row' or 'col', got {axis!r}")
        for row, col in coords:
            if not (0 <= row < n and 0 <= col < n):
                raise ValueError(f"coordinate ({row},{col}) outside {n}x{n}")
        # Direct callers (drills, loadgen) get the same withholding
        # intercept the queued path applies per sample.
        _check_withheld(entry, coords)
        if serve_mode() == "host":
            return self._gate(entry, self._host_batch(entry, coords, axis))
        try:
            chaos.proof_serve()
            proofs = self._batched(entry, coords, axis)
        except Exception:  # noqa: BLE001 — the host path is the answer
            proofs = self._host_batch(entry, coords, axis)
            recoveries().inc(seam="proof.serve", outcome="degraded")
        return self._gate(entry, proofs)

    @staticmethod
    def _gate(entry, proofs: list[ShareProof]) -> list[ShareProof]:
        """The verification gate: when armed (adversarial tampering or
        $CELESTIA_SERVE_VERIFY=1), every proof must verify against the
        entry's committed data root before it leaves the sampler.  A
        failure is an attack detection (malformed square / wrong root):
        counted, black-boxed, and raised — never served as valid."""
        if not _verify_gate_armed(entry):
            return proofs
        # One batched device program decides the whole queue
        # (serve/verify.py); bit-identical to per-proof host verify,
        # host fallback on any batched fault via the proof.verify seam.
        from celestia_app_tpu.serve.verify import verify_proofs

        for ok in verify_proofs(proofs, entry.data_root):
            if ok:
                continue
            from celestia_app_tpu.chaos.adversary import detections
            from celestia_app_tpu.serve import heal
            from celestia_app_tpu.trace.flight_recorder import note_trigger

            detections().inc(kind="bad_proof")
            note_trigger(
                "root_mismatch",
                reason="serve_verification",
                height=getattr(entry, "height", 0),
            )
            heal.note_detection(
                "bad_proof", getattr(entry, "height", None), entry=entry
            )
            raise BadProofDetected(
                "assembled proof does not verify against the committed "
                f"data root at height {getattr(entry, 'height', 0)} "
                "(malformed square or wrong root)"
            )
        return proofs

    def _batched(self, entry, coords, axis: str = "row") -> list[ShareProof]:
        lat = _latency()
        n = 2 * entry.k
        # Row sampling proves leaf `col` of tree `row`; column sampling
        # the transpose — leaf `row` of column tree `col`, whose root is
        # data-root leaf 2k + col.
        if axis == "col":
            plans = [_sample_coords(n, row) for row, _ in coords]
            trees = [col for _, col in coords]
        else:
            plans = [_sample_coords(n, col) for _, col in coords]
            trees = [row for row, _ in coords]
        node_idx: list[int] = []
        for tree, plan in zip(trees, plans):
            node_idx.extend(
                entry.flat_index(tree, lvl, i) for lvl, i in plan
            )
        t0 = time.perf_counter()
        nodes = entry.gather(axis, node_idx)
        shares = entry.gather_shares(coords)
        lat.observe(time.perf_counter() - t0, phase="gather")

        t1 = time.perf_counter()
        from celestia_app_tpu import merkle

        all_roots = entry.row_roots + entry.col_roots
        out: list[ShareProof] = []
        pos = 0
        for (row, col), plan, share_row in zip(coords, plans, shares):
            share = bytes(share_row.tobytes())
            nmt_nodes = tuple(
                bytes(nodes[pos + i].tobytes()) for i in range(len(plan))
            )
            pos += len(plan)
            ns = (
                share[:NAMESPACE_SIZE]
                if row < entry.k and col < entry.k
                else PARITY_NAMESPACE_BYTES
            )
            if axis == "col":
                leaf, root_index = row, n + col
            else:
                leaf, root_index = col, row
            out.append(ShareProof(
                data=(share,),
                share_proofs=(NmtRangeProof(leaf, leaf + 1, nmt_nodes, n),),
                namespace=ns,
                row_proof=RowProof(
                    row_roots=(all_roots[root_index],),
                    proofs=(tuple(
                        merkle.path_from_levels(entry.root_levels, root_index)
                    ),),
                    start_row=root_index,
                    end_row=root_index + 1,
                    total=2 * n,
                ),
            ))
        lat.observe(time.perf_counter() - t1, phase="assemble")
        return out

    def _host_batch(self, entry, coords, axis: str = "row") -> list[ShareProof]:
        return [self.host_proof(entry, row, col, axis) for row, col in coords]

    @staticmethod
    def host_proof(entry, row: int, col: int, axis: str = "row") -> ShareProof:
        """The pure-host lowering: rebuild the row tree from the shares,
        re-derive the data-root audit path recursively.  MUST stay
        byte-identical to _batched (the serve plane's exactness seam,
        pinned by tests/test_das_proofs.py and the chaos soak's sampling
        drill)."""
        import numpy as np

        from celestia_app_tpu import merkle

        eds = entry.eds
        n = 2 * entry.k
        share = bytes(np.asarray(eds._eds[row, col]).tobytes())
        if axis == "col":
            tree = eds.col_tree(col, host=True)
            proof = prove_range_from_levels(tree.levels(), row, row + 1)
            root_index = n + col
        else:
            tree = eds.row_tree(row, host=True)
            proof = prove_range_from_levels(tree.levels(), col, col + 1)
            root_index = row
        all_roots = entry.row_roots + entry.col_roots
        ns = (
            share[:NAMESPACE_SIZE]
            if row < entry.k and col < entry.k
            else PARITY_NAMESPACE_BYTES
        )
        return ShareProof(
            data=(share,),
            share_proofs=(proof,),
            namespace=ns,
            row_proof=RowProof(
                row_roots=(all_roots[root_index],),
                proofs=(tuple(merkle.proof(all_roots, root_index)),),
                start_row=root_index,
                end_row=root_index + 1,
                total=len(all_roots),
            ),
        )

"""The sharded proof-serving plane: row-partitioned NMT forests.

$CELESTIA_SERVE_SHARDS=N (N > 1) partitions every retained height's two
flat (N_nodes, 90) forests row-wise across a 1D device mesh
(parallel/mesh.py, axis "serve"), under the SNIPPETS pjit contract:

  * ADMISSION lays the forest out exactly once — the forest build
    program itself carries committed `out_shardings`
    (kernels/fused.jit_forest_sharded), so there is no second
    device_put and no implicit reshard;
  * GATHER dispatches the whole micro-batch as ONE sharded program
    whose `in_shardings` name the same layout
    (parallel/mesh.sharded_gather_fn); each sample's proof-node rows
    are routed host-side to the shard that owns them (coordinate ->
    shard is a pure function of the level layout: contiguous equal row
    blocks, one integer divide) and no shard reads another's block.

Byte-identity is structural: a gather returns the same rows whatever
the layout, so the sharded path, the single-device batched path, and
the pure-host fallback are pinned identical (tests/test_serve_sharded).

Degradation ladder (read side, mirroring fused->staged->host):

  sharded gather        chaos seam proof.shard ($CELESTIA_CHAOS
      |  shard_fail=<p>) or any real fault in the sharded program
      v
  single-device batched the plain jnp.take the unsharded plane runs
      |  (ticks celestia_recoveries_total{seam="proof.shard"})
      v
  host                  the sampler's existing proof.serve fallback

The serve mesh shape and per-shard resident forest bytes surface on the
/healthz "serve" block (ForestCache.stats) and the
celestia_serve_shard_resident_bytes gauge; each sharded dispatch ticks
celestia_serve_shard_gathers_total{shard} with the rows each shard
served (bounded: one label value per shard).
"""

from __future__ import annotations

import os
import threading
import weakref

import numpy as np

from celestia_app_tpu.parallel.mesh import (
    SERVE_AXIS,
    device_mesh,
    padded_rows,
    route_to_shards,
    row_sharding,
    shard_of_row,
    sharded_gather_fn,
    sharded_share_gather_fn,
)
from celestia_app_tpu.serve.cache import CachedForest


def serve_shards() -> int:
    """$CELESTIA_SERVE_SHARDS: how many devices the serve plane's
    forests are partitioned across (<=1 = the single-device plane,
    the default).  Clamped to the local device count, loudly; a
    MALFORMED value also warns loudly (once per value) instead of
    silently disabling sharding — the $CELESTIA_PIPE_PANEL precedent:
    an operator who asked for a sharded plane must not quietly get an
    unsharded one."""
    raw = os.environ.get("CELESTIA_SERVE_SHARDS", "0") or "0"
    try:
        want = int(raw)
    except ValueError:
        import warnings

        warnings.warn(
            f"CELESTIA_SERVE_SHARDS={raw!r} is not an integer; "
            "serving UNSHARDED",
            stacklevel=2,
        )
        return 0
    if want <= 1:
        return 0
    import jax

    have = len(jax.devices())
    if want > have:
        import warnings

        warnings.warn(
            f"CELESTIA_SERVE_SHARDS={want} but only {have} devices; "
            f"sharding the serve plane over {have}",
            stacklevel=2,
        )
        return have
    return want


def serve_mesh(shards: int):
    return device_mesh(shards, SERVE_AXIS)


def leaf_shard_of(k: int, shards: int, row: int, col: int,
                  axis: str = "row") -> int:
    """Owning shard of a sampled coordinate's level-0 forest node — THE
    coordinate->shard routing function (pure layout math, one divide),
    shared by the sampler's per-sample label (ShardedCachedForest
    .leaf_shard) and the serving planes' payload label
    (serve/api.payload_shard_label) so the two can never desynchronize.

    Row sampling proves leaf `col` of row tree `row`; column sampling
    the transpose.  The level-0 node of (tree, leaf) sits at flat row
    tree*width0 + leaf (forest_level_layout: offsets[0] == 0)."""
    n = 2 * k
    rows_per_shard = padded_rows(n * (2 * n - 1), shards) // shards
    tree, leaf = (col, row) if axis == "col" else (row, col)
    return shard_of_row(tree * n + leaf, rows_per_shard)


def eds_share_layout(buf):
    """(mesh, axis, shards) when `buf` is a device array row-partitioned
    across >1 devices on a named mesh axis — the committed layout the
    sharded extend pipeline (kernels/panel_sharded.py) retains its EDS
    under — else None.  Pure introspection: the serve plane discovers
    share sharding from the buffer it was handed, so the extend knob and
    the serve knob never have to agree."""
    try:
        from jax.sharding import NamedSharding
    except Exception:  # chaos-ok: no jax — host tier only
        return None
    sh = getattr(buf, "sharding", None)
    if not isinstance(sh, NamedSharding):
        return None
    spec = tuple(sh.spec)
    if not spec or spec[0] is None or any(s is not None for s in spec[1:]):
        return None
    axis = spec[0]
    if isinstance(axis, (tuple, list)):
        if len(axis) != 1:
            return None
        axis = axis[0]
    shards = int(sh.mesh.shape[axis])
    if shards < 2:
        return None
    return sh.mesh, str(axis), shards


def sharded_share_gather(buf, coords) -> np.ndarray | None:
    """Gather [(row, col), ...] shares from a row-sharded EDS buffer as
    ONE sharded program, each coordinate routed host-side to its owning
    shard (flat share offset r*n + c; contiguous row blocks flatten to
    contiguous flat blocks, so it is the same one-divide routing the
    forest gather uses).  Returns None when `buf` is not share-sharded
    (the caller's single-device take answers); falls back the same way —
    ticking celestia_recoveries_total{seam="proof.shard"} — on an
    injected (chaos shard_fail) or real fault, so the read-side rung
    ladder covers shares exactly as it covers forests.  in_shardings
    name the extend pipeline's committed layout: a retained EDS is
    NEVER resharded by a serve read (pinned to buffer pointers in
    tests/test_panel_sharded.py)."""
    layout = eds_share_layout(buf)
    if layout is None:
        return None
    mesh, axis, shards = layout
    rows, n_cols, width = (int(x) for x in buf.shape)
    rows_local = rows // shards
    flat_idx = np.asarray(
        [r * n_cols + c for r, c in coords], dtype=np.int64
    )
    try:
        from celestia_app_tpu import chaos

        chaos.proof_shard()
        import jax

        local, (shard, slot), counts = route_to_shards(
            flat_idx, shards, rows_local * n_cols
        )
        fn = sharded_share_gather_fn(
            mesh, axis, rows_local, n_cols, width, int(local.shape[1])
        )
        idx = jax.device_put(local, row_sharding(mesh, axis))
        out = np.asarray(fn(buf, idx))  # (shards, bucket, width)
        _count_share_rows(counts)
        return out[shard, slot]
    except Exception:  # noqa: BLE001 — single-device rung answers
        from celestia_app_tpu.chaos.degrade import recoveries

        recoveries().inc(seam="proof.shard", outcome="degraded")
        return None


def _count_share_rows(counts) -> None:
    from celestia_app_tpu.trace.metrics import registry

    ctr = registry().counter(
        "celestia_serve_share_gathers_total",
        "EDS shares gathered per extend shard (one sharded program per "
        "share read; bounded: one label per shard)",
    )
    for s, n in enumerate(counts):
        if n:
            ctr.inc(int(n), shard=str(s))


class ShardedCachedForest(CachedForest):
    """One height's retained proof state, forests row-partitioned.

    Same surface as CachedForest — the sampler, the healing engine, and
    the spill tier are oblivious — plus the committed-sharding fields
    the never-reshards test pins: `committed_sharding` is the ONE
    NamedSharding both the admission build's out_shardings and every
    gather's in_shardings name.
    """

    def __init__(self, height: int, eds, row_flat, col_flat, mesh,
                 axis: str = SERVE_AXIS):
        super().__init__(height, eds, row_flat, col_flat)
        self.mesh = mesh
        self.axis = axis
        self.shards = mesh.shape[axis]
        n = 2 * self.k
        self.forest_rows = n * (2 * n - 1)
        self.rows_per_shard = padded_rows(self.forest_rows, self.shards) // self.shards
        self.committed_sharding = row_sharding(mesh, axis)

    # --- routing -------------------------------------------------------------
    def leaf_shard(self, row: int, col: int, axis: str = "row") -> int:
        """The bounded per-sample `shard` metric label (leaf_shard_of,
        instantiated on this entry's square size and shard count)."""
        return leaf_shard_of(self.k, self.shards, row, col, axis)

    # --- the sharded gather --------------------------------------------------
    def _sharded_gather(self, axis: str, flat_indices) -> np.ndarray:
        import jax

        flat = self._flat(axis)
        local, (shard, slot), counts = route_to_shards(
            flat_indices, self.shards, self.rows_per_shard
        )
        fn = sharded_gather_fn(
            self.mesh, self.axis, self.rows_per_shard,
            int(flat.shape[-1]), int(local.shape[1]),
        )
        idx = jax.device_put(local, self.committed_sharding)
        out = np.asarray(fn(flat, idx))  # (shards, bucket, 90)
        result = out[shard, slot]  # one fancy-index, batch order
        self._count_shard_rows(counts)
        return result

    @staticmethod
    def _count_shard_rows(counts) -> None:
        from celestia_app_tpu.trace.metrics import registry

        ctr = registry().counter(
            "celestia_serve_shard_gathers_total",
            "forest rows gathered per serve shard (one sharded program "
            "per micro-batch dispatch; bounded: one label per shard)",
        )
        for s, n in enumerate(counts):
            if n:
                ctr.inc(n, shard=str(s))

    def gather(self, axis: str, flat_indices) -> np.ndarray:
        """The read-side rung ladder: sharded program -> single-device
        take -> (caller's) host fallback.  A fault in the sharded
        dispatch — injected via the chaos seam proof.shard
        (shard_fail=<p>) or real — degrades THIS gather to the plain
        single-device path the unsharded plane runs, bit-identically;
        a fault there too propagates to the sampler, whose existing
        proof.serve fallback answers on the pure-host rung."""
        flat = self._flat(axis)
        if isinstance(flat, np.ndarray):  # spilled: host tier, base path
            return super().gather(axis, flat_indices)
        try:
            from celestia_app_tpu import chaos

            chaos.proof_shard()
            return self._sharded_gather(axis, flat_indices)
        except Exception:  # noqa: BLE001 — single-device rung answers
            from celestia_app_tpu.chaos.degrade import recoveries

            recoveries().inc(seam="proof.shard", outcome="degraded")
            return super().gather(axis, flat_indices)

    # --- introspection -------------------------------------------------------
    def shard_resident_bytes(self) -> dict[str, int]:
        """Per-shard resident forest bytes (both axes) — the /healthz
        serve block's mesh view.  Uniform by construction (equal row
        blocks), reported per shard so a lopsided future layout shows."""
        per = self.rows_per_shard * 90 * 2
        return {str(s): per for s in range(self.shards)}


def build_entry(height: int, eds) -> CachedForest:
    """Build one height's retained entry: the admission seam shared by
    ForestCache.put / .readmit and the retention-disabled serve path.

    $CELESTIA_SERVE_SHARDS > 1 routes the forest build through the
    sharded program (committed out_shardings — laid out once, here) and
    wraps the entry as ShardedCachedForest; otherwise the single-device
    build, byte-identical.
    """
    import jax.numpy as jnp

    shards = serve_shards()
    if shards > 1:
        from celestia_app_tpu.kernels.fused import jit_forest_sharded

        mesh = serve_mesh(shards)
        row_flat, col_flat = jit_forest_sharded(eds.k, mesh, SERVE_AXIS)(
            jnp.asarray(eds._eds)
        )
        return ShardedCachedForest(height, eds, row_flat, col_flat, mesh)
    from celestia_app_tpu.kernels.fused import jit_forest

    row_flat, col_flat = jit_forest(eds.k)(jnp.asarray(eds._eds))
    return CachedForest(height, eds, row_flat, col_flat)


# Per-cache contributions to the process-wide resident-bytes gauge:
# the gauge must be (a) re-set to 0 for a label whose bytes left the
# device tier (never report forests that no longer exist) and (b)
# AGGREGATED across caches in a multi-node process (one node's stats()
# refresh must not zero another node's resident bytes).  WeakKey so a
# dropped cache's contribution dies with it.
_CACHE_SHARD_BYTES: weakref.WeakKeyDictionary = weakref.WeakKeyDictionary()
_PUBLISHED_SHARD_LABELS: set[str] = set()
_GAUGE_LOCK = threading.Lock()


def mesh_stats(cache, entries) -> dict | None:
    """The /healthz serve block's "mesh" view over one cache's resident
    entries: shard count, axis, and per-shard resident forest bytes
    summed across heights; None when that cache's plane is unsharded.
    The exported gauge sums every live cache's contribution."""
    shards = 0
    per: dict[str, int] = {}
    for entry in entries:
        if not isinstance(entry, ShardedCachedForest):
            continue
        shards = max(shards, entry.shards)
        if entry.device_resident:
            for s, b in entry.shard_resident_bytes().items():
                per[s] = per.get(s, 0) + b
    with _GAUGE_LOCK:
        _CACHE_SHARD_BYTES[cache] = per
        totals: dict[str, int] = {}
        for contrib in _CACHE_SHARD_BYTES.values():
            for s, b in contrib.items():
                totals[s] = totals.get(s, 0) + b
        labels = set(totals) | _PUBLISHED_SHARD_LABELS
        if labels:
            from celestia_app_tpu.trace.metrics import registry

            gauge = registry().gauge(
                "celestia_serve_shard_resident_bytes",
                "resident forest bytes per serve shard (device tier, "
                "summed across this process's serve caches)",
            )
            # Every label ever published gets a fresh value — stale
            # shards (evicted, spilled, narrower mesh) drop to 0.
            for s in sorted(labels, key=int):
                gauge.set(totals.get(s, 0), shard=s)
            _PUBLISHED_SHARD_LABELS.update(labels)
    if not shards:
        return None
    return {
        "shards": shards,
        "axis": SERVE_AXIS,
        "per_shard_resident_bytes": per,
    }

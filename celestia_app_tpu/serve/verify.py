"""Batched proof verification — the read side's verify twin of the sampler.

Every consumer of DAS proofs (das_loadgen's swarm clients, the heal
engine's survivor check, the sampler's $CELESTIA_SERVE_VERIFY gate)
used to verify one proof at a time on host via `ShareProof.verify`.
This module re-decides a whole queue in one jitted program
(kernels/verify.py) behind the same batched<->host bit-identical seam
discipline every other lowering uses:

    * `verify_proofs(proofs, data_root)` -> accept/reject vector,
      IDENTICAL to `[p.verify(root) for p in proofs]` on every input —
      canonical single-share samples ride the device program (bucketed
      by tree shape, batch padded to a power of two so recompilation is
      bounded); anything else (multi-row inclusion proofs, malformed
      shapes an attacker could hand us) routes to the host verifier,
      whose verdict the batched path matches by definition.
    * $CELESTIA_VERIFY_MODE=host pins the pure-host path.
    * chaos key `verify_fail` (seam `proof.verify`) fails the batched
      dispatch; the fallback re-decides the WHOLE queue on host and
      ticks celestia_chaos_recoveries_total{seam="proof.verify"} — the
      read-side analog of the sampler's proof.serve absorb.
    * `leaf_digests(ns, shares)` batches the heal engine's survivor
      check (one dispatch for all gathered coordinates) with the same
      fallback discipline.

Index plans are host ints derived from the SAME
`range_proof_node_coords` DFS plan the sampler serves proofs with, so
batched and host verdicts agree by construction, not by luck.
"""

from __future__ import annotations

import functools
import os

import numpy as np

from celestia_app_tpu import chaos
from celestia_app_tpu.constants import (
    NAMESPACE_SIZE,
    NMT_NODE_SIZE,
    SHARE_SIZE,
)
from celestia_app_tpu.nmt.hasher import NmtHasher
from celestia_app_tpu.nmt.proof import range_proof_node_coords


def verify_mode() -> str:
    """$CELESTIA_VERIFY_MODE: "batched" (default) or "host"."""
    mode = os.environ.get("CELESTIA_VERIFY_MODE", "batched").strip().lower()
    return mode if mode in ("batched", "host") else "batched"


def _verified_counter():
    from celestia_app_tpu.trace.metrics import registry

    return registry().counter(
        "celestia_verified_samples_total",
        "DAS samples verified, by verifier mode",
    )


@functools.lru_cache(maxsize=8192)
def _sibling_perm(total: int, start: int) -> tuple[int, ...]:
    """DFS-position -> level permutation for a single-leaf proof: entry
    `lvl` is where `prove_range`'s DFS emitted the level-`lvl` sibling
    (subtree index (start >> lvl) ^ 1).  Derived from the SAME
    `range_proof_node_coords` plan the sampler serves with, so the
    batched fold consumes exactly the node the host walk consumes."""
    coords = range_proof_node_coords(total, start, start + 1)
    pos = {c: j for j, c in enumerate(coords)}
    ln = total.bit_length() - 1
    return tuple(pos[(lvl, (start >> lvl) ^ 1)] for lvl in range(ln))


class _Bucket:
    """Assembly state for one (nmt levels, row levels) tree shape."""

    __slots__ = ("idxs", "ns", "shares", "sibs", "starts", "row_roots",
                 "slots", "row_slots", "row_parts", "row_paths",
                 "row_indices", "row_data_roots")

    def __init__(self):
        self.idxs: list[int] = []
        self.ns: list[bytes] = []
        self.shares: list[bytes] = []
        self.sibs: list[bytes] = []
        self.starts: list[int] = []
        self.row_roots: list[bytes] = []
        self.slots: list[int] = []
        self.row_slots: dict = {}
        self.row_parts: list[bytes] = []
        self.row_paths: list[bytes] = []
        self.row_indices: list[int] = []
        self.row_data_roots: list[bytes] = []


def _pad_rows(raw: bytes, count: int, width: int, pad_to: int) -> np.ndarray:
    """bytes of `count` rows -> (pad_to, width) uint8, padding by
    repeating row 0 (batch padded to a power of two so the jit
    specializations per tree shape stay bounded)."""
    arr = np.frombuffer(raw, dtype=np.uint8).reshape(count, width)
    if pad_to == count:
        return arr
    return np.concatenate(
        [arr, np.broadcast_to(arr[0], (pad_to - count, width))]
    )


def _bit_flags(indices: list[int], levels: int, pad_to: int) -> np.ndarray:
    """(pad_to, levels) bool: bit `lvl` of each index — fold step `lvl`
    has the running digest on the RIGHT (sibling folds from the left)."""
    arr = np.zeros(pad_to, dtype=np.int64)
    arr[: len(indices)] = indices
    arr[len(indices):] = indices[0]
    return ((arr[:, None] >> np.arange(levels)) & 1).astype(bool)


def _pow2(n: int) -> int:
    return 1 << (n - 1).bit_length() if n > 1 else 1


def _verify_canonical(proofs, roots, out: np.ndarray) -> list[int]:
    """Batched verdicts for every CANONICAL sample in the queue, written
    into `out`; returns the queue positions that are NOT canonical
    (multi-row inclusion proofs, malformed shapes) for the caller to
    route to the host verifier.

    Canonical = the DAS-sample shape: single 512-byte share, single row,
    power-of-two trees, exact node/path counts.  The shape checks are
    exhaustive on purpose — the batched program assumes fixed sizes, and
    a malformed proof is attacker input, not a bug.

    One NMT dispatch per tree-shape bucket over all samples + one
    row-root fold over the bucket's UNIQUE (row root, audit path, data
    root) triples — s samples of one height share a handful of row
    roots, so the row leg costs ~n, not ~s."""
    from celestia_app_tpu.kernels.verify import (
        fold_row_roots,
        verify_nmt_samples,
    )

    rest: list[int] = []
    buckets: dict[tuple[int, int], _Bucket] = {}
    for i, proof in enumerate(proofs):
        try:
            data = proof.data
            nmts = proof.share_proofs
            rp = proof.row_proof
            if len(data) != 1 or len(nmts) != 1:
                raise ValueError
            nmt = nmts[0]
            share = data[0]
            namespace = proof.namespace
            total = nmt.total
            start = nmt.start
            nodes = nmt.nodes
            ln = total.bit_length() - 1
            if (
                len(share) != SHARE_SIZE
                or len(namespace) != NAMESPACE_SIZE
                or total < 2
                or total & (total - 1)
                or nmt.end - start != 1
                or not 0 <= start < total
                or len(nodes) != ln
                or any(len(nd) != NMT_NODE_SIZE for nd in nodes)
            ):
                raise ValueError
            row_roots_f = rp.row_roots
            paths = rp.proofs
            rtotal = rp.total
            row = rp.start_row
            lr = rtotal.bit_length() - 1
            if (
                len(row_roots_f) != 1
                or len(paths) != 1
                or rp.end_row - row != 1
                or len(row_roots_f[0]) != NMT_NODE_SIZE
                or rtotal < 2
                or rtotal & (rtotal - 1)
                or not 0 <= row < rtotal
                or len(paths[0]) != lr
                or any(len(h) != 32 for h in paths[0])
                or len(roots[i]) != 32
            ):
                raise ValueError
        except (TypeError, AttributeError, ValueError):
            rest.append(i)
            continue
        bucket = buckets.get((ln, lr))
        if bucket is None:
            bucket = buckets[(ln, lr)] = _Bucket()
        bucket.idxs.append(i)
        bucket.ns.append(namespace)
        bucket.shares.append(share)
        perm = _sibling_perm(total, start)
        bucket.sibs.append(b"".join([nodes[j] for j in perm]))
        bucket.starts.append(start)
        row_root = row_roots_f[0]
        bucket.row_roots.append(row_root)
        key = (row_root, paths[0], row, roots[i])
        slot = bucket.row_slots.get(key)
        if slot is None:
            slot = bucket.row_slots[key] = len(bucket.row_parts)
            bucket.row_parts.append(row_root)
            bucket.row_paths.append(b"".join(paths[0]))
            bucket.row_indices.append(row)
            bucket.row_data_roots.append(roots[i])
        bucket.slots.append(slot)

    for (ln, lr), bk in buckets.items():
        b, u = len(bk.idxs), len(bk.row_parts)
        bp, up = _pow2(b), _pow2(u)
        nmt_ok = np.asarray(verify_nmt_samples(
            _pad_rows(b"".join(bk.ns), b, NAMESPACE_SIZE, bp),
            _pad_rows(b"".join(bk.shares), b, SHARE_SIZE, bp),
            _pad_rows(b"".join(bk.sibs), b, ln * NMT_NODE_SIZE, bp).reshape(
                bp, ln, NMT_NODE_SIZE
            ),
            _bit_flags(bk.starts, ln, bp),
            _pad_rows(b"".join(bk.row_roots), b, NMT_NODE_SIZE, bp),
        ))[:b]
        row_ok = np.asarray(fold_row_roots(
            _pad_rows(b"".join(bk.row_parts), u, NMT_NODE_SIZE, up),
            _pad_rows(b"".join(bk.row_paths), u, lr * 32, up).reshape(
                up, lr, 32
            ),
            _bit_flags(bk.row_indices, lr, up),
            _pad_rows(b"".join(bk.row_data_roots), u, 32, up),
        ))[:u]
        out[bk.idxs] = nmt_ok & row_ok[bk.slots]
    return rest


def _verify_host(proofs, roots) -> list[bool]:
    verdicts = [bool(p.verify(r)) for p, r in zip(proofs, roots)]
    _verified_counter().inc(len(proofs), mode="host")
    return verdicts


def verify_proofs(proofs, data_root) -> list[bool]:
    """Accept/reject vector for a queue of ShareProofs.

    `data_root` is one 32-byte root for the whole queue or a per-proof
    sequence (mixed-height queues).  Identical to
    `[p.verify(root) for p in proofs]` on every input."""
    proofs = list(proofs)
    if not proofs:
        return []
    if isinstance(data_root, (bytes, bytearray)):
        roots = [bytes(data_root)] * len(proofs)
    else:
        roots = [bytes(r) for r in data_root]
    if len(roots) != len(proofs):
        raise ValueError(
            f"{len(roots)} data roots for {len(proofs)} proofs"
        )
    if verify_mode() == "host":
        return _verify_host(proofs, roots)
    try:
        chaos.proof_verify()
        accept = np.zeros(len(proofs), dtype=bool)
        rest = _verify_canonical(proofs, roots, accept)
        if len(rest) < len(proofs):
            _verified_counter().inc(len(proofs) - len(rest), mode="batched")
        verdicts = accept.tolist()
        if rest:
            host = _verify_host([proofs[i] for i in rest],
                                [roots[i] for i in rest])
            for j, i in enumerate(rest):
                verdicts[i] = host[j]
        return verdicts
    except Exception:
        from celestia_app_tpu.chaos.degrade import recoveries

        recoveries().inc(seam="proof.verify", outcome="degraded")
        return _verify_host(proofs, roots)


def verify_share_proof(proof, data_root: bytes) -> bool:
    """Single-proof convenience over `verify_proofs`."""
    return verify_proofs([proof], data_root)[0]


def _leaf_digests_host(ns: np.ndarray, shares: np.ndarray) -> np.ndarray:
    digests = [
        NmtHasher.hash_leaf(ns[i].tobytes() + shares[i].tobytes())
        for i in range(len(ns))
    ]
    return np.frombuffer(b"".join(digests), dtype=np.uint8).reshape(
        len(digests), NMT_NODE_SIZE
    ) if digests else np.zeros((0, NMT_NODE_SIZE), dtype=np.uint8)


def leaf_digests(ns: np.ndarray, shares: np.ndarray) -> np.ndarray:
    """(N, 29) x (N, D) uint8 -> (N, 90) NMT leaf digests in ONE batched
    dispatch — the heal engine's survivor check rides this instead of a
    per-coordinate host loop.  Host fallback (NmtHasher.hash_leaf) is
    byte-identical and reachable via the same `verify_fail` seam."""
    ns = np.ascontiguousarray(ns, dtype=np.uint8)
    shares = np.ascontiguousarray(shares, dtype=np.uint8)
    if len(ns) == 0:
        return np.zeros((0, NMT_NODE_SIZE), dtype=np.uint8)
    if verify_mode() == "host":
        return _leaf_digests_host(ns, shares)
    try:
        chaos.proof_verify()
        from celestia_app_tpu.kernels.verify import nmt_leaf_digests

        return np.asarray(nmt_leaf_digests(ns, shares))
    except Exception:
        from celestia_app_tpu.chaos.degrade import recoveries

        recoveries().inc(seam="proof.verify", outcome="degraded")
        return _leaf_digests_host(ns, shares)

"""HealingEngine: the detect -> repair -> re-serve control loop.

Every leg existed in isolation before this module — the sampling plane
DETECTS (ShareWithheld / BadProofDetected, PR 10), `da/repair` rebuilds a
square from >= 25% survivors at device speed, and ForestCache re-admits —
but a detection ended at an HTTP 410/502 and a flight bundle.  This is
the ACeD-style availability-oracle loop (arXiv 2011.00102): the node that
notices a gap CLOSES it, so downstream consumers never see one.

One heal, five measured phases (`celestia_heal_seconds{phase}`):

  detect    detection-signal latency: first detection note -> heal start
            (the queue wait; the sampling-side time-to-first-detection is
            the drill's separate detect_ms, per arXiv 2201.07287's
            P(detect | s samples) model)
  gather    collect the surviving shares for the height: withheld
            coordinates never answer, and every fetched share is
            verified against the node's COMMITTED NMT leaf digests (the
            retained forest's level-0 nodes chain to the DAH this node
            signed) — tampered bytes can never enter the repair as
            "survivors"
  repair    batched device repair (da/repair.py), riding
            chaos/degrade.guarded_dispatch: an injected dispatch fault
            mid-repair walks the ladder, never wedges the node
  verify    the recovered square's roots are re-derived and compared
            bit-for-bit against the committed DAH BEFORE anything else
            can see the bytes — a heal that cannot prove itself is a
            failed attempt, never a served square
  readmit   re-admission into ForestCache through the single-flight
            gate (ForestCache.readmit: coalesces with a concurrent
            rebuild, evicts any adversary-tampered per-height memo) and
            the entry is marked `healed`, so the previously-withheld
            coordinates serve from the node's own verified store

plus `total` (detection note -> re-admitted).  Outcomes land on
`celestia_heal_total{outcome}`:

  healed        the height serves again, root-verified
  irrecoverable the survivor set is below the k-survivor threshold
                (da/repair.IrrecoverableSquare) — no retry can help
  quarantined   bounded retry/backoff exhausted without a verified
                recovery

Failed heights enter QUARANTINE: their detections stay terminal
(410/502), no heal storm re-enqueues them, and the state is visible in
the /healthz "heal" block and `GET /heal`.  Heights mid-heal are
RETRYABLE: `DasProvider.entry` raises HealingInProgress, which the HTTP
planes map to 503 + Retry-After and the gRPC Das service to UNAVAILABLE
— a client that backs off lands on the healed height.

Both terminal transitions black-box: `heal_completed` /
`heal_quarantined` flight-recorder triggers carry the node name, height,
outcome, per-phase latencies, and attempt count.

Wiring: construct a HealingEngine over a DasProvider (it registers
itself module-wide and as `provider.healer`); the detection sites
(serve/sampler, da/repair) publish through `note_detection`, which is
one registry walk and never raises.  `$CELESTIA_HEAL=1` makes a
ServingNode wire and start one automatically (rpc/server.NodeServer).
scripts/chaos_soak.py drills the loop single-node and as a multi-node
quorum; the measured rounds land in ADV_rNN.json under bench_trend's
`heal` gate.
"""

from __future__ import annotations

import collections
import threading
import time

#: Heal outcomes (the `celestia_heal_total{outcome}` label values).
HEAL_OUTCOMES = ("healed", "quarantined", "irrecoverable")

#: Measured phases of one heal (`celestia_heal_seconds{phase}`).
HEAL_PHASES = ("detect", "gather", "repair", "verify", "readmit", "total")


class HealingInProgress(RuntimeError):
    """The height is being healed right now: retryable (HTTP 503 +
    Retry-After / gRPC UNAVAILABLE), never the terminal 410/502 — the
    client that backs off and retries lands on the healed height."""

    def __init__(self, height: int, retry_after_s: float):
        super().__init__(
            f"height {height} is being healed (detected attack under "
            f"repair); retry in {retry_after_s:g}s"
        )
        self.height = height
        self.retry_after_s = retry_after_s


def heal_enabled() -> bool:
    """$CELESTIA_HEAL=1: a ServingNode wires and starts a HealingEngine
    over its DasProvider automatically (default off: detection without
    reaction, the pre-PR-12 behavior)."""
    import os

    return os.environ.get("CELESTIA_HEAL", "") == "1"


def heal_seconds():
    from celestia_app_tpu.trace.metrics import DEVICE_SECONDS_BUCKETS, registry

    return registry().histogram(
        "celestia_heal_seconds",
        "self-healing loop latency by phase (detect = detection note to "
        "heal start; gather/repair/verify/readmit per attempt; total = "
        "detection note to re-admitted)",
        buckets=DEVICE_SECONDS_BUCKETS,
    )


def heal_total():
    from celestia_app_tpu.trace.metrics import registry

    return registry().counter(
        "celestia_heal_total",
        "heal attempts resolved, by outcome "
        "(healed / quarantined / irrecoverable)",
    )


# --- the engine registry (how detection sites find their engine) ------------

_REG_LOCK = threading.Lock()
_ENGINES: list["HealingEngine"] = []


def register(engine: "HealingEngine") -> None:
    with _REG_LOCK:
        if engine not in _ENGINES:
            _ENGINES.append(engine)


def unregister(engine: "HealingEngine") -> None:
    with _REG_LOCK:
        if engine in _ENGINES:
            _ENGINES.remove(engine)


def engines() -> tuple["HealingEngine", ...]:
    with _REG_LOCK:
        return tuple(_ENGINES)


def _reset_for_tests() -> None:
    with _REG_LOCK:
        _ENGINES.clear()


def note_detection(kind: str, height, entry=None) -> None:
    """Publish one detection signal (withheld / bad_proof / root_mismatch)
    to whichever registered engine owns the height.  The hot-path face of
    the subscription: no engine registered = one tuple read; NEVER raises
    (a heal trigger that takes down the detection path is worse than no
    healing at all)."""
    if height is None:
        return
    for eng in engines():
        try:
            eng.note(kind, int(height), entry=entry)
        except Exception:  # chaos-ok: healing must never break detection
            pass


def heal_health_block():
    """The /healthz "heal" block: None when no engine is registered, one
    engine's state directly, or {name: state} for a multi-node process."""
    engs = engines()
    if not engs:
        return None
    if len(engs) == 1:
        return engs[0].state()
    return {e.name: e.state() for e in engs}


def heal_payload() -> dict:
    """GET /heal: every registered engine's state, keyed by engine name —
    a pure function of engine state, so all planes serve identical
    bytes."""
    return {"engines": {e.name: e.state() for e in engines()}}


def default_survivors(height: int, view, honest):
    """The default gather: (shares (n,n,S) uint8, present (n,n) bool).

    `view` is the adversary-filtered serve view (what the network answers
    this node); `honest` is the node's retained proof state, whose forest
    level-0 leaf digests chain to the DAH the node committed.  Two rules:

      * a coordinate the adversary withholds never answers — the
        simulation's fetch failure (chaos.active_adversary's withheld
        set IS the model's ground truth of "nobody served this");
      * every share that DOES answer is verified against the committed
        leaf digest before it may count as a survivor — a malformed
        share hashes to the wrong leaf and is excluded, so tampered
        bytes cannot poison the repair (the survivors stay authoritative
        inside da/repair, so this gate must hold at the door).
    """
    import numpy as np

    from celestia_app_tpu import chaos
    from celestia_app_tpu.constants import (
        NAMESPACE_SIZE,
        PARITY_NAMESPACE_BYTES,
    )

    k = view.k
    n = 2 * k
    shares = np.array(np.asarray(view.eds._eds), dtype=np.uint8, copy=True)
    present = np.ones((n, n), dtype=bool)
    adv = chaos.active_adversary()
    if adv is not None and adv.withhold_frac > 0:
        for (r, c) in adv.withheld_set(height, n):
            present[r, c] = False
    # ONE gather for every committed level-0 digest (the whole height
    # answers only the retryable status while this runs, so the gather
    # phase must not pay n round trips where one take suffices), then
    # ONE batched leaf-hash dispatch over every coordinate that answered
    # (serve/verify.leaf_digests — host NmtHasher fallback byte-identical
    # via the proof.verify seam): a share that hashes to the wrong
    # committed digest is excluded, so tampered bytes cannot poison the
    # repair.
    expect = honest.gather("row", [
        honest.flat_index(r, 0, c) for r in range(n) for c in range(n)
    ]).reshape(n, n, -1)
    from celestia_app_tpu.serve.verify import leaf_digests

    coords = [(r, c) for r in range(n) for c in range(n) if present[r, c]]
    if coords:
        rows = np.array([r for r, _ in coords])
        cols = np.array([c for _, c in coords])
        ns = shares[rows, cols, :NAMESPACE_SIZE].copy()
        parity = (rows >= k) | (cols >= k)
        ns[parity] = np.frombuffer(PARITY_NAMESPACE_BYTES, dtype=np.uint8)
        got = leaf_digests(ns, shares[rows, cols])
        ok = np.all(got == expect[rows, cols], axis=1)
        present[rows[~ok], cols[~ok]] = False
    return shares, present


class HealingEngine:
    """The per-node heal loop over one DasProvider.

    Detection notes enqueue a height and mark it mid-heal (samples get
    the retryable status immediately); `start()` runs a worker thread,
    `process_pending()` drains synchronously (drills, tests).  Bounded
    retry with exponential backoff per height; terminal failures land in
    quarantine, never in a retry storm.
    """

    def __init__(self, provider, *, name: str = "node",
                 committed_dah=None, survivors=None,
                 max_attempts: int = 3, backoff_s: float = 0.02,
                 retry_after_s: float = 1.0, sleep=time.sleep):
        self.provider = provider
        self.name = name
        self.max_attempts = max(int(max_attempts), 1)
        self.backoff_s = backoff_s
        self.retry_after_s = retry_after_s
        self._committed = committed_dah  # callable(height) -> DAH override
        self._survivors = survivors or default_survivors
        self._sleep = sleep
        self._cv = threading.Condition()
        self._queue: collections.deque = collections.deque()
        self._healing: dict[int, dict] = {}
        # Bounded (oldest evicted): a long-lived node under sustained
        # attack must not grow its health payload or RSS with chain
        # height.  An ancient evicted quarantine record means that
        # height would be re-attempted on a fresh detection — by then
        # the world has usually changed; terminal-forever is not worth
        # an unbounded map.
        self._quarantined: collections.OrderedDict = collections.OrderedDict()
        self._healed: collections.OrderedDict = collections.OrderedDict()
        self._healed_count = 0
        self._last: dict | None = None
        self._thread: threading.Thread | None = None
        self._stop_flag = False
        provider.healer = self
        register(self)

    #: Retained terminal records (memory bound, not a semantic window).
    MAX_RECORDS = 1024
    #: Quarantined heights serialized into state() (the health payload
    #: must stay bounded like /namespaces' top-N cap).
    STATE_QUARANTINED = 16

    # --- subscription -------------------------------------------------------
    def note(self, kind: str, height: int, entry=None) -> bool:
        """One detection signal.  Returns True when the height was
        enqueued for healing; False when it is not this engine's (the
        entry's owning cache is another node's), already mid-heal (the
        healer's own repair hitting RootMismatch must not recurse), or
        quarantined (terminal: no heal storm)."""
        if entry is not None:
            if getattr(entry, "owner", None) is not self.provider.cache:
                return False
        elif not self.provider.cache.contains(height):
            return False
        with self._cv:
            if height in self._healing or height in self._quarantined:
                return False
            self._healing[height] = {
                "kind": kind,
                "t0": time.perf_counter(),
                "t0_ns": time.time_ns(),
            }
            self._queue.append(height)
            self._cv.notify()
        return True

    def healing(self, height: int) -> bool:
        with self._cv:
            return height in self._healing

    def is_quarantined(self, height: int) -> bool:
        with self._cv:
            return height in self._quarantined

    # --- processing ---------------------------------------------------------
    def start(self) -> "HealingEngine":
        with self._cv:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop_flag = False
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"healer-{self.name}"
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stop_flag:
                    self._cv.wait()
                if not self._queue and self._stop_flag:
                    return
                height = self._queue.popleft()
            try:
                self._heal_one(height)
            except Exception:  # chaos-ok: a dead worker = permanent 503s
                # _heal_one guards its own bookkeeping; this is the
                # belt-and-braces floor — whatever slipped through must
                # not kill the drain loop, or every later detection
                # would mark its height mid-heal forever with nobody
                # left to heal it.
                pass

    def process_pending(self) -> list[tuple[int, str]]:
        """Drain the queue synchronously (drills / tests / a node with no
        worker thread); returns [(height, outcome)]."""
        out: list[tuple[int, str]] = []
        while True:
            with self._cv:
                if not self._queue:
                    return out
                height = self._queue.popleft()
            out.append((height, self._heal_one(height)))

    def close(self, timeout_s: float = 10.0) -> None:
        with self._cv:
            self._stop_flag = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout_s)
        unregister(self)
        if getattr(self.provider, "healer", None) is self:
            self.provider.healer = None

    # --- one heal -----------------------------------------------------------
    def _heal_one(self, height: int) -> str:
        from celestia_app_tpu.da.repair import IrrecoverableSquare
        from celestia_app_tpu.trace.flight_recorder import note_trigger
        from celestia_app_tpu.trace.tracer import traced

        with self._cv:
            info = self._healing.get(height)
        if info is None:  # raced a concurrent resolution
            return "skipped"
        outcome = "quarantined"
        detail = None
        phases_ms: dict[str, float] = {}
        attempt = 0
        try:
            lat = heal_seconds()
            lat.observe(time.perf_counter() - info["t0"], phase="detect")
            for attempt in range(1, self.max_attempts + 1):
                try:
                    phases_ms = self._attempt(height, lat)
                    outcome = "healed"
                    break
                except IrrecoverableSquare as e:
                    # Below the k-survivor threshold: retrying cannot
                    # mint shares that do not exist.
                    outcome, detail = "irrecoverable", f"{e}"
                    break
                except Exception as e:  # chaos-ok: bounded retry, then quarantine
                    detail = f"{type(e).__name__}: {e}"
                    if attempt < self.max_attempts:
                        self._sleep(
                            min(self.backoff_s * 2 ** (attempt - 1), 1.0)
                        )
        finally:
            # The height LEAVES the mid-heal state no matter what raised
            # above — a stranded _healing entry would answer 503 forever
            # with nobody left to heal it.
            total_s = time.perf_counter() - info["t0"]
            rec = {
                "height": height,
                "kind": info["kind"],
                "outcome": outcome,
                "attempts": attempt,
                "total_ms": round(total_s * 1e3, 3),
                "phases_ms": phases_ms,
                "detail": detail,
            }
            with self._cv:
                self._healing.pop(height, None)
                self._last = rec
                if outcome == "healed":
                    self._healed[height] = rec
                    self._healed_count += 1
                    while len(self._healed) > self.MAX_RECORDS:
                        self._healed.popitem(last=False)
                else:
                    self._quarantined[height] = rec
                    while len(self._quarantined) > self.MAX_RECORDS:
                        self._quarantined.popitem(last=False)
        lat.observe(total_s, phase="total")
        heal_total().inc(outcome=outcome)
        from celestia_app_tpu.trace.context import current_context

        ctx = current_context()
        traced().write(
            "heal", node=self.name, height=height, kind=info["kind"],
            outcome=outcome, attempts=attempt, total_ms=rec["total_ms"],
            # The per-phase split and (when the heal runs under a request
            # trace, e.g. a detection on the serve path) the trace_id:
            # the height timeline stitches this row's anatomy from them.
            phases_ms=phases_ms,
            trace_id=ctx.trace_id if ctx is not None else None,
        )
        note_trigger(
            "heal_completed" if outcome == "healed" else "heal_quarantined",
            node=self.name, height=height, kind=info["kind"],
            outcome=outcome, attempts=attempt, total_ms=rec["total_ms"],
            phases_ms=phases_ms, detail=detail,
        )
        return outcome

    def _attempt(self, height: int, lat) -> dict[str, float]:
        """gather -> repair -> verify -> readmit, each timed; raises on
        any failed leg (the retry/quarantine policy lives in the
        caller)."""
        from celestia_app_tpu.da.dah import DataAvailabilityHeader
        from celestia_app_tpu.da.repair import RootMismatch, repair

        provider = self.provider
        t = time.perf_counter()
        honest = provider._honest_entry(height)
        view = provider.serve_view(height)
        shares, present = self._survivors(height, view, honest)
        gather_s = time.perf_counter() - t
        lat.observe(gather_s, phase="gather")

        committed = (
            self._committed(height)
            if self._committed is not None
            else DataAvailabilityHeader(
                row_roots=list(honest.row_roots),
                column_roots=list(honest.col_roots),
            )
        )
        t = time.perf_counter()
        # The sweep and re-extension ride guarded_dispatch inside repair:
        # a chaos dispatch_fail here walks the ladder, never wedges us.
        recovered = repair(shares, present, height=height)
        repair_s = time.perf_counter() - t
        lat.observe(repair_s, phase="repair")

        t = time.perf_counter()
        got = DataAvailabilityHeader.from_eds(recovered)
        if not got.equals(committed) or (
            recovered.data_root() != committed.hash()
        ):
            # Root-verify BEFORE anything can see the bytes: a recovery
            # that cannot prove itself is a failed attempt, not a served
            # square.
            raise RootMismatch(
                f"healed square at height {height} does not reproduce "
                "the committed DAH"
            )
        verify_s = time.perf_counter() - t
        lat.observe(verify_s, phase="verify")

        t = time.perf_counter()
        provider.cache.readmit(height, recovered, healed=True)
        readmit_s = time.perf_counter() - t
        lat.observe(readmit_s, phase="readmit")
        return {
            "gather": round(gather_s * 1e3, 3),
            "repair": round(repair_s * 1e3, 3),
            "verify": round(verify_s * 1e3, 3),
            "readmit": round(readmit_s * 1e3, 3),
        }

    # --- introspection ------------------------------------------------------
    def state(self) -> dict:
        """The /healthz "heal" block / GET /heal unit: bounded, JSON-safe
        (only the newest STATE_QUARANTINED quarantine records serialize —
        the /namespaces top-N discipline; `quarantined_total` keeps the
        full count honest)."""
        with self._cv:
            newest = sorted(self._quarantined)[-self.STATE_QUARANTINED:]
            return {
                "healing": sorted(self._healing),
                "quarantined": {
                    str(h): {
                        "outcome": self._quarantined[h]["outcome"],
                        "kind": self._quarantined[h]["kind"],
                        "attempts": self._quarantined[h]["attempts"],
                        "detail": self._quarantined[h]["detail"],
                    }
                    for h in newest
                },
                "quarantined_total": len(self._quarantined),
                "healed": self._healed_count,
                "last": dict(self._last) if self._last else None,
            }

"""serve/: the batched proof-serving plane — the READ side of DA.

Everything before this package wrote: build squares, commit roots, page
when a p99 burns.  This package is what light clients actually consume —
NMT inclusion proofs for sampled shares (the DAS workload, "millions of
users" in ROADMAP terms; ACeD's scalable DA-oracle read path):

  cache.py    ForestCache: device-resident EDS + row/col NMT forests for
              the last $CELESTIA_SERVE_HEIGHTS heights (LRU), host spill
              below that — proofs never become unservable, only slower.
  sampler.py  ProofSampler: queued sample requests answered a whole batch
              per dispatch (share gather + vectorized Merkle-path
              extraction from the cached forest), with a pure-host
              fallback pinned bit-identical (the fused->staged seam of
              the read side; chaos seam `proof.serve`).
  api.py      DasProvider: the one payload builder all three RPC planes
              serve, so GetShareProof / GetSharesByNamespace responses
              are byte-identical across JSON-RPC, REST, and gRPC by
              construction (the /metrics exposition pattern).
  heal.py     HealingEngine: the detect -> repair -> re-serve loop — a
              ShareWithheld / BadProofDetected / RootMismatch detection
              triggers batched repair from verified survivors, the
              recovered square is root-verified against the committed
              DAH, re-admitted (ForestCache.readmit), and the withheld
              coordinates serve again; failures land in per-height
              quarantine ($CELESTIA_HEAL=1 wires one automatically).

Wire-up: ServingNode retains each committed height's EDS into its cache
(rpc/server.py) and registers a DasProvider on the shared exposition
handler, which mounts `GET /das/share_proof` and `GET /das/shares` on
every serving plane; the gRPC plane additionally speaks a real
celestia.tpu.das.v1.Das service carrying the same payload bytes.
"""

from __future__ import annotations

import os

from celestia_app_tpu.serve.cache import ForestCache  # noqa: F401
from celestia_app_tpu.serve.sampler import ProofSampler, serve_mode  # noqa: F401


def serve_heights() -> int:
    """$CELESTIA_SERVE_HEIGHTS: device-resident cached heights (LRU size);
    0 disables retention entirely (proofs rebuild from block txs)."""
    try:
        return int(os.environ.get("CELESTIA_SERVE_HEIGHTS", "4") or "4")
    except ValueError:
        return 4


def spill_heights() -> int:
    """$CELESTIA_SERVE_SPILL: host-spill tier size (heights evicted from
    the device tier land here as numpy copies before dropping entirely);
    default 2x the device tier."""
    try:
        raw = os.environ.get("CELESTIA_SERVE_SPILL", "")
        return int(raw) if raw else 2 * serve_heights()
    except ValueError:
        return 2 * serve_heights()

"""ForestCache: device-resident EDS + NMT forests over the last N heights.

`kernels/fused.py` materializes every NMT level on device and throws all
but the 4k roots away; the serve plane's unlock is keeping them.  At
cache admission one extra dispatch (`kernels.fused.jit_forest`) rebuilds
both axis forests from the retained EDS buffer into two flat (N, 90)
device arrays — every inner node of every row/column tree, indexable by
(tree, level, index) via `forest_level_layout` — after which a whole
batch of DAS sample proofs is two gathers (serve/sampler.py), zero
hashes.

Tiers (all bounded, so the serve plane's memory is a knob, not a leak):

  device  the last $CELESTIA_SERVE_HEIGHTS heights, LRU — jnp arrays,
          answering batches at gather speed;
  host    the next $CELESTIA_SERVE_SPILL evicted heights as numpy copies
          (same bytes; numpy gathers) — slower, never unservable;
  gone    beyond spill the entry drops; the DasProvider rebuilds the
          square from the block store's raw txs on demand (the
          pre-existing querier path) and re-admits it.

A cache hit/miss and the tier it landed on tick
celestia_serve_cache_{hits,misses}_total; evictions tick
celestia_serve_cache_evictions_total{tier}; /healthz's ServingNode layer
reports resident heights + hit ratio so a stuck-at-cold cache is one
probe away.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict

import numpy as np


def _caches_owned_bytes() -> int:
    """Device-tier bytes held by every live ForestCache: the retained
    EDS buffer plus both flat forests per resident height.  Spilled
    (host-tier) entries are host RAM the allocator reports elsewhere."""
    total = 0
    for cache in list(_ALL_CACHES):
        with cache._lock:
            entries = list(cache._device.values())
        for e in entries:
            try:
                total += int(e.eds._eds.nbytes)
                total += int(e.row_flat.nbytes) + int(e.col_flat.nbytes)
            except Exception:  # chaos-ok: entry mid-spill/deleted
                continue
    return total


_ALL_CACHES: "weakref.WeakSet[ForestCache]" = weakref.WeakSet()

from celestia_app_tpu.trace.device_ledger import (  # noqa: E402
    register_owner as _register_owner,
)

_register_owner("serve_forest_cache", _caches_owned_bytes)


class _ForestLineTree:
    """The `levels()` surface of one row/column tree, backed by flat
    forest arrays — what eds.row_tree returns when a forest is resident,
    so nmt.proof.prove_range_from_levels assembles proofs by indexing."""

    def __init__(self, forest: "CachedForest", axis: str, index: int):
        self._forest = forest
        self._axis = axis
        self._index = index
        self._levels: list[list[bytes]] | None = None

    def levels(self) -> list[list[bytes]]:
        if self._levels is None:
            self._levels = self._forest.line_levels(self._axis, self._index)
        return self._levels

    def root(self) -> bytes:
        return self.levels()[-1][0]


class CachedForest:
    """One height's retained proof state.

    Holds the EDS (2k, 2k, S) buffer, both flat forests, the host root
    list + memoized data-root tree levels (merkle.levels_from_leaves, so
    RowProof audit paths are indexing too), and the ODS namespace grid
    for namespace-range queries.  `spill()` converts the device arrays to
    numpy in place — the bytes every accessor returns are identical
    either way (the tier only moves where the gather runs).
    """

    def __init__(self, height: int, eds, row_flat, col_flat):
        from celestia_app_tpu import merkle
        from celestia_app_tpu.kernels.fused import forest_level_layout

        self.height = height
        self.k = eds.k
        self.eds = eds
        self.device_resident = True
        # Provenance for the healing loop (serve/heal.py): `owner` is the
        # admitting ForestCache (detection signals route to the engine
        # whose cache owns the sampled entry); `healed` marks a height
        # recovered by repair and ROOT-VERIFIED locally — the adversary
        # sits between this node and the network, not between this node
        # and its own verified store, so healed entries are served
        # without the withholding/tampering intercepts.
        self.owner = None
        self.healed = False
        self.row_flat = row_flat  # (N, 90) — all row-tree levels, flat
        self.col_flat = col_flat
        # Share sharding (the multi-chip extend plane, kernels/
        # panel_sharded.py): when the retained EDS buffer arrived
        # row-partitioned across an extend mesh, admission keeps it
        # AS-IS — no copy, no reshard — and share reads route each
        # coordinate to its owning shard (gather_shares below).
        # Discovered from the buffer, not an env knob, so a process can
        # serve sharded and unsharded heights side by side.
        from celestia_app_tpu.serve.shard import eds_share_layout

        layout = eds_share_layout(eds._eds)
        self.share_shards = layout[2] if layout is not None else 0
        self.widths, self.offsets = forest_level_layout(self.k)
        self.row_roots = eds.row_roots()
        self.col_roots = eds.col_roots()
        self.data_root = eds.data_root()
        self.root_levels = merkle.levels_from_leaves(
            self.row_roots + self.col_roots
        )
        eds.attach_forest(self)

    # --- indexing ----------------------------------------------------------
    def flat_index(self, tree: int, level: int, index: int) -> int:
        """Flat row of node (tree, level, index) — forest_level_layout's
        contract, shared with the sampler's batch index plan."""
        return self.offsets[level] + tree * self.widths[level] + index

    def _flat(self, axis: str):
        return self.row_flat if axis == "row" else self.col_flat

    def gather(self, axis: str, flat_indices) -> np.ndarray:
        """(len(flat_indices), 90) node bytes in one take — jnp on the
        device tier, numpy after spill; same bytes either way."""
        flat = self._flat(axis)
        if isinstance(flat, np.ndarray):
            return flat[np.asarray(flat_indices, dtype=np.int64)]
        import jax.numpy as jnp

        return np.asarray(
            jnp.take(flat, jnp.asarray(flat_indices, dtype=jnp.int32), axis=0)
        )

    def gather_shares(self, coords) -> np.ndarray:
        """(B, SHARE_SIZE) shares for [(row, col), ...] in one take.

        A share-sharded EDS (the multi-chip extend plane's committed
        row partition) answers as ONE sharded program with each
        coordinate routed to its owning shard's buffer — no reshard,
        ever (serve/shard.sharded_share_gather); a fault there degrades
        to the single-device take below, bit-identically."""
        n = 2 * self.k
        buf = self.eds._eds
        if self.share_shards and not isinstance(buf, np.ndarray):
            from celestia_app_tpu.serve.shard import sharded_share_gather

            out = sharded_share_gather(buf, coords)
            if out is not None:
                return out
        idx = [r * n + c for r, c in coords]
        if isinstance(buf, np.ndarray):
            flat = buf.reshape(n * n, buf.shape[-1])
            return flat[np.asarray(idx, dtype=np.int64)]
        import jax.numpy as jnp

        flat = buf.reshape(n * n, buf.shape[-1])
        return np.asarray(
            jnp.take(flat, jnp.asarray(idx, dtype=jnp.int32), axis=0)
        )

    def line_levels(self, axis: str, index: int) -> list[list[bytes]]:
        """All digest levels of one tree, as host bytes (one gather)."""
        idx = [
            self.flat_index(index, lvl, i)
            for lvl, w in enumerate(self.widths)
            for i in range(w)
        ]
        nodes = self.gather(axis, idx)
        levels: list[list[bytes]] = []
        pos = 0
        for w in self.widths:
            levels.append(
                [bytes(nodes[pos + i].tobytes()) for i in range(w)]
            )
            pos += w
        return levels

    def line_tree(self, axis: str, index: int) -> _ForestLineTree:
        return _ForestLineTree(self, axis, index)

    # --- tier movement -----------------------------------------------------
    def spill(self) -> None:
        """Device -> host: numpy copies of the EDS and both forests (the
        proofs keep serving, the gathers just run on host memory)."""
        if not self.device_resident:
            return
        self.row_flat = np.asarray(self.row_flat)
        self.col_flat = np.asarray(self.col_flat)
        self.eds._eds = np.asarray(self.eds._eds)
        self.device_resident = False
        self.share_shards = 0  # the host copy is one buffer, unsharded


class ForestCache:
    """LRU over heights, two tiers (device + host spill), thread-safe."""

    def __init__(self, heights: int | None = None, spill: int | None = None):
        self._heights = heights
        self._spill = spill
        self._lock = threading.Lock()
        self._device: OrderedDict[int, CachedForest] = OrderedDict()
        self._host: OrderedDict[int, CachedForest] = OrderedDict()
        self._hits = {"device": 0, "host": 0}
        self._misses = 0
        self._last_eviction: int | None = None
        # Single-flight per height: concurrent misses on one height must
        # not each pay a forest dispatch (and transiently hold N copies
        # of the EDS+forests) only for the last put to win.
        self._building: dict = {}
        _ALL_CACHES.add(self)

    def _capacity(self) -> tuple[int, int]:
        from celestia_app_tpu.serve import serve_heights, spill_heights

        return (
            self._heights if self._heights is not None else serve_heights(),
            self._spill if self._spill is not None else spill_heights(),
        )

    # --- admission ---------------------------------------------------------
    def put(self, height: int, eds) -> CachedForest | None:
        """Retain one height: build the forest (ONE extra dispatch) and
        admit it to the device tier, evicting oldest-first down the
        tiers.  Returns the entry, or None when retention is disabled
        ($CELESTIA_SERVE_HEIGHTS=0).

        Retention is also the write-after-retain fence for the stream
        pipeline's persistent buffer ring: admitting here runs
        `eds.attach_forest`, which notifies the ring that fed this square
        (parallel/pipeline._BufferRing.pin) so the staging slot behind it
        is swapped — never overwritten — while this entry serves proofs
        (donation may alias the upload into the retained EDS)."""
        cap, spill_cap = self._capacity()
        if cap <= 0:
            return None
        with self._lock:
            existing = self._device.get(height)
            if existing is not None:
                self._device.move_to_end(height)
                return existing
            gate = self._building.get(height)
            if gate is None:
                gate = self._building[height] = threading.Lock()
        with gate:
            with self._lock:
                existing = self._device.get(height)
                if existing is not None:  # a concurrent put already built it
                    self._device.move_to_end(height)
                    self._building.pop(height, None)
                    return existing
            from celestia_app_tpu.serve.shard import build_entry

            t0 = time.perf_counter()
            entry = build_entry(height, eds)
            build_ms = (time.perf_counter() - t0) * 1e3
            entry.owner = self
            # Admission happens INSIDE the gate: a concurrent put that
            # passes the gate next must find the entry resident, or the
            # single-flight promise ("one forest dispatch per height")
            # would leak through the build->admit window.
            spilled, dropped = self._admit(entry, cap, spill_cap)
        self._building.pop(height, None)
        self._trace_admission("admit", height, build_ms, spilled, dropped)
        self._count_evictions(len(spilled), len(dropped))
        self._publish_residency()
        self._invalidate_tamper_memo(height)
        return entry

    def _admit(self, entry: CachedForest, cap: int, spill_cap: int
               ) -> tuple[list[int], list[int]]:
        """Insert `entry` at the device tier's MRU end (REPLACING any
        resident same-height entry), spill device overflow to host, drop
        host overflow; returns (spilled heights, dropped heights).
        Caller holds the height's build gate."""
        evicted: list[CachedForest] = []
        dropped: list[int] = []
        with self._lock:
            self._host.pop(entry.height, None)  # re-admission promotes
            self._device[entry.height] = entry
            self._device.move_to_end(entry.height)
            while len(self._device) > cap:
                h, old = self._device.popitem(last=False)
                evicted.append(old)
                self._last_eviction = h
            for old in evicted:
                old.spill()
                self._host[old.height] = old
                self._host.move_to_end(old.height)
            while len(self._host) > spill_cap:
                h, _old = self._host.popitem(last=False)
                dropped.append(h)
        return [e.height for e in evicted], dropped

    def readmit(self, height: int, eds, *, healed: bool = True
                ) -> CachedForest | None:
        """Repair-driven re-admission: install the RECOVERED (already
        root-verified — serve/heal.py's verify phase gates this call)
        square for a height, replacing whatever is resident.

        Rides the same per-height single-flight gate as `put`, so a
        heal racing a rebuild-on-miss coalesces: when the gate opens on
        an entry already serving the same data root (the rebuild won the
        race with identical bytes), that entry is KEPT — one forest
        build total, and its retention pins (eds._retain_cb, the PR 9
        write-after-retain fence) are left untouched — and only marked
        healed.  Either way the adversary's per-height tamper memo is
        evicted, so recovery is visible on the very next request, with
        no process restart."""
        cap, spill_cap = self._capacity()
        if cap <= 0:  # retention disabled: nothing to re-admit into
            self._invalidate_tamper_memo(height)
            return None
        with self._lock:
            gate = self._building.get(height)
            if gate is None:
                gate = self._building[height] = threading.Lock()
        root = eds.data_root()
        with gate:
            with self._lock:
                existing = self._device.get(height) or self._host.get(height)
            if existing is not None and existing.data_root == root:
                # Keep the resident entry on whichever tier it lives on
                # (its gathers already serve these exact bytes); only
                # freshen its LRU slot and mark it healed.
                entry = existing
                entry.healed = entry.healed or healed
                spilled, dropped = [], []
                build_ms = 0.0
                with self._lock:
                    if height in self._device:
                        self._device.move_to_end(height)
                    elif height in self._host:
                        self._host.move_to_end(height)
            else:
                from celestia_app_tpu.serve.shard import build_entry

                t0 = time.perf_counter()
                entry = build_entry(height, eds)
                build_ms = (time.perf_counter() - t0) * 1e3
                entry.owner = self
                entry.healed = healed
                spilled, dropped = self._admit(entry, cap, spill_cap)
        self._building.pop(height, None)
        self._trace_admission("readmit", height, build_ms, spilled, dropped)
        self._count_evictions(len(spilled), len(dropped))
        self._publish_residency()
        self._invalidate_tamper_memo(height)
        return entry

    @staticmethod
    def _invalidate_tamper_memo(height: int) -> None:
        """Every (re-)admission drops the adversary's memoized tampered
        view of the height: the memo exists so one attack serves ONE
        corrupted square, but a square that was re-admitted (healed,
        rebuilt) is new state — serving the stale tampered copy would
        hide the recovery until a process restart.  One injector read
        when no chaos is configured; never raises."""
        try:
            from celestia_app_tpu import chaos

            adv = chaos.active_adversary()
            if adv is not None:
                adv.invalidate_tampered(height)
        except Exception:  # chaos-ok: admission must not depend on chaos state
            pass

    def contains(self, height: int) -> bool:
        """Counter-free residency probe (any tier) — the healing engine's
        "is this height mine" check must not skew hit/miss accounting."""
        with self._lock:
            return height in self._device or height in self._host

    @staticmethod
    def _trace_admission(event: str, height: int, build_ms: float,
                         spilled: list[int], dropped: list[int]) -> None:
        """One `forest_cache` row per admission (with the forest-build
        dispatch time) plus one per height it pushed down a tier — the
        height timeline's retention-churn signal (trace/timeline.py)."""
        from celestia_app_tpu.trace.tracer import traced

        tracer = traced()
        tracer.write("forest_cache", event=event, height=height,
                     forest_build_ms=round(build_ms, 3))
        for h in spilled:
            tracer.write("forest_cache", event="spill", height=h)
        for h in dropped:
            tracer.write("forest_cache", event="drop", height=h)

    def _count_evictions(self, spilled: int, dropped: int) -> None:
        if not (spilled or dropped):
            return
        from celestia_app_tpu.trace.metrics import registry

        ev = registry().counter(
            "celestia_serve_cache_evictions_total",
            "serve-cache evictions by destination tier "
            "(device->host spill; host->dropped)",
        )
        if spilled:
            ev.inc(spilled, tier="host")
        if dropped:
            ev.inc(dropped, tier="dropped")

    def _publish_residency(self) -> None:
        from celestia_app_tpu.trace.metrics import registry

        gauge = registry().gauge(
            "celestia_serve_cache_resident",
            "heights resident in the serve cache, by tier",
        )
        with self._lock:
            gauge.set(len(self._device), tier="device")
            gauge.set(len(self._host), tier="host")

    # --- lookup ------------------------------------------------------------
    def get(self, height: int) -> tuple[CachedForest | None, str]:
        """(entry, tier) where tier is "device" / "host" / "miss"."""
        from celestia_app_tpu.trace.metrics import registry

        with self._lock:
            entry = self._device.get(height)
            if entry is not None:
                self._device.move_to_end(height)
                self._hits["device"] += 1
                tier = "device"
            else:
                entry = self._host.get(height)
                if entry is not None:
                    self._host.move_to_end(height)
                    self._hits["host"] += 1
                    tier = "host"
                else:
                    self._misses += 1
                    tier = "miss"
        if entry is not None:
            registry().counter(
                "celestia_serve_cache_hits_total",
                "serve-cache lookups answered, by tier",
            ).inc(tier=tier)
        else:
            registry().counter(
                "celestia_serve_cache_misses_total",
                "serve-cache lookups that fell through to a rebuild",
            ).inc()
        return entry, tier

    # --- introspection ------------------------------------------------------
    def stats(self) -> dict:
        """The /healthz "serve" block: residency, hit ratio, last
        eviction — a stuck-at-cold cache (all misses, nothing resident)
        is one probe away.  When the plane is sharded
        ($CELESTIA_SERVE_SHARDS > 1, serve/shard.py) the "mesh" key
        reports the shard count, axis, and per-shard resident forest
        bytes; None on the single-device plane."""
        from celestia_app_tpu.serve.shard import mesh_stats

        with self._lock:
            hits = dict(self._hits)
            misses = self._misses
            total = hits["device"] + hits["host"] + misses
            entries = list(self._device.values())
            out = {
                "device_heights": sorted(self._device),
                "host_heights": sorted(self._host),
                "hits": hits,
                "misses": misses,
                "hit_ratio": (
                    round((hits["device"] + hits["host"]) / total, 4)
                    if total else None
                ),
                "last_eviction": self._last_eviction,
            }
        out["mesh"] = mesh_stats(self, entries)
        return out

    def reset_for_tests(self) -> None:
        with self._lock:
            self._device.clear()
            self._host.clear()
            self._hits = {"device": 0, "host": 0}
            self._misses = 0
            self._last_eviction = None

"""DasProvider: the ONE payload builder every serving plane answers with.

The repo's cross-plane identity pattern (trace/exposition.py): byte-equal
responses are structural when all planes call one renderer, never a test
invariant to chase.  The JSON-RPC server, the REST gateway, and the gRPC
plane's debug sidecar all route `GET /das/share_proof` and
`GET /das/shares` through the shared observability handler, which calls
the registered DasProvider here; the real gRPC Das service
(rpc/grpc_plane.py) and the JSON-RPC POST methods (rpc/server.py) carry
the same `render()` bytes / payload dicts.

Payloads are a pure function of chain state (height, coordinates, the
committed proofs) — cache tier, timing, and plane never leak in, so two
scrapes of the same request on different planes are identical bytes.
Every served proof verifies against the height's committed DAH data root
via the existing ShareProof.verify (clients reconstruct the dataclasses
with rpc/codec.share_proof_from_json).
"""

from __future__ import annotations

import json
import threading
from collections import OrderedDict

from celestia_app_tpu.constants import NAMESPACE_SIZE, PARITY_NAMESPACE_BYTES


def render(payload: dict) -> bytes:
    """Canonical response bytes (sorted keys, compact separators) — the
    byte-identity unit shared by the GET routes and the gRPC service."""
    return json.dumps(payload, sort_keys=True, separators=(",", ":")).encode()


def payload_namespace_label(payload) -> str:
    """The CAPPED per-tenant label of a served payload (the PR 4
    accounting plane's cardinality contract): the proved share's
    namespace for share_proof payloads, the queried namespace for
    shares payloads, the reserved `other` bucket when the payload
    carries none (parity shares, errors, absent payloads)."""
    from celestia_app_tpu.trace.square_journal import (
        OTHER_LABEL,
        capped_namespace_label,
        namespace_label,
    )

    ns_hex = None
    if isinstance(payload, dict):
        ns_hex = payload.get("namespace")
        if ns_hex is None and isinstance(payload.get("proof"), dict):
            ns_hex = payload["proof"].get("namespace")
    if not isinstance(ns_hex, str) or not ns_hex:
        return OTHER_LABEL
    try:
        ns = bytes.fromhex(ns_hex)
    except ValueError:
        return OTHER_LABEL
    if ns == PARITY_NAMESPACE_BYTES:
        # Parity shares are not a tenant (the sampler's twin
        # _proof_namespace_label applies the same fold): 3/4 of uniform
        # DAS coordinates would otherwise burn a capped-cardinality slot
        # on 0xff..ff and split this counter from the latency histogram.
        return OTHER_LABEL
    return capped_namespace_label(namespace_label(ns))


def payload_shard_label(payload) -> str:
    """Bounded `shard` label of one served payload: the serve shard
    owning the sampled coordinate's leaf node (serve/shard.py's routing
    math on the payload's own row/col/square_size), "0" whenever the
    plane is unsharded or the payload carries no coordinate (namespace
    queries, errors).  One env read on the single-device plane."""
    from celestia_app_tpu.serve.shard import leaf_shard_of, serve_shards

    shards = serve_shards()
    if shards <= 1 or not isinstance(payload, dict):
        return "0"
    k, row, col = (
        payload.get("square_size"), payload.get("row"), payload.get("col")
    )
    if not all(isinstance(v, int) for v in (k, row, col)):
        return "0"
    return str(leaf_shard_of(k, shards, row, col, payload.get("axis", "row")))


def count_served(plane: str, kind: str, payload=None) -> None:
    """One served DAS response: per-plane, per-kind, per-tenant (capped
    namespace label, the PR 4 accounting plane), and — when the serve
    plane is sharded — per owning shard (bounded by the shard count)."""
    from celestia_app_tpu.trace.metrics import registry

    registry().counter(
        "celestia_proofs_served_total",
        "DAS proofs served, by serving plane, query kind, (capped) "
        "namespace, and owning serve shard",
    ).inc(
        plane=plane, kind=kind,
        namespace=payload_namespace_label(payload),
        shard=payload_shard_label(payload),
    )
    # The height timeline's closing event: the FIRST served answer for a
    # height finalizes its record and observes the critical-path
    # histograms (trace/timeline.py); later serves just bump the count.
    if isinstance(payload, dict) and payload.get("height") is not None:
        from celestia_app_tpu.trace.timeline import timeline

        timeline().note_first_serve(payload.get("height"), plane, kind)


class UnknownHeight(KeyError):
    """No cached, spilled, or rebuildable square at this height (a 404)."""


# --- DAS coverage map ---------------------------------------------------------
#
# Which coordinates of a retained height have actually been DECIDED by the
# serving plane — the observable both PCMT papers' P(detect|s) curves are
# a function of.  A cell is ticked where a payload is decided: a served
# share_proof / namespace range / attestation set marks its coordinates
# `sampled` (or `verified` when the verification gate was armed and the
# proofs chained to the committed root), and the terminal refusals mark
# them with DISTINCT states — `withheld` (410: the proposer hid the
# share) and `tampered` (502: the served view contradicts the committed
# root) — so the map separates "nobody asked" from "asked and refused".
# Precedence is refusal > verified > sampled > unseen: a cell never
# forgets the worst thing it proved.

COVERAGE_STATES = ("sampled", "verified", "withheld", "tampered")
_STATE_RANK = {"sampled": 1, "verified": 2, "withheld": 3, "tampered": 4}
_RANK_NAME = ("unseen",) + COVERAGE_STATES
_RANK_CHAR = ".svwt"
#: Retained coverage maps (per height); oldest evicted — matches the
#: serve cache's "last N heights" retention shape without coupling to it.
COVERAGE_RETAIN = 64
#: Bitmaps render inline on /das/coverage only up to this edge (cells =
#: edge^2); larger squares serve counts + ratio with map_omitted=true.
MAX_COVERAGE_MAP_EDGE = 64

_COVERAGE_LOCK = threading.Lock()
_COVERAGE: OrderedDict[int, "CoverageMap"] = OrderedDict()


class CoverageMap:
    """Per-height coordinate state grid over the EXTENDED square (2k x
    2k), one byte per cell holding the state rank."""

    def __init__(self, height: int, k: int):
        self.height = height
        self.k = k
        self.cells = bytearray((2 * k) * (2 * k))

    def tick(self, coords, state: str) -> None:
        rank = _STATE_RANK[state]
        n = 2 * self.k
        for row, col in coords:
            if 0 <= row < n and 0 <= col < n:
                i = row * n + col
                if rank > self.cells[i]:
                    self.cells[i] = rank

    def counts(self) -> dict[str, int]:
        by_rank = [0] * len(_RANK_NAME)
        for c in self.cells:
            by_rank[c] += 1
        return {name: by_rank[i] for i, name in enumerate(_RANK_NAME)}

    def ratio(self) -> float:
        """Fraction of coordinates with ANY decision (served or refused)
        — refused cells count as covered: a refusal IS a detection
        datapoint, not a gap in sampling."""
        total = len(self.cells)
        if not total:
            return 0.0
        return sum(1 for c in self.cells if c) / total

    def payload(self) -> dict:
        n = 2 * self.k
        out: dict = {
            "height": self.height,
            "square_size": self.k,
            "ratio": self.ratio(),
            "counts": self.counts(),
        }
        if n <= MAX_COVERAGE_MAP_EDGE:
            out["map"] = [
                "".join(_RANK_CHAR[c] for c in self.cells[r * n:(r + 1) * n])
                for r in range(n)
            ]
            out["map_omitted"] = False
        else:
            out["map_omitted"] = True
        return out


def coverage_tick(height: int, k: int, coords, state: str) -> None:
    """Record one payload decision on the height's coverage map and
    refresh `celestia_das_coverage_ratio{k}` (the gauge tracks the most
    recently ticked height per square size; per-height detail lives on
    GET /das/coverage)."""
    from celestia_app_tpu.trace.metrics import registry

    with _COVERAGE_LOCK:
        cov = _COVERAGE.get(height)
        if cov is None or cov.k != k:
            cov = _COVERAGE[height] = CoverageMap(height, k)
        _COVERAGE.move_to_end(height)
        while len(_COVERAGE) > COVERAGE_RETAIN:
            _COVERAGE.popitem(last=False)
        cov.tick(coords, state)
        ratio = cov.ratio()
    registry().gauge(
        "celestia_das_coverage_ratio",
        "fraction of the most recently sampled height's extended-square "
        "coordinates with a decided DAS payload (served or refused), "
        "per square size",
    ).set(ratio, k=str(k))


def coverage_payload(height: int) -> dict | None:
    with _COVERAGE_LOCK:
        cov = _COVERAGE.get(height)
        return cov.payload() if cov is not None else None


def coverage_snapshot() -> dict:
    """Summary of every retained height's coverage (no bitmaps) — the
    flight-recorder bundle block and the /das/coverage height listing."""
    with _COVERAGE_LOCK:
        return {
            str(h): {
                "square_size": cov.k,
                "ratio": cov.ratio(),
                "counts": cov.counts(),
            }
            for h, cov in sorted(_COVERAGE.items())
        }


def coverage_response(query_params: dict):
    """GET /das/coverage -> (status, content_type, bytes): per-height
    bitmap with ?height=, the retained-heights summary without — a pure
    function of coverage state, byte-identical on every plane."""
    raw = query_params.get("height")
    if raw is None:
        return 200, "application/json", render({"heights": coverage_snapshot()})
    try:
        height = int(raw)
    except ValueError:
        return 400, "application/json", json.dumps(
            {"error": f"height must be an integer, got {raw!r}"}
        ).encode()
    payload = coverage_payload(height)
    if payload is None:
        return 404, "application/json", json.dumps(
            {"error": f"no coverage recorded at height {height}"}
        ).encode()
    return 200, "application/json", render(payload)


def _reset_coverage_for_tests() -> None:
    with _COVERAGE_LOCK:
        _COVERAGE.clear()


#: Hard cap on samples per attestation request: bounds the gather, the
#: multiproof assembly, and the response body a single query can demand.
MAX_ATTESTATION_SAMPLES = 4096


def parse_attestation_samples(spec: str) -> list[tuple[int, int, str]]:
    """Parse an attestation sample spec — comma-joined `row:col[:axis]`
    items (axis defaults to "row") — into the CANONICAL sample list:
    sorted by (axis, tree, leaf), duplicates dropped.  Every plane parses
    the same spec through this one function, so the canonical order (and
    with it the payload bytes) is structural, not per-plane."""
    out: set[tuple[int, int, str]] = set()
    if not spec.strip():
        raise ValueError("samples spec is empty (want row:col[:axis],...)")
    for item in spec.split(","):
        parts = item.strip().split(":")
        if len(parts) not in (2, 3):
            raise ValueError(
                f"bad sample {item!r} (want row:col or row:col:axis)"
            )
        axis = parts[2] if len(parts) == 3 else "row"
        if axis not in ("row", "col"):
            raise ValueError(f"axis must be 'row' or 'col', got {axis!r}")
        try:
            row, col = int(parts[0]), int(parts[1])
        except ValueError as e:
            raise ValueError(f"bad sample {item!r}: {e}") from e
        if row < 0 or col < 0:
            raise ValueError(f"bad sample {item!r}: negative coordinate")
        out.add((row, col, axis))
    if len(out) > MAX_ATTESTATION_SAMPLES:
        raise ValueError(
            f"{len(out)} samples exceed the per-request cap "
            f"{MAX_ATTESTATION_SAMPLES}"
        )
    # Canonical order: by (axis, tree index, leaf index) — the grouping
    # the multiproof assembly walks, so tree order and range order in the
    # payload are the sort order, never insertion order.
    def key(s):
        row, col, axis = s
        tree, leaf = (row, col) if axis == "row" else (col, row)
        return (axis, tree, leaf)

    return sorted(out, key=key)


def _attestation_latency():
    from celestia_app_tpu.trace.metrics import DEVICE_SECONDS_BUCKETS, registry

    return registry().histogram(
        "celestia_attestation_latency_seconds",
        "attestation build latency by phase (parse/gather/assemble/verify)",
        buckets=DEVICE_SECONDS_BUCKETS,
    )


class DasProvider:
    """Binds a ForestCache + ProofSampler + an optional rebuild source.

    `rebuild(height)` returns an ExtendedDataSquare for a height the
    cache no longer holds (a ServingNode reconstructs it from the block
    store's raw txs — the querier path), or None when the height is
    genuinely unknown; the rebuilt square is re-admitted so the next
    sample is a hit.
    """

    def __init__(self, cache=None, sampler=None, rebuild=None):
        import threading

        from celestia_app_tpu.serve.cache import ForestCache
        from celestia_app_tpu.serve.sampler import ProofSampler

        self.cache = cache if cache is not None else ForestCache()
        self.sampler = sampler if sampler is not None else ProofSampler()
        self.rebuild = rebuild
        # The node's HealingEngine (serve/heal.py), when one is wired:
        # heights mid-heal answer retryable statuses instead of the
        # terminal detection errors.
        self.healer = None
        # Serializes the miss path: N concurrent requests for one evicted
        # height must cost ONE square rebuild + forest build, not N.
        self._rebuild_lock = threading.Lock()

    def entry(self, height: int):
        healer = self.healer
        if healer is not None and healer.healing(height):
            from celestia_app_tpu.serve.heal import HealingInProgress

            # Mid-heal is RETRYABLE (503 + Retry-After / UNAVAILABLE),
            # never the terminal 410/502: the detection that started the
            # heal already got its terminal status, and the client that
            # backs off lands on the healed height.
            raise HealingInProgress(height, healer.retry_after_s)
        return self.serve_view(height)

    def serve_view(self, height: int):
        """The (possibly adversary-filtered) view of a height, WITHOUT
        the mid-heal gate — what the network answers this node.  The
        healing engine gathers from this view (and trusts none of it
        unverified); `entry()` adds the gate for external samplers."""
        entry = self._honest_entry(height)
        if getattr(entry, "healed", False):
            # A height recovered by repair and root-verified locally is
            # served from this node's own store: the withholding /
            # tampering proposer sits between the node and the network,
            # not between the node and its verified bytes.
            return entry
        # The adversary seam: a tampering proposer (malform_shares /
        # wrong_root in $CELESTIA_CHAOS) serves a corrupted VIEW of the
        # height — same object every request, honest cache untouched —
        # which the sampler's verification gate then detects.
        from celestia_app_tpu import chaos

        adv = chaos.active_adversary()
        if adv is not None and adv.tampers():
            return adv.tamper_entry(entry)
        return entry

    def _honest_entry(self, height: int):
        entry, tier = self.cache.get(height)
        if entry is not None:
            return entry
        with self._rebuild_lock:
            entry, tier = self.cache.get(height)  # a peer may have rebuilt
            if entry is not None:
                return entry
            eds = self.rebuild(height) if self.rebuild is not None else None
            if eds is None:
                raise UnknownHeight(f"no square known at height {height}")
            entry = self.cache.put(height, eds)
        if entry is None:  # retention disabled: serve without admitting
            from celestia_app_tpu.serve.shard import build_entry

            entry = build_entry(height, eds)
        return entry

    # --- payload builders ---------------------------------------------------
    def share_proof_payload(
        self, height: int, row: int, col: int, axis: str = "row"
    ) -> dict:
        from celestia_app_tpu.rpc.codec import to_jsonable
        from celestia_app_tpu.serve.sampler import (
            BadProofDetected,
            ShareWithheld,
            _verify_gate_armed,
        )

        entry = self.entry(height)
        try:
            proof = self.sampler.share_proof(entry, row, col, axis=axis)
        except ShareWithheld:
            coverage_tick(height, entry.k, [(row, col)], "withheld")
            raise
        except BadProofDetected:
            coverage_tick(height, entry.k, [(row, col)], "tampered")
            raise
        coverage_tick(
            height, entry.k, [(row, col)],
            "verified" if _verify_gate_armed(entry) else "sampled",
        )
        return {
            "height": height,
            "row": row,
            "col": col,
            "axis": axis,
            "square_size": entry.k,
            "proof": to_jsonable(proof),
            "data_root": entry.data_root.hex(),
        }

    def shares_payload(self, height: int, namespace_hex: str) -> dict:
        from celestia_app_tpu.proof.share_proof import ods_namespace_range
        from celestia_app_tpu.rpc.codec import to_jsonable

        try:
            namespace = bytes.fromhex(namespace_hex)
        except ValueError as e:
            raise ValueError(f"namespace must be hex: {e}") from e
        if len(namespace) != NAMESPACE_SIZE:
            raise ValueError(
                f"namespace must be {NAMESPACE_SIZE} bytes, "
                f"got {len(namespace)}"
            )
        # Read-path QoS: a namespace query names its tenant up front, so
        # the proof-rate gate runs BEFORE any gather work (the sampler's
        # share_proof twin charges the served share's label instead).
        from celestia_app_tpu import qos
        from celestia_app_tpu.trace.square_journal import (
            capped_namespace_label,
            namespace_label,
        )

        enf = qos.enforcer()
        if enf is not None:
            enf.admit_proof(capped_namespace_label(namespace_label(namespace)))
        entry = self.entry(height)
        rng = ods_namespace_range(entry.eds, namespace)
        payload: dict = {
            "height": height,
            "namespace": namespace_hex.lower(),
            "square_size": entry.k,
            "data_root": entry.data_root.hex(),
        }
        if rng is None:
            payload.update({"found": False, "shares": 0, "proof": None})
            return payload
        from celestia_app_tpu.proof.share_proof import new_share_inclusion_proof

        proof = new_share_inclusion_proof(entry.eds, rng[0], rng[1])
        # The same verification gate the sampler applies to share_proof:
        # under a tampering adversary (or $CELESTIA_SERVE_VERIFY=1) a
        # namespace payload built from the served view must chain to the
        # committed root before it leaves — BadProofDetected (502 /
        # DATA_LOSS on the planes) instead of a 200 endorsing forged
        # state.  The found=False branch serves no proof, so there is
        # nothing to endorse there.
        from celestia_app_tpu.serve.sampler import (
            BadProofDetected,
            _verify_gate_armed,
        )

        coords = [(i // entry.k, i % entry.k) for i in range(rng[0], rng[1])]
        try:
            self.sampler._gate(entry, [proof])
        except BadProofDetected:
            coverage_tick(height, entry.k, coords, "tampered")
            raise
        coverage_tick(
            height, entry.k, coords,
            "verified" if _verify_gate_armed(entry) else "sampled",
        )
        payload.update({
            "found": True,
            "start": rng[0],
            "end": rng[1],
            "shares": rng[1] - rng[0],
            "proof": to_jsonable(proof),
        })
        return payload

    def attestation_payload(self, height: int, samples: str) -> dict:
        """One deduped multiproof attestation for a SET of samples.

        s independent `share_proof` responses repeat the upper tree nodes
        of every shared row/column; this payload serializes each NMT node
        ONCE per tree (nmt/proof.multiproof) and each data-root audit
        node once per (level, sibling) coordinate, so the wire cost grows
        ~log instead of ~s x log.  Per-sample ShareProofs reconstruct
        byte-identically from the tables (rpc/codec.
        share_proofs_from_attestation), which is also how the verify gate
        here decides the payload — the gate verifies EXACTLY the bytes a
        client would.

        Same refusal semantics as share_proof: withheld coordinates raise
        ShareWithheld (410), a tampered view fails the verification gate
        with BadProofDetected (502), mid-heal heights answer 503."""
        import time

        from celestia_app_tpu import merkle
        from celestia_app_tpu.nmt.proof import multiproof_from_levels
        from celestia_app_tpu.serve.sampler import (
            ShareWithheld,
            _check_withheld,
            _qos_gate_sample,
        )
        from celestia_app_tpu.trace.metrics import registry

        lat = _attestation_latency()
        t0 = time.perf_counter()
        sample_list = parse_attestation_samples(samples)
        entry = self.entry(height)
        n = 2 * entry.k
        for row, col, _axis in sample_list:
            if not (row < n and col < n):
                raise ValueError(f"coordinate ({row},{col}) outside {n}x{n}")
        coords = [(row, col) for row, col, _axis in sample_list]
        # The same per-sample refusals the share_proof path applies, in
        # canonical order: the FIRST withheld coordinate fails the
        # request (410); every data-quadrant sample pays its tenant's
        # proof-rate token before any gather work.  A withheld set is a
        # DETECTION over the whole requested set — the coverage map
        # records every asked coordinate under the refusal state.
        try:
            _check_withheld(entry, coords)
        except ShareWithheld:
            coverage_tick(height, entry.k, coords, "withheld")
            raise
        for row, col, _axis in sample_list:
            _qos_gate_sample(entry, row, col)
        lat.observe(time.perf_counter() - t0, phase="parse")

        t1 = time.perf_counter()
        shares = entry.gather_shares(coords)  # ONE gather for the set
        lat.observe(time.perf_counter() - t1, phase="gather")

        t2 = time.perf_counter()
        by_tree: dict = {}  # (axis, tree) -> [leaf, ...]  (sorted already)
        for row, col, axis in sample_list:
            tree, leaf = (row, col) if axis == "row" else (col, row)
            by_tree.setdefault((axis, tree), []).append(leaf)
        nodes: list[bytes] = []
        root_nodes: list[bytes] = []
        root_table: dict[tuple[int, int], int] = {}
        trees: list[dict] = []
        all_roots = entry.row_roots + entry.col_roots
        for (axis, tree), leaves in by_tree.items():
            mp = multiproof_from_levels(
                entry.line_levels(axis, tree),
                [(leaf, leaf + 1) for leaf in leaves],
            )
            offset = len(nodes)
            nodes.extend(mp.nodes)
            root_index = tree if axis == "row" else n + tree
            path = merkle.path_from_levels(entry.root_levels, root_index)
            refs: list[int] = []
            for lvl, sib in enumerate(path):
                coord = (lvl, (root_index >> lvl) ^ 1)
                j = root_table.get(coord)
                if j is None:
                    j = root_table[coord] = len(root_nodes)
                    root_nodes.append(sib)
                refs.append(j)
            trees.append({
                "axis": axis,
                "index": tree,
                "total": mp.total,
                "root": all_roots[root_index].hex(),
                "ranges": [[s, e] for s, e in mp.ranges],
                "node_refs": [
                    [j + offset for j in rr] for rr in mp.node_refs
                ],
                "root_index": root_index,
                "root_total": len(all_roots),
                "root_path_refs": refs,
            })
        payload = {
            "height": height,
            "square_size": entry.k,
            "data_root": entry.data_root.hex(),
            "samples": [
                {"row": row, "col": col, "axis": axis}
                for row, col, axis in sample_list
            ],
            "shares": [bytes(s.tobytes()).hex() for s in shares],
            "trees": trees,
            "nodes": [nd.hex() for nd in nodes],
            "root_nodes": [nd.hex() for nd in root_nodes],
        }
        lat.observe(time.perf_counter() - t2, phase="assemble")

        # The verification gate decides the reconstructed per-sample
        # proofs — the exact dataclasses a light client rebuilds from
        # these bytes — through the batched verifier (sampler._gate ->
        # serve/verify.verify_proofs): a tampered view or forged root is
        # a BadProofDetected (502), never a served attestation.
        t3 = time.perf_counter()
        from celestia_app_tpu.rpc.codec import share_proofs_from_attestation
        from celestia_app_tpu.serve.sampler import (
            BadProofDetected,
            _verify_gate_armed,
        )

        armed = _verify_gate_armed(entry)
        if armed:
            try:
                self.sampler._gate(
                    entry, share_proofs_from_attestation(payload)
                )
            except BadProofDetected:
                coverage_tick(height, entry.k, coords, "tampered")
                raise
        coverage_tick(
            height, entry.k, coords, "verified" if armed else "sampled"
        )
        lat.observe(time.perf_counter() - t3, phase="verify")

        registry().counter(
            "celestia_attestation_bytes_total",
            "attestation response bytes built (canonical render), the "
            "numerator of bytes-per-verified-sample",
        ).inc(float(len(render(payload))))
        registry().counter(
            "celestia_attestation_samples_total",
            "samples covered by built attestations",
        ).inc(float(len(sample_list)))
        return payload

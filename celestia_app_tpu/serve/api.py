"""DasProvider: the ONE payload builder every serving plane answers with.

The repo's cross-plane identity pattern (trace/exposition.py): byte-equal
responses are structural when all planes call one renderer, never a test
invariant to chase.  The JSON-RPC server, the REST gateway, and the gRPC
plane's debug sidecar all route `GET /das/share_proof` and
`GET /das/shares` through the shared observability handler, which calls
the registered DasProvider here; the real gRPC Das service
(rpc/grpc_plane.py) and the JSON-RPC POST methods (rpc/server.py) carry
the same `render()` bytes / payload dicts.

Payloads are a pure function of chain state (height, coordinates, the
committed proofs) — cache tier, timing, and plane never leak in, so two
scrapes of the same request on different planes are identical bytes.
Every served proof verifies against the height's committed DAH data root
via the existing ShareProof.verify (clients reconstruct the dataclasses
with rpc/codec.share_proof_from_json).
"""

from __future__ import annotations

import json

from celestia_app_tpu.constants import NAMESPACE_SIZE, PARITY_NAMESPACE_BYTES


def render(payload: dict) -> bytes:
    """Canonical response bytes (sorted keys, compact separators) — the
    byte-identity unit shared by the GET routes and the gRPC service."""
    return json.dumps(payload, sort_keys=True, separators=(",", ":")).encode()


def payload_namespace_label(payload) -> str:
    """The CAPPED per-tenant label of a served payload (the PR 4
    accounting plane's cardinality contract): the proved share's
    namespace for share_proof payloads, the queried namespace for
    shares payloads, the reserved `other` bucket when the payload
    carries none (parity shares, errors, absent payloads)."""
    from celestia_app_tpu.trace.square_journal import (
        OTHER_LABEL,
        capped_namespace_label,
        namespace_label,
    )

    ns_hex = None
    if isinstance(payload, dict):
        ns_hex = payload.get("namespace")
        if ns_hex is None and isinstance(payload.get("proof"), dict):
            ns_hex = payload["proof"].get("namespace")
    if not isinstance(ns_hex, str) or not ns_hex:
        return OTHER_LABEL
    try:
        ns = bytes.fromhex(ns_hex)
    except ValueError:
        return OTHER_LABEL
    if ns == PARITY_NAMESPACE_BYTES:
        # Parity shares are not a tenant (the sampler's twin
        # _proof_namespace_label applies the same fold): 3/4 of uniform
        # DAS coordinates would otherwise burn a capped-cardinality slot
        # on 0xff..ff and split this counter from the latency histogram.
        return OTHER_LABEL
    return capped_namespace_label(namespace_label(ns))


def payload_shard_label(payload) -> str:
    """Bounded `shard` label of one served payload: the serve shard
    owning the sampled coordinate's leaf node (serve/shard.py's routing
    math on the payload's own row/col/square_size), "0" whenever the
    plane is unsharded or the payload carries no coordinate (namespace
    queries, errors).  One env read on the single-device plane."""
    from celestia_app_tpu.serve.shard import leaf_shard_of, serve_shards

    shards = serve_shards()
    if shards <= 1 or not isinstance(payload, dict):
        return "0"
    k, row, col = (
        payload.get("square_size"), payload.get("row"), payload.get("col")
    )
    if not all(isinstance(v, int) for v in (k, row, col)):
        return "0"
    return str(leaf_shard_of(k, shards, row, col, payload.get("axis", "row")))


def count_served(plane: str, kind: str, payload=None) -> None:
    """One served DAS response: per-plane, per-kind, per-tenant (capped
    namespace label, the PR 4 accounting plane), and — when the serve
    plane is sharded — per owning shard (bounded by the shard count)."""
    from celestia_app_tpu.trace.metrics import registry

    registry().counter(
        "celestia_proofs_served_total",
        "DAS proofs served, by serving plane, query kind, (capped) "
        "namespace, and owning serve shard",
    ).inc(
        plane=plane, kind=kind,
        namespace=payload_namespace_label(payload),
        shard=payload_shard_label(payload),
    )


class UnknownHeight(KeyError):
    """No cached, spilled, or rebuildable square at this height (a 404)."""


#: Hard cap on samples per attestation request: bounds the gather, the
#: multiproof assembly, and the response body a single query can demand.
MAX_ATTESTATION_SAMPLES = 4096


def parse_attestation_samples(spec: str) -> list[tuple[int, int, str]]:
    """Parse an attestation sample spec — comma-joined `row:col[:axis]`
    items (axis defaults to "row") — into the CANONICAL sample list:
    sorted by (axis, tree, leaf), duplicates dropped.  Every plane parses
    the same spec through this one function, so the canonical order (and
    with it the payload bytes) is structural, not per-plane."""
    out: set[tuple[int, int, str]] = set()
    if not spec.strip():
        raise ValueError("samples spec is empty (want row:col[:axis],...)")
    for item in spec.split(","):
        parts = item.strip().split(":")
        if len(parts) not in (2, 3):
            raise ValueError(
                f"bad sample {item!r} (want row:col or row:col:axis)"
            )
        axis = parts[2] if len(parts) == 3 else "row"
        if axis not in ("row", "col"):
            raise ValueError(f"axis must be 'row' or 'col', got {axis!r}")
        try:
            row, col = int(parts[0]), int(parts[1])
        except ValueError as e:
            raise ValueError(f"bad sample {item!r}: {e}") from e
        if row < 0 or col < 0:
            raise ValueError(f"bad sample {item!r}: negative coordinate")
        out.add((row, col, axis))
    if len(out) > MAX_ATTESTATION_SAMPLES:
        raise ValueError(
            f"{len(out)} samples exceed the per-request cap "
            f"{MAX_ATTESTATION_SAMPLES}"
        )
    # Canonical order: by (axis, tree index, leaf index) — the grouping
    # the multiproof assembly walks, so tree order and range order in the
    # payload are the sort order, never insertion order.
    def key(s):
        row, col, axis = s
        tree, leaf = (row, col) if axis == "row" else (col, row)
        return (axis, tree, leaf)

    return sorted(out, key=key)


def _attestation_latency():
    from celestia_app_tpu.trace.metrics import DEVICE_SECONDS_BUCKETS, registry

    return registry().histogram(
        "celestia_attestation_latency_seconds",
        "attestation build latency by phase (parse/gather/assemble/verify)",
        buckets=DEVICE_SECONDS_BUCKETS,
    )


class DasProvider:
    """Binds a ForestCache + ProofSampler + an optional rebuild source.

    `rebuild(height)` returns an ExtendedDataSquare for a height the
    cache no longer holds (a ServingNode reconstructs it from the block
    store's raw txs — the querier path), or None when the height is
    genuinely unknown; the rebuilt square is re-admitted so the next
    sample is a hit.
    """

    def __init__(self, cache=None, sampler=None, rebuild=None):
        import threading

        from celestia_app_tpu.serve.cache import ForestCache
        from celestia_app_tpu.serve.sampler import ProofSampler

        self.cache = cache if cache is not None else ForestCache()
        self.sampler = sampler if sampler is not None else ProofSampler()
        self.rebuild = rebuild
        # The node's HealingEngine (serve/heal.py), when one is wired:
        # heights mid-heal answer retryable statuses instead of the
        # terminal detection errors.
        self.healer = None
        # Serializes the miss path: N concurrent requests for one evicted
        # height must cost ONE square rebuild + forest build, not N.
        self._rebuild_lock = threading.Lock()

    def entry(self, height: int):
        healer = self.healer
        if healer is not None and healer.healing(height):
            from celestia_app_tpu.serve.heal import HealingInProgress

            # Mid-heal is RETRYABLE (503 + Retry-After / UNAVAILABLE),
            # never the terminal 410/502: the detection that started the
            # heal already got its terminal status, and the client that
            # backs off lands on the healed height.
            raise HealingInProgress(height, healer.retry_after_s)
        return self.serve_view(height)

    def serve_view(self, height: int):
        """The (possibly adversary-filtered) view of a height, WITHOUT
        the mid-heal gate — what the network answers this node.  The
        healing engine gathers from this view (and trusts none of it
        unverified); `entry()` adds the gate for external samplers."""
        entry = self._honest_entry(height)
        if getattr(entry, "healed", False):
            # A height recovered by repair and root-verified locally is
            # served from this node's own store: the withholding /
            # tampering proposer sits between the node and the network,
            # not between the node and its verified bytes.
            return entry
        # The adversary seam: a tampering proposer (malform_shares /
        # wrong_root in $CELESTIA_CHAOS) serves a corrupted VIEW of the
        # height — same object every request, honest cache untouched —
        # which the sampler's verification gate then detects.
        from celestia_app_tpu import chaos

        adv = chaos.active_adversary()
        if adv is not None and adv.tampers():
            return adv.tamper_entry(entry)
        return entry

    def _honest_entry(self, height: int):
        entry, tier = self.cache.get(height)
        if entry is not None:
            return entry
        with self._rebuild_lock:
            entry, tier = self.cache.get(height)  # a peer may have rebuilt
            if entry is not None:
                return entry
            eds = self.rebuild(height) if self.rebuild is not None else None
            if eds is None:
                raise UnknownHeight(f"no square known at height {height}")
            entry = self.cache.put(height, eds)
        if entry is None:  # retention disabled: serve without admitting
            from celestia_app_tpu.serve.shard import build_entry

            entry = build_entry(height, eds)
        return entry

    # --- payload builders ---------------------------------------------------
    def share_proof_payload(
        self, height: int, row: int, col: int, axis: str = "row"
    ) -> dict:
        from celestia_app_tpu.rpc.codec import to_jsonable

        entry = self.entry(height)
        proof = self.sampler.share_proof(entry, row, col, axis=axis)
        return {
            "height": height,
            "row": row,
            "col": col,
            "axis": axis,
            "square_size": entry.k,
            "proof": to_jsonable(proof),
            "data_root": entry.data_root.hex(),
        }

    def shares_payload(self, height: int, namespace_hex: str) -> dict:
        from celestia_app_tpu.proof.share_proof import ods_namespace_range
        from celestia_app_tpu.rpc.codec import to_jsonable

        try:
            namespace = bytes.fromhex(namespace_hex)
        except ValueError as e:
            raise ValueError(f"namespace must be hex: {e}") from e
        if len(namespace) != NAMESPACE_SIZE:
            raise ValueError(
                f"namespace must be {NAMESPACE_SIZE} bytes, "
                f"got {len(namespace)}"
            )
        # Read-path QoS: a namespace query names its tenant up front, so
        # the proof-rate gate runs BEFORE any gather work (the sampler's
        # share_proof twin charges the served share's label instead).
        from celestia_app_tpu import qos
        from celestia_app_tpu.trace.square_journal import (
            capped_namespace_label,
            namespace_label,
        )

        enf = qos.enforcer()
        if enf is not None:
            enf.admit_proof(capped_namespace_label(namespace_label(namespace)))
        entry = self.entry(height)
        rng = ods_namespace_range(entry.eds, namespace)
        payload: dict = {
            "height": height,
            "namespace": namespace_hex.lower(),
            "square_size": entry.k,
            "data_root": entry.data_root.hex(),
        }
        if rng is None:
            payload.update({"found": False, "shares": 0, "proof": None})
            return payload
        from celestia_app_tpu.proof.share_proof import new_share_inclusion_proof

        proof = new_share_inclusion_proof(entry.eds, rng[0], rng[1])
        # The same verification gate the sampler applies to share_proof:
        # under a tampering adversary (or $CELESTIA_SERVE_VERIFY=1) a
        # namespace payload built from the served view must chain to the
        # committed root before it leaves — BadProofDetected (502 /
        # DATA_LOSS on the planes) instead of a 200 endorsing forged
        # state.  The found=False branch serves no proof, so there is
        # nothing to endorse there.
        self.sampler._gate(entry, [proof])
        payload.update({
            "found": True,
            "start": rng[0],
            "end": rng[1],
            "shares": rng[1] - rng[0],
            "proof": to_jsonable(proof),
        })
        return payload

    def attestation_payload(self, height: int, samples: str) -> dict:
        """One deduped multiproof attestation for a SET of samples.

        s independent `share_proof` responses repeat the upper tree nodes
        of every shared row/column; this payload serializes each NMT node
        ONCE per tree (nmt/proof.multiproof) and each data-root audit
        node once per (level, sibling) coordinate, so the wire cost grows
        ~log instead of ~s x log.  Per-sample ShareProofs reconstruct
        byte-identically from the tables (rpc/codec.
        share_proofs_from_attestation), which is also how the verify gate
        here decides the payload — the gate verifies EXACTLY the bytes a
        client would.

        Same refusal semantics as share_proof: withheld coordinates raise
        ShareWithheld (410), a tampered view fails the verification gate
        with BadProofDetected (502), mid-heal heights answer 503."""
        import time

        from celestia_app_tpu import merkle
        from celestia_app_tpu.nmt.proof import multiproof_from_levels
        from celestia_app_tpu.serve.sampler import (
            _check_withheld,
            _qos_gate_sample,
        )
        from celestia_app_tpu.trace.metrics import registry

        lat = _attestation_latency()
        t0 = time.perf_counter()
        sample_list = parse_attestation_samples(samples)
        entry = self.entry(height)
        n = 2 * entry.k
        for row, col, _axis in sample_list:
            if not (row < n and col < n):
                raise ValueError(f"coordinate ({row},{col}) outside {n}x{n}")
        coords = [(row, col) for row, col, _axis in sample_list]
        # The same per-sample refusals the share_proof path applies, in
        # canonical order: the FIRST withheld coordinate fails the
        # request (410); every data-quadrant sample pays its tenant's
        # proof-rate token before any gather work.
        _check_withheld(entry, coords)
        for row, col, _axis in sample_list:
            _qos_gate_sample(entry, row, col)
        lat.observe(time.perf_counter() - t0, phase="parse")

        t1 = time.perf_counter()
        shares = entry.gather_shares(coords)  # ONE gather for the set
        lat.observe(time.perf_counter() - t1, phase="gather")

        t2 = time.perf_counter()
        by_tree: dict = {}  # (axis, tree) -> [leaf, ...]  (sorted already)
        for row, col, axis in sample_list:
            tree, leaf = (row, col) if axis == "row" else (col, row)
            by_tree.setdefault((axis, tree), []).append(leaf)
        nodes: list[bytes] = []
        root_nodes: list[bytes] = []
        root_table: dict[tuple[int, int], int] = {}
        trees: list[dict] = []
        all_roots = entry.row_roots + entry.col_roots
        for (axis, tree), leaves in by_tree.items():
            mp = multiproof_from_levels(
                entry.line_levels(axis, tree),
                [(leaf, leaf + 1) for leaf in leaves],
            )
            offset = len(nodes)
            nodes.extend(mp.nodes)
            root_index = tree if axis == "row" else n + tree
            path = merkle.path_from_levels(entry.root_levels, root_index)
            refs: list[int] = []
            for lvl, sib in enumerate(path):
                coord = (lvl, (root_index >> lvl) ^ 1)
                j = root_table.get(coord)
                if j is None:
                    j = root_table[coord] = len(root_nodes)
                    root_nodes.append(sib)
                refs.append(j)
            trees.append({
                "axis": axis,
                "index": tree,
                "total": mp.total,
                "root": all_roots[root_index].hex(),
                "ranges": [[s, e] for s, e in mp.ranges],
                "node_refs": [
                    [j + offset for j in rr] for rr in mp.node_refs
                ],
                "root_index": root_index,
                "root_total": len(all_roots),
                "root_path_refs": refs,
            })
        payload = {
            "height": height,
            "square_size": entry.k,
            "data_root": entry.data_root.hex(),
            "samples": [
                {"row": row, "col": col, "axis": axis}
                for row, col, axis in sample_list
            ],
            "shares": [bytes(s.tobytes()).hex() for s in shares],
            "trees": trees,
            "nodes": [nd.hex() for nd in nodes],
            "root_nodes": [nd.hex() for nd in root_nodes],
        }
        lat.observe(time.perf_counter() - t2, phase="assemble")

        # The verification gate decides the reconstructed per-sample
        # proofs — the exact dataclasses a light client rebuilds from
        # these bytes — through the batched verifier (sampler._gate ->
        # serve/verify.verify_proofs): a tampered view or forged root is
        # a BadProofDetected (502), never a served attestation.
        t3 = time.perf_counter()
        from celestia_app_tpu.rpc.codec import share_proofs_from_attestation
        from celestia_app_tpu.serve.sampler import _verify_gate_armed

        if _verify_gate_armed(entry):
            self.sampler._gate(entry, share_proofs_from_attestation(payload))
        lat.observe(time.perf_counter() - t3, phase="verify")

        registry().counter(
            "celestia_attestation_bytes_total",
            "attestation response bytes built (canonical render), the "
            "numerator of bytes-per-verified-sample",
        ).inc(float(len(render(payload))))
        registry().counter(
            "celestia_attestation_samples_total",
            "samples covered by built attestations",
        ).inc(float(len(sample_list)))
        return payload

"""gRPC serving plane: the cosmos service surface ecosystem clients speak.

The reference node serves gRPC alongside RPC/API
(/root/reference/app/app.go:712-735; testnode wires all three,
test/util/testnode/network.go:38-43).  This plane exposes the same service
shapes over real gRPC (grpcio, generic byte-level handlers — no codegen;
message codecs are hand-rolled on encoding/proto like the rest of the wire
layer, protoc-cross-validated by tests/test_proto_wire.py):

  cosmos.tx.v1beta1.Service/BroadcastTx            submit a signed TxRaw
  cosmos.tx.v1beta1.Service/GetTx                  confirmation lookup
  cosmos.auth.v1beta1.Query/Account                number/sequence for signing
  cosmos.bank.v1beta1.Query/Balance                spot balance
  cosmos.staking.v1beta1.Query/Validators          bonded set (txsim stake)
  cosmos.base.tendermint.v1beta1.Service/GetLatestBlock   chain id + height

`GrpcNode` is the client half: it implements the node surface TxClient
consumes (broadcast / query_account / tx_status / validators / chain_id),
so txsim and user.TxClient run unchanged against a gRPC endpoint — the
done-criterion of VERDICT r3 next-step #6.
"""

from __future__ import annotations

from concurrent import futures
from dataclasses import dataclass

from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    encode_bytes_field,
    encode_varint_field,
)

# --- message codecs (cosmos protos, standard field numbers) ----------------


def _tx_response(height: int, txhash: str, code: int, raw_log: str,
                 gas_wanted: int = 0, gas_used: int = 0) -> bytes:
    """cosmos.base.abci.v1beta1.TxResponse {height=1, txhash=2, code=4,
    raw_log=6, gas_wanted=10, gas_used=11}."""
    out = b""
    if height:
        out += encode_varint_field(1, height)
    out += encode_bytes_field(2, txhash.encode())
    if code:
        out += encode_varint_field(4, code)
    if raw_log:
        out += encode_bytes_field(6, raw_log.encode())
    if gas_wanted:
        out += encode_varint_field(10, gas_wanted)
    if gas_used:
        out += encode_varint_field(11, gas_used)
    return out


def _parse_tx_response(raw: bytes) -> dict:
    out = {"height": 0, "txhash": "", "code": 0, "raw_log": "",
           "gas_wanted": 0, "gas_used": 0}
    for num, wt, val in decode_fields(raw):
        if num == 1 and wt == WIRE_VARINT:
            out["height"] = val
        elif num == 2 and wt == WIRE_LEN:
            out["txhash"] = val.decode()
        elif num == 4 and wt == WIRE_VARINT:
            out["code"] = val
        elif num == 6 and wt == WIRE_LEN:
            out["raw_log"] = val.decode()
        elif num == 10 and wt == WIRE_VARINT:
            out["gas_wanted"] = val
        elif num == 11 and wt == WIRE_VARINT:
            out["gas_used"] = val
    return out


def _field_str(raw: bytes, num: int) -> str:
    for n, wt, val in decode_fields(raw):
        if n == num and wt == WIRE_LEN:
            return val.decode()
    return ""


def _field_bytes(raw: bytes, num: int) -> bytes:
    for n, wt, val in decode_fields(raw):
        if n == num and wt == WIRE_LEN:
            return val
    return b""


def _field_int(raw: bytes, num: int) -> int:
    for n, wt, val in decode_fields(raw):
        if n == num and wt == WIRE_VARINT:
            return val
    return 0


# --- server ----------------------------------------------------------------


def _handlers(node) -> dict:
    """method path suffix -> unary handler(bytes) -> bytes.

    State reads hold `node.lock` (when the node has one): gRPC workers run
    concurrently with the proposer loop, and the unlocked TestNode query
    methods read `cms.working` mid-commit — the JSON-RPC plane's rpc_*
    wrappers take the same lock (rpc/server.py:581,946)."""
    from contextlib import nullcontext

    def node_lock():
        return getattr(node, "lock", None) or nullcontext()

    def broadcast_tx(req: bytes) -> bytes:
        # BroadcastTxRequest {tx_bytes=1, mode=2}; mode BROADCAST_MODE_SYNC
        # semantics: CheckTx result, inclusion async (the only mode the
        # reference chain's clients rely on; pkg/user polls GetTx after).
        tx_bytes = _field_bytes(req, 1)
        res = node.broadcast(tx_bytes)
        import hashlib

        txhash = hashlib.sha256(tx_bytes).hexdigest().upper()
        return encode_bytes_field(
            1,
            _tx_response(0, txhash, res.code, res.log, res.gas_wanted,
                         getattr(res, "gas_used", 0)),
        )

    def get_tx(req: bytes) -> bytes:
        # GetTxRequest {hash=1 (hex)}; NotFound -> empty response (the
        # client treats an absent tx_response as "not yet included").
        txhash = _field_str(req, 1)
        with node_lock():
            status = node.tx_status(bytes.fromhex(txhash))
        if status is None:
            return b""
        height, code, log = status
        return encode_bytes_field(2, _tx_response(height, txhash, code, log))

    def query_account(req: bytes) -> bytes:
        # QueryAccountRequest {address=1} -> {account=1 Any(BaseAccount)}.
        addr = _field_str(req, 1)
        with node_lock():
            acc = node.query_account(addr)
        if acc is None:
            return b""
        base = (
            encode_bytes_field(1, acc.address.encode())
            + encode_varint_field(3, acc.account_number)
            + encode_varint_field(4, acc.sequence)
        )
        any_acc = encode_bytes_field(
            1, b"/cosmos.auth.v1beta1.BaseAccount"
        ) + encode_bytes_field(2, base)
        return encode_bytes_field(1, any_acc)

    def query_balance(req: bytes) -> bytes:
        # QueryBalanceRequest {address=1, denom=2} -> {balance=1 Coin}.
        from celestia_app_tpu.state.accounts import BankKeeper

        addr = _field_str(req, 1)
        denom = _field_str(req, 2) or "utia"
        with node_lock():
            amount = BankKeeper(node.app.cms.working).balance(addr, denom)
        coin = encode_bytes_field(1, denom.encode()) + encode_bytes_field(
            2, str(amount).encode()
        )
        return encode_bytes_field(1, coin)

    def query_validators(req: bytes) -> bytes:
        # QueryValidatorsRequest -> {validators=1 repeated Validator
        # {operator_address=1, tokens=5}} — the fields txsim's stake
        # sequence reads.
        with node_lock():
            vals = node.validators()
        out = b""
        for v in vals:
            val = encode_bytes_field(
                1, v["address"].encode()
            ) + encode_bytes_field(5, str(v.get("power", 0)).encode())
            out += encode_bytes_field(1, val)
        return out

    def get_latest_block(req: bytes) -> bytes:
        # GetLatestBlockResponse {block=2 {header=1 {chain_id=2, height=3}}}.
        header = encode_bytes_field(2, node.chain_id.encode()) + encode_varint_field(
            3, node.app.height
        )
        return encode_bytes_field(2, encode_bytes_field(1, header))

    def simulate(req: bytes) -> bytes:
        # SimulateRequest {tx_bytes=2} -> SimulateResponse {gas_info=1
        # {gas_wanted=1, gas_used=2}}: the gas-estimation endpoint
        # cosmjs/TxClient call before signing for real (sig verification
        # and the gas limit waived, state discarded).
        tx_bytes = _field_bytes(req, 2)
        with node_lock():
            res = node.app.simulate_tx(tx_bytes)
        if res.code != 0:
            # Keep the unary shape and report failure through an absent
            # gas_info + Result.log (cosmos.base.abci.v1beta1.Result
            # {data=1, log=2, events=3}).
            return encode_bytes_field(
                2, encode_bytes_field(2, res.log.encode())
            )
        gas_info = encode_varint_field(1, res.gas_wanted) + encode_varint_field(
            2, res.gas_used
        )
        return encode_bytes_field(1, gas_info)

    def get_node_info(req: bytes) -> bytes:
        # GetNodeInfoResponse {default_node_info=1 {network=4, version=5,
        # moniker=7}} — the fields cosmjs reads on connect.
        info = (
            encode_bytes_field(4, node.chain_id.encode())
            + encode_bytes_field(5, b"celestia-app-tpu")
            + encode_bytes_field(7, b"tpu-node")
        )
        return encode_bytes_field(1, info)

    def query_delegation(req: bytes) -> bytes:
        # QueryDelegationRequest {delegator_addr=1, validator_addr=2} ->
        # {delegation_response=1 {delegation=1 {delegator_address=1,
        # validator_address=2, shares=3}, balance=2 Coin}} — the fields
        # staking dashboards read; shares reported 1:1 with tokens (this
        # framework's delegation records are token-denominated).
        from celestia_app_tpu.state.staking import StakingKeeper

        delegator = _field_str(req, 1)
        validator = _field_str(req, 2)
        with node_lock():
            amount = StakingKeeper(node.app.cms.working).delegation(
                delegator, validator
            )
        if amount == 0:
            return b""
        # shares: gogoproto Dec wire format is the 10^18-scaled integer's
        # plain digits (big.Int text), NOT a human decimal string — a dot
        # would fail Go clients' Dec.Unmarshal.  Shares track tokens 1:1.
        delegation = (
            encode_bytes_field(1, delegator.encode())
            + encode_bytes_field(2, validator.encode())
            + encode_bytes_field(3, str(amount * 10**18).encode())
        )
        balance = encode_bytes_field(1, b"utia") + encode_bytes_field(
            2, str(amount).encode()
        )
        return encode_bytes_field(
            1,
            encode_bytes_field(1, delegation) + encode_bytes_field(2, balance),
        )

    def query_proposals(req: bytes) -> bytes:
        # QueryProposalsRequest -> {proposals=1 repeated Proposal
        # {proposal_id=1, status=3}} — the id/status pair explorers poll
        # (field 2 is the content Any in cosmos.gov.v1beta1.Proposal and
        # must not be squatted by a varint).
        from celestia_app_tpu.modules.gov import GovKeeper
        from celestia_app_tpu.state.staking import StakingKeeper

        with node_lock():
            store = node.app.cms.working
            from celestia_app_tpu.state.accounts import BankKeeper

            props = GovKeeper(
                store, StakingKeeper(store), BankKeeper(store)
            ).proposals()
        out = b""
        for p in props:
            out += encode_bytes_field(
                1,
                encode_varint_field(1, p.pid)
                + encode_varint_field(3, int(p.status)),
            )
        return out

    def query_blob_params(req: bytes) -> bytes:
        # celestia.blob.v1 QueryParamsResponse {params=1 {
        # gas_per_blob_byte=1, gov_max_square_size=2}}.
        with node_lock():
            params = encode_varint_field(
                1, node.app.gas_per_blob_byte
            ) + encode_varint_field(2, node.app.gov_max_square_size)
        return encode_bytes_field(1, params)

    return {
        "cosmos.tx.v1beta1.Service": {
            "BroadcastTx": broadcast_tx,
            "GetTx": get_tx,
            "Simulate": simulate,
        },
        "cosmos.auth.v1beta1.Query": {"Account": query_account},
        "cosmos.bank.v1beta1.Query": {"Balance": query_balance},
        "cosmos.staking.v1beta1.Query": {
            "Validators": query_validators,
            "Delegation": query_delegation,
        },
        "cosmos.gov.v1beta1.Query": {"Proposals": query_proposals},
        "celestia.blob.v1.Query": {"Params": query_blob_params},
        "cosmos.base.tendermint.v1beta1.Service": {
            "GetLatestBlock": get_latest_block,
            "GetNodeInfo": get_node_info,
        },
    }


@dataclass
class GrpcPlane:
    server: object
    port: int

    @property
    def target(self) -> str:
        return f"127.0.0.1:{self.port}"

    def stop(self, grace: float = 0.5) -> None:
        self.server.stop(grace)


def serve_grpc(node, port: int = 0, max_workers: int = 8) -> GrpcPlane:
    """Start the gRPC plane for a node; returns the live server + port."""
    import grpc

    ident = lambda b: b  # byte-level (de)serialization; codecs above

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    for service, methods in _handlers(node).items():
        rpc_handlers = {
            name: grpc.unary_unary_rpc_method_handler(
                (lambda fn: lambda req, ctx: fn(req))(fn),
                request_deserializer=ident,
                response_serializer=ident,
            )
            for name, fn in methods.items()
        }
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(service, rpc_handlers),)
        )
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    server.start()
    return GrpcPlane(server, bound)


# --- client ----------------------------------------------------------------


class GrpcNode:
    """TxClient-compatible node surface over a gRPC channel.

    Implements broadcast / query_account / tx_status / validators /
    chain_id — the exact interface user.TxClient and txsim consume — so
    they run against a gRPC endpoint unchanged.
    """

    def __init__(self, target: str):
        import grpc

        self._channel = grpc.insecure_channel(target)
        ident = lambda b: b
        self._call = {
            name: self._channel.unary_unary(
                path, request_serializer=ident, response_deserializer=ident
            )
            for name, path in {
                "broadcast": "/cosmos.tx.v1beta1.Service/BroadcastTx",
                "get_tx": "/cosmos.tx.v1beta1.Service/GetTx",
                "simulate": "/cosmos.tx.v1beta1.Service/Simulate",
                "node_info": "/cosmos.base.tendermint.v1beta1.Service/GetNodeInfo",
                "account": "/cosmos.auth.v1beta1.Query/Account",
                "balance": "/cosmos.bank.v1beta1.Query/Balance",
                "validators": "/cosmos.staking.v1beta1.Query/Validators",
                "delegation": "/cosmos.staking.v1beta1.Query/Delegation",
                "proposals": "/cosmos.gov.v1beta1.Query/Proposals",
                "blob_params": "/celestia.blob.v1.Query/Params",
                "latest": "/cosmos.base.tendermint.v1beta1.Service/GetLatestBlock",
            }.items()
        }

    def close(self) -> None:
        self._channel.close()

    # --- TxClient surface ---------------------------------------------------
    @property
    def chain_id(self) -> str:
        hdr = _field_bytes(_field_bytes(self._call["latest"](b""), 2), 1)
        return _field_str(hdr, 2)

    def height(self) -> int:
        hdr = _field_bytes(_field_bytes(self._call["latest"](b""), 2), 1)
        return _field_int(hdr, 3)

    def broadcast(self, raw_tx: bytes):
        from celestia_app_tpu.app.app import TxResult

        resp = _parse_tx_response(
            _field_bytes(self._call["broadcast"](encode_bytes_field(1, raw_tx)), 1)
        )
        return TxResult(
            code=resp["code"], log=resp["raw_log"],
            gas_wanted=resp["gas_wanted"], gas_used=resp["gas_used"],
        )

    def query_account(self, address: str):
        from celestia_app_tpu.state.accounts import Account

        resp = self._call["account"](encode_bytes_field(1, address.encode()))
        any_acc = _field_bytes(resp, 1)
        if not any_acc:
            return None
        base = _field_bytes(any_acc, 2)
        return Account(
            address=_field_str(base, 1), pubkey=b"",
            account_number=_field_int(base, 3), sequence=_field_int(base, 4),
        )

    def tx_status(self, tx_hash: bytes):
        resp = self._call["get_tx"](
            encode_bytes_field(1, tx_hash.hex().upper().encode())
        )
        tr = _field_bytes(resp, 2)
        if not tr:
            return None
        parsed = _parse_tx_response(tr)
        return parsed["height"], parsed["code"], parsed["raw_log"]

    def balance(self, address: str, denom: str = "utia") -> int:
        resp = self._call["balance"](
            encode_bytes_field(1, address.encode())
            + encode_bytes_field(2, denom.encode())
        )
        return int(_field_str(_field_bytes(resp, 1), 2) or 0)

    def produce_block(self, timeout_s: float = 120.0):
        """The cosmos gRPC surface has no dev produce-block hook; wait for
        the served node's proposer loop to commit the next height (txsim's
        per-round block barrier), shaped like TestNode.produce_block.

        Default waits out a worst-case first-ever-square-size jit compile
        inside the proposer loop (35-50 s on the 1-core box — the same
        cold-compile allowance RemoteNode's socket timeout makes,
        rpc/client.py:40-44); steady-state blocks commit in well under a
        second."""
        import time

        start = self.height()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.height() > start:
                return {"height": self.height()}, []
            time.sleep(0.05)
        raise TimeoutError(f"no block committed past height {start}")

    def validators(self) -> list[dict]:
        out = []
        for num, wt, val in decode_fields(self._call["validators"](b"")):
            if num == 1 and wt == WIRE_LEN:
                # "address"/"power" match the in-process node surface so
                # txsim's sequences stay node-agnostic.
                out.append({
                    "address": _field_str(val, 1),
                    "power": int(_field_str(val, 5) or 0),
                })
        return out

    def delegation(self, delegator: str, validator: str) -> int:
        """Delegated utia of (delegator, validator); 0 if none."""
        resp = self._call["delegation"](
            encode_bytes_field(1, delegator.encode())
            + encode_bytes_field(2, validator.encode())
        )
        dr = _field_bytes(resp, 1)
        if not dr:
            return 0
        return int(_field_str(_field_bytes(dr, 2), 2) or 0)

    def proposals(self) -> list[dict]:
        """[{id, status}] of every proposal on chain."""
        out = []
        for num, wt, val in decode_fields(self._call["proposals"](b"")):
            if num == 1 and wt == WIRE_LEN:
                out.append({
                    "id": _field_int(val, 1),
                    "status": _field_int(val, 3),
                })
        return out

    def blob_params(self) -> dict:
        """{gas_per_blob_byte, gov_max_square_size} (celestia.blob.v1)."""
        p = _field_bytes(self._call["blob_params"](b""), 1)
        return {
            "gas_per_blob_byte": _field_int(p, 1),
            "gov_max_square_size": _field_int(p, 2),
        }

    def simulate(self, raw_tx: bytes) -> tuple[int, int, str]:
        """(gas_wanted, gas_used, log) of simulating `raw_tx`; gas_used 0
        with a log on failure."""
        resp = self._call["simulate"](encode_bytes_field(2, raw_tx))
        gas_info = _field_bytes(resp, 1)
        if gas_info:
            return _field_int(gas_info, 1), _field_int(gas_info, 2), ""
        return 0, 0, _field_str(_field_bytes(resp, 2), 2)

    def node_info(self) -> dict:
        """{network, version, moniker} (GetNodeInfo, the cosmjs connect
        handshake)."""
        info = _field_bytes(self._call["node_info"](b""), 1)
        return {
            "network": _field_str(info, 4),
            "version": _field_str(info, 5),
            "moniker": _field_str(info, 7),
        }

"""gRPC serving plane: the cosmos service surface ecosystem clients speak.

The reference node serves gRPC alongside RPC/API
(/root/reference/app/app.go:712-735; testnode wires all three,
test/util/testnode/network.go:38-43).  This plane exposes the same service
shapes over real gRPC (grpcio, generic byte-level handlers — no codegen;
message codecs are hand-rolled on encoding/proto like the rest of the wire
layer, protoc-cross-validated by tests/test_proto_wire.py):

  cosmos.tx.v1beta1.Service/BroadcastTx|GetTx|Simulate    tx lifecycle
  cosmos.auth.v1beta1.Query/Account                number/sequence for signing
  cosmos.bank.v1beta1.Query/Balance                spot balance
  cosmos.staking.v1beta1.Query/Validators|Delegation      bonded set (paged)
  cosmos.gov.v1beta1.Query/Proposals               paged proposal list
  cosmos.distribution.v1beta1.Query/DelegationRewards|CommunityPool
  cosmos.slashing.v1beta1.Query/SigningInfo|SigningInfos|Params
  celestia.blob.v1.Query/Params                    blob module params
  celestia.minfee.v1.Query/NetworkMinGasPrice      network fee floor
  celestia.signal.v1.Query/VersionTally            upgrade signal tally
  celestia.qgb.v1.Query/AttestationRequestByNonce|LatestAttestationNonce|
      EVMAddress                                   blobstream relayer reads
  cosmos.base.tendermint.v1beta1.Service/GetLatestBlock|GetNodeInfo
  celestia.tpu.subscription.v1.Subscription/WaitTx long-poll tx commit
      (this framework's analog of Tendermint's websocket /subscribe —
      the reference serves that from celestia-core RPC, not gRPC)
  celestia.tpu.das.v1.Das/GetShareProof|GetSharesByNamespace|
      GetAttestation                              the DAS sampling surface
      (serve/): responses carry the canonical serve/api.render payload
      bytes, byte-identical to the HTTP planes' GET /das/* bodies

List queries speak cosmos.base.query.v1beta1 PageRequest/PageResponse
(offset/limit/count_total/reverse; next_key is an opaque offset cursor).

Alongside the gRPC listener, `serve_grpc` starts a health/debug HTTP
sidecar (GrpcPlane.debug_url) mounting the shared observability handler —
GET /metrics, /trace_tables[/<name>], /healthz — byte-identical to the
JSON-RPC and REST planes' exposition (trace/exposition.py).

`GrpcNode` is the client half: it implements the node surface TxClient
consumes (broadcast / query_account / tx_status / validators / chain_id),
so txsim and user.TxClient run unchanged against a gRPC endpoint — the
done-criterion of VERDICT r3 next-step #6.
"""

from __future__ import annotations

from concurrent import futures
from dataclasses import dataclass

from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    encode_bytes_field,
    encode_varint_field,
)

# --- message codecs (cosmos protos, standard field numbers) ----------------


def _tx_response(height: int, txhash: str, code: int, raw_log: str,
                 gas_wanted: int = 0, gas_used: int = 0) -> bytes:
    """cosmos.base.abci.v1beta1.TxResponse {height=1, txhash=2, code=4,
    raw_log=6, gas_wanted=10, gas_used=11}."""
    out = b""
    if height:
        out += encode_varint_field(1, height)
    out += encode_bytes_field(2, txhash.encode())
    if code:
        out += encode_varint_field(4, code)
    if raw_log:
        out += encode_bytes_field(6, raw_log.encode())
    if gas_wanted:
        out += encode_varint_field(10, gas_wanted)
    if gas_used:
        out += encode_varint_field(11, gas_used)
    return out


def _parse_tx_response(raw: bytes) -> dict:
    out = {"height": 0, "txhash": "", "code": 0, "raw_log": "",
           "gas_wanted": 0, "gas_used": 0}
    for num, wt, val in decode_fields(raw):
        if num == 1 and wt == WIRE_VARINT:
            out["height"] = val
        elif num == 2 and wt == WIRE_LEN:
            out["txhash"] = val.decode()
        elif num == 4 and wt == WIRE_VARINT:
            out["code"] = val
        elif num == 6 and wt == WIRE_LEN:
            out["raw_log"] = val.decode()
        elif num == 10 and wt == WIRE_VARINT:
            out["gas_wanted"] = val
        elif num == 11 and wt == WIRE_VARINT:
            out["gas_used"] = val
    return out


def _field_str(raw: bytes, num: int) -> str:
    for n, wt, val in decode_fields(raw):
        if n == num and wt == WIRE_LEN:
            return val.decode()
    return ""


def _field_bytes(raw: bytes, num: int) -> bytes:
    for n, wt, val in decode_fields(raw):
        if n == num and wt == WIRE_LEN:
            return val
    return b""


def _field_int(raw: bytes, num: int) -> int:
    for n, wt, val in decode_fields(raw):
        if n == num and wt == WIRE_VARINT:
            return val
    return 0


# --- pagination (cosmos.base.query.v1beta1) --------------------------------


def _parse_page_request(req: bytes, field_num: int) -> dict:
    """PageRequest {key=1, offset=2, limit=3, count_total=4, reverse=5}
    embedded at `field_num` of the enclosing query request. The `key`
    cursor is this plane's next_key from the previous page (an opaque
    offset, as the sdk's store keys are opaque to clients)."""
    page = _field_bytes(req, field_num)
    out = {"offset": 0, "limit": 0, "count_total": False, "reverse": False}
    if not page:
        return out
    for n, wt, val in decode_fields(page):
        if n == 1 and wt == WIRE_LEN and val:
            try:
                out["offset"] = int(val.decode())
            except ValueError:
                pass
        elif n == 2 and wt == WIRE_VARINT:
            out["offset"] = val
        elif n == 3 and wt == WIRE_VARINT:
            out["limit"] = val
        elif n == 4 and wt == WIRE_VARINT:
            out["count_total"] = bool(val)
        elif n == 5 and wt == WIRE_VARINT:
            out["reverse"] = bool(val)
    return out


def _paginate(items: list, page: dict) -> tuple[list, bytes]:
    """Apply a parsed PageRequest; returns (page_items, PageResponse bytes
    {next_key=1, total=2})."""
    if page["reverse"]:
        items = list(reversed(items))
    total = len(items)
    start = min(max(page["offset"], 0), total)  # clamp hostile cursors
    end = total if not page["limit"] else min(start + page["limit"], total)
    resp = b""
    if end < total:
        resp += encode_bytes_field(1, str(end).encode())
    if page["count_total"]:
        resp += encode_varint_field(2, total)
    return items[start:end], resp


def encode_page_request(offset: int = 0, limit: int = 0,
                        count_total: bool = False, reverse: bool = False,
                        key: bytes = b"") -> bytes:
    out = b""
    if key:
        out += encode_bytes_field(1, key)
    if offset:
        out += encode_varint_field(2, offset)
    if limit:
        out += encode_varint_field(3, limit)
    if count_total:
        out += encode_varint_field(4, 1)
    if reverse:
        out += encode_varint_field(5, 1)
    return out


def _parse_page_response(raw: bytes) -> dict:
    return {"next_key": _field_bytes(raw, 1), "total": _field_int(raw, 2)}


# --- server ----------------------------------------------------------------

# Cap on concurrently PARKED WaitTx long-polls (see wait_tx handler); kept
# below serve_grpc's worker-pool size so subscriptions can never starve
# the unary queries sharing the pool.
_WAIT_TX_MAX_PARKED = 8


class _Abort(Exception):
    """Handler-raised gRPC failure: carries the StatusCode NAME (the grpc
    module is imported lazily — serve_grpc resolves the name to the real
    code when it aborts the RPC) plus human-readable details.  Without
    this, a malformed client input surfaced as an opaque UNKNOWN wrapping
    a Python traceback."""

    def __init__(self, code: str, details: str):
        self.code = code
        self.details = details
        super().__init__(details)


def _qos_abort(e: Exception) -> Exception:
    """Map a per-tenant QoS refusal (qos.QosThrottled) onto the typed
    RESOURCE_EXHAUSTED abort whose detail string is qos.py's ONE
    canonical payload — the very bytes the HTTP planes serve as their
    429 bodies, so the three planes stay byte-identical.  Any other
    exception passes through unchanged."""
    from celestia_app_tpu.qos import QosThrottled, throttle_body

    if isinstance(e, QosThrottled):
        return _Abort("RESOURCE_EXHAUSTED", throttle_body(e).decode())
    return e


def _tx_hash_bytes(txhash: str) -> bytes:
    """Validate and decode a client-supplied hex tx hash, stripping
    whitespace and accepting either case; INVALID_ARGUMENT on anything
    else (empty, odd length, non-hex) instead of a ValueError-backed
    opaque gRPC error."""
    cleaned = txhash.strip()
    if not cleaned:
        raise _Abort("INVALID_ARGUMENT", "empty tx hash")
    try:
        return bytes.fromhex(cleaned)
    except ValueError:
        raise _Abort(
            "INVALID_ARGUMENT",
            f"malformed tx hash {cleaned[:80]!r}: expected hex",
        ) from None


def _handlers(node) -> dict:
    """method path suffix -> unary handler(bytes) -> bytes.

    State reads hold `node.lock` (when the node has one): gRPC workers run
    concurrently with the proposer loop, and the unlocked TestNode query
    methods read `cms.working` mid-commit — the JSON-RPC plane's rpc_*
    wrappers take the same lock (rpc/server.py:581,946)."""
    from contextlib import nullcontext

    def node_lock():
        return getattr(node, "lock", None) or nullcontext()

    def broadcast_tx(req: bytes) -> bytes:
        # BroadcastTxRequest {tx_bytes=1, mode=2}; mode BROADCAST_MODE_SYNC
        # semantics: CheckTx result, inclusion async (the only mode the
        # reference chain's clients rely on; pkg/user polls GetTx after).
        from celestia_app_tpu.trace.context import (
            current_context,
            new_context,
            use_context,
        )

        tx_bytes = _field_bytes(req, 1)
        # Request entry: the trace the tx carries to the block that
        # commits it (trace/context.py; resolvable via /trace_tables/spans
        # on the debug sidecar).  serve_grpc's wrapper has already ADOPTED
        # an incoming x-celestia-trace metadata entry (adopt_context) —
        # child it so the cross-node submit stays one trace.
        parent = current_context()
        ctx = (
            parent.child(layer="rpc", plane="grpc")
            if parent is not None
            else new_context(layer="rpc", plane="grpc")
        )
        with use_context(ctx):
            try:
                res = node.broadcast(tx_bytes)
            except Exception as e:
                raise _qos_abort(e) from None
        import hashlib

        txhash = hashlib.sha256(tx_bytes).hexdigest().upper()
        return encode_bytes_field(
            1,
            _tx_response(0, txhash, res.code, res.log, res.gas_wanted,
                         getattr(res, "gas_used", 0)),
        )

    def get_tx(req: bytes) -> bytes:
        # GetTxRequest {hash=1 (hex)}; NotFound -> empty response (the
        # client treats an absent tx_response as "not yet included").
        # Same up-front hash validation as WaitTx: malformed hex answers
        # INVALID_ARGUMENT, never an opaque ValueError-backed error.
        txhash = _field_str(req, 1).strip()
        raw_hash = _tx_hash_bytes(txhash)
        with node_lock():
            status = node.tx_status(raw_hash)
        if status is None:
            return b""
        height, code, log = status
        return encode_bytes_field(2, _tx_response(height, txhash, code, log))

    def query_account(req: bytes) -> bytes:
        # QueryAccountRequest {address=1} -> {account=1 Any(BaseAccount)}.
        addr = _field_str(req, 1)
        with node_lock():
            acc = node.query_account(addr)
        if acc is None:
            return b""
        base = (
            encode_bytes_field(1, acc.address.encode())
            + encode_varint_field(3, acc.account_number)
            + encode_varint_field(4, acc.sequence)
        )
        any_acc = encode_bytes_field(
            1, b"/cosmos.auth.v1beta1.BaseAccount"
        ) + encode_bytes_field(2, base)
        return encode_bytes_field(1, any_acc)

    def query_balance(req: bytes) -> bytes:
        # QueryBalanceRequest {address=1, denom=2} -> {balance=1 Coin}.
        from celestia_app_tpu.state.accounts import BankKeeper

        addr = _field_str(req, 1)
        denom = _field_str(req, 2) or "utia"
        with node_lock():
            amount = BankKeeper(node.app.cms.working).balance(addr, denom)
        coin = encode_bytes_field(1, denom.encode()) + encode_bytes_field(
            2, str(amount).encode()
        )
        return encode_bytes_field(1, coin)

    def query_validators(req: bytes) -> bytes:
        # QueryValidatorsRequest {status=1, pagination=2} -> {validators=1
        # repeated Validator {operator_address=1, tokens=5}, pagination=2}
        # — the fields txsim's stake sequence reads, paged.  tokens uses
        # the sdk convention (power x PowerReduction), matching the REST
        # plane; the two previously disagreed (REST utia vs gRPC raw
        # power), which skewed any client mixing the planes by 10^6.
        from celestia_app_tpu.state.staking import POWER_REDUCTION

        with node_lock():
            vals = node.validators()
        page_vals, page_resp = _paginate(vals, _parse_page_request(req, 2))
        out = b""
        for v in page_vals:
            tokens = v.get("power", 0) * POWER_REDUCTION
            val = encode_bytes_field(
                1, v["address"].encode()
            ) + encode_bytes_field(5, str(tokens).encode())
            out += encode_bytes_field(1, val)
        if page_resp:
            out += encode_bytes_field(2, page_resp)
        return out

    def get_latest_block(req: bytes) -> bytes:
        # GetLatestBlockResponse {block=2 {header=1 {chain_id=2, height=3}}}.
        header = encode_bytes_field(2, node.chain_id.encode()) + encode_varint_field(
            3, node.app.height
        )
        return encode_bytes_field(2, encode_bytes_field(1, header))

    def simulate(req: bytes) -> bytes:
        # SimulateRequest {tx_bytes=2} -> SimulateResponse {gas_info=1
        # {gas_wanted=1, gas_used=2}}: the gas-estimation endpoint
        # cosmjs/TxClient call before signing for real (sig verification
        # and the gas limit waived, state discarded).
        tx_bytes = _field_bytes(req, 2)
        with node_lock():
            res = node.app.simulate_tx(tx_bytes)
        if res.code != 0:
            # Keep the unary shape and report failure through an absent
            # gas_info + Result.log (cosmos.base.abci.v1beta1.Result
            # {data=1, log=2, events=3}).
            return encode_bytes_field(
                2, encode_bytes_field(2, res.log.encode())
            )
        gas_info = encode_varint_field(1, res.gas_wanted) + encode_varint_field(
            2, res.gas_used
        )
        return encode_bytes_field(1, gas_info)

    def get_node_info(req: bytes) -> bytes:
        # GetNodeInfoResponse {default_node_info=1 {network=4, version=5,
        # moniker=7}} — the fields cosmjs reads on connect.
        info = (
            encode_bytes_field(4, node.chain_id.encode())
            + encode_bytes_field(5, b"celestia-app-tpu")
            + encode_bytes_field(7, b"tpu-node")
        )
        return encode_bytes_field(1, info)

    def query_delegation(req: bytes) -> bytes:
        # QueryDelegationRequest {delegator_addr=1, validator_addr=2} ->
        # {delegation_response=1 {delegation=1 {delegator_address=1,
        # validator_address=2, shares=3}, balance=2 Coin}} — the fields
        # staking dashboards read; shares reported 1:1 with tokens (this
        # framework's delegation records are token-denominated).
        from celestia_app_tpu.state.staking import StakingKeeper

        delegator = _field_str(req, 1)
        validator = _field_str(req, 2)
        with node_lock():
            amount = StakingKeeper(node.app.cms.working).delegation(
                delegator, validator
            )
        if amount == 0:
            return b""
        # shares: gogoproto Dec wire format is the 10^18-scaled integer's
        # plain digits (big.Int text), NOT a human decimal string — a dot
        # would fail Go clients' Dec.Unmarshal.  Shares track tokens 1:1.
        delegation = (
            encode_bytes_field(1, delegator.encode())
            + encode_bytes_field(2, validator.encode())
            + encode_bytes_field(3, str(amount * 10**18).encode())
        )
        balance = encode_bytes_field(1, b"utia") + encode_bytes_field(
            2, str(amount).encode()
        )
        return encode_bytes_field(
            1,
            encode_bytes_field(1, delegation) + encode_bytes_field(2, balance),
        )

    def query_proposals(req: bytes) -> bytes:
        # QueryProposalsRequest -> {proposals=1 repeated Proposal
        # {proposal_id=1, status=3}} — the id/status pair explorers poll
        # (field 2 is the content Any in cosmos.gov.v1beta1.Proposal and
        # must not be squatted by a varint).
        from celestia_app_tpu.modules.gov import GovKeeper
        from celestia_app_tpu.state.staking import StakingKeeper

        with node_lock():
            store = node.app.cms.working
            from celestia_app_tpu.state.accounts import BankKeeper

            props = GovKeeper(
                store, StakingKeeper(store), BankKeeper(store)
            ).proposals()
        # gov v1beta1 QueryProposalsRequest carries pagination at field 4.
        page_props, page_resp = _paginate(props, _parse_page_request(req, 4))
        out = b""
        for p in page_props:
            out += encode_bytes_field(
                1,
                encode_varint_field(1, p.pid)
                + encode_varint_field(3, int(p.status)),
            )
        if page_resp:
            out += encode_bytes_field(2, page_resp)
        return out

    def query_blob_params(req: bytes) -> bytes:
        # celestia.blob.v1 QueryParamsResponse {params=1 {
        # gas_per_blob_byte=1, gov_max_square_size=2}}.
        with node_lock():
            params = encode_varint_field(
                1, node.app.gas_per_blob_byte
            ) + encode_varint_field(2, node.app.gov_max_square_size)
        return encode_bytes_field(1, params)

    def query_min_gas_price(req: bytes) -> bytes:
        # celestia.minfee.v1 QueryNetworkMinGasPriceResponse
        # {network_min_gas_price=1 Dec} (x/minfee/query.proto). Dec rides
        # the wire as the 10^18-scaled integer's digits (gogoproto Dec).
        from celestia_app_tpu.modules.minfee import MinFeeKeeper

        with node_lock():
            price = MinFeeKeeper(node.app.cms.working).network_min_gas_price()
        return encode_bytes_field(1, str(price.raw).encode())

    def query_version_tally(req: bytes) -> bytes:
        # celestia.signal.v1 QueryVersionTallyRequest {version=1} ->
        # {voting_power=1, threshold_power=2, total_voting_power=3}
        # (x/signal/query.proto).
        from celestia_app_tpu.modules.signal.keeper import SignalKeeper
        from celestia_app_tpu.state.staking import StakingKeeper

        version = _field_int(req, 1)
        with node_lock():
            store = node.app.cms.working
            power, threshold, total = SignalKeeper(
                store, StakingKeeper(store)
            ).version_tally(version)
        return (
            encode_varint_field(1, power)
            + encode_varint_field(2, threshold)
            + encode_varint_field(3, total)
        )

    def _blobstream_keeper(store):
        from celestia_app_tpu.modules.blobstream.keeper import BlobstreamKeeper
        from celestia_app_tpu.state.staking import StakingKeeper

        return BlobstreamKeeper(store, StakingKeeper(store))

    def query_attestation_by_nonce(req: bytes) -> bytes:
        # celestia.qgb.v1 QueryAttestationRequestByNonceRequest {nonce=1}
        # -> {attestation=1 Any{type_url=1, value=2}}; empty when unknown.
        nonce = _field_int(req, 1)
        with node_lock():
            att = _blobstream_keeper(node.app.cms.working).get_attestation(nonce)
        if att is None:
            return b""
        type_url = ("/celestia.qgb.v1.Valset" if att.KIND == 1
                    else "/celestia.qgb.v1.DataCommitment")
        any_att = encode_bytes_field(1, type_url.encode()) + encode_bytes_field(
            2, att.marshal()
        )
        return encode_bytes_field(1, any_att)

    def query_latest_attestation_nonce(req: bytes) -> bytes:
        # celestia.qgb.v1 QueryLatestAttestationNonceResponse {nonce=1}.
        with node_lock():
            nonce = _blobstream_keeper(node.app.cms.working).latest_nonce()
        return encode_varint_field(1, nonce) if nonce else b""

    def query_evm_address(req: bytes) -> bytes:
        # celestia.qgb.v1 QueryEVMAddressRequest {validator_address=1} ->
        # {evm_address=1}; empty when unregistered.
        validator = _field_str(req, 1)
        with node_lock():
            evm = _blobstream_keeper(node.app.cms.working).evm_address(validator)
        return encode_bytes_field(1, evm.encode()) if evm else b""

    def query_delegation_rewards(req: bytes) -> bytes:
        # cosmos.distribution.v1beta1 QueryDelegationRewardsRequest
        # {delegator_address=1, validator_address=2} -> {rewards=1 repeated
        # DecCoin {denom=1, amount=2 Dec}}.
        from celestia_app_tpu.modules.distribution.keeper import (
            DistributionKeeper,
        )
        from celestia_app_tpu.state.staking import StakingKeeper

        delegator = _field_str(req, 1)
        validator = _field_str(req, 2)
        with node_lock():
            store = node.app.cms.working
            pending = DistributionKeeper(store).pending_rewards(
                StakingKeeper(store), delegator, validator
            )
        if not pending:
            return b""
        coin = encode_bytes_field(1, b"utia") + encode_bytes_field(
            2, str(pending * 10**18).encode()
        )
        return encode_bytes_field(1, coin)

    def query_community_pool(req: bytes) -> bytes:
        # QueryCommunityPoolResponse {pool=1 repeated DecCoin}.
        from celestia_app_tpu.modules.distribution.keeper import (
            DistributionKeeper,
        )

        with node_lock():
            pool = DistributionKeeper(node.app.cms.working).community_pool()
        if not pool.raw:
            return b""
        coin = encode_bytes_field(1, b"utia") + encode_bytes_field(
            2, str(pool.raw).encode()
        )
        return encode_bytes_field(1, coin)

    def _signing_info_msg(addr: str, info) -> bytes:
        # cosmos.slashing.v1beta1 ValidatorSigningInfo {address=1,
        # index_offset=3, jailed_until=4 Timestamp{seconds=1, nanos=2},
        # tombstoned=5, missed_blocks_counter=6}.
        out = encode_bytes_field(1, addr.encode())
        if info.index_offset:
            out += encode_varint_field(3, info.index_offset)
        if info.jailed_until_ns:
            ts = encode_varint_field(1, info.jailed_until_ns // 10**9)
            nanos = info.jailed_until_ns % 10**9
            if nanos:
                ts += encode_varint_field(2, nanos)
            out += encode_bytes_field(4, ts)
        if info.tombstoned:
            out += encode_varint_field(5, 1)
        if info.missed_blocks:
            out += encode_varint_field(6, info.missed_blocks)
        return out

    def query_signing_info(req: bytes) -> bytes:
        # QuerySigningInfoRequest {cons_address=1} -> {val_signing_info=1}.
        from celestia_app_tpu.modules.slashing.keeper import SlashingKeeper

        addr = _field_str(req, 1)
        with node_lock():
            info = SlashingKeeper(node.app.cms.working).signing_info(addr)
        return encode_bytes_field(1, _signing_info_msg(addr, info))

    def query_signing_infos(req: bytes) -> bytes:
        # QuerySigningInfosRequest {pagination=1} -> {info=1 repeated,
        # pagination=2}.
        from celestia_app_tpu.modules.slashing.keeper import SlashingKeeper

        with node_lock():
            infos = SlashingKeeper(node.app.cms.working).signing_infos()
        page_infos, page_resp = _paginate(infos, _parse_page_request(req, 1))
        out = b""
        for addr, info in page_infos:
            out += encode_bytes_field(1, _signing_info_msg(addr, info))
        if page_resp:
            out += encode_bytes_field(2, page_resp)
        return out

    def query_slashing_params(req: bytes) -> bytes:
        # QueryParamsResponse {params=1 {signed_blocks_window=1,
        # min_signed_per_window=2 Dec, downtime_jail_duration=3
        # Duration{seconds=1, nanos=2}, slash_fraction_double_sign=4 Dec,
        # slash_fraction_downtime=5 Dec}}.
        from celestia_app_tpu.modules.slashing.keeper import SlashingKeeper

        with node_lock():
            p = SlashingKeeper(node.app.cms.working).params()
        dur = encode_varint_field(1, p.downtime_jail_duration_ns // 10**9)
        nanos = p.downtime_jail_duration_ns % 10**9
        if nanos:
            dur += encode_varint_field(2, nanos)
        params = (
            encode_varint_field(1, p.signed_blocks_window)
            + encode_bytes_field(2, str(p.min_signed_per_window.raw).encode())
            + encode_bytes_field(3, dur)
            + encode_bytes_field(4, str(p.slash_fraction_double_sign.raw).encode())
            + encode_bytes_field(5, str(p.slash_fraction_downtime.raw).encode())
        )
        return encode_bytes_field(1, params)

    # Parked WaitTx waiters are capped below the worker-pool size so
    # long-polls can never starve the unary queries sharing the pool;
    # past the cap a waiter degrades to an immediate status check (the
    # client sees a fast not-yet-committed answer and may re-subscribe).
    import threading

    wait_slots = threading.Semaphore(_WAIT_TX_MAX_PARKED)

    def wait_tx(req: bytes) -> bytes:
        # Subscription service (this framework's long-poll analog of the
        # Tendermint websocket /subscribe tm.event='Tx'; the reference
        # serves that from celestia-core's RPC, not gRPC). Request
        # {hash=1 hex, timeout_ms=2}; response {tx_response=2 TxResponse}
        # mirroring GetTxResponse so clients share parsing; empty on
        # timeout. Deliberately NOT under node_lock — the wait parks on
        # the commit event and would deadlock the proposer loop.
        # Validate the client hex BEFORE any fromhex: malformed hashes
        # answer INVALID_ARGUMENT, not an opaque ValueError-backed error.
        txhash = _field_str(req, 1).strip()
        raw_hash = _tx_hash_bytes(txhash)
        timeout_ms = _field_int(req, 2)
        if timeout_ms <= 0:
            # Absent/zero timeout: immediate status check, no park (proto3
            # cannot distinguish the two, so 0 must not mean "default").
            status = node.tx_status(raw_hash)
            if status is None:
                return b""
            height, code, log = status
            return encode_bytes_field(
                2, _tx_response(height, txhash, code, log))
        if wait_slots.acquire(blocking=False):
            try:
                status = node.wait_tx(
                    raw_hash, min(timeout_ms, 110_000) / 1000.0
                )
            finally:
                wait_slots.release()
        else:  # all park slots busy: degrade to a poll-style check
            status = node.tx_status(raw_hash)
        if status is None:
            return b""
        height, code, log = status
        return encode_bytes_field(2, _tx_response(height, txhash, code, log))

    def _node_das_provider():
        get = getattr(node, "das_provider", None)
        if get is None:
            raise _Abort(
                "UNIMPLEMENTED", "this node serves no DAS surface (serve/)"
            )
        return get()

    def _das_payload(build, kind: str) -> bytes:
        from celestia_app_tpu.serve.api import UnknownHeight
        from celestia_app_tpu.serve.heal import HealingInProgress
        from celestia_app_tpu.serve.sampler import (
            BadProofDetected,
            ShareWithheld,
        )

        try:
            payload = build()
        except UnknownHeight as e:
            raise _Abort("NOT_FOUND", str(e)) from None
        except HealingInProgress as e:
            # The HTTP planes' 503 + Retry-After: the height is mid-heal
            # (serve/heal.py) — RETRYABLE, never the terminal
            # FAILED_PRECONDITION/DATA_LOSS the detections answer.  A
            # client that backs off and retries lands on the healed
            # height.
            raise _Abort("UNAVAILABLE", str(e)) from None
        except ShareWithheld as e:
            # The HTTP planes' 410 Gone: the share is committed but being
            # withheld — the light client's detection signal, distinct
            # from NOT_FOUND (height unknown) and from INVALID_ARGUMENT
            # (ShareWithheld is a LookupError, so without this clause it
            # would escape as an opaque UNKNOWN).
            raise _Abort(
                "FAILED_PRECONDITION", f"withholding detected: {e}"
            ) from None
        except BadProofDetected as e:
            # The HTTP planes' 502: committed root and served square
            # disagree — caught at the verification gate.  Must precede
            # the ValueError clause (BadProofDetected subclasses it):
            # a detected attack is not a malformed client request.
            raise _Abort("DATA_LOSS", str(e)) from None
        except (TypeError, ValueError) as e:
            raise _Abort("INVALID_ARGUMENT", str(e)) from None
        except Exception as e:
            # The HTTP planes' 429: a per-tenant proof-rate limit refused
            # this read (qos.py) — RESOURCE_EXHAUSTED carrying the same
            # canonical bytes.  Anything else keeps propagating.
            raise _qos_abort(e) from None
        from celestia_app_tpu.serve.api import count_served, render

        # Counted where the payload dict is in hand: the per-tenant
        # (capped namespace) label rides the same counter on every plane.
        count_served("grpc", kind, payload)
        return encode_bytes_field(1, render(payload))

    def das_share_proof(req: bytes) -> bytes:
        # celestia.tpu.das.v1 GetShareProofRequest {height=1, row=2,
        # col=3, axis=4 ("row" default / "col")} -> {payload=1 bytes}:
        # the canonical serve/api.render bytes, so the gRPC answer is
        # byte-identical to the GET /das/share_proof body on the HTTP
        # planes.
        provider = _node_das_provider()
        height, row, col = (
            _field_int(req, 1), _field_int(req, 2), _field_int(req, 3)
        )
        axis = _field_str(req, 4) or "row"
        return _das_payload(
            lambda: provider.share_proof_payload(height, row, col, axis=axis),
            "share_proof",
        )

    def das_shares_by_namespace(req: bytes) -> bytes:
        # GetSharesByNamespaceRequest {height=1, namespace=2 (29-byte
        # hex string)} -> {payload=1 bytes}.
        provider = _node_das_provider()
        height, ns_hex = _field_int(req, 1), _field_str(req, 2)
        return _das_payload(
            lambda: provider.shares_payload(height, ns_hex), "shares"
        )

    def das_attestation(req: bytes) -> bytes:
        # GetAttestationRequest {height=1, samples=2 (comma-joined
        # row:col[:axis] spec)} -> {payload=1 bytes}: the canonical
        # serve/api.render bytes of the deduped multiproof attestation —
        # byte-identical to the GET /das/attestation body on the HTTP
        # planes.
        provider = _node_das_provider()
        height, samples = _field_int(req, 1), _field_str(req, 2)
        return _das_payload(
            lambda: provider.attestation_payload(height, samples),
            "attestation",
        )

    return {
        "cosmos.tx.v1beta1.Service": {
            "BroadcastTx": broadcast_tx,
            "GetTx": get_tx,
            "Simulate": simulate,
        },
        "cosmos.auth.v1beta1.Query": {"Account": query_account},
        "cosmos.bank.v1beta1.Query": {"Balance": query_balance},
        "cosmos.staking.v1beta1.Query": {
            "Validators": query_validators,
            "Delegation": query_delegation,
        },
        "cosmos.gov.v1beta1.Query": {"Proposals": query_proposals},
        "celestia.blob.v1.Query": {"Params": query_blob_params},
        "celestia.minfee.v1.Query": {
            "NetworkMinGasPrice": query_min_gas_price,
        },
        "celestia.signal.v1.Query": {"VersionTally": query_version_tally},
        "celestia.qgb.v1.Query": {
            "AttestationRequestByNonce": query_attestation_by_nonce,
            "LatestAttestationNonce": query_latest_attestation_nonce,
            "EVMAddress": query_evm_address,
        },
        "cosmos.distribution.v1beta1.Query": {
            "DelegationRewards": query_delegation_rewards,
            "CommunityPool": query_community_pool,
        },
        "cosmos.slashing.v1beta1.Query": {
            "SigningInfo": query_signing_info,
            "SigningInfos": query_signing_infos,
            "Params": query_slashing_params,
        },
        "cosmos.base.tendermint.v1beta1.Service": {
            "GetLatestBlock": get_latest_block,
            "GetNodeInfo": get_node_info,
        },
        "celestia.tpu.subscription.v1.Subscription": {"WaitTx": wait_tx},
        "celestia.tpu.das.v1.Das": {
            "GetShareProof": das_share_proof,
            "GetSharesByNamespace": das_shares_by_namespace,
            "GetAttestation": das_attestation,
        },
    }


@dataclass
class GrpcPlane:
    server: object
    port: int
    debug_httpd: object = None
    debug_port: int = 0

    @property
    def target(self) -> str:
        return f"127.0.0.1:{self.port}"

    @property
    def debug_url(self) -> str:
        return f"http://127.0.0.1:{self.debug_port}"

    def stop(self, grace: float = 0.5) -> None:
        self.server.stop(grace)
        if self.debug_httpd is not None:
            self.debug_httpd.shutdown()
            self.debug_httpd.server_close()


def _serve_debug_port(host: str, port: int):
    """The gRPC plane's health/debug sidecar: gRPC has no GET surface, so
    the shared observability handler (trace/exposition.py — /metrics,
    /trace_tables, /healthz) rides a tiny HTTP server next to it, the same
    bytes the JSON-RPC and REST planes serve."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from celestia_app_tpu.trace.exposition import (
        handle_observability_get_adopted,
        send_observability_404,
        send_observability_response,
    )

    class _DebugHandler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet
            pass

        def do_GET(self):  # noqa: N802 — http.server API
            # Adopts an incoming x-celestia-trace header, same as the
            # other planes; 404s carry Content-Length so keep-alive
            # scrapers do not stall on the connection.
            resp = handle_observability_get_adopted(self, plane="grpc")
            if resp is None:
                send_observability_404(self)
                return
            send_observability_response(self, resp)

    httpd = ThreadingHTTPServer((host, port), _DebugHandler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


def serve_grpc(node, port: int = 0, max_workers: int = 16,
               debug_port: int | None = 0) -> GrpcPlane:
    """Start the gRPC plane for a node; returns the live server + port.

    `debug_port` (default: ephemeral) also starts the plane's health/debug
    HTTP sidecar serving the shared /metrics, /trace_tables, and /healthz;
    pass None to disable it."""
    import grpc

    ident = lambda b: b  # byte-level (de)serialization; codecs above

    def wrap(fn):
        from celestia_app_tpu.trace.context import (
            TRACE_HEADER,
            adopt_context,
            use_context,
        )

        def handler(req, ctx):
            # Cross-node propagation: x-celestia-trace rides gRPC
            # invocation metadata; ADOPT it (same trace_id, fresh
            # span_id, this node's node_id) so handler spans stitch
            # into the caller's trace.
            wire = None
            for key, value in ctx.invocation_metadata() or ():
                if key == TRACE_HEADER:
                    wire = value
                    break
            trace_ctx = adopt_context(wire)
            try:
                if trace_ctx is not None:
                    with use_context(trace_ctx):
                        return fn(req)
                return fn(req)
            except _Abort as e:  # typed handler failure -> proper status
                ctx.abort(grpc.StatusCode[e.code], e.details)

        return handler

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    for service, methods in _handlers(node).items():
        rpc_handlers = {
            name: grpc.unary_unary_rpc_method_handler(
                wrap(fn),
                request_deserializer=ident,
                response_serializer=ident,
            )
            for name, fn in methods.items()
        }
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(service, rpc_handlers),)
        )
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    server.start()
    debug_httpd = None
    debug_bound = 0
    if debug_port is not None:
        debug_httpd = _serve_debug_port("127.0.0.1", debug_port)
        debug_bound = debug_httpd.server_address[1]
    return GrpcPlane(server, bound, debug_httpd, debug_bound)


# --- client ----------------------------------------------------------------


class GrpcNode:
    """TxClient-compatible node surface over a gRPC channel.

    Implements broadcast / query_account / tx_status / validators /
    chain_id — the exact interface user.TxClient and txsim consume — so
    they run against a gRPC endpoint unchanged.
    """

    def __init__(self, target: str):
        import grpc

        self._channel = grpc.insecure_channel(target)
        ident = lambda b: b

        def traced_call(call):
            # Cross-node propagation: the active trace context rides as
            # x-celestia-trace invocation metadata on every unary call,
            # so the served node ADOPTS it (serve_grpc's wrapper) and
            # its spans stitch under the caller's trace_id.
            def invoke(req, **kwargs):
                from celestia_app_tpu.trace.context import (
                    TRACE_HEADER,
                    serialize_context,
                )

                wire = serialize_context()
                if wire is not None and "metadata" not in kwargs:
                    kwargs["metadata"] = ((TRACE_HEADER, wire),)
                return call(req, **kwargs)

            return invoke

        self._call = {
            name: traced_call(self._channel.unary_unary(
                path, request_serializer=ident, response_deserializer=ident
            ))
            for name, path in {
                "broadcast": "/cosmos.tx.v1beta1.Service/BroadcastTx",
                "get_tx": "/cosmos.tx.v1beta1.Service/GetTx",
                "simulate": "/cosmos.tx.v1beta1.Service/Simulate",
                "node_info": "/cosmos.base.tendermint.v1beta1.Service/GetNodeInfo",
                "account": "/cosmos.auth.v1beta1.Query/Account",
                "balance": "/cosmos.bank.v1beta1.Query/Balance",
                "validators": "/cosmos.staking.v1beta1.Query/Validators",
                "delegation": "/cosmos.staking.v1beta1.Query/Delegation",
                "proposals": "/cosmos.gov.v1beta1.Query/Proposals",
                "blob_params": "/celestia.blob.v1.Query/Params",
                "latest": "/cosmos.base.tendermint.v1beta1.Service/GetLatestBlock",
                "min_gas_price": "/celestia.minfee.v1.Query/NetworkMinGasPrice",
                "version_tally": "/celestia.signal.v1.Query/VersionTally",
                "attestation": "/celestia.qgb.v1.Query/AttestationRequestByNonce",
                "latest_nonce": "/celestia.qgb.v1.Query/LatestAttestationNonce",
                "evm_address": "/celestia.qgb.v1.Query/EVMAddress",
                "delegation_rewards":
                    "/cosmos.distribution.v1beta1.Query/DelegationRewards",
                "community_pool":
                    "/cosmos.distribution.v1beta1.Query/CommunityPool",
                "signing_info": "/cosmos.slashing.v1beta1.Query/SigningInfo",
                "signing_infos": "/cosmos.slashing.v1beta1.Query/SigningInfos",
                "slashing_params": "/cosmos.slashing.v1beta1.Query/Params",
                "wait_tx": "/celestia.tpu.subscription.v1.Subscription/WaitTx",
                "das_share_proof": "/celestia.tpu.das.v1.Das/GetShareProof",
                "das_shares":
                    "/celestia.tpu.das.v1.Das/GetSharesByNamespace",
                "das_attestation":
                    "/celestia.tpu.das.v1.Das/GetAttestation",
            }.items()
        }

    def close(self) -> None:
        self._channel.close()

    # --- TxClient surface ---------------------------------------------------
    @property
    def chain_id(self) -> str:
        hdr = _field_bytes(_field_bytes(self._call["latest"](b""), 2), 1)
        return _field_str(hdr, 2)

    def height(self) -> int:
        hdr = _field_bytes(_field_bytes(self._call["latest"](b""), 2), 1)
        return _field_int(hdr, 3)

    def broadcast(self, raw_tx: bytes):
        from celestia_app_tpu.app.app import TxResult

        resp = _parse_tx_response(
            _field_bytes(self._call["broadcast"](encode_bytes_field(1, raw_tx)), 1)
        )
        return TxResult(
            code=resp["code"], log=resp["raw_log"],
            gas_wanted=resp["gas_wanted"], gas_used=resp["gas_used"],
        )

    def query_account(self, address: str):
        from celestia_app_tpu.state.accounts import Account

        resp = self._call["account"](encode_bytes_field(1, address.encode()))
        any_acc = _field_bytes(resp, 1)
        if not any_acc:
            return None
        base = _field_bytes(any_acc, 2)
        return Account(
            address=_field_str(base, 1), pubkey=b"",
            account_number=_field_int(base, 3), sequence=_field_int(base, 4),
        )

    def tx_status(self, tx_hash: bytes):
        resp = self._call["get_tx"](
            encode_bytes_field(1, tx_hash.hex().upper().encode())
        )
        tr = _field_bytes(resp, 2)
        if not tr:
            return None
        parsed = _parse_tx_response(tr)
        return parsed["height"], parsed["code"], parsed["raw_log"]

    def balance(self, address: str, denom: str = "utia") -> int:
        resp = self._call["balance"](
            encode_bytes_field(1, address.encode())
            + encode_bytes_field(2, denom.encode())
        )
        return int(_field_str(_field_bytes(resp, 1), 2) or 0)

    def produce_block(self, timeout_s: float = 120.0):
        """The cosmos gRPC surface has no dev produce-block hook; wait for
        the served node's proposer loop to commit the next height (txsim's
        per-round block barrier), shaped like TestNode.produce_block.

        Default waits out a worst-case first-ever-square-size jit compile
        inside the proposer loop (35-50 s on the 1-core box — the same
        cold-compile allowance RemoteNode's socket timeout makes,
        rpc/client.py:40-44); steady-state blocks commit in well under a
        second."""
        import time

        start = self.height()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.height() > start:
                return {"height": self.height()}, []
            time.sleep(0.05)
        raise TimeoutError(f"no block committed past height {start}")

    def validators(self) -> list[dict]:
        from celestia_app_tpu.state.staking import POWER_REDUCTION

        out = []
        for num, wt, val in decode_fields(self._call["validators"](b"")):
            if num == 1 and wt == WIRE_LEN:
                # "address"/"power" match the in-process node surface so
                # txsim's sequences stay node-agnostic; the wire carries
                # tokens (power x PowerReduction, the sdk convention).
                out.append({
                    "address": _field_str(val, 1),
                    "power": int(_field_str(val, 5) or 0) // POWER_REDUCTION,
                })
        return out

    def delegation(self, delegator: str, validator: str) -> int:
        """Delegated utia of (delegator, validator); 0 if none."""
        resp = self._call["delegation"](
            encode_bytes_field(1, delegator.encode())
            + encode_bytes_field(2, validator.encode())
        )
        dr = _field_bytes(resp, 1)
        if not dr:
            return 0
        return int(_field_str(_field_bytes(dr, 2), 2) or 0)

    def proposals(self) -> list[dict]:
        """[{id, status}] of every proposal on chain."""
        out = []
        for num, wt, val in decode_fields(self._call["proposals"](b"")):
            if num == 1 and wt == WIRE_LEN:
                out.append({
                    "id": _field_int(val, 1),
                    "status": _field_int(val, 3),
                })
        return out

    def blob_params(self) -> dict:
        """{gas_per_blob_byte, gov_max_square_size} (celestia.blob.v1)."""
        p = _field_bytes(self._call["blob_params"](b""), 1)
        return {
            "gas_per_blob_byte": _field_int(p, 1),
            "gov_max_square_size": _field_int(p, 2),
        }

    def simulate(self, raw_tx: bytes) -> tuple[int, int, str]:
        """(gas_wanted, gas_used, log) of simulating `raw_tx`; gas_used 0
        with a log on failure."""
        resp = self._call["simulate"](encode_bytes_field(2, raw_tx))
        gas_info = _field_bytes(resp, 1)
        if gas_info:
            return _field_int(gas_info, 1), _field_int(gas_info, 2), ""
        return 0, 0, _field_str(_field_bytes(resp, 2), 2)

    def node_info(self) -> dict:
        """{network, version, moniker} (GetNodeInfo, the cosmjs connect
        handshake)."""
        info = _field_bytes(self._call["node_info"](b""), 1)
        return {
            "network": _field_str(info, 4),
            "version": _field_str(info, 5),
            "moniker": _field_str(info, 7),
        }

    def wait_tx(self, tx_hash: bytes, timeout_s: float = 30.0):
        """Subscription confirm: parks server-side on the commit event
        (WaitTx long-poll) instead of polling GetTx; (height, code, log)
        or None on timeout. TxClient._confirm rides this automatically.

        Re-subscribes while deadline remains: when the server's park slots
        are exhausted it degrades to an immediate status check, so a
        single call returning empty does not mean the timeout elapsed."""
        import time

        import grpc

        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining < 0.05:  # sub-50ms: not worth another round-trip
                return None
            req = encode_bytes_field(1, tx_hash.hex().upper().encode())
            req += encode_varint_field(2, int(remaining * 1000))
            t0 = time.monotonic()
            try:
                resp = self._call["wait_tx"](req, timeout=remaining + 10.0)
            except grpc.RpcError:
                return None  # deadline/transport fault == timed out
            tr = _field_bytes(resp, 2)
            if tr:
                parsed = _parse_tx_response(tr)
                return parsed["height"], parsed["code"], parsed["raw_log"]
            if time.monotonic() - t0 < 0.5:
                time.sleep(0.2)  # degraded to poll mode: pace re-subscribes

    def validators_page(self, offset: int = 0, limit: int = 0,
                        count_total: bool = False) -> tuple[list[dict], dict]:
        """One page of the validator set; returns (validators, {next_key,
        total})."""
        from celestia_app_tpu.state.staking import POWER_REDUCTION

        req = encode_bytes_field(
            2, encode_page_request(offset, limit, count_total)
        )
        resp = self._call["validators"](req)
        out = []
        for num, wt, val in decode_fields(resp):
            if num == 1 and wt == WIRE_LEN:
                out.append({
                    "address": _field_str(val, 1),
                    "power": int(_field_str(val, 5) or 0) // POWER_REDUCTION,
                })
        return out, _parse_page_response(_field_bytes(resp, 2))

    def proposals_page(self, offset: int = 0, limit: int = 0,
                       count_total: bool = False) -> tuple[list[dict], dict]:
        """One page of proposals; returns (proposals, {next_key, total})."""
        req = encode_bytes_field(
            4, encode_page_request(offset, limit, count_total)
        )
        resp = self._call["proposals"](req)
        out = []
        for num, wt, val in decode_fields(resp):
            if num == 1 and wt == WIRE_LEN:
                out.append({"id": _field_int(val, 1),
                            "status": _field_int(val, 3)})
        return out, _parse_page_response(_field_bytes(resp, 2))

    def network_min_gas_price(self) -> int:
        """The x/minfee network min gas price as the 10^18-scaled raw
        integer (gogoproto Dec wire form)."""
        return int(_field_str(self._call["min_gas_price"](b""), 1) or 0)

    def version_tally(self, version: int) -> dict:
        """{voting_power, threshold_power, total_voting_power} for an
        app version (x/signal)."""
        resp = self._call["version_tally"](encode_varint_field(1, version))
        return {
            "voting_power": _field_int(resp, 1),
            "threshold_power": _field_int(resp, 2),
            "total_voting_power": _field_int(resp, 3),
        }

    def attestation(self, nonce: int):
        """The blobstream attestation at `nonce` (Valset or
        DataCommitment), or None."""
        from celestia_app_tpu.modules.blobstream.keeper import (
            _unmarshal_attestation,
        )

        resp = self._call["attestation"](encode_varint_field(1, nonce))
        any_att = _field_bytes(resp, 1)
        if not any_att:
            return None
        return _unmarshal_attestation(_field_bytes(any_att, 2))

    def latest_attestation_nonce(self) -> int:
        return _field_int(self._call["latest_nonce"](b""), 1)

    def evm_address(self, validator: str) -> str | None:
        resp = self._call["evm_address"](
            encode_bytes_field(1, validator.encode())
        )
        addr = _field_str(resp, 1)
        return addr or None

    def delegation_rewards(self, delegator: str, validator: str) -> int:
        """Pending utia rewards of (delegator, validator); whole-utia
        floor of the Dec amount."""
        resp = self._call["delegation_rewards"](
            encode_bytes_field(1, delegator.encode())
            + encode_bytes_field(2, validator.encode())
        )
        coin = _field_bytes(resp, 1)
        if not coin:
            return 0
        return int(_field_str(coin, 2) or 0) // 10**18

    def community_pool(self) -> int:
        """Community pool balance as the 10^18-scaled raw integer."""
        coin = _field_bytes(self._call["community_pool"](b""), 1)
        return int(_field_str(coin, 2) or 0)

    def signing_info(self, validator: str) -> dict:
        resp = self._call["signing_info"](
            encode_bytes_field(1, validator.encode())
        )
        return self._parse_signing_info(_field_bytes(resp, 1))

    def signing_infos(self, offset: int = 0, limit: int = 0,
                      count_total: bool = False) -> tuple[list[dict], dict]:
        req = encode_bytes_field(
            1, encode_page_request(offset, limit, count_total)
        )
        resp = self._call["signing_infos"](req)
        infos = [
            self._parse_signing_info(val)
            for num, wt, val in decode_fields(resp)
            if num == 1 and wt == WIRE_LEN
        ]
        return infos, _parse_page_response(_field_bytes(resp, 2))

    @staticmethod
    def _parse_signing_info(raw: bytes) -> dict:
        ts = _field_bytes(raw, 4)
        jailed_until_ns = _field_int(ts, 1) * 10**9 + _field_int(ts, 2)
        return {
            "address": _field_str(raw, 1),
            "index_offset": _field_int(raw, 3),
            "jailed_until_ns": jailed_until_ns,
            "tombstoned": bool(_field_int(raw, 5)),
            "missed_blocks": _field_int(raw, 6),
        }

    def share_proof_bytes(self, height: int, row: int, col: int,
                          axis: str = "row") -> bytes:
        """Raw canonical payload bytes of GetShareProof — byte-identical
        to the HTTP planes' GET /das/share_proof body (the cross-plane
        identity tests compare exactly this)."""
        req = (
            encode_varint_field(1, height)
            + encode_varint_field(2, row)
            + encode_varint_field(3, col)
        )
        if axis != "row":
            req += encode_bytes_field(4, axis.encode())
        return _field_bytes(self._call["das_share_proof"](req), 1)

    def share_proof(self, height: int, row: int, col: int,
                    axis: str = "row") -> dict:
        """GetShareProof payload as a dict; `proof` reconstructs via
        rpc/codec.share_proof_from_json for client-side verify()."""
        import json

        return json.loads(self.share_proof_bytes(height, row, col, axis))

    def shares_by_namespace_bytes(self, height: int, namespace_hex: str) -> bytes:
        req = encode_varint_field(1, height) + encode_bytes_field(
            2, namespace_hex.encode()
        )
        return _field_bytes(self._call["das_shares"](req), 1)

    def shares_by_namespace(self, height: int, namespace_hex: str) -> dict:
        import json

        return json.loads(self.shares_by_namespace_bytes(height, namespace_hex))

    def attestation_bytes(self, height: int, samples: str) -> bytes:
        """Raw canonical payload bytes of GetAttestation — byte-identical
        to the HTTP planes' GET /das/attestation body (the cross-plane
        identity tests compare exactly this)."""
        req = encode_varint_field(1, height) + encode_bytes_field(
            2, samples.encode()
        )
        return _field_bytes(self._call["das_attestation"](req), 1)

    def das_attestation(self, height: int, samples: str) -> dict:
        """GetAttestation payload as a dict; per-sample proofs reconstruct
        via rpc/codec.share_proofs_from_attestation for client-side
        verification (host verify() or the batched verifier).  (Named
        das_attestation: `attestation(nonce)` is the blobstream query.)"""
        import json

        return json.loads(self.attestation_bytes(height, samples))

    def slashing_params(self) -> dict:
        p = _field_bytes(self._call["slashing_params"](b""), 1)
        dur = _field_bytes(p, 3)
        return {
            "signed_blocks_window": _field_int(p, 1),
            "min_signed_per_window": int(_field_str(p, 2) or 0),
            "downtime_jail_duration_ns":
                _field_int(dur, 1) * 10**9 + _field_int(dur, 2),
            "slash_fraction_double_sign": int(_field_str(p, 4) or 0),
            "slash_fraction_downtime": int(_field_str(p, 5) or 0),
        }

"""JSON wire codec for RPC payloads: dataclasses <-> JSON-safe dicts.

Bytes travel as hex strings; nested dataclasses/tuples recurse. The proof
reconstructors rebuild the exact dataclass types so `verify()` runs
client-side on wire-fetched proofs (the light-client contract).
"""

from __future__ import annotations

import dataclasses
from typing import Any


def to_jsonable(obj: Any) -> Any:
    if isinstance(obj, bytes):
        return obj.hex()
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            f.name: to_jsonable(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
    if isinstance(obj, (list, tuple)):
        return [to_jsonable(x) for x in obj]
    if isinstance(obj, dict):
        return {k: to_jsonable(v) for k, v in obj.items()}
    return obj


def share_proof_from_json(d: dict):
    from celestia_app_tpu.nmt.proof import NmtRangeProof
    from celestia_app_tpu.proof.share_proof import RowProof, ShareProof

    rp = d["row_proof"]
    row_proof = RowProof(
        row_roots=tuple(bytes.fromhex(r) for r in rp["row_roots"]),
        proofs=tuple(
            tuple(bytes.fromhex(h) for h in path) for path in rp["proofs"]
        ),
        start_row=rp["start_row"],
        end_row=rp["end_row"],
        total=rp["total"],
    )
    share_proofs = tuple(
        NmtRangeProof(
            start=p["start"],
            end=p["end"],
            nodes=tuple(bytes.fromhex(n) for n in p["nodes"]),
            total=p["total"],
        )
        for p in d["share_proofs"]
    )
    return ShareProof(
        data=tuple(bytes.fromhex(s) for s in d["data"]),
        share_proofs=share_proofs,
        namespace=bytes.fromhex(d["namespace"]),
        row_proof=row_proof,
    )


def state_proof_from_json(d: dict):
    from celestia_app_tpu.state.smt import StateProof

    return StateProof(
        key=bytes.fromhex(d["key"]),
        value=None if d["value"] is None else bytes.fromhex(d["value"]),
        path=[(bit, bytes.fromhex(sib)) for bit, sib in d["path"]],
        leaf_kh=None if d["leaf_kh"] is None else bytes.fromhex(d["leaf_kh"]),
        leaf_vh=None if d["leaf_vh"] is None else bytes.fromhex(d["leaf_vh"]),
    )

"""JSON wire codec for RPC payloads: dataclasses <-> JSON-safe dicts.

Bytes travel as hex strings; nested dataclasses/tuples recurse. The proof
reconstructors rebuild the exact dataclass types so `verify()` runs
client-side on wire-fetched proofs (the light-client contract).
"""

from __future__ import annotations

import dataclasses
from typing import Any


def to_jsonable(obj: Any) -> Any:
    if isinstance(obj, bytes):
        return obj.hex()
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            f.name: to_jsonable(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
    if isinstance(obj, (list, tuple)):
        return [to_jsonable(x) for x in obj]
    if isinstance(obj, dict):
        return {k: to_jsonable(v) for k, v in obj.items()}
    return obj


def share_proof_from_json(d: dict):
    from celestia_app_tpu.nmt.proof import NmtRangeProof
    from celestia_app_tpu.proof.share_proof import RowProof, ShareProof

    rp = d["row_proof"]
    row_proof = RowProof(
        row_roots=tuple(bytes.fromhex(r) for r in rp["row_roots"]),
        proofs=tuple(
            tuple(bytes.fromhex(h) for h in path) for path in rp["proofs"]
        ),
        start_row=rp["start_row"],
        end_row=rp["end_row"],
        total=rp["total"],
    )
    share_proofs = tuple(
        NmtRangeProof(
            start=p["start"],
            end=p["end"],
            nodes=tuple(bytes.fromhex(n) for n in p["nodes"]),
            total=p["total"],
        )
        for p in d["share_proofs"]
    )
    return ShareProof(
        data=tuple(bytes.fromhex(s) for s in d["data"]),
        share_proofs=share_proofs,
        namespace=bytes.fromhex(d["namespace"]),
        row_proof=row_proof,
    )


def share_proofs_from_attestation(d: dict):
    """Per-sample ShareProofs reconstructed from an attestation payload
    (serve/api.DasProvider.attestation_payload) — pure indexing into the
    deduped node tables, byte-identical to fetching each sample's
    share_proof alone.  This is BOTH the light client's reconstructor
    and the serve-side verification gate's input, so what the gate
    decides is exactly what a client would verify.

    Raises ValueError/KeyError/IndexError on malformed payloads
    (attacker-shaped input maps to a 400-class refusal, never a crash).
    """
    from celestia_app_tpu.constants import (
        NAMESPACE_SIZE,
        PARITY_NAMESPACE_BYTES,
    )
    from celestia_app_tpu.nmt.proof import NmtRangeProof
    from celestia_app_tpu.proof.share_proof import RowProof, ShareProof

    k = d["square_size"]
    samples, shares = d["samples"], d["shares"]
    nodes = [bytes.fromhex(nd) for nd in d["nodes"]]
    root_nodes = [bytes.fromhex(nd) for nd in d["root_nodes"]]
    if len(samples) != len(shares):
        raise ValueError(
            f"{len(samples)} samples but {len(shares)} shares"
        )
    out: list[ShareProof] = []
    pos = 0
    for tree in d["trees"]:
        axis, index = tree["axis"], tree["index"]
        root = bytes.fromhex(tree["root"])
        root_path = tuple(root_nodes[j] for j in tree["root_path_refs"])
        row_proof = RowProof(
            row_roots=(root,),
            proofs=(root_path,),
            start_row=tree["root_index"],
            end_row=tree["root_index"] + 1,
            total=tree["root_total"],
        )
        if len(tree["ranges"]) != len(tree["node_refs"]):
            raise ValueError("ranges/node_refs length mismatch")
        for (start, end), refs in zip(tree["ranges"], tree["node_refs"]):
            if pos >= len(samples):
                raise ValueError("more tree ranges than samples")
            s = samples[pos]
            row, col = s["row"], s["col"]
            tree_of, leaf = (row, col) if s["axis"] == "row" else (col, row)
            if s["axis"] != axis or tree_of != index or leaf != start:
                raise ValueError(
                    f"sample {pos} ({row},{col},{s['axis']}) does not "
                    f"match tree {axis}:{index} range [{start},{end})"
                )
            share = bytes.fromhex(shares[pos])
            ns = (
                share[:NAMESPACE_SIZE]
                if row < k and col < k
                else PARITY_NAMESPACE_BYTES
            )
            out.append(ShareProof(
                data=(share,),
                share_proofs=(NmtRangeProof(
                    start=start,
                    end=end,
                    nodes=tuple(nodes[j] for j in refs),
                    total=tree["total"],
                ),),
                namespace=ns,
                row_proof=row_proof,
            ))
            pos += 1
    if pos != len(samples):
        raise ValueError(f"{len(samples) - pos} samples not covered by trees")
    return out


def state_proof_from_json(d: dict):
    from celestia_app_tpu.state.smt import StateProof

    return StateProof(
        key=bytes.fromhex(d["key"]),
        value=None if d["value"] is None else bytes.fromhex(d["value"]),
        path=[(bit, bytes.fromhex(sib)) for bit, sib in d["path"]],
        leaf_kh=None if d["leaf_kh"] is None else bytes.fromhex(d["leaf_kh"]),
        leaf_vh=None if d["leaf_vh"] is None else bytes.fromhex(d["leaf_vh"]),
    )

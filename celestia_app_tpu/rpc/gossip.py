"""Gossip consensus: the round machine driven over a peer-to-peer flood.

Replaces the proposer-push replication (VERDICT r2 missing #3) for devnet
validators: proposals and votes are broadcast to peers and RELAYED with
dedup (a flood mesh), so votes reach quorum without routing through the
proposer, and a tx submitted to any node reaches the proposer by relay —
the reference's p2p gossip shape (celestia-core consensus reactor +
mempool v1 gossip, app/default_overrides.go:258-284) without per-peer TCP
streams.

Division of labor:
  * consensus/machine.py — WHAT to do (pure Tendermint rules);
  * this driver — WHEN and WHERE: locks, timers, catch-up, payload
    storage, and executing the machine's effects (network sends happen
    strictly OUTSIDE the node lock — a relay cycle back into a waiting
    handler must never deadlock);
  * rpc/server.py `rpc_consensus` — the HTTP ingress, one endpoint for
    both message kinds.

The proposal payload carries the full block (txs), the height-1 Commit
record (Tendermint's LastCommit: the canonical precommit set every node
uses for x/slashing liveness — nodes may have collected different
precommit subsets themselves), and the evidence list, so every validator
executes the block with identical inputs.
"""

from __future__ import annotations

import threading

from celestia_app_tpu.app import BlockData
from celestia_app_tpu.consensus.machine import (
    BroadcastProposal,
    BroadcastVote,
    Decided,
    EvidenceFound,
    Locked,
    Proposal,
    RequestProposal,
    RoundJournal,
    RoundMachine,
    ScheduleTimeout,
)
from celestia_app_tpu.consensus.votes import (
    NIL,
    Commit,
    ConsensusError,
    Vote,
    block_id,
    verify_commit,
)

# Devnet-scale timeouts (seconds): (base, per-round delta).
FAST_TIMEOUTS = {
    "propose": (0.6, 0.3),
    "prevote": (0.4, 0.2),
    "precommit": (0.4, 0.2),
}


class ConsensusDriver:
    """Owns the RoundMachine lifecycle for a ServingNode.

    All machine access happens under node.lock; every network send is
    queued in an outbox and flushed after the lock is released.
    """

    def __init__(
        self, node, timeouts=None, interval_s: float = 0.2,
        latency_s: float = 0.0, jitter_s: float = 0.0,
        wal_path: str | None = None,
    ):
        self.node = node
        self.timeouts = timeouts or FAST_TIMEOUTS
        self.interval_s = interval_s
        # Double-sign protection across restarts (consensus/wal.py): own
        # votes journal durably before broadcast; locks are restored into
        # the next machine for the same height.
        self.wal = None
        if wal_path is not None:
            from celestia_app_tpu.consensus.wal import VoteWAL

            self.wal = VoteWAL(wal_path)
        # Chaos injection (the BitTwister analog, reference
        # test/e2e/benchmark/benchmark.go:112-119): every peer send sleeps
        # latency_s plus a deterministic per-message jitter in
        # [0, jitter_s], modeling a slow link without containers.
        self.latency_s = latency_s
        self.jitter_s = jitter_s
        self.machine: RoundMachine | None = None
        # block_hash -> {"data": BlockData, "time_ns": int,
        #                "last_commit": dict|None, "evidence": list}
        self.payloads: dict[bytes, dict] = {}
        # msg dedup (flood termination): id -> height, pruned by height so
        # the bound never wholesale-forgets in-flight heights (a clear()
        # would let the current height's messages re-flood).
        self.seen: dict[tuple, int] = {}
        # Messages that arrived between heights (machine torn down) or for
        # a near-future height: replayed when the next machine starts —
        # dedup marks them seen on arrival, so without this they'd be lost.
        self.backlog: list[dict] = []
        self.evidence_pool: list = []  # Equivocations awaiting inclusion
        # height -> validator map that height's machine ran under.  A
        # LastCommit for height H-1 must verify against the set bonded AT
        # H-1 — the post-H-1 set has already dropped anyone jailed by
        # block H-1, and verify_commit treats their (legitimate) precommit
        # as foreign, which would make every height-H proposal invalid on
        # every node (chain-wide halt after any jailing event).
        self.valsets: dict[int, dict] = {}
        self._timers: list[threading.Timer] = []
        self._stopped = False
        # peer url -> consecutive failed sends (gates per-send retries:
        # a link mid-streak is not worth multiplying timeouts on).
        self._peer_fail_streak: dict = {}

    # --- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        outbox: list = []
        with self.node.lock:
            self._new_height_locked(outbox)
        self._send_all(outbox)
        # Gossip that arrived before start() sits in the backlog (dedup
        # marked it seen on arrival): replay it into the fresh machine.
        self._drain_backlog()

    def stop(self) -> None:
        self._stopped = True
        for t in self._timers:
            t.cancel()
        # A timer that was already firing may be mid-send: wait it out so
        # no thread outlives the node (interpreter-exit safety).
        for t in self._timers:
            if t.is_alive():
                t.join(timeout=5.0)
        if self.wal is not None:
            self.wal.close()

    def _new_height_locked(self, outbox: list) -> None:
        node = self.node
        height = node.app.height + 1
        validators = node._validator_set()
        order = sorted(validators)
        if order:
            # Rotate by height so the height-H round-0 proposer matches the
            # push plane's is_proposer rotation shape.
            shift = (height - 1) % len(order)
            order = order[shift:] + order[:shift]
        locked_round, locked_value = -1, None
        sign_guard = None
        if self.wal is not None:
            restored = self.wal.lock_for(height)
            if restored is not None:
                locked_round, locked_value = restored
            sign_guard = self.wal.may_sign
            # Prune in batches: prune() rewrites + fsyncs the whole
            # journal, which must not run on every height transition
            # under the node lock (appends stay cheap in between).
            if height % 128 == 0:
                self.wal.prune(height - 2)
        self.machine = RoundMachine(
            node.chain_id, height, validators, order or ["<none>"],
            my_address=node._operator_address(),
            my_key=node.validator_key,
            timeouts=self.timeouts,
            sign_guard=sign_guard,
            locked_value=locked_value,
            locked_round=locked_round,
            # One round_journal row per (height, round), fsync time from
            # the WAL's cumulative counter (trace/ pulls the table).
            journal=RoundJournal(
                fsync_ms_source=(
                    (lambda: self.wal.fsync_ms_total)
                    if self.wal is not None else None
                ),
            ),
        )
        self.valsets[height] = validators
        for h in [h for h in self.valsets if h < height - 128]:
            del self.valsets[h]
        self._execute_locked(self.machine.start(), outbox)

    # --- effect execution (under lock) -------------------------------------
    def _execute_locked(self, effects: list, outbox: list) -> None:
        for e in effects:
            if isinstance(e, BroadcastVote):
                outbox.append({
                    "kind": "vote",
                    "height": e.vote.height,
                    "vote": e.vote.marshal().hex(),
                })
            elif isinstance(e, BroadcastProposal):
                p = e.proposal
                payload = self.payloads[p.block_hash]
                outbox.append({
                    "kind": "proposal",
                    "height": p.height,
                    "round": p.round,
                    "pol_round": p.pol_round,
                    "proposer": p.proposer,
                    "signature": p.signature.hex(),
                    "block_hash": p.block_hash.hex(),
                    "block": {
                        "time_ns": payload["time_ns"],
                        "data_hash": payload["data"].hash.hex(),
                        "square_size": payload["data"].square_size,
                        "txs": [t.hex() for t in payload["data"].txs],
                    },
                    "last_commit": payload["last_commit"],
                    "evidence": payload["evidence"],
                })
            elif isinstance(e, ScheduleTimeout):
                self._schedule(e)
            elif isinstance(e, RequestProposal):
                self._propose_locked(e, outbox)
            elif isinstance(e, Decided):
                self._commit_decided_locked(e)
            elif isinstance(e, EvidenceFound):
                eq = e.equivocation
                if eq.key() not in self.node._used_evidence:
                    self.evidence_pool.append(eq)
            elif isinstance(e, Locked):
                if self.wal is not None:
                    self.wal.record_lock(
                        self.machine.height, e.round, e.block_hash
                    )

    def _schedule(self, t: ScheduleTimeout) -> None:
        if self._stopped or self.machine is None:
            return  # a Decided earlier in the same effect list ended the height
        height = self.machine.height
        timer = threading.Timer(
            t.delay, self._fire_timeout, args=(height, t.round, t.step)
        )
        timer.daemon = True
        timer.start()
        self._timers.append(timer)
        # Bound the list (fired timers linger otherwise).
        if len(self._timers) > 256:
            self._timers = [x for x in self._timers if x.is_alive()]

    def _fire_timeout(self, height: int, round: int, step: str) -> None:
        if self._stopped:
            return
        outbox: list = []
        with self.node.lock:
            m = self.machine
            if m is None or m.height != height or m.decided is not None:
                return  # stale: the height moved on
            self._execute_locked(m.on_timeout(round, step), outbox)
        self._send_all(outbox)

    def _propose_locked(self, req: RequestProposal, outbox: list) -> None:
        """Build (or reuse) the block for our proposer turn."""
        node = self.node
        height = self.machine.height
        if req.block_hash != NIL and req.block_hash in self.payloads:
            # Re-propose the valid value from an earlier polka, unchanged.
            bid = req.block_hash
        else:
            from celestia_app_tpu.testutil.testnode import BLOCK_INTERVAL_NS
            from celestia_app_tpu.trace.context import trace_span, use_context

            time_ns = node.app.last_block_time_ns + BLOCK_INTERVAL_NS
            reaped = node.mempool.reap(node.block_max_bytes())
            # The block adopts the first reaped tx's submission trace so
            # one trace_id spans submit -> ... -> DAH -> commit; the round
            # journal rows for this height carry it too.
            block_ctx = node._block_trace_context(reaped, height)
            if self.machine.journal is not None:
                self.machine.journal.trace_id = block_ctx.trace_id
            with use_context(block_ctx), trace_span(
                "block_propose", layer="consensus", e2e="propose",
                height=height, round=req.round, n_txs=len(reaped),
            ):
                data = node.app.prepare_proposal(reaped)
                if not node.app.process_proposal(data):
                    raise AssertionError("node rejected its own proposal")
            prev_commit = node._commits.get(height - 1)
            evidence = [
                eq for eq in self.evidence_pool
                if eq.key() not in node._used_evidence
            ]
            bid = block_id(data.hash, node.app.cms.last_app_hash, time_ns)
            self.payloads[bid] = {
                "data": data,
                "time_ns": time_ns,
                "last_commit": (
                    prev_commit.to_json() if prev_commit is not None else None
                ),
                "evidence": node._evidence_to_wire(tuple(evidence)),
            }
        self._execute_locked(self.machine.on_own_proposal(bid), outbox)

    def _commit_decided_locked(self, d: Decided) -> None:
        node = self.node
        m = self.machine
        payload = self.payloads[d.block_hash]
        data: BlockData = payload["data"]
        time_ns: int = payload["time_ns"]
        last_commit = payload["last_commit"]
        signers = (
            {
                Vote.unmarshal(bytes.fromhex(v)).validator
                for v in last_commit["precommits"]
            }
            if last_commit is not None
            else None
        )
        evidence = node._parse_evidence(payload["evidence"] or [])
        prev_app_hash = node.app.cms.last_app_hash
        node._commit_block_data(
            data, time_ns, last_commit_signers=signers, evidence=evidence
        )
        record = Commit(
            m.height, d.block_hash, d.precommits, data.hash, prev_app_hash,
            round=d.round, time_ns=time_ns,
        )
        node._commits[m.height] = record
        for eq in evidence:
            node._used_evidence.add(eq.key())
        self.evidence_pool = [
            eq for eq in self.evidence_pool
            if eq.key() not in node._used_evidence
        ]
        self.payloads.clear()
        self.machine = None
        if not self._stopped:
            timer = threading.Timer(self.interval_s, self._start_next_height)
            timer.daemon = True
            timer.start()
            self._timers.append(timer)

    def _start_next_height(self) -> None:
        if self._stopped:
            return
        outbox: list = []
        with self.node.lock:
            if self.machine is None:
                self._new_height_locked(outbox)
        self._send_all(outbox)
        self._drain_backlog()

    def _drain_backlog(self) -> None:
        """Replay gap-buffered messages (already dedup-marked, so they
        bypass handle())."""
        with self.node.lock:
            backlog, self.backlog = self.backlog, []
            current = self.machine.height if self.machine else 0
        for msg in backlog:
            if int(msg.get("height", 0)) >= current:
                try:
                    self._process(msg)
                except ConsensusError:
                    pass

    #: Re-relay fan-out cap.  The ORIGINATOR of a message already sends it
    #: to every peer directly (full one-hop coverage on healthy links);
    #: receiver relays exist to route around dead/slow links, so a small
    #: deterministic subset suffices — without the cap the flood costs
    #: O(n^2) sends per message, which drowns large devnets (the
    #: reference's gossip also maintains a bounded peer set, not a clique).
    RELAY_FANOUT = 6

    # --- ingress -----------------------------------------------------------
    def handle(self, msg: dict) -> dict:
        """rpc_consensus: dedup, authenticate, relay, process."""
        from celestia_app_tpu.trace.metrics import registry

        kind = str(msg.get("kind", "unknown"))
        registry().counter(
            "celestia_gossip_msgs_total", "consensus gossip messages"
        ).inc(kind=kind, direction="in")
        msg_id = self._msg_id(msg)
        with self.node.lock:
            if msg_id in self.seen:
                registry().counter(
                    "celestia_gossip_dedup_hits_total",
                    "gossip messages dropped as already-seen (flood termination)",
                ).inc(kind=kind)
                return {"ok": True, "dup": True}
            self.seen[msg_id] = int(msg.get("height", 0) or 0)
            if len(self.seen) > 100_000:
                cur = self.machine.height if self.machine else self.node.app.height
                # Normal case: drop long-committed heights.  The claimed
                # height is attacker-controlled, so this alone is not a
                # bound — if a flood pins heights inside the live window,
                # fall back to the hard clear() (dedup re-warms quickly);
                # without it every further message pays an O(n) rebuild
                # under the lock and memory grows without limit.
                pruned = {
                    i: h for i, h in self.seen.items() if cur - 2 <= h <= cur + 64
                }
                self.seen = pruned if len(pruned) <= 90_000 else {msg_id: 0}
        # Relay outside the lock (flood; dedup terminates it) — but only
        # AFTER wire-level authentication: dedup cannot bound an
        # unauthenticated sender (every mutated junk copy hashes to a
        # fresh id), so unverified bytes must never fan out mesh-wide.
        if self._wire_verify(msg):
            self.node.gossip_pool.submit(self._relay, msg)
        # Cross-node propagation: ADOPT the sender's trace stamped on the
        # envelope (rpc/transport.deliver) — same trace_id, fresh
        # span_id, this node's node_id — so consensus spans on every hop
        # of the flood stitch under the originator's trace.
        from celestia_app_tpu.trace.context import adopt_context, use_context

        trace_ctx = adopt_context(msg.get("trace"))
        try:
            if trace_ctx is not None:
                with use_context(trace_ctx):
                    self._process(msg)
            else:
                self._process(msg)
        except ConsensusError:
            return {"ok": False}
        return {"ok": True}

    def _wire_verify(self, msg: dict) -> bool:
        """Authenticate a message against the best-known validator set
        WITHOUT applying it — the relay admission check.  A message that
        fails (malformed, unknown signer, bad signature) is still handed
        to _process (a backlogged future-height message may verify once
        the valset catches up) but is not re-relayed by THIS node; the
        originator already sent it to every peer directly."""
        try:
            with self.node.lock:
                m = self.machine
                vals = (
                    dict(m.validators)
                    if m is not None
                    else self.node._validator_set()
                )
            kind = msg.get("kind")
            if kind == "vote":
                vote = Vote.unmarshal(bytes.fromhex(msg["vote"]))
                entry = vals.get(vote.validator)
                return entry is not None and vote.verify(
                    entry[0], self.node.chain_id
                )
            if kind == "proposal":
                prop = Proposal(
                    int(msg["height"]), int(msg["round"]),
                    bytes.fromhex(msg["block_hash"]), int(msg["pol_round"]),
                    msg["proposer"], bytes.fromhex(msg["signature"]),
                )
                entry = vals.get(prop.proposer)
                if entry is None or not entry[0].verify(
                    prop.sign_bytes(self.node.chain_id), prop.signature
                ):
                    return False
                # The proposal signature does NOT cover the block payload
                # (only the signed block id binds it): without this check a
                # tampered-payload copy of one honest proposal hashes to a
                # fresh msg id yet still carries a valid signature — an
                # unbounded relay flood of full block bytes.  Conservative
                # on purpose: proposals for heights whose prev app hash we
                # don't hold locally are not re-relayed (the originator
                # already reached every peer one hop).
                block = msg.get("block") or {}
                try:
                    bid = block_id(
                        bytes.fromhex(block["data_hash"]),
                        self.node.app.cms.last_app_hash,
                        int(block["time_ns"]),
                    )
                except (KeyError, ValueError):
                    return False
                return bid == prop.block_hash
            return False
        except (KeyError, ValueError, TypeError):
            return False

    @staticmethod
    def _msg_id(msg: dict) -> tuple:
        # The PAYLOAD is part of a proposal's identity: the proposal
        # signature does not cover the block bytes (the signed block id
        # does, indirectly), so without this a tampered relay copy would
        # dedup-block the genuine message mesh-wide and censor an honest
        # proposal.  Shared with the chaos drills via rpc/transport.py.
        from celestia_app_tpu.rpc import transport

        return transport.msg_id(msg)

    def _process(self, msg: dict) -> None:
        node = self.node
        height = int(msg.get("height", 0))
        # A node that discovers it is behind catches up from the block
        # store first (outside the machine), then restarts its machine.
        with node.lock:
            behind = self.machine is not None and height > self.machine.height
        if behind:
            try:
                node._catch_up(height - 1)
            except ValueError:
                pass  # peers can't serve yet; the message may still apply
        outbox: list = []
        with node.lock:
            m = self.machine
            if m is None:
                # Between heights: keep for replay at the next start.
                if height >= node.app.height + 1 and len(self.backlog) < 1000:
                    self.backlog.append(msg)
                return
            if m.height < node.app.height + 1:
                # Blocks were applied behind this machine's back (catch-up):
                # drop the stale machine and start at the new height.
                self._new_height_locked(outbox)
                m = self.machine
            if height != m.height:
                if height > m.height and len(self.backlog) < 1000:
                    self.backlog.append(msg)
                self._send_all_later(outbox)
                return
            if msg["kind"] == "vote":
                vote = Vote.unmarshal(bytes.fromhex(msg["vote"]))
                self._execute_locked(m.on_vote(vote), outbox)
            elif msg["kind"] == "proposal":
                prop = Proposal(
                    height, int(msg["round"]), bytes.fromhex(msg["block_hash"]),
                    int(msg["pol_round"]), msg["proposer"],
                    bytes.fromhex(msg["signature"]),
                )
                if not m.verify_proposal(prop):
                    # Unauthenticated garbage (forged signature, wrong
                    # proposer): DROP.  Feeding it to the machine as an
                    # invalid proposal would let any unauthenticated
                    # sender draw a nil prevote per round — a liveness
                    # DoS against an honest proposer.
                    return
                verdict = self._validate_payload(prop, msg)
                if verdict is None:
                    # Payload does not match the SIGNED block id (a
                    # tampered relay copy, or this node's state diverged):
                    # not the proposer's content — drop and let the
                    # propose timeout govern, never blame the proposer.
                    return
                self._execute_locked(m.on_proposal(prop, verdict), outbox)
        self._send_all(outbox)

    def _validate_payload(self, prop: Proposal, msg: dict) -> bool | None:
        """Block-level validation under the node lock.

        Returns True (prevote it), False (the proposer's own signed
        content is invalid: nil prevote), or None (the payload is NOT
        what the proposer signed — tampered relay copy or local state
        divergence — so drop without judging the proposer; the signed
        block id binds data root, prev app hash, and time, which is what
        separates the two cases)."""
        node = self.node
        block = msg.get("block") or {}
        try:
            data = BlockData(
                txs=tuple(bytes.fromhex(t) for t in block["txs"]),
                square_size=int(block["square_size"]),
                hash=bytes.fromhex(block["data_hash"]),
            )
            time_ns = int(block["time_ns"])
        except (KeyError, ValueError):
            return None  # malformed relay copy, not the proposer's content
        if block_id(data.hash, node.app.cms.last_app_hash, time_ns) != prop.block_hash:
            return None
        if time_ns <= node.app.last_block_time_ns:
            return False  # block time must advance (BFT time monotonicity)
        # The prevote window's speculative extend (the PR 9 seam's round-
        # machine call site, $CELESTIA_PIPE_SPECULATE): the payload is the
        # proposer's signed content, so enqueue the square's extension NOW
        # — the device dispatch runs across the LastCommit signature batch
        # and ante validation below, and process_proposal's root check
        # claims the finished result.  A round change re-proposing
        # different bytes makes the next compute() DISCARD the claim
        # (celestia_speculation_total{outcome="discard"}; drilled by
        # tests and scripts/chaos_soak.py's speculation drill).
        node.app.speculate_proposal(data, height=prop.height,
                                    round_=prop.round)
        # LastCommit: required after height 1; must attest the block id
        # this node itself committed at H-1 (its own stored record — NOT a
        # driver-local cache, which goes stale when heights apply via
        # block-store catch-up) and verify against the validator set that
        # height ran under.
        last_commit = msg.get("last_commit")
        if prop.height > 1:
            if last_commit is None:
                return False
            try:
                rec = Commit.from_json(last_commit)
            except (KeyError, ValueError):
                return False
            if rec.height != prop.height - 1:
                return False
            own = node._commits.get(prop.height - 1)
            if own is not None and rec.block_hash != own.block_hash:
                return False
            prev_vals = self.valsets.get(prop.height - 1)
            if prev_vals is None:
                # No machine ran at H-1 here (catch-up gap): the block
                # store keeps the set every committed height ran under, so
                # a freshly caught-up node verifies the H-1 precommits
                # against the right set even across a jailing boundary.
                prev_vals = getattr(node, "_valsets_by_height", {}).get(
                    prop.height - 1
                )
            if prev_vals is None:
                # Height H-1 predates this node entirely (state sync): the
                # current bonded set is the last-resort approximation.
                prev_vals = self.machine.validators
            if not verify_commit(prev_vals, node.chain_id, rec):
                return False
        elif last_commit is not None:
            return False
        if not node.app.process_proposal(data):
            return False
        self.payloads[prop.block_hash] = {
            "data": data,
            "time_ns": time_ns,
            "last_commit": last_commit,
            "evidence": msg.get("evidence") or [],
        }
        return True

    # --- egress ------------------------------------------------------------
    def _relay(self, msg: dict) -> None:
        """Re-relay a received message to a bounded peer subset.

        The subset is derived from the MESSAGE id, so each message takes
        a different window — a link missed by one message's window is
        covered by the next's, and a lost individual message is healed by
        the round machine (timeout -> next round) or height catch-up.
        Full coverage per message is only guaranteed one hop from the
        originator (which sends to every peer); partial topologies with
        node degree above the fan-out trade per-message delivery
        certainty for bounded flood cost, exactly like the reference's
        bounded peer set."""
        peers = self.node.peers()
        if len(peers) > self.RELAY_FANOUT:
            import hashlib as _hashlib

            start = _hashlib.sha256(repr(self._msg_id(msg)).encode()).digest()[0]
            start %= len(peers)
            peers = [
                peers[(start + i) % len(peers)]
                for i in range(self.RELAY_FANOUT)
            ]
        if self.latency_s or self.jitter_s:
            # One pool task per peer: a serial sleep-per-peer loop would
            # park a gossip worker for fanout x latency per message.
            for peer in peers:
                self.node.gossip_pool.submit(self._send_to, peer, [msg])
            return
        for peer in peers:
            self._send_to(peer, [msg])

    def _send_all(self, msgs: list) -> None:
        """Originator broadcast: every peer, full coverage."""
        if not msgs:
            return
        peers = self.node.peers()
        from celestia_app_tpu.trace.metrics import registry

        registry().gauge(
            "celestia_gossip_peers", "configured gossip peer count"
        ).set(len(peers))
        if self.latency_s or self.jitter_s:
            # Per-peer fan-out so injected latency costs one delay, not
            # one per link (a real network delays links in parallel).
            for peer in peers:
                self.node.gossip_pool.submit(self._send_to, peer, list(msgs))
            return
        for peer in peers:
            self._send_to(peer, msgs)

    #: Bounded per-peer send retries: a blip on one link costs a short
    #: backoff instead of relying solely on the round machine's timeouts
    #: to route around it.  Final failure still falls back to the flood
    #: (the relay mesh + catch-up heal lost messages).  Delivery itself —
    #: chaos seam, retry gate, failure streaks — lives in rpc/transport.py
    #: (crypto-free, so the chaos drills exercise it without the signing
    #: stack).
    SEND_RETRIES = 2

    def _send_to(self, peer, msgs: list) -> None:
        import time as _time

        from celestia_app_tpu.rpc import transport
        from celestia_app_tpu.trace.metrics import registry

        sent = registry().counter(
            "celestia_gossip_msgs_total", "consensus gossip messages"
        )
        key = getattr(peer, "url", None) or id(peer)
        for msg in msgs:
            sent.inc(kind=str(msg.get("kind", "unknown")), direction="out")
            if self.latency_s or self.jitter_s:
                jitter = 0.0
                if self.jitter_s:
                    import hashlib as _hashlib

                    digest = _hashlib.sha256(repr(msg).encode()).digest()
                    jitter = self.jitter_s * digest[0] / 255.0
                _time.sleep(self.latency_s + jitter)
            transport.deliver(
                peer.consensus, msg, streak=self._peer_fail_streak,
                key=key, retries=self.SEND_RETRIES,
            )

    def _send_all_later(self, msgs: list) -> None:
        if msgs:
            self.node.gossip_pool.submit(self._send_all, msgs)

"""The serving plane: JSON-RPC over HTTP (sockets, multi-process).

The reference runs gRPC/API/RPC servers around the app even in tests
(app/app.go:712-735, test/util/testnode/network.go:38-43); its RPC plane is
JSON-RPC over HTTP. This package is that wire for the TPU framework:

  * `rpc.server.ServingNode` — a node (App + mempool + proposer loop) that
    serves broadcast/query/proof endpoints and replicates blocks to peer
    validators over sockets;
  * `rpc.client.RemoteNode` — the client-side handle presenting the same
    node surface TxClient/txsim consume in-process, but over HTTP;
  * `rpc.devnet` — a multi-process devnet: N validator processes with a
    rotating proposer exchanging proposals over the wire.
"""

# Lazy exports: ServingNode pulls in the full app stack (and through it
# the signing backend's optional `cryptography` dependency).  The wire
# planes in this package (grpc_plane, api_gateway, codec) are importable
# without any of that — a client-only or handler-level consumer (tests in
# a slim image included) must not pay the app import to reach them.
__all__ = ["RemoteNode", "ServingNode", "serve"]


def __getattr__(name: str):
    if name == "RemoteNode":
        from celestia_app_tpu.rpc.client import RemoteNode

        return RemoteNode
    if name in ("ServingNode", "serve"):
        from celestia_app_tpu.rpc import server

        return getattr(server, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
